"""Predict router: fan a batch's unique keys out over the serving shards.

The router is the client half of the serving tier: it packs a RowBlock
with a scorer (serving/scoring.py), splits each table's sorted-unique
key list into the per-shard contiguous ranges of the same even
``shard_range`` split the shards loaded, fetches every shard's rows in
parallel, and scores on the reassembled compact tables — bit-identical
to the trainer's own predict (the scorer's contract).

Consistency: every shard reply carries the model ``version`` its rows
came from. A hot swap landing mid-fan-out can hand back a mixed set;
the router detects the mismatch and replays the whole fan-out
(serve.router.epoch_retries) until the versions agree — a scored batch
is always computed from ONE snapshot version, which rides back to the
caller.

Fault tolerance: shard RPCs ride stable per-connection sender ids with
monotone sequence numbers. A socket error inside the retry window
(WH_SERVE_RETRY_SEC) re-resolves the shard's uri (a respawned shard
re-registers with the scheduler; the resolver picks the new address
up), redials, and resends the SAME seq — the shard's reply cache
returns the original reply when the first send actually landed, so a
retried fetch can never straddle two versions. Busy bounces
(WH_NET_MAX_INFLIGHT) back off and resend on the same connection.
"""

from __future__ import annotations

import contextlib
import heapq
import socket as _socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

import wormhole_tpu.serving.fastpath as _fastpath
from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.runtime import overload as _overload
from wormhole_tpu.runtime import retry as _retrylib
from wormhole_tpu.runtime.net import (
    busy_backoff, connect_with_retry, recv_frame, send_frame,
)
from wormhole_tpu.utils.manifest import shard_range

_ROUTER_REQUESTS = _obs.REGISTRY.counter("serve.router.requests")
_ROUTER_RETRIES = _obs.REGISTRY.counter("serve.router.retries")
_EPOCH_RETRIES = _obs.REGISTRY.counter("serve.router.epoch_retries")
_FAILURES = _obs.REGISTRY.counter("serve.router.failures")
# same series the shard's pre-dispatch shed uses: "requests shed on an
# expired deadline", wherever in the stack the expiry was caught
_SHED_DEADLINE = _obs.REGISTRY.counter("serve.shed.deadline")
_LATENCY_S = _obs.REGISTRY.histogram("serve.latency_s")

# stage decomposition of one predict request (docs/serving.md): the
# sum of pack+fanout+sum+score p50s should explain the latency p50,
# and fanout further splits into wire vs shard queue/serve time via
# the queue_s/served_s fields fetch replies carry back
_STAGE_PACK_S = _obs.REGISTRY.histogram("serve.stage.pack_s")
_STAGE_FANOUT_S = _obs.REGISTRY.histogram("serve.stage.fanout_s")
_STAGE_WIRE_S = _obs.REGISTRY.histogram("serve.stage.wire_s")
_STAGE_QUEUE_S = _obs.REGISTRY.histogram("serve.stage.queue_s")
_STAGE_SCORE_S = _obs.REGISTRY.histogram("serve.stage.score_s")
_STAGE_SUM_S = _obs.REGISTRY.histogram("serve.stage.sum_s")
# score-mode fast path: per-request coalescer queue wait, the slowest
# shard's own kernel time per round (overlaps fanout, like wire/queue),
# and the micro-batcher round accounting
_STAGE_BATCH_WAIT_S = _obs.REGISTRY.histogram("serve.stage.batch_wait_s")
_STAGE_PARTIAL_S = _obs.REGISTRY.histogram("serve.stage.partial_s")
_BATCH_ROUNDS = _obs.REGISTRY.counter("serve.batch.rounds")
_BATCH_COALESCED = _obs.REGISTRY.counter("serve.batch.coalesced")
_BATCH_FLUSH_FULL = _obs.REGISTRY.counter("serve.batch.flush_full")
_BATCH_FLUSH_TIMEOUT = _obs.REGISTRY.counter("serve.batch.flush_timeout")
_BATCH_SIZE = _obs.REGISTRY.histogram("serve.batch.size")

_EPOCH_REPLAYS = 8  # fan-out replays before a mixed-version batch fails


class _HedgeTimer:
    """One long-lived scheduler thread multiplexing every pending hedge
    arm. ``threading.Timer`` spawns a THREAD per arm; at serving rates
    (2 fetches x hundreds of qps) that thread churn alone costs
    double-digit percent of capacity — measured 355 -> 301 qps on the
    serve lab's closed-loop probe. Here arming is a heap push; entries
    whose request completed first (``done`` set) are dropped at fire
    time, so there is no cancel path to race with."""

    def __init__(self):
        self._cond = threading.Condition()
        self._heap: list = []  # (fire_at, tiebreak, fire, done)
        self._n = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    #: batch scheduler wakeups: hedge delays are tail-scale (tens of
    #: ms), so a couple ms of firing slack is free — waking per entry
    #: at serving rates is not
    _GRANULARITY_S = 0.002

    def arm(self, delay_s: float, fire: Callable[[], None],
            done: threading.Event) -> None:
        at = time.monotonic() + delay_s
        with self._cond:
            if self._stop:
                return
            if self._thread is None:  # lazy: only hedging routers pay
                self._thread = threading.Thread(
                    target=self._loop, name="serve-hedge", daemon=True)
                self._thread.start()
            self._n += 1
            # only a new EARLIEST entry moves the scheduler's wake-up
            # time; notifying per arm would wake it at the full
            # request rate for nothing
            is_head = not self._heap or at < self._heap[0][0]
            heapq.heappush(self._heap, (at, self._n, fire, done))
            if is_head:
                self._cond.notify()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()

    def _loop(self) -> None:
        while True:
            due = []
            with self._cond:
                while not self._stop:
                    # purge entries whose request already completed —
                    # the common case, since only tail requests outlive
                    # their hedge delay
                    while self._heap and self._heap[0][3].is_set():
                        heapq.heappop(self._heap)
                    if not self._heap:
                        self._cond.wait()
                        continue
                    wait = self._heap[0][0] - time.monotonic()
                    if wait <= 0:
                        now = time.monotonic()
                        while self._heap and self._heap[0][0] <= now:
                            e = heapq.heappop(self._heap)
                            if not e[3].is_set():
                                due.append(e)
                        break
                    self._cond.wait(max(wait, self._GRANULARITY_S))
                if self._stop:
                    return
            for _, _, fire, done in due:
                if not done.is_set():
                    try:
                        fire()
                    except Exception:
                        pass  # e.g. pool shut down mid-close


class _Slot:
    """One pooled shard connection with a STABLE sender identity: the
    seq counter survives redials, so a retried frame after a reconnect
    reuses its seq and hits the shard's reply cache."""

    def __init__(self, sender: str):
        self.sender = sender
        self.seq = 0
        self.sock = None
        self.f = None

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
        self.sock = None
        self.f = None


class _BatchReq:
    """One predict request parked in the micro-batcher: its ScorePack,
    the caller's trace context and ambient deadline (batcher-thread
    rounds rebind both), and the result slots the round fills."""

    __slots__ = ("pack", "ctx", "dl", "t0", "t_enq", "done",
                 "scores", "version", "meta", "error")

    def __init__(self, pack, ctx, dl, t0):
        self.pack = pack
        self.ctx = ctx
        self.dl = dl            # absolute time.monotonic deadline | None
        self.t0 = t0            # pack start (end-to-end latency origin)
        self.t_enq = time.perf_counter()
        self.done = threading.Event()
        self.scores = None
        self.version = 0
        self.meta: dict = {}
        self.error: Optional[BaseException] = None


class _Batcher:
    """Dynamic micro-batcher: concurrent ``predict_block`` calls park
    here and one dedicated thread drains them into coalesced score
    rounds of at most WH_SERVE_BATCH_MAX members.

    With the default WH_SERVE_BATCH_WAIT_MS=0 there is no artificial
    linger — batching is *continuous*: while one round executes, new
    arrivals queue, and the next round takes them all. Under a closed
    loop the round size self-regulates to roughly the offered
    concurrency; an idle router serves singles at zero added latency.
    A positive linger holds a non-full round open for stragglers,
    flushing early when any member's deadline would otherwise expire
    mid-round — and is skipped entirely while degraded mode is active
    (admission's job is shedding load then, not shaping bursts)."""

    def __init__(self, router: "Router", max_batch: int, wait_s: float):
        self._router = router
        self._max = max(int(max_batch), 1)
        self._wait = max(float(wait_s), 0.0)
        self._cond = threading.Condition()
        self._q: List[_BatchReq] = []  # wormlint: guarded-by(self._cond)
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True)
        self._thread.start()

    def submit(self, req: _BatchReq):
        with self._cond:
            if self._stop:
                raise RuntimeError("router closed")
            self._q.append(req)
            self._cond.notify()
        req.done.wait()
        if req.error is not None:
            raise req.error
        return req.scores, req.version, req.meta

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()

    def _linger(self) -> None:
        """Hold a non-full round open up to the linger budget, clamped
        by the earliest member deadline. Two clock domains on purpose:
        the linger is perf_counter (like every stage time), deadlines
        are absolute time.monotonic — never mix them."""
        end = time.perf_counter() + self._wait
        while not self._stop and len(self._q) < self._max:
            wait = end - time.perf_counter()
            dls = [r.dl for r in self._q if r.dl is not None]
            if dls:
                wait = min(wait, min(dls) - time.monotonic())
            if wait <= 0:
                _BATCH_FLUSH_TIMEOUT.inc()
                return
            self._cond.wait(wait)
        if len(self._q) >= self._max:
            _BATCH_FLUSH_FULL.inc()

    def _loop(self) -> None:  # wormlint: thread-entry
        while True:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait()
                if not self._q and self._stop:
                    return
                if (self._wait > 0 and len(self._q) < self._max
                        and not self._router._degrade.active()):
                    self._linger()
                batch = self._q[: self._max]
                del self._q[: self._max]
            if batch:
                self._router._score_round(batch)


class Router:
    """Thread-safe fan-out/merge client over a serving shard group."""

    def __init__(self, uris: List[str], scorer, sender: str = "router",
                 retry_deadline: Optional[float] = None,
                 resolver: Optional[Callable[[], Optional[List[str]]]] = None,
                 connect_deadline: float = 10.0,
                 mode: Optional[str] = None):
        self.scorer = scorer
        self.sender = sender
        self.resolver = resolver
        self.retry_deadline = (float(knob_value("WH_SERVE_RETRY_SEC"))
                               if retry_deadline is None
                               else float(retry_deadline))
        self.connect_deadline = connect_deadline
        self._lock = threading.Lock()
        self._uris = list(uris)  # wormlint: guarded-by(self._lock)
        self.world = len(uris)
        self._free: Dict[int, list] = {r: [] for r in range(self.world)}
        # pooled (sock, file) pairs for hedge backups: a hedge must ride
        # a DIFFERENT connection than the primary it insures (the win
        # path severs the primary's socket), but dialing fresh per
        # hedge costs more than the duplicate fetch itself — dedup is
        # keyed on the frame's (sender, seq), not the connection
        self._hedge_free: Dict[int, list] = {
            r: [] for r in range(self.world)}
        self._slot_ids = 0  # wormlint: guarded-by(self._lock)
        self._pool = ThreadPoolExecutor(
            max_workers=max(8, 2 * self.world),
            thread_name_prefix="serve-router")
        # overload machinery: hedged fetches (WH_HEDGE — None when off,
        # so the hot path pays one attribute check) and degraded-mode
        # serving under sustained SLO burn (WH_DEGRADE)
        self._hedge = _overload.hedge_tracker()
        self._hedge_timer = _HedgeTimer()
        self._degrade = _overload.DegradeController()
        # client-edge admission (WH_ADMIT_AIMD): overload queues form
        # HERE, ahead of any shard gate — bounce at entry so admitted
        # requests see bounded queueing instead of everyone expiring
        # mid-queue (see overload.router_gate)
        self._gate = _overload.router_gate()
        # one hello up front: table row counts drive the key split, and
        # a shard configured for a different world would shard-range
        # differently than this router splits
        # per-row-count shard boundary vectors for _split: the even
        # shard_range split depends only on (rows, world), so the
        # per-request python loop of searchsorted pairs collapses to
        # one cached boundary array + one vectorized searchsorted
        self._split_edges: Dict[int, np.ndarray] = {}
        # opt-in reply quantization (WH_SERVE_WIRE): stamped on every
        # fetch/score request header; a stamped shard bf16-truncates
        # its reply floats at send time, halving reply bytes under the
        # documented ulp contract (docs/serving.md). Default raw keeps
        # serving bit-identical to the trainer's own predict. An old
        # shard ignores the stamp and replies raw — the decode path is
        # per-array self-describing, so mixed groups still work.
        # Validated BEFORE dialing so a bad knob fails fast.
        sw = str(knob_value("WH_SERVE_WIRE") or "").strip().lower()
        if sw in ("", "raw", "off", "0"):
            sw = ""
        elif sw != "bf16":
            raise ValueError(
                f"unknown WH_SERVE_WIRE {sw!r} (expected 'raw' or 'bf16')")
        self.serve_wire = sw
        hello = self._rpc(0, {"op": "hello"}, {})[0]
        if int(hello["world"]) != self.world:
            raise RuntimeError(
                f"shard 0 serves world={hello['world']} but the router "
                f"was given {self.world} uris")
        self.full_rows = {k: int(v)
                          for k, v in hello["full_rows"].items()}
        # serving dataflow (WH_SERVE_MODE): 'score' fans partial-margin
        # work out to the shards through the micro-batcher; 'fetch' is
        # the row-shipping fallback; 'auto' takes the fast path when
        # the scorer implements a shard-local kernel
        mode = (str(knob_value("WH_SERVE_MODE"))
                if mode is None else str(mode))
        if mode == "auto":
            mode = ("score" if getattr(scorer, "score_kind", None)
                    else "fetch")
        if mode not in ("fetch", "score"):
            raise ValueError(f"unknown WH_SERVE_MODE {mode!r}")
        self.mode = mode
        self._batcher: Optional[_Batcher] = None
        if mode == "score":
            key_table = scorer.tables[0]
            self._score_edges = _fastpath.shard_edges(
                self.full_rows[key_table], self.world)
            self._batcher = _Batcher(
                self, int(knob_value("WH_SERVE_BATCH_MAX")),
                float(knob_value("WH_SERVE_BATCH_WAIT_MS")) / 1e3)

    @staticmethod
    def from_scheduler(client, scorer, world: int,
                       timeout: float = 60.0, **kw) -> "Router":
        """Build against a scheduler's registered ``--serve`` group; the
        resolver keeps following re-registrations (shard respawns)."""

        def resolve() -> Optional[List[str]]:
            try:
                got = client.call(op="serve_nodes", world=world)
                return got["uris"] if got.get("ready") else None
            except Exception:
                return None

        deadline = time.monotonic() + timeout
        uris = resolve()
        while not uris:
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"serve group never fully registered ({world} shards)")
            time.sleep(0.2)
            uris = resolve()
        return Router(uris, scorer, resolver=resolve, **kw)

    # -- connection pool ----------------------------------------------------
    def _acquire(self, r: int) -> _Slot:
        with self._lock:
            if self._free[r]:
                return self._free[r].pop()
            self._slot_ids += 1
            return _Slot(f"{self.sender}:{r}:{self._slot_ids}")

    def _release(self, r: int, slot: _Slot) -> None:
        with self._lock:
            self._free[r].append(slot)

    def _dial(self, slot: _Slot, r: int) -> None:
        # short per-attempt deadline: a dead shard's old port must fail
        # fast so the outer retry loop re-consults the resolver (which
        # is where a respawned shard's NEW uri shows up) instead of
        # burning the whole budget dialing a port nobody listens on
        with self._lock:
            uri = self._uris[r]
        host, port = uri.rsplit(":", 1)
        slot.sock = connect_with_retry((host, int(port)),
                                       min(self.connect_deadline, 1.0))
        slot.f = slot.sock.makefile("rwb")

    def _refresh_uris(self) -> None:
        if self.resolver is None:
            return
        got = self.resolver()
        if got and len(got) == self.world:
            with self._lock:
                self._uris = list(got)

    # -- RPC ----------------------------------------------------------------
    def _send_recv(self, f, r: int, hdr: dict,
                   arrays: Dict[str, np.ndarray],
                   budget: Optional[_retrylib.RetryBudget] = None,
                   abandon_busy: bool = False) -> tuple[dict, dict]:
        """One send + reply on an established connection, resending the
        same seq-stamped frame through busy bounces. A hedge passes
        `abandon_busy`: a busy shard must not absorb EXTRA (backup)
        load, so the hedge gives up instead of backing off."""
        send_frame(f, hdr, arrays)
        while True:
            got = recv_frame(f)
            if got is None:
                raise ConnectionResetError(
                    f"serve shard {r} closed the connection")
            reply, rarr, _ = got
            if reply.get("busy") and abandon_busy:
                raise _HedgeAbandoned()
            if busy_backoff(reply, budget):
                # bounced before dispatch: resend the same seq-stamped
                # frame after the load-scaled, jittered hint
                send_frame(f, hdr, arrays)
                continue
            return reply, rarr

    def _attempt(self, slot: _Slot, r: int, hdr: dict,
                 arrays: Dict[str, np.ndarray],
                 budget: _retrylib.RetryBudget) -> tuple[dict, dict]:
        """One connected attempt, hedged for fetches when WH_HEDGE is
        on: the hedge scheduler fires after the rolling-quantile delay
        and — budget permitting — sends the SAME (sender, seq) frame on
        a fresh ephemeral connection. The shard's per-sender reply cache makes
        the duplicate exactly-once (whichever copy dispatches second is
        answered from the cache with the ORIGINAL bytes), so the hedge
        can never double-score. If the backup answers first it severs
        the pooled socket to unblock the primary's recv, and the
        primary's error path returns the backup's reply."""
        hedge = (self._hedge if hdr.get("op") in ("fetch", "score")
                 else None)
        delay = hedge.delay_s() if hedge is not None else None
        if delay is None:
            return self._send_recv(slot.f, r, hdr, arrays, budget)
        done = threading.Event()
        lock = threading.Lock()
        state: dict = {}

        def fire():  # wormlint: thread-entry
            if done.is_set() or not hedge.try_issue():
                return
            conn = None
            ok = False
            try:
                with self._lock:
                    uri = self._uris[r]
                    if self._hedge_free[r]:
                        conn = self._hedge_free[r].pop()
                if conn is None:
                    host, port = uri.rsplit(":", 1)
                    sock = connect_with_retry((host, int(port)), 1.0)
                    conn = (sock, sock.makefile("rwb"))
                got = self._send_recv(conn[1], r, hdr,
                                      arrays, abandon_busy=True)
                ok = True
                with lock:
                    if not done.is_set():
                        state["reply"] = got
                        # sever the pooled socket: the primary's
                        # blocked recv turns into the error path,
                        # which hands back this reply
                        if slot.sock is not None:
                            try:
                                slot.sock.shutdown(_socket.SHUT_RDWR)
                            except OSError:
                                pass
                        slot.close()
            except Exception:
                pass  # best-effort tail insurance; the primary decides
            finally:
                if conn is not None:
                    if ok:
                        with self._lock:
                            self._hedge_free[r].append(conn)
                    else:
                        try:
                            conn[0].close()
                        except OSError:
                            pass

        # the RPC itself runs on the router pool so a slow hedge never
        # delays OTHER due hedges on the scheduler thread; stale
        # entries (done already set) are dropped at fire time
        self._hedge_timer.arm(
            delay, lambda: self._pool.submit(fire), done)
        try:
            got = self._send_recv(slot.f, r, hdr, arrays, budget)
            with lock:
                done.set()
            return got
        except (OSError, ConnectionError):
            with lock:
                done.set()
                if "reply" in state:
                    hedge.won()
                    return state["reply"]
            raise

    def _rpc(self, r: int, header: dict,
             arrays: Dict[str, np.ndarray]) -> tuple[dict, dict]:
        slot = self._acquire(r)
        try:
            hdr = dict(header, sender=slot.sender, seq=slot.seq)
            slot.seq += 1
            budget = _retrylib.RetryBudget(max(self.retry_deadline, 0.0),
                                           base_s=0.1, op="serve.rpc")
            # the budget's window — tightened by any ambient request
            # deadline — rides every frame sent below as its `dl`
            with budget.bind():
                while True:
                    try:
                        if slot.f is None:
                            self._dial(slot, r)
                        t_req = time.perf_counter()
                        reply, rarr = self._attempt(slot, r, hdr, arrays,
                                                    budget)
                        if "error" in reply:
                            raise RuntimeError(
                                f"serve shard {r}: {reply['error']}")
                        if self._hedge is not None \
                                and hdr.get("op") in ("fetch", "score"):
                            self._hedge.observe(
                                time.perf_counter() - t_req)
                        budget.succeeded()
                        return reply, rarr
                    except (OSError, ConnectionError) as e:
                        slot.close()
                        if budget.expired:
                            budget.give_up(e)
                        _ROUTER_RETRIES.inc()
                        # a respawned shard re-registered under a new
                        # uri; the resolver hands it to the next dial
                        self._refresh_uris()
                        budget.sleep()
        finally:
            self._release(r, slot)

    # -- fan-out ------------------------------------------------------------
    def _split(self, keys: np.ndarray, rows: int) -> List[slice]:
        """Per-shard contiguous slices of a sorted key vector under the
        even split (keys are sorted, so each shard's keys are one run).
        The shard boundaries are a pure function of (rows, world) —
        cached, so each request pays ONE vectorized searchsorted."""
        edges = self._split_edges.get(rows)
        if edges is None:
            edges = np.asarray(
                [shard_range(rows, r, self.world)[0]
                 for r in range(self.world)] + [rows], np.int64)
            self._split_edges[rows] = edges
        cuts = np.searchsorted(keys, edges)
        return [slice(int(cuts[r]), int(cuts[r + 1]))
                for r in range(self.world)]

    def _rpc_traced(self, ctx, dl, r: int, header: dict,
                    arrays: Dict[str, np.ndarray]) -> tuple[dict, dict]:
        """Pool-thread RPC entry: rebind the request's trace context
        AND its deadline (executor threads don't inherit thread-locals)
        so the frame carries both over the wire and the shard's span
        links back."""
        with _overload.bind(dl):
            if ctx is None:
                return self._rpc(r, header, arrays)
            with _trace.bind(ctx):
                with _trace.request_span(
                        f"serve.rpc.{header.get('op', 'fetch')}",
                        cat="serve", shard=r):
                    return self._rpc(r, header, arrays)

    def _fanout(self, packed) -> tuple[list, list, int]:
        """One fetch round: returns (jobs, replies, model version) or
        raises on a mixed-version set (caller replays)."""
        tables = list(self.scorer.tables)
        splits = {t: self._split(packed.keys[t], self.full_rows[t])
                  for t in tables}
        jobs = []  # (rank, tables present, key arrays)
        for r in range(self.world):
            present = [t for t in tables
                       if splits[t][r].stop > splits[t][r].start]
            if not present:
                continue
            arrays = {f"k:{t}": packed.keys[t][splits[t][r]]
                      for t in present}
            jobs.append((r, present, arrays))
        ctx = _trace.current_ctx()
        dl = _overload.current()
        base = {"op": "fetch"}
        if self.serve_wire:
            base["wire"] = self.serve_wire
        futs = [self._pool.submit(
            self._rpc_traced, ctx, dl, r,
            dict(base, tables=present), arrays)
            for r, present, arrays in jobs]
        got = [f.result() for f in futs]
        versions = {int(reply["version"]) for reply, _ in got}
        if len(versions) > 1:
            raise _MixedVersions(versions, jobs, got)
        return jobs, got, versions.pop()

    def _merge(self, jobs: list, got: list) -> Dict[str, np.ndarray]:
        """Reassemble per-shard row pieces into each table's compact
        rows (shard order == key order, so concatenation suffices)."""
        pieces: Dict[str, list] = {t: [] for t in self.scorer.tables}
        for (_, present, _), (_, rarr) in zip(jobs, got):
            for t in present:
                pieces[t].append(np.asarray(rarr[f"r:{t}"]))
        return {t: (p[0] if len(p) == 1 else np.concatenate(p))
                for t, p in pieces.items()}

    def predict_block(self, blk) -> tuple[np.ndarray, int]:
        """Score one RowBlock; returns (scores[:size], model version).
        Outside degraded mode the scores are guaranteed to come from
        ONE snapshot version (use `predict_block_ex` to see the
        degraded stamp)."""
        scores, version, _ = self.predict_block_ex(blk)
        return scores, version

    def predict_block_ex(self, blk) -> tuple[np.ndarray, int, dict]:
        """`predict_block` plus the reply metadata: ``degraded`` (1 =
        bounded-staleness mixed-version scores served under sustained
        SLO burn, stamped per the overload contract) and, when
        degraded, the ``versions`` the rows spanned."""
        ctx = _trace.start_request()
        # default per-request deadline (WH_DEADLINE_MS): bound only
        # when the caller didn't bind one — an explicit caller budget
        # always wins
        dl_ms = float(knob_value("WH_DEADLINE_MS"))
        dl_cm = (_overload.bind_in(dl_ms / 1e3)
                 if dl_ms > 0 and _overload.current() is None
                 else _overload.bind(None))
        with dl_cm, _trace.bind(ctx):
            # already-expired budget: shed before paying for pack or
            # fan-out — the shards would only bounce it at dispatch
            rem = _overload.remaining()
            if (rem is not None and rem <= 0
                    and knob_value("WH_DEADLINE_SHED")):
                _SHED_DEADLINE.inc()
                raise _overload.Shed(
                    "deadline expired before router fan-out")
            gate = self._gate
            if gate is not None and not gate.try_enter("predict"):
                raise _overload.Shed(
                    f"router admission: saturated "
                    f"(limit {gate.limit}, {gate.inflight} in flight)")
            t0 = time.perf_counter()
            try:
                with _trace.request_span("serve.request", cat="serve"):
                    if self._batcher is not None:
                        return self._predict_score(blk)
                    return self._predict_block(blk)
            finally:
                if gate is not None:
                    gate.leave("predict", time.perf_counter() - t0)

    def _predict_block(self, blk) -> tuple[np.ndarray, int, dict]:
        t0 = time.perf_counter()
        packed = self.scorer.pack(blk)
        _STAGE_PACK_S.observe(time.perf_counter() - t0)
        meta = {"degraded": 0}
        try:
            # fan-out is timed from the FIRST attempt: a hot swap
            # landing mid-round costs a full replay plus backoff, and
            # that burned budget must land in a stage or the
            # explained_frac identity (sum of stage means == latency
            # mean) breaks for every request in a swap window
            tf0 = time.perf_counter()
            for attempt in range(_EPOCH_REPLAYS):
                try:
                    with _trace.request_span("serve.stage.fanout",
                                             cat="serve"):
                        jobs, got, version = self._fanout(packed)
                except _MixedVersions as mv:
                    _EPOCH_RETRIES.inc()
                    # replays burn latency budget; they feed the burn
                    # window that arms degraded mode
                    self._degrade.observe_replay()
                    if self._degrade.active():
                        # degraded mode: stop paying for strict version
                        # consistency — serve the mixed-version rows we
                        # already hold, stamped so the caller knows
                        jobs, got = mv.jobs, mv.got
                        version = max(mv.versions)
                        meta = {"degraded": 1,
                                "versions": sorted(mv.versions)}
                        self._degrade.served_degraded()
                    else:
                        # a hot swap landed mid-fan-out; replay against
                        # the (now uniform) new version. Shard watchers
                        # can be skewed by up to their poll interval,
                        # so back off exponentially until the replays
                        # span at least one full WH_SERVE_POLL_SEC —
                        # immediate replays would all burn inside the
                        # skew window
                        poll = float(knob_value("WH_SERVE_POLL_SEC"))
                        time.sleep(min(0.01 * (2 ** attempt),
                                       max(poll, 0.01)))
                        continue
                fanout = time.perf_counter() - tf0
                # wire share = fan-out wall minus the slowest shard's
                # own (queue + serve) time, which replies carry back
                slowest = max(
                    (float(r.get("served_s", 0.0))
                     + float(r.get("queue_s", 0.0)) for r, _ in got),
                    default=0.0)
                queued = max((float(r.get("queue_s", 0.0))
                              for r, _ in got), default=0.0)
                _STAGE_FANOUT_S.observe(fanout)
                _STAGE_WIRE_S.observe(max(fanout - slowest, 0.0))
                _STAGE_QUEUE_S.observe(queued)
                tm0 = time.perf_counter()
                with _trace.request_span("serve.stage.sum", cat="serve"):
                    rows = self._merge(jobs, got)
                _STAGE_SUM_S.observe(time.perf_counter() - tm0)
                ts0 = time.perf_counter()
                scores = self.scorer.score(packed, rows)
                _STAGE_SCORE_S.observe(time.perf_counter() - ts0)
                _ROUTER_REQUESTS.inc()
                lat = time.perf_counter() - t0
                _LATENCY_S.observe(lat)
                self._degrade.observe(lat)
                return scores, version, meta
            raise RuntimeError(
                f"shard versions never agreed after {_EPOCH_REPLAYS} "
                "fan-out replays")
        except Exception:
            _FAILURES.inc()
            raise

    # -- score fast path ----------------------------------------------------
    def _predict_score(self, blk) -> tuple[np.ndarray, int, dict]:
        """Score-mode entry: pack on the caller thread (cheap — live
        COO entries only), park in the micro-batcher, and block until
        the round that carried this request completes."""
        t0 = time.perf_counter()
        try:
            pack = self.scorer.pack_score(blk)
        except Exception:
            _FAILURES.inc()  # round failures are counted by the round
            raise
        _STAGE_PACK_S.observe(time.perf_counter() - t0)
        req = _BatchReq(pack, _trace.current_ctx(), _overload.current(),
                        t0)
        return self._batcher.submit(req)

    def _score_fanout(self, pack) -> tuple[list, list, int]:
        """One score round's fan-out: partition the round pack's
        entries by owning shard, issue one ``score`` RPC per non-empty
        shard, and check the replies came from ONE model version.
        Returns (jobs, replies, version); jobs carry the permutation
        needed to scatter the partial products back."""
        order, counts = _fastpath.partition(pack.idx, self._score_edges)
        if order is None:
            si, sv, ss = pack.idx, pack.val, pack.seg
        else:
            si, sv, ss = pack.idx[order], pack.val[order], pack.seg[order]
        starts = np.concatenate(([0], np.cumsum(counts)))
        hdr = {"op": "score", "kind": self.scorer.score_kind,
               "rows": pack.rows, **self.scorer.score_header()}
        if self.serve_wire:
            hdr["wire"] = self.serve_wire
        difacto = self.scorer.score_kind == "difacto"
        jobs = []  # (rank, payload arrays)
        for r in range(self.world):
            a, b = int(starts[r]), int(starts[r + 1])
            if a == b:
                continue
            arrays = {"i": si[a:b], "v": sv[a:b]}
            if difacto:
                arrays["s"] = ss[a:b]
            jobs.append((r, arrays))
        if not jobs:
            # a zero-nnz round still needs a version to stamp: shard 0
            # scores an empty payload (all folds come back zero)
            jobs = [(0, {"i": si[:0], "v": sv[:0]}
                     if not difacto else
                     {"i": si[:0], "v": sv[:0], "s": ss[:0]})]
        ctx = _trace.current_ctx()
        dl = _overload.current()
        futs = [self._pool.submit(self._rpc_traced, ctx, dl, r,
                                  dict(hdr), arrays)
                for r, arrays in jobs]
        got = [f.result() for f in futs]
        versions = {int(reply["version"]) for reply, _ in got}
        if len(versions) > 1:
            raise _MixedVersions(versions, (jobs, order), got)
        return (jobs, order), got, versions.pop()

    def _score_assemble(self, pack, cuts, jobs_order, got):
        """Scatter the per-shard partial products back into original
        nonzero order, fold per row, and slice per micro-batch member.
        The fold is the bitwise mirror of the trainer's segment_sum
        (serving/fastpath.py docstring)."""
        jobs, order = jobs_order
        parts = [np.asarray(rarr["p"]) for _, rarr in got]
        prod = _fastpath.restore_order(len(pack.idx), order, parts)
        extras = {}
        if self.scorer.score_kind == "difacto":
            # cross-shard reassociation point of the documented ulp
            # contract: per-shard [rows, k] partials summed rank-major
            xv = np.asarray(got[0][1]["xv"]).copy()
            x2 = np.asarray(got[0][1]["x2"]).copy()
            for _, rarr in got[1:]:
                xv += np.asarray(rarr["xv"])
                x2 += np.asarray(rarr["x2"])
            extras = {"xv": xv, "x2": x2}
        scores = self.scorer.finalize(pack, prod, extras)
        return [scores[cuts[m]: cuts[m + 1]]
                for m in range(len(cuts) - 1)]

    def _score_round(self, batch: List[_BatchReq]) -> None:
        """Execute one coalesced fan-out on the batcher thread and
        complete every member. Runs the same replay/degrade loop as
        the fetch path: a hot swap landing mid-fan-out replays the
        round; under sustained burn the mixed partials are served
        stamped degraded (summing partials across versions is exactly
        the bounded-staleness contract mixed fetched rows have)."""
        now = time.perf_counter()
        _BATCH_ROUNDS.inc()
        _BATCH_SIZE.observe(len(batch))
        if len(batch) > 1:
            _BATCH_COALESCED.inc(len(batch) - 1)
        for m in batch:
            _STAGE_BATCH_WAIT_S.observe(now - m.t_enq)
        dls = [m.dl for m in batch]
        dl = None if any(d is None for d in dls) else max(dls)
        ctx = next((m.ctx for m in batch if m.ctx is not None), None)
        try:
            with _overload.bind(dl), (
                    _trace.bind(ctx) if ctx is not None
                    else contextlib.nullcontext()):
                self._score_round_bound(batch)
        except BaseException as e:
            for m in batch:
                _FAILURES.inc()
                m.error = e
                m.done.set()

    def _score_round_bound(self, batch) -> None:
        # the fanout stage covers everything from round assembly to
        # the last reply of the attempt that SUCCEEDED: concat,
        # partition, the RPCs, and any mixed-version replays plus
        # their backoff. All of it is real per-member wall time, and
        # an unattributed stage is exactly what the explained_frac
        # gate exists to catch
        tf0 = time.perf_counter()
        pack, cuts = _fastpath.concat_packs([m.pack for m in batch])
        for attempt in range(_EPOCH_REPLAYS):
            meta = {"degraded": 0}
            try:
                with _trace.request_span("serve.stage.fanout",
                                         cat="serve"):
                    jobs_order, got, version = self._score_fanout(pack)
            except _MixedVersions as mv:
                _EPOCH_RETRIES.inc()
                self._degrade.observe_replay()
                if self._degrade.active():
                    jobs_order, got = mv.jobs, mv.got
                    version = max(mv.versions)
                    meta = {"degraded": 1,
                            "versions": sorted(mv.versions)}
                    self._degrade.served_degraded()
                else:
                    poll = float(knob_value("WH_SERVE_POLL_SEC"))
                    time.sleep(min(0.01 * (2 ** attempt),
                                   max(poll, 0.01)))
                    continue
            fanout = time.perf_counter() - tf0
            slowest = max(
                (float(r.get("served_s", 0.0))
                 + float(r.get("queue_s", 0.0)) for r, _ in got),
                default=0.0)
            queued = max((float(r.get("queue_s", 0.0))
                          for r, _ in got), default=0.0)
            partial = max((float(r.get("served_s", 0.0))
                           for r, _ in got), default=0.0)
            # stage histograms are per-REQUEST distributions, like
            # serve.latency_s: a round's stage time is observed once
            # per member. Round-weighted means would understate the
            # member-weighted time whenever big rounds are slow rounds
            # (they are — queue buildup grows both together), breaking
            # the explained_frac identity
            wire = max(fanout - slowest, 0.0)
            for _ in batch:
                _STAGE_FANOUT_S.observe(fanout)
                _STAGE_WIRE_S.observe(wire)
                _STAGE_QUEUE_S.observe(queued)
                _STAGE_PARTIAL_S.observe(partial)
            tm0 = time.perf_counter()
            with _trace.request_span("serve.stage.sum", cat="serve"):
                per_member = self._score_assemble(pack, cuts,
                                                  jobs_order, got)
            dt_sum = time.perf_counter() - tm0
            for _ in batch:
                _STAGE_SUM_S.observe(dt_sum)
            now = time.perf_counter()
            for m, scores in zip(batch, per_member):
                _ROUTER_REQUESTS.inc()
                lat = now - m.t0
                _LATENCY_S.observe(lat)
                self._degrade.observe(lat)
                m.scores = scores
                m.version = version
                m.meta = meta
                m.done.set()
            return
        raise RuntimeError(
            f"shard versions never agreed after {_EPOCH_REPLAYS} "
            "fan-out replays")

    def close(self) -> None:
        if self._batcher is not None:
            self._batcher.close()
        self._hedge_timer.close()
        self._pool.shutdown(wait=False)
        with self._lock:
            slots = [s for free in self._free.values() for s in free]
            for free in self._free.values():
                free.clear()
            hconns = [c for free in self._hedge_free.values()
                      for c in free]
            for free in self._hedge_free.values():
                free.clear()
        for s in slots:
            s.close()
        for sock, _ in hconns:
            try:
                sock.close()
            except OSError:
                pass


class _MixedVersions(Exception):
    """Fan-out replies spanned a hot swap. Internal replay signal that
    carries the mixed payload, so degraded mode can serve it as a
    bounded-staleness reply instead of discarding the round."""

    def __init__(self, versions: set, jobs: list, got: list):
        super().__init__(f"mixed shard versions {sorted(versions)}")
        self.versions = versions
        self.jobs = jobs
        self.got = got


class _HedgeAbandoned(Exception):
    """A hedge met a busy shard and gave up (a backup request must
    never add load a primary would have backed off from)."""
