"""Online serving tier: predict-as-a-service over training snapshots.

Shards (``ModelServer``) each hold one ``shard_range`` slice of a
``write_snapshot_set``/ps_server snapshot set, hot-swap to newer
versions the moment the manifest says they are complete, and answer
row-fetch RPCs.  A ``Router`` fans a batch's unique keys out over the
shards and scores on the reassembled compact tables with a model
scorer — bit-identical to the trainer's own predict path.
"""

from wormhole_tpu.serving.router import Router
from wormhole_tpu.serving.scoring import (
    DifactoScorer, LinearScorer, PackedBatch,
)
from wormhole_tpu.serving.server import (
    ModelServer, ServingModel, load_with_retry, run_serve_role,
)

__all__ = [
    "DifactoScorer",
    "LinearScorer",
    "ModelServer",
    "PackedBatch",
    "Router",
    "ServingModel",
    "load_with_retry",
    "run_serve_role",
]
