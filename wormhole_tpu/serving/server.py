"""Serving shard: a read-only key-range slice of the model, hot-swapped.

A ``ModelServer`` is the online half of the PS plane: it loads the
``<base>_part-<rank>.npz`` snapshot set a training job's
``ps_server.start_snapshots`` writes (discovered through the
``<base>_MANIFEST.json`` of utils/manifest.py, so a set mid-replacement
can never be silently mixed), re-shards the FULL tables over the
``--serve`` world with the same even ``shard_range`` split the trainers
use, and answers row-fetch RPCs over the runtime/net.py frame protocol.
The router (serving/router.py) fans a predict batch's unique keys out
across the shards and scores on the gathered rows — so the serving
world size is independent of the training ``-s`` world.

Hot swap: a watcher thread polls the manifest every WH_SERVE_POLL_SEC.
When the version grows it loads the new set into a STANDBY model object
off the request path, then flips the active pointer under a lock the
dispatch path holds only for the pointer read — the request-visible
stall is the pointer swap, not the load (serve.swap_stall_s measures
it). In-flight requests keep the old object alive and finish on the
version they started with; every reply carries its model ``version`` so
the router can detect (and re-fetch across) a mid-batch flip.

Retries are exactly-once in the reply sense: fetches are seq-stamped
per sender and the last reply per sender is cached, so a retried frame
(after a busy bounce or a socket error) returns the ORIGINAL reply —
same rows, same version — instead of re-reading possibly newer state.

Wire codec: a fetch/score request stamped ``wire=bf16`` (router knob
WH_SERVE_WIRE) has its reply floats bf16-truncated at send time —
half the reply bytes under the ulp contract of docs/distributed.md.
The reply cache stores raw arrays and the truncation is deterministic,
so duplicates stay bit-identical on the wire; the default (no stamp)
keeps serving byte-for-byte identical to the trainer's own predict.
"""

from __future__ import annotations

import os
import socket
import socketserver
import threading
import time
from typing import Dict, Optional

import numpy as np

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.obs import pyprof as _pyprof
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.runtime import faults
from wormhole_tpu.runtime import overload as _overload
from wormhole_tpu.runtime.net import busy_reply, recv_frame, send_frame
from wormhole_tpu.serving.fastpath import shard_score as _shard_score
from wormhole_tpu.utils import manifest as _manifest

_REQUESTS = _obs.REGISTRY.counter("serve.requests")
_ROWS = _obs.REGISTRY.counter("serve.rows")
_SWAPS = _obs.REGISTRY.counter("serve.swaps")
_DEDUP_HITS = _obs.REGISTRY.counter("serve.dedup_hits")
_MODEL_EPOCH = _obs.REGISTRY.gauge("serve.model_epoch")
_SWAP_STALL_S = _obs.REGISTRY.histogram("serve.swap_stall_s")
_SHED_DEADLINE = _obs.REGISTRY.counter("serve.shed.deadline")
_SHED_BUSY = _obs.REGISTRY.counter("serve.shed.busy")

_TORN_RETRIES = 3


class ServingModel:
    """One shard's slice of every table at ONE manifest version —
    immutable once built, so requests scoring against it mid-swap need
    no locks. Rows are addressed by GLOBAL row id; the slice covers
    ``shard_range(full_rows[t], rank, world)`` of each table."""

    def __init__(self, base: str, rank: int, world: int,
                 man: Optional[dict] = None):
        man = man if man is not None else _manifest.read_manifest(base)
        if not _manifest.complete(man):
            raise FileNotFoundError(
                f"no complete snapshot manifest at "
                f"{_manifest.manifest_path(base)}")
        self.full_rows = {k: int(v)
                          for k, v in man.get("full_rows", {}).items()}
        self.ranges = {t: _manifest.shard_range(rows, rank, world)
                       for t, rows in self.full_rows.items()}
        self.tables, meta = _manifest.load_slices(base, self.ranges, man)
        self.version = int(meta["version"])
        self.clock = int(meta["clock"])
        self.rank = rank
        self.world = world
        self._base = base
        self._man = man
        # full-table replicas for the score fast path (e.g. difacto's
        # V: hashed mod vb, so a w-range partition scatters its rows
        # across every shard) — loaded lazily on the first score that
        # names the table, then eagerly on standby models off-path
        self._replicated: Dict[str, np.ndarray] = {}
        self._rep_lock = threading.Lock()

    def replicated(self, table: str) -> np.ndarray:
        """The FULL ``table`` at this model's version (not just this
        shard's slice). Torn reads are retried only while the on-disk
        manifest still names this version; once a newer set is
        committed the raise is correct — the watcher's swap is already
        in flight and the router replays against it."""
        got = self._replicated.get(table)
        if got is not None:
            return got
        with self._rep_lock:
            got = self._replicated.get(table)
            if got is not None:
                return got
            rng = {table: (0, self.full_rows[table])}
            for _ in range(_TORN_RETRIES):
                try:
                    tables, _ = _manifest.load_slices(
                        self._base, rng, self._man)
                    break
                except _manifest.TornSnapshot:
                    man = _manifest.read_manifest(self._base)
                    if int(man.get("version", -1)) != self.version:
                        raise
                    time.sleep(0.02)
            else:
                tables, _ = _manifest.load_slices(
                    self._base, rng, self._man)
            self._replicated[table] = tables[table]
            return tables[table]

    def fetch(self, table: str, keys: np.ndarray) -> np.ndarray:
        """Rows at GLOBAL ids ``keys`` (must fall in this shard's
        range — the router's split guarantees it)."""
        lo, hi = self.ranges[table]
        keys = np.asarray(keys, np.int64)
        if len(keys) and (keys[0] < lo or keys[-1] >= hi):
            raise KeyError(
                f"keys outside shard range [{lo}, {hi}) of {table!r}")
        return self.tables[table][keys - lo]


def load_with_retry(base: str, rank: int, world: int,
                    deadline_s: float = 0.0) -> ServingModel:
    """Build a ServingModel, retrying torn reads (a part replaced
    between the manifest and part reads) and — with a deadline —
    waiting for the FIRST complete manifest to appear (a serving shard
    launched alongside the trainer starts before any snapshot exists)."""
    deadline = time.monotonic() + deadline_s
    while True:
        torn: Optional[Exception] = None
        for _ in range(_TORN_RETRIES):
            try:
                return ServingModel(base, rank, world)
            except _manifest.TornSnapshot as e:
                torn = e  # fresh manifest names the replacement files
            except FileNotFoundError:
                torn = None
                break
        if torn is not None:
            raise torn
        if time.monotonic() >= deadline:
            raise FileNotFoundError(
                f"no complete snapshot manifest at "
                f"{_manifest.manifest_path(base)} after "
                f"{deadline_s:.0f}s")
        time.sleep(0.2)


class _ServeHandler(socketserver.StreamRequestHandler):
    def handle(self):
        self.connection.setsockopt(socket.IPPROTO_TCP,
                                   socket.TCP_NODELAY, 1)
        srv = self.server.model_server  # type: ignore
        with srv._conns_lock:
            srv._conns.add(self.connection)
        try:
            self._serve(srv)
        except (OSError, ConnectionError):
            # a peer that vanished mid-frame (or a router that severed
            # this socket after a hedge win) is an ordinary disconnect,
            # not a handler error worth a traceback
            pass
        finally:
            with srv._conns_lock:
                srv._conns.discard(self.connection)

    def _serve(self, srv: "ModelServer"):
        while True:
            got = recv_frame(self.rfile)
            if got is None:
                return
            header, arrays, _ = got
            t_in = time.perf_counter()
            op = header.get("op")
            # a frame whose propagated deadline expired in transit gets
            # a shed reply, not a handler: nobody is waiting for the
            # result, and under overload every shed admits a request
            # someone IS still waiting for
            if _overload.should_shed(header):
                _SHED_DEADLINE.inc()
                send_frame(self.wfile, dict(_overload.shed_reply(header),
                                            version=srv.version))
                continue
            # admission gate (fixed WH_NET_MAX_INFLIGHT or AIMD), same
            # contract as the PS shards: a bounced frame was never
            # dispatched, so the client resends the SAME seq and the
            # reply cache keeps the retry exactly-once
            if not srv._gate.try_enter(op):
                _SHED_BUSY.inc()
                send_frame(self.wfile,
                           dict(busy_reply(srv._gate.busy_hint_ms()),
                                version=srv.version))
                continue
            try:
                # chaos hook: a serve shard sends no request frames of
                # its own, so the net-fault send hook never sees its
                # ops — arm them at dispatch instead. net:slow@fetch
                # models a slow shard; the sleep lands inside the gate
                # so AIMD and the SLO burn see the degraded service time
                if faults.ACTIVE is not None:
                    faults.ACTIVE.frame(op)
                # adopt the trace context a sampled request carried, so
                # this shard's spans stitch under the router's fan-out —
                # and the request's remaining deadline, so downstream
                # work this handler does inherits the budget
                with _trace.bind_wire(header), \
                        _overload.bind(_overload.header_deadline(header)):
                    resp_header, resp_arrays = srv._dispatch(
                        header, arrays, t_in)
            finally:
                srv._gate.leave(op, time.perf_counter() - t_in)
            # opt-in serving wire codec (WH_SERVE_WIRE on the router):
            # a fetch/score request stamped wire=bf16 gets its reply
            # floats bf16-truncated AT SEND TIME. The reply cache keeps
            # RAW arrays, so a retried or hedged duplicate re-encodes
            # to the exact same bytes (RNE truncation is deterministic)
            # — exactly-once still means bit-identical duplicates.
            fb = (2 if (header.get("wire") == "bf16"
                        and op in ("fetch", "score")
                        and "error" not in resp_header) else 0)
            send_frame(self.wfile, resp_header, resp_arrays,
                       fixed_bytes=fb)
            if header.get("op") == "shutdown":
                srv._shutdown.set()
                return


class _ServeServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ModelServer:
    """One serving shard process: loads its slice, serves fetches,
    watches the manifest for newer versions and hot-swaps to them."""

    def __init__(self, rank: int, world: int, base: str,
                 host: str = "127.0.0.1", port: int = 0,
                 poll_sec: Optional[float] = None,
                 deadline_s: Optional[float] = None):
        self.rank = rank
        self.world = world
        self.base = base
        self.poll_sec = (float(knob_value("WH_SERVE_POLL_SEC"))
                         if poll_sec is None else float(poll_sec))
        if deadline_s is None:
            deadline_s = float(knob_value("WH_SERVE_RETRY_SEC"))
        self._model = load_with_retry(base, rank, world, deadline_s)
        _MODEL_EPOCH.set(float(self._model.version))
        # dispatch reads the active pointer under this lock; the watcher
        # holds it only for the pointer flip, so the request-visible
        # swap stall is the flip, never the standby load
        self._flip_lock = threading.Lock()
        # reply cache: sender -> (seq, resp_header, resp_arrays); the
        # router uses one sender id per connection with monotone seqs,
        # so caching the latest reply covers every retry pattern
        self._replies: Dict[str, tuple] = {}
        self._replies_lock = threading.Lock()
        # tables score headers asked to replicate (e.g. difacto's V):
        # remembered so a standby model loads its replicas OFF the
        # request path, before the flip
        self._replicate: set = set()
        self._gate = _overload.AdmissionController()
        self._shutdown = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._srv = _ServeServer((host, port), _ServeHandler)
        self._srv.model_server = self  # type: ignore
        self._watcher: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    @property
    def uri(self) -> str:
        h, p = self._srv.server_address[:2]
        return f"{h}:{p}"

    @property
    def version(self) -> int:
        return self._model.version

    def serve(self) -> None:
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()
        self._watcher = threading.Thread(target=self._watch_loop,
                                         daemon=True)
        self._watcher.start()

    def wait_shutdown(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown.wait(timeout)

    def stop(self) -> None:
        self._shutdown.set()
        self._srv.shutdown()
        self._srv.server_close()
        # sever live handler connections so a stopped shard looks like a
        # dead process to the router (retry path), not a hung socket
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -- hot swap -----------------------------------------------------------
    def _watch_loop(self) -> None:  # wormlint: thread-entry
        _pyprof.tag_thread("watcher")
        while not self._shutdown.wait(self.poll_sec):
            try:
                self.maybe_swap()
            except Exception as e:
                # a torn or half-written set is retried next poll; the
                # active model keeps serving
                print(f"[serve {self.rank}] swap attempt failed: {e}",
                      flush=True)

    def maybe_swap(self) -> bool:
        """Load and flip to a newer snapshot version if one is on disk.
        Returns True when a swap happened. Safe to call directly (tests
        and the lab use it for deterministic swaps)."""
        standby = None
        for _ in range(_TORN_RETRIES):
            man = _manifest.read_manifest(self.base)
            if not _manifest.complete(man):
                return False
            if int(man["version"]) <= self._model.version:
                return False
            try:
                standby = ServingModel(self.base, self.rank, self.world,
                                       man)
                break
            except _manifest.TornSnapshot:
                # a part was replaced under this manifest (a set write is
                # in flight); re-read — the committed manifest names the
                # replacement files
                time.sleep(0.02)
        if standby is None:
            return False  # still torn; the next poll retries
        for t in sorted(self._replicate):
            standby.replicated(t)  # off-path: requests still see old
        t0 = time.perf_counter()
        with self._flip_lock:
            old = self._model.version
            self._model = standby
        stall = time.perf_counter() - t0
        _SWAP_STALL_S.observe(stall)
        _SWAPS.inc()
        _MODEL_EPOCH.set(float(standby.version))
        _trace.event("serve.swap", cat="serve", rank=self.rank,
                     version=standby.version, prev=old,
                     stall_ms=round(stall * 1e3, 3))
        print(f"[serve {self.rank}] swapped to snapshot version "
              f"{standby.version} (was {old}, "
              f"stall {stall * 1e3:.2f} ms)", flush=True)
        return True

    # -- ops ----------------------------------------------------------------
    def _dispatch(self, header: dict,  # wormlint: thread-entry
                  arrays: dict,
                  t_in: Optional[float] = None) -> tuple[dict, dict]:
        op = header.get("op")
        t0 = time.perf_counter()
        try:
            with _trace.request_span(f"serve.shard.{op}", cat="serve",
                                     rank=self.rank):
                resp = self._dispatch_op(op, header, arrays)
            if op in ("fetch", "score") and "queue_s" not in resp[0] \
                    and "error" not in resp[0]:
                # stage attribution for the router: how long the frame
                # waited behind the gate/handler, and how long the fetch
                # itself took. A cached (retried) reply keeps the
                # ORIGINAL numbers — same bytes as the first send.
                resp[0]["queue_s"] = round(
                    t0 - t_in, 6) if t_in is not None else 0.0
                resp[0]["served_s"] = round(time.perf_counter() - t0, 6)
            return resp
        except Exception as e:  # a bad request must not kill the shard
            return {"error": repr(e), "version": self.version}, {}
        finally:
            _obs.REGISTRY.histogram(f"serve.op.{op}_s").observe(
                time.perf_counter() - t0)

    def _dispatch_op(self, op, header: dict,
                     arrays: dict) -> tuple[dict, dict]:
        _REQUESTS.inc()
        # one pointer read per request: rows AND the stamped version come
        # from the same immutable model object even if a swap lands
        # mid-request
        with self._flip_lock:
            m = self._model
        if op == "hello":
            sender = header.get("sender", "?")
            with self._replies_lock:
                cached = self._replies.get(sender)
            return {"ok": 1, "rank": self.rank, "world": self.world,
                    "version": m.version, "full_rows": m.full_rows,
                    "tables": sorted(m.tables),
                    "last_seq": cached[0] if cached else -1}, {}
        if op in ("fetch", "score"):
            sender = header.get("sender", "?")
            seq = int(header.get("seq", -1))
            # one reply cache for BOTH data-plane ops: hedges and
            # socket-error retries resend the same (sender, seq), so a
            # duplicate score is answered with the ORIGINAL partials —
            # same bytes, same version — never recomputed
            if seq >= 0:
                with self._replies_lock:
                    cached = self._replies.get(sender)
                if cached is not None and cached[0] == seq:
                    _DEDUP_HITS.inc()
                    return cached[1], cached[2]
            if op == "score":
                for t in header.get("rep", ()):
                    self._replicate.add(t)
                    m.replicated(t)
                out = _shard_score(header, arrays, m)
                _ROWS.inc(len(arrays.get("i", ())))
            else:
                out = {}
                nrows = 0
                for t in header.get("tables", []):
                    rows = m.fetch(t, arrays[f"k:{t}"])
                    out[f"r:{t}"] = rows
                    nrows += len(rows)
                _ROWS.inc(nrows)
            resp = ({"ok": 1, "version": m.version, "seq": seq}, out)
            if seq >= 0:
                with self._replies_lock:
                    self._replies[sender] = (seq, *resp)
            return resp
        if op == "stats":
            return {"ok": 1, "version": m.version, "rank": self.rank,
                    "metrics": _obs.REGISTRY.snapshot()}, {}
        if op == "shutdown":
            return {"ok": 1, "version": m.version}, {}
        return {"error": f"unknown op {op!r}", "version": m.version}, {}


def run_serve_role(cfg, env) -> dict:
    """Entry for a launcher-spawned ``--serve`` process (role dispatch in
    apps/_runner.run_minibatch_app): load the shard, register with the
    scheduler (re-registration after a respawn is the recovery signal
    the router's resolver picks up), heartbeat with piggybacked metrics,
    exit when the job announces shutdown."""
    from wormhole_tpu.runtime.tracker import SchedulerClient

    base = str(knob_value("WH_SERVE_SNAPSHOT") or "")
    if not base:
        snap_dir = os.environ.get("WH_SNAPSHOT_DIR", "")
        if not snap_dir:
            raise RuntimeError(
                "serve role needs WH_SERVE_SNAPSHOT or the launcher's "
                "snapshot dir (WH_SNAPSHOT_DIR) to locate the model")
        base = os.path.join(snap_dir, "srv")
    world = max(int(getattr(env, "num_serve", 1)), 1)
    # startup must outlast the trainer's FIRST snapshot cycle, which the
    # router retry window does not have to
    deadline = max(float(knob_value("WH_SERVE_RETRY_SEC")), 120.0)
    server = ModelServer(env.rank, world, base, deadline_s=deadline)
    server.serve()
    client = SchedulerClient(env.scheduler_uri, f"serve-{env.rank}")
    client.call(op="register_serve", rank=env.rank, uri=server.uri)
    print(f"[serve {env.rank}] serving {base} version "
          f"{server.version} at {server.uri}", flush=True)
    try:
        while not server.wait_shutdown(2.0):
            try:
                r = client.call(op="epoch",
                                metrics=_obs.REGISTRY.snapshot())
            except Exception:
                break  # scheduler gone: the job is over
            if r.get("shutdown"):
                break
    finally:
        server.stop()
    return {}
