"""Model scorers: predict math over compact row sets, bit-matched to
training.

A serving shard holds only its row range, so the router cannot run the
learner's full-table ``predict_step``. Instead each scorer packs a
RowBlock exactly the way the trainer's CPU/XLA path does
(``to_device_batch`` — identical seg/val arrays, identical padding),
collects the batch's sorted-unique keys per table, and scores over a
COMPACT table whose rows were gathered from the shards. Because the
compact remap satisfies ``compact[remap[j]] == full[idx[j]]`` row for
row, every elementwise product and the ``segment_sum`` reduction see
the SAME float operands in the SAME order as the trainer's jitted
``spmv``/``_fm_forward`` — so the margins are bit-identical to the
model owner's own ``predict_batch`` (the serving tier's correctness
contract; tests/test_serving.py asserts equality, not closeness).

The contract is against the trainer's SINGLE-DEVICE program: a trainer
predicting through a data-sharded mesh compiles a different (equally
valid) XLA program whose fusion/reassociation can move individual
margins by an ulp. Scores are deterministic either way — the scorer is
one fixed program — but "bit-identical to the trainer" means the 1x1
mesh path.

Compact tables are zero-padded up to a power-of-two capacity so the
jitted kernels compile O(log capacity) times, not once per batch shape;
padded rows are never indexed by the remap, so their contents cannot
perturb the result.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.data.rowblock import RowBlock, to_device_batch
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.ops.spmv import row_squares, spmm, spmv
import wormhole_tpu.serving.fastpath as _fastpath

_MIN_CAP = 256


def _cap(n: int) -> int:
    """Power-of-two compact-table capacity (bounded jit-cache growth)."""
    return max(_MIN_CAP, 1 << max(int(n) - 1, 0).bit_length())


def _padded(rows: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros((cap,) + rows.shape[1:], np.float32)
    out[: len(rows)] = rows
    return out


@dataclasses.dataclass
class PackedBatch:
    """One RowBlock, packed for sharded scoring: the fixed-shape COO
    arrays (trainer-identical), the sorted-unique key list each table's
    rows must be fetched for, and the compact remaps per key space."""

    seg: np.ndarray                    # int32[capacity]
    val: np.ndarray                    # float32[capacity]
    size: int                          # live rows (score rows returned)
    keys: Dict[str, np.ndarray]        # table -> sorted-unique int64 keys
    remap: Dict[str, np.ndarray]       # key space -> int32[capacity]
    dropped_rows: int = 0


@partial(jax.jit, static_argnames=("num_rows",))
def _linear_margin(seg, idxc, val, w, *, num_rows: int):
    return spmv(seg, idxc, val, w, num_rows)


@partial(jax.jit, static_argnames=("num_rows", "threshold", "l1_shrk"))
def _fm_margin(seg, idxc, vidxc, val, w, cnt, V, *,
               num_rows: int, threshold: int, l1_shrk: bool):
    # mirror of models/difacto._fm_forward over the compact domain: the
    # admission mask, both quadratic terms, and the reduction order are
    # operand-for-operand the trainer's
    admit = cnt >= threshold
    if l1_shrk:
        admit = admit & (w != 0)
    admit_nz = jnp.take(admit.astype(jnp.float32), idxc)
    xw = spmv(seg, idxc, val, w, num_rows)
    vval = val * admit_nz
    xv = spmm(seg, vidxc, vval, V, num_rows)
    x2v2 = row_squares(seg, vidxc, vval, V, num_rows)
    return xw + 0.5 * jnp.sum(xv * xv - x2v2, axis=-1)


class LinearScorer:
    """Margins for the linear apps: serves ``w`` only. ``cfg`` is a
    LinearConfig (or anything with minibatch/row_capacity/num_buckets/
    prob_predict)."""

    #: tables fetched from the shards, and the key space each indexes
    tables = ("w",)
    #: shard-local scoring kernel (serving/fastpath.py); routers in
    #: WH_SERVE_MODE=auto take the fast path when this is set
    score_kind = "linear"

    def __init__(self, cfg):
        self.cfg = cfg

    def pack_score(self, blk: RowBlock) -> _fastpath.ScorePack:
        cfg = self.cfg
        with _trace.request_span("serve.stage.pack", cat="serve",
                                 rows=blk.size):
            return _fastpath.pack_score(blk, cfg.minibatch,
                                        cfg.row_capacity,
                                        cfg.num_buckets)

    def score_header(self) -> dict:
        return {}

    def finalize(self, pack: _fastpath.ScorePack, prod: np.ndarray,
                 extras: Dict[str, np.ndarray]) -> np.ndarray:
        return _fastpath.finalize_linear(
            pack, prod, getattr(self.cfg, "prob_predict", False))

    def pack(self, blk: RowBlock) -> PackedBatch:
        cfg = self.cfg
        with _trace.request_span("serve.stage.pack", cat="serve",
                                 rows=blk.size):
            db = to_device_batch(blk, cfg.minibatch, cfg.row_capacity,
                                 cfg.num_buckets)
            uniq, idxc = np.unique(db.idx, return_inverse=True)
            return PackedBatch(
                seg=db.seg, val=db.val,
                size=min(blk.size, cfg.minibatch) - db.dropped_rows,
                keys={"w": uniq.astype(np.int64)},
                remap={"w": idxc.astype(np.int32)},
                dropped_rows=db.dropped_rows)

    def score(self, packed: PackedBatch,
              rows: Dict[str, np.ndarray]) -> np.ndarray:
        with _trace.request_span("serve.stage.score", cat="serve",
                                 keys=len(packed.keys["w"])):
            cap = _cap(len(packed.keys["w"]))
            xw = _linear_margin(
                jnp.asarray(packed.seg), jnp.asarray(packed.remap["w"]),
                jnp.asarray(packed.val),
                jnp.asarray(_padded(rows["w"], cap)),
                num_rows=self.cfg.minibatch)
            out = np.asarray(xw)[: packed.size]
            if getattr(self.cfg, "prob_predict", False):
                out = 1.0 / (1.0 + np.exp(-out))
            return out


class DifactoScorer:
    """FM margins for the difacto app: serves ``w``/``cnt`` (bucket key
    space) and ``V`` (embedding key space, ``key % vb``). Admission is
    recomputed from the served ``cnt`` rows exactly as the trainer's
    forward does, so a never-admitted bucket scores as unallocated."""

    tables = ("w", "cnt", "V")
    score_kind = "difacto"

    def __init__(self, cfg):
        self.cfg = cfg

    def pack(self, blk: RowBlock) -> PackedBatch:
        cfg = self.cfg
        with _trace.request_span("serve.stage.pack", cat="serve",
                                 rows=blk.size):
            db = to_device_batch(blk, cfg.minibatch, cfg.row_capacity,
                                 cfg.num_buckets)
            uniq_w, idxc = np.unique(db.idx, return_inverse=True)
            # the V key space is uniq_w folded mod vb: unique over the
            # (already deduplicated) uniq_w is the same sorted key set
            # and inverse as unique over the full per-nonzero vidx —
            # one O(u log u) pass instead of a second O(nnz log nnz)
            uv_small, inv_small = np.unique(
                (uniq_w % np.int32(cfg.vb)).astype(np.int32),
                return_inverse=True)
            uniq_v = uv_small
            vidxc = inv_small[idxc]
            uniq_w = uniq_w.astype(np.int64)
            uniq_v = uniq_v.astype(np.int64)
            return PackedBatch(
                seg=db.seg, val=db.val,
                size=min(blk.size, cfg.minibatch) - db.dropped_rows,
                keys={"w": uniq_w, "cnt": uniq_w, "V": uniq_v},
                remap={"w": idxc.astype(np.int32),
                       "V": vidxc.astype(np.int32)},
                dropped_rows=db.dropped_rows)

    def pack_score(self, blk: RowBlock) -> _fastpath.ScorePack:
        cfg = self.cfg
        with _trace.request_span("serve.stage.pack", cat="serve",
                                 rows=blk.size):
            return _fastpath.pack_score(blk, cfg.minibatch,
                                        cfg.row_capacity,
                                        cfg.num_buckets)

    def score_header(self) -> dict:
        cfg = self.cfg
        return {"threshold": int(cfg.threshold),
                "l1_shrk": int(bool(cfg.l1_shrk)),
                "vb": int(cfg.vb), "rep": ["V"]}

    def finalize(self, pack: _fastpath.ScorePack, prod: np.ndarray,
                 extras: Dict[str, np.ndarray]) -> np.ndarray:
        return _fastpath.finalize_difacto(
            pack, prod, extras["xv"], extras["x2"],
            getattr(self.cfg, "prob_predict", False))

    def score(self, packed: PackedBatch,
              rows: Dict[str, np.ndarray]) -> np.ndarray:
        cfg = self.cfg
        with _trace.request_span("serve.stage.score", cat="serve",
                                 keys=len(packed.keys["w"])):
            cap_w = _cap(len(packed.keys["w"]))
            cap_v = _cap(len(packed.keys["V"]))
            margin = _fm_margin(
                jnp.asarray(packed.seg), jnp.asarray(packed.remap["w"]),
                jnp.asarray(packed.remap["V"]), jnp.asarray(packed.val),
                jnp.asarray(_padded(rows["w"], cap_w)),
                jnp.asarray(_padded(rows["cnt"], cap_w)),
                jnp.asarray(_padded(rows["V"], cap_v)),
                num_rows=cfg.minibatch, threshold=int(cfg.threshold),
                l1_shrk=bool(cfg.l1_shrk))
            out = np.asarray(margin)[: packed.size]
            if getattr(cfg, "prob_predict", False):
                out = 1.0 / (1.0 + np.exp(-out))
            return out
