"""dmlc_tpu: launch a distributed wormhole-tpu job.

Parity with the reference trackers (dmlc-core tracker/dmlc_local.py,
dmlc_ssh-style multi-host, dmlc_mpi.py, dmlc_yarn.py — reference
doc/common/build.rst:53-123): spawn 1 scheduler + N worker processes of
the same program, wiring the role / rank / rendezvous env vars the
program reads via `runtime.node_env()`.

Mapping the reference's launch dimensions onto TPU:
- `-n` workers = host processes, one per TPU host in a pod slice (or N
  local processes for the single-host / CPU-mesh integration tests —
  exactly how the reference tests multi-node on localhost,
  data_parallel_test.cc:8).
- `-s` servers = parameter-server processes (runtime/ps_server.py): each
  owns a bucket-range shard of every state table; workers push deltas /
  pull merged state through them with bounded staleness, so all workers
  train ONE model (async_sgd.h:240-288 parity). Within each worker the
  device mesh additionally shards tables over its local devices.
- multi-host pods: `--hosts a,b,c` runs the scheduler locally and
  spawns the role processes across the hosts round-robin through
  `--ssh-cmd` (plain ssh by default; point it at a gcloud wrapper for
  TPU pods — docs/distributed.md has the recipe). Each worker also gets
  a rank so apps can call jax.distributed.initialize and form the
  global device mesh over ICI/DCN; the control plane stays the same.

Usage:
  python -m wormhole_tpu.launcher.dmlc_tpu -n 4 -s 2 -- \
      python -m wormhole_tpu.apps.linear learn/linear/demo.conf
  python -m wormhole_tpu.launcher.dmlc_tpu -n 4 -s 2 \
      --hosts tpu-vm-0,tpu-vm-1,tpu-vm-2,tpu-vm-3 -- \
      python -m wormhole_tpu.apps.linear gs_demo.conf
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _default_host_ip() -> str | None:
    """A launch-host address remote role processes can dial back to (the
    dmlc ssh tracker's socket.getsockname trick: no traffic is sent; the
    OS just picks the outbound interface). Probes a routable target
    first (as the dmlc tracker does); returns None when no interface
    can be determined so the caller can fail loudly instead of handing
    remote roles an undialable 127.0.0.1."""
    for probe in ("8.8.8.8", "10.255.255.255"):
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((probe, 1))
                ip = s.getsockname()[0]
            if not ip.startswith("127."):
                return ip
        except OSError:
            continue
    return None


def _stream(prefix: str, pipe, out, on_line=None):
    for line in iter(pipe.readline, b""):
        out.write(f"[{prefix}] ".encode() + line)
        out.flush()
        if on_line is not None:
            try:
                on_line(line)
            except Exception:
                pass  # a watcher bug must never break output streaming


def launch(num_workers: int, num_servers: int, cmd: list[str],
           node_timeout: float = 30.0,
           env_extra: dict | None = None,
           hosts: list[str] | None = None,
           ssh_cmd: str = "ssh",
           remote_cwd: str | None = None,
           scheduler_host: str | None = None,
           coord_port: int = 0,
           max_server_restarts: int = 0,
           max_worker_restarts: int = 0,
           max_scheduler_restarts: int = 0,
           num_serve: int = 0,
           max_serve_restarts: int = 0,
           snapshot_dir: str | None = None,
           elastic: bool = False,
           pass_env: tuple[str, ...] = ("JAX_PLATFORMS", "XLA_FLAGS",
                                        "PYTHONPATH", "WH_PS_PLANE",
                                        "WH_NET_COMPRESS",
                                        "WH_WIRE", "WH_WIRE_EF",
                                        "WH_WIRE_COMP", "WH_SERVE_WIRE",
                                        "WH_TRACE_SAMPLE",
                                        "WH_OBS_SCRAPE_SEC",
                                        "WH_OBS_SCRAPE_PORT",
                                        "WH_ELASTIC_SEC", "WH_ELASTIC_MIN",
                                        "WH_ELASTIC_MAX",
                                        "WH_ELASTIC_PLAN",
                                        "WH_RETRY_BASE_SEC",
                                        "WH_RETRY_CAP_SEC",
                                        "WH_PROF", "WH_PROF_HZ",
                                        "WH_PROF_BUDGET_PCT",
                                        "WH_FLIGHT", "WH_FLIGHT_RING",
                                        "WH_FLIGHT_DECISIONS",
                                        "WH_FLIGHT_SNAPS",
                                        "WH_FLIGHT_DIR",
                                        "WH_FLIGHT_MIN_SEC",
                                        "WH_SAN", "WH_SAN_SAMPLE",
                                        "WH_SAN_DUMP_DIR")) -> int:
    """Spawn the scheduler + N workers of `cmd`; stream their output with
    role prefixes; return the first nonzero exit code (0 if all clean).
    On scheduler exit, surviving workers are terminated (the reference
    tracker's process-group teardown).

    With `hosts`, the scheduler runs locally and the server/worker
    processes are spawned round-robin across the hosts via `ssh_cmd`
    (the dmlc ssh-tracker model): each remote invocation is
    `<ssh_cmd> <host> 'cd <remote_cwd> && env <contract> <cmd>'` — the
    same WH_* env contract either way, with the scheduler URI bound on a
    launch-host address the remote nodes can dial. The jax.distributed
    coordinator lands on hosts[0] (worker 0's host) at `coord_port`.

    With `max_server_restarts > 0` the launcher becomes the ps plane's
    supervisor (the ps-lite node-manager role): a server process that
    dies mid-job is respawned — up to the cap, per rank — with
    WH_RESTORE_EPOCH bumped so it restores its latest shard snapshot
    from `snapshot_dir` (auto-allocated when not given) and re-announces
    its new URI; workers ride the death out through PSClient's fenced
    retry (WH_PS_RETRY_SEC, exported automatically). Snapshot respawn is
    local-launch only for now (a remote host's respawn would need the
    ssh round-trip plumbed through the stream threads).

    `max_worker_restarts > 0` extends the same supervision to WORKER
    processes, for the BSP allreduce apps (runtime/allreduce.py): a
    respawned worker re-registers with the tracker (bumping the group
    generation), loads its version-stamped checkpoint from
    `snapshot_dir`, and replays its missed collectives from peers'
    result caches. Unlike supervised servers, a worker's FINAL exit
    code always folds into the job's: workers define job success.

    `num_serve > 0` adds a group of online serving shards
    (serving/server.py): each loads its range of the newest snapshot
    set under WH_SNAPSHOT_DIR (or WH_SERVE_SNAPSHOT), registers its
    predict endpoint with the scheduler, and hot-swaps as training
    writes newer versions. Serving is infrastructure, not workload:
    shard exit codes never fold into the job's (the launcher kills
    leftovers at teardown), and `max_serve_restarts > 0` respawns a
    shard that dies mid-job — routers chase the new uri through the
    scheduler's serve_nodes op.

    `max_scheduler_restarts > 0` closes the last single point of
    failure: the scheduler journals every state-mutating control-plane
    op under the snapshot dir (WH_SCHED_JOURNAL, on by default), and a
    scheduler that CRASHES mid-job is respawned on the SAME pinned URI
    with a bumped incarnation — it replays the journal and resumes the
    job where it died, while workers ride the outage out under
    WH_SCHED_RETRY_SEC (exported automatically). Only a clean
    `announce_shutdown` exit (code 0) tears the job down; crash vs
    shutdown is distinguished by exit code, fixing the old blanket
    kill-everything-on-scheduler-exit behavior.

    `elastic=True` makes the WORKER SET itself dynamic: WH_ELASTIC=1 is
    exported so the scheduler runs its membership controller
    (WH_ELASTIC_PLAN scripted churn, or gauge-driven sizing), and the
    launcher runs an elastic supervisor thread that polls the
    scheduler's `elastic` op — when the target exceeds the live count
    it spawns fresh worker ranks (WH_ELASTIC_JOIN=1, so they `join` the
    running job mid-pass); shrinking is the scheduler's half (it marks
    workers retiring; they drain, flush, `leave`, and exit 0).
    Local-launch only, like snapshot respawn."""
    multi = bool(hosts)
    recovery = max_server_restarts > 0 and num_servers > 0
    recovery_w = max_worker_restarts > 0 and num_workers > 0
    recovery_s = max_scheduler_restarts > 0
    if (recovery or recovery_w or recovery_s
            or num_serve > 0) and snapshot_dir is None:
        import tempfile

        snapshot_dir = tempfile.mkdtemp(prefix="wh_ps_snap_")
    if multi:
        sched_host = scheduler_host or _default_host_ip()
        if not sched_host:
            raise RuntimeError(
                "--hosts mode: could not auto-detect a launch-host IP the "
                "remote roles can dial back to (every interface probe "
                "failed or resolved to loopback); pass --scheduler-host "
                "explicitly")
    else:
        sched_host = "127.0.0.1"
    # WH_SCHED_PORT pins the scheduler's RPC port so an outside process
    # (tools/chaos_lab.py's serve-tier driver, obs_top) can dial the job
    # without scraping logs; 0/unset keeps the ephemeral default
    sched_port = int(os.environ.get("WH_SCHED_PORT", "0") or 0)
    uri = f"{sched_host}:{sched_port or _free_port()}"
    # one run id for the whole job so every node's trace spans and the
    # final report carry the same tag (obs/trace.py reads WH_RUN_ID)
    run_id = os.environ.get("WH_RUN_ID") or f"wh-{int(time.time())}-{os.getpid()}"
    obs_dir = os.environ.get("WH_OBS_DIR")
    # jax.distributed rendezvous for apps that opt into the global-mesh
    # mode (parallel/multihost.py); worker 0 binds it on first use. On a
    # pod, worker 0 lives on hosts[0]; coord_port must be free THERE, so
    # it is explicit (the launcher can only probe local ports).
    if multi:
        coord_uri = f"{hosts[0]}:{coord_port or 29477}"
    else:
        coord_uri = f"127.0.0.1:{_free_port()}"

    def contract(role: str, rank: int) -> dict:
        env = dict(
            WH_ROLE=role,
            WH_RANK=str(rank),
            WH_NUM_WORKERS=str(num_workers),
            WH_NUM_SERVERS=str(num_servers),
            WH_NUM_SERVE=str(num_serve),
            WH_SCHEDULER_URI=uri,
            WH_COORD_URI=coord_uri,
            WH_NODE_TIMEOUT=str(node_timeout),
            WH_RUN_ID=run_id,
        )
        if obs_dir:
            # remote spawns don't inherit the launch-host environment;
            # exporting it in the contract keeps telemetry on for them
            # too (each node appends to its host-local WH_OBS_DIR)
            env["WH_OBS_DIR"] = obs_dir
        if snapshot_dir:
            env["WH_SNAPSHOT_DIR"] = snapshot_dir
        if elastic:
            env["WH_ELASTIC"] = "1"
        if recovery and not os.environ.get("WH_PS_RETRY_SEC"):
            # worker-side retry budget: generous enough to span a server
            # death + respawn + snapshot restore + re-registration; an
            # exported WH_PS_RETRY_SEC (or env_extra below) overrides
            env["WH_PS_RETRY_SEC"] = str(max(120.0, node_timeout * 4))
        if recovery_w and not os.environ.get("WH_BSP_RETRY_SEC"):
            # survivor-side stall budget for a blocked BSP collective:
            # must span a worker death + respawn + checkpoint load
            env["WH_BSP_RETRY_SEC"] = str(max(120.0, node_timeout * 4))
        if recovery_s and not os.environ.get("WH_SCHED_RETRY_SEC"):
            # client-side scheduler-RPC retry window: must span a
            # scheduler death + respawn + journal replay; the reply
            # cache keeps the retries exactly-once
            env["WH_SCHED_RETRY_SEC"] = str(max(120.0, node_timeout * 4))
        if env_extra:
            env.update({k: str(v) for k, v in env_extra.items()})
        return env

    def spawn(role: str, rank: int,
              extra: dict | None = None) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(contract(role, rank))
        if extra:
            env.update(extra)
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    def spawn_remote(role: str, rank: int,
                     extra: dict | None = None) -> subprocess.Popen:
        # workers spread over hosts by rank; servers, then serving
        # shards, continue the round-robin after them so a host gets at
        # most ceil((n+s+serve)/len(hosts)) processes
        if role == "worker":
            slot = rank
        elif role == "server":
            slot = num_workers + rank
        else:  # serve
            slot = num_workers + num_servers + rank
        host = hosts[slot % len(hosts)]
        kv = dict(contract(role, rank))
        if extra:
            kv.update(extra)
        for k in pass_env:
            if k in os.environ and k not in kv:
                kv[k] = os.environ[k]
        line = "cd " + shlex.quote(remote_cwd or os.getcwd())
        line += " && env " + " ".join(
            shlex.quote(f"{k}={v}") for k, v in kv.items())
        line += " " + " ".join(shlex.quote(c) for c in cmd)
        argv = shlex.split(ssh_cmd) + [host, line]
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    role_spawn = spawn_remote if multi else spawn
    sched = spawn("scheduler", 0)  # the tracker node always runs locally
    server_procs = {r: role_spawn("server", r) for r in range(num_servers)}
    worker_procs = {r: role_spawn("worker", r) for r in range(num_workers)}
    serve_procs = {r: role_spawn("serve", r) for r in range(num_serve)}
    procs = {"scheduler": sched}
    procs.update({f"server-{r}": p for r, p in server_procs.items()})
    procs.update({f"worker-{r}": p for r, p in worker_procs.items()})
    procs.update({f"serve-{r}": p for r, p in serve_procs.items()})
    threads = []

    def scrape_report(line: bytes) -> None:
        """Scheduler stdout watcher: the scheduler prints the aggregated
        run report as a machine line (`[run-report] {json}`); persist it
        when the scheduler process couldn't (e.g. its WH_OBS_DIR is on
        another filesystem view). Written only when the file is absent —
        the scheduler's own write wins when both see the same dir."""
        marker = b"[run-report] "
        if not obs_dir or not line.startswith(marker):
            return
        path = os.path.join(obs_dir, "run_report.json")
        if os.path.exists(path):
            return
        report = json.loads(line[len(marker):].decode())
        os.makedirs(obs_dir, exist_ok=True)
        tmp = f"{path}.launcher.{os.getpid()}.tmp"
        with open(tmp, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)

    def watch_output(name: str, p: subprocess.Popen,
                     on_line=None) -> None:
        t = threading.Thread(target=_stream,
                             args=(name, p.stdout, sys.stdout.buffer,
                                   on_line),
                             daemon=True)
        t.start()
        threads.append(t)

    for name, p in procs.items():
        watch_output(name, p,
                     on_line=scrape_report if name == "scheduler" else None)

    stop_respawn = threading.Event()

    def respawn_loop(role: str, label: str, r: int, table: dict,
                     cap: int) -> None:
        """Supervise one role process: a nonzero/signal exit mid-job gets
        the process respawned with a bumped WH_RESTORE_EPOCH (snapshot /
        BSP-checkpoint restore), up to the cap."""
        restarts = 0
        while True:
            p = table[r]
            code = p.wait()
            if stop_respawn.is_set() or code == 0:
                return
            if restarts >= cap:
                print(f"[dmlc_tpu] ERROR: {label}-{r} died again "
                      f"(exit {code}) and max_{role}_restarts="
                      f"{cap} is exhausted; not "
                      "respawning — the job will fail", flush=True)
                return
            restarts += 1
            print(f"[dmlc_tpu] {label}-{r} died (exit {code}); "
                  f"respawning with restore epoch {restarts} "
                  f"({restarts}/{cap})", flush=True)
            np_ = role_spawn(role, r,
                             {"WH_RESTORE_EPOCH": str(restarts)})
            table[r] = np_
            procs[f"{role}-{r}"] = np_
            watch_output(f"{role}-{r}", np_)

    monitors = []
    if recovery:
        for r in range(num_servers):
            m = threading.Thread(target=respawn_loop,
                                 args=("server", "ps server", r,
                                       server_procs, max_server_restarts),
                                 daemon=True)
            m.start()
            monitors.append(m)
    if recovery_w:
        for r in range(num_workers):
            m = threading.Thread(target=respawn_loop,
                                 args=("worker", "worker", r,
                                       worker_procs, max_worker_restarts),
                                 daemon=True)
            m.start()
            monitors.append(m)
    if max_serve_restarts > 0 and num_serve > 0:
        for r in range(num_serve):
            m = threading.Thread(target=respawn_loop,
                                 args=("serve", "serve shard", r,
                                       serve_procs, max_serve_restarts),
                                 daemon=True)
            m.start()
            monitors.append(m)

    if elastic and not multi:
        # elastic supervisor: the GROW half of the membership loop. The
        # scheduler decides the target (and handles shrink itself via
        # retire flags); this thread only turns target > live into
        # fresh worker processes. Joiners get rank numbers past the
        # launch set — rank is an identity, not an index.
        from wormhole_tpu.obs import metrics as _wh_obs
        from wormhole_tpu.runtime.tracker import SchedulerClient

        _SPAWNS = _wh_obs.REGISTRY.counter("elastic.spawns")
        _RETIRES = _wh_obs.REGISTRY.counter("elastic.retires")
        next_rank = [num_workers]
        seen_retiring: set = set()

        def elastic_loop() -> None:
            cli = SchedulerClient(uri, node="launcher",
                                  connect_deadline=node_timeout)
            poll = max(
                float(os.environ.get("WH_ELASTIC_SEC", "5") or 5) / 2.0,
                0.5)
            while not stop_respawn.wait(poll):
                try:
                    r = cli.call(op="elastic")
                except (OSError, ConnectionError, RuntimeError):
                    continue  # scheduler busy/gone; next tick decides
                for n in r.get("retiring", []):
                    if n not in seen_retiring:
                        seen_retiring.add(n)
                        _RETIRES.inc()
                target = r.get("target")
                if target is None or r.get("shutdown"):
                    # once shutdown is announced, workers draining out
                    # make alive < target look like a deficit; spawning
                    # into a dying job strands the joiner against a
                    # scheduler that exits before it can register
                    continue
                alive = sum(1 for p in worker_procs.values()
                            if p.poll() is None)
                while alive < int(target):
                    rank = next_rank[0]
                    next_rank[0] += 1
                    print(f"[dmlc_tpu] elastic: spawning worker-{rank} "
                          f"(target {target}, {alive} alive)", flush=True)
                    p = spawn("worker", rank, {"WH_ELASTIC_JOIN": "1"})
                    worker_procs[rank] = p
                    procs[f"worker-{rank}"] = p
                    watch_output(f"worker-{rank}", p)
                    _SPAWNS.inc()
                    alive += 1

        m = threading.Thread(target=elastic_loop, daemon=True)
        m.start()
        monitors.append(m)
    try:
        # scheduler supervision: a CLEAN exit (code 0, after
        # announce_shutdown) tears the job down; a crash respawns the
        # scheduler on the same pinned URI — it replays its journal and
        # resumes — while workers ride their WH_SCHED_RETRY_SEC budgets.
        # Without supervision every scheduler exit tears down (legacy).
        sched_restarts = 0
        while True:
            rc = sched.wait()
            if rc == 0 or not recovery_s or stop_respawn.is_set():
                break
            if sched_restarts >= max_scheduler_restarts:
                print(f"[dmlc_tpu] ERROR: scheduler died again "
                      f"(exit {rc}) and max_scheduler_restarts="
                      f"{max_scheduler_restarts} is exhausted; not "
                      "respawning — the job will fail", flush=True)
                break
            sched_restarts += 1
            print(f"[dmlc_tpu] scheduler died (exit {rc}); respawning "
                  f"on {uri} with journal replay "
                  f"({sched_restarts}/{max_scheduler_restarts})",
                  flush=True)
            sched = spawn("scheduler", 0,
                          {"WH_RESTORE_EPOCH": str(sched_restarts)})
            procs["scheduler"] = sched
            watch_output("scheduler", sched, on_line=scrape_report)
        stop_respawn.set()  # teardown begins: server exits are expected
        # give workers a grace period to drain, then terminate leftovers.
        # A signal death is a NEGATIVE returncode — fold it to a
        # nonzero exit instead of letting max() hide it behind a clean
        # scheduler (a worker SIGTERM'd mid-predict must fail the job).
        def fold(code: int) -> None:
            nonlocal rc
            if code != 0 and rc == 0:
                rc = code if code > 0 else 1
        # snapshot CURRENT incarnations (a supervised worker killed
        # mid-job was replaced in worker_procs by its respawn; the dead
        # incarnation's 137 is recovery working, not job failure — but
        # the final incarnation's code always counts)
        for p in (list(worker_procs.values()) + list(server_procs.values())
                  + list(serve_procs.values())):
            try:
                code = p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.send_signal(signal.SIGTERM)
                try:
                    code = p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    code = 1
            if p in serve_procs.values():
                # serving shards are infrastructure with no natural end:
                # they exit when the scheduler goes away (or get killed
                # here); their codes never define job success
                continue
            if recovery and p in server_procs.values():
                # with supervision on, a server's exit code is not the
                # job's: an injected/real kill that recovery absorbed
                # must not fail a run whose workers finished clean
                # (failures surface through workers or the scheduler)
                continue
            fold(code)
        return rc
    finally:
        stop_respawn.set()
        for p in list(procs.values()):
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=2)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dmlc_tpu",
        description="local multi-process launcher (dmlc_local.py parity)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=1,
                    help="parameter-server processes (0 = replica mode)")
    ap.add_argument("--node-timeout", type=float, default=30.0)
    ap.add_argument("--max-server-restarts", type=int, default=0,
                    help="respawn a dead ps server up to N times per "
                         "rank, restoring its latest shard snapshot "
                         "(0 = no recovery: a server death fails the "
                         "job fast with resume guidance)")
    ap.add_argument("--max-worker-restarts", type=int, default=0,
                    help="respawn a dead worker up to N times per rank "
                         "(BSP allreduce apps recover it from its "
                         "version checkpoint; 0 = a worker death fails "
                         "the job)")
    ap.add_argument("--max-scheduler-restarts", type=int, default=0,
                    help="respawn a crashed scheduler up to N times on "
                         "the same pinned URI; it replays its "
                         "control-plane journal (WH_SCHED_JOURNAL under "
                         "the snapshot dir) and resumes the job while "
                         "clients retry under WH_SCHED_RETRY_SEC "
                         "(0 = legacy: any scheduler exit ends the job)")
    ap.add_argument("--serve", type=int, default=0, dest="num_serve",
                    help="online serving shards to run alongside the "
                         "job (serving/server.py): each serves its "
                         "range of the newest snapshot set under the "
                         "snapshot dir and hot-swaps as training "
                         "writes newer versions")
    ap.add_argument("--max-serve-restarts", type=int, default=0,
                    help="respawn a dead serving shard up to N times "
                         "per rank; routers re-resolve its new uri "
                         "through the scheduler")
    ap.add_argument("--snapshot-dir", default=None,
                    help="directory for the servers' periodic shard "
                         "snapshots (default: a fresh temp dir when "
                         "recovery is on)")
    ap.add_argument("--elastic", action="store_true",
                    help="dynamic worker membership: the scheduler "
                         "sizes the worker set (WH_ELASTIC_PLAN "
                         "scripted churn or gauge-driven control) and "
                         "the launcher spawns joining workers; "
                         "retiring workers drain and leave without a "
                         "job restart (local launch only)")
    ap.add_argument("-H", "--hosts", default=None,
                    help="comma-separated hosts to spawn role processes "
                         "on via --ssh-cmd (scheduler stays local); "
                         "omit for an all-local launch")
    ap.add_argument("--hostfile", default=None,
                    help="file with one host per line (dmlc ssh-tracker "
                         "convention); merged with --hosts")
    ap.add_argument("--ssh-cmd", default="ssh",
                    help="remote shell command; invoked as "
                         "`<ssh-cmd> <host> '<remote command line>'` "
                         "(e.g. 'ssh -o StrictHostKeyChecking=no', or a "
                         "gcloud tpu-vm wrapper script)")
    ap.add_argument("--remote-cwd", default=None,
                    help="working directory on the hosts (default: the "
                         "launch host's cwd — fine for shared "
                         "filesystems / identical pod VM images)")
    ap.add_argument("--scheduler-host", default=None,
                    help="launch-host address the remote nodes dial for "
                         "the control plane (default: auto-detected "
                         "outbound interface)")
    ap.add_argument("--coord-port", type=int, default=0,
                    help="jax.distributed coordinator port on the first "
                         "host (global-mesh mode on pods)")
    ap.add_argument("--plane", choices=("auto", "tcp", "hot"),
                    default=None,
                    help="parameter-plane selection for the spawned "
                         "workers (exports WH_PS_PLANE): hot keeps the "
                         "tables device-resident with the server group "
                         "as a flush-barrier cold tier — requires all "
                         "data-parallel workers in one process with "
                         ">= 2 local devices; default: the workers' own "
                         "WH_PS_PLANE / auto detection")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="program to launch (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given")
    hosts = [h.strip() for h in (args.hosts or "").split(",") if h.strip()]
    if args.hostfile:
        with open(args.hostfile) as fh:
            hosts += [ln.strip() for ln in fh if ln.strip()
                      and not ln.startswith("#")]
    return launch(args.num_workers, args.num_servers, cmd,
                  node_timeout=args.node_timeout,
                  env_extra=({"WH_PS_PLANE": args.plane}
                             if args.plane else None),
                  hosts=hosts or None, ssh_cmd=args.ssh_cmd,
                  remote_cwd=args.remote_cwd,
                  scheduler_host=args.scheduler_host,
                  coord_port=args.coord_port,
                  max_server_restarts=args.max_server_restarts,
                  max_worker_restarts=args.max_worker_restarts,
                  max_scheduler_restarts=args.max_scheduler_restarts,
                  num_serve=args.num_serve,
                  max_serve_restarts=args.max_serve_restarts,
                  snapshot_dir=args.snapshot_dir,
                  elastic=args.elastic)


if __name__ == "__main__":
    sys.exit(main())
