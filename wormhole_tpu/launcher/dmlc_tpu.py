"""dmlc_tpu: launch a distributed wormhole-tpu job.

Parity with the reference trackers (dmlc-core tracker/dmlc_local.py,
dmlc_mpi.py, dmlc_yarn.py — reference doc/common/build.rst:53-123): spawn
1 scheduler + N worker processes of the same program, wiring the role /
rank / rendezvous env vars the program reads via `runtime.node_env()`.

Mapping the reference's launch dimensions onto TPU:
- `-n` workers = host processes, one per TPU host in a pod slice (or N
  local processes for the single-host / CPU-mesh integration tests —
  exactly how the reference tests multi-node on localhost,
  data_parallel_test.cc:8).
- `-s` servers = parameter-server processes (runtime/ps_server.py): each
  owns a bucket-range shard of every state table; workers push deltas /
  pull merged state through them with bounded staleness, so all workers
  train ONE model (async_sgd.h:240-288 parity). Within each worker the
  device mesh additionally shards tables over its local devices.
- multi-host pods: each worker also gets a rank so apps can call
  jax.distributed.initialize and form the global device mesh over
  ICI/DCN; the control plane here stays the same.

Usage:
  python -m wormhole_tpu.launcher.dmlc_tpu -n 4 -s 2 -- \
      python -m wormhole_tpu.apps.linear learn/linear/demo.conf
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _stream(prefix: str, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"[{prefix}] ".encode() + line)
        out.flush()


def launch(num_workers: int, num_servers: int, cmd: list[str],
           node_timeout: float = 30.0,
           env_extra: dict | None = None) -> int:
    """Spawn the scheduler + N workers of `cmd`; stream their output with
    role prefixes; return the first nonzero exit code (0 if all clean).
    On scheduler exit, surviving workers are terminated (the reference
    tracker's process-group teardown)."""
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    # jax.distributed rendezvous for apps that opt into the global-mesh
    # mode (parallel/multihost.py); worker 0 binds it on first use
    coord_uri = f"127.0.0.1:{_free_port()}"

    def spawn(role: str, rank: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(
            WH_ROLE=role,
            WH_RANK=str(rank),
            WH_NUM_WORKERS=str(num_workers),
            WH_NUM_SERVERS=str(num_servers),
            WH_SCHEDULER_URI=uri,
            WH_COORD_URI=coord_uri,
            WH_NODE_TIMEOUT=str(node_timeout),
        )
        if env_extra:
            env.update({k: str(v) for k, v in env_extra.items()})
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    sched = spawn("scheduler", 0)
    servers = [spawn("server", r) for r in range(num_servers)]
    workers = [spawn("worker", r) for r in range(num_workers)]
    procs = {"scheduler": sched}
    procs.update({f"server-{r}": p for r, p in enumerate(servers)})
    procs.update({f"worker-{r}": p for r, p in enumerate(workers)})
    threads = []
    for name, p in procs.items():
        t = threading.Thread(target=_stream,
                             args=(name, p.stdout, sys.stdout.buffer),
                             daemon=True)
        t.start()
        threads.append(t)
    try:
        rc = sched.wait()
        # give workers a grace period to drain, then terminate leftovers
        for p in workers + servers:
            try:
                rc = max(rc, p.wait(timeout=10))
            except subprocess.TimeoutExpired:
                p.send_signal(signal.SIGTERM)
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
        return rc
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=2)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dmlc_tpu",
        description="local multi-process launcher (dmlc_local.py parity)")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=1,
                    help="parameter-server processes (0 = replica mode)")
    ap.add_argument("--node-timeout", type=float, default=30.0)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="program to launch (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given")
    return launch(args.num_workers, args.num_servers, cmd,
                  node_timeout=args.node_timeout)


if __name__ == "__main__":
    sys.exit(main())
