from wormhole_tpu.solver.workload import Workload, WorkloadPool  # noqa: F401
from wormhole_tpu.solver.progress import Progress  # noqa: F401
from wormhole_tpu.solver.minibatch_solver import MinibatchSolver  # noqa: F401
