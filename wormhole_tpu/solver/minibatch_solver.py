"""The train/val/predict pass loop: scheduler + streaming workers.

Parity with reference learn/solver/minibatch_solver.h + iter_solver.h:
- `run()` drives `max_data_pass` passes of TRAIN then VAL, with model
  load before (model_in / load_iter) and saves during (save_iter) and
  after (model_out) — minibatch_solver.h:85-137.
- each pass dispatches virtual file parts from a WorkloadPool to loader
  workers (data_parallel.h:93-115); here workers are host threads that
  parse minibatches into a bounded queue (the max_concurrency
  backpressure of minibatch_solver.h:284-329) while the main thread runs
  the jitted device steps — async I/O under synchronous XLA steps.
- a progress row prints every print_sec (minibatch_solver.h:169-192) and
  a `stop()` hook supports early stopping (minibatch_solver.h:47-59).
- predict writes one output file per part (iter_solver.h:140-156).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from wormhole_tpu.data.minibatch import MinibatchIter
from wormhole_tpu.data import pack_cache as _pc
from wormhole_tpu.obs import pyprof as _pyprof
from wormhole_tpu.obs import report as _report
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.obs.metrics import REGISTRY
from wormhole_tpu.solver.progress import Progress
from wormhole_tpu.solver.workload import WorkloadPool, WorkType
from wormhole_tpu.utils import checkpoint as ckpt
from wormhole_tpu.utils.perf import Perf, maybe_trace


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() not in ("", "0", "false", "off")


class LoaderController:
    """Stall-driven sizing of the loader thread pool, adjusted between
    passes (loaders are pass-scoped threads, so the pass is the natural
    measurement window — the tf.data AUTOTUNE idea with a coarser
    clock). Inputs per pass, read from the same numbers the obs gauges
    carry: the main thread's total queue-wait (``loader.stall_s``) and
    how often the queue was found well-stocked (``queue.depth``).

    Policy (hysteresis keeps it from oscillating):
    - stall above ``grow_stall`` of wall => the device out-ran the
      loaders; grow by 1 (by 2 when starved hard, > 3x the threshold);
    - stall under ``shrink_stall`` AND the queue was >= half full on
      most gets => loaders are over-provisioned; shrink by 1. The
      queue-fullness gate stops a shrink when stall is low merely
      because the pass was short.
    PERF.md's headline measurement is the motivating data point: 2
    loader threads starve a ~17 ms device step behind ~100 ms packs,
    3 restore headroom."""

    def __init__(self, initial: int, lo: int = 1, hi: int | None = None,
                 grow_stall: float = 0.15, shrink_stall: float = 0.02):
        self.n = max(int(initial), lo)
        self.lo = lo
        # loaders spend most of their time in I/O and GIL-released numpy,
        # so 2x oversubscription over the cores is a sane ceiling
        self.hi = hi if hi is not None else max(2 * (os.cpu_count() or 2),
                                                self.n)
        self.grow_stall = grow_stall
        self.shrink_stall = shrink_stall
        self.decisions: list[dict] = []

    def record_pass(self, stall_s: float, wall_s: float, n_steps: int,
                    queue_high_frac: float) -> int:
        """Fold one pass's numbers in; returns the pool size to use for
        the next pass. Passes too short to be a signal (< 4 steps) leave
        the size unchanged."""
        stall_frac = stall_s / max(wall_s, 1e-9)
        new = self.n
        why = "steady"
        if n_steps >= 4:
            if stall_frac > self.grow_stall:
                step = 2 if stall_frac > 3 * self.grow_stall else 1
                new = min(self.n + step, self.hi)
                why = "starved"
            elif stall_frac < self.shrink_stall and queue_high_frac > 0.5:
                new = max(self.n - 1, self.lo)
                why = "overfed"
        self.decisions.append({
            "from": self.n, "to": new, "why": why,
            "stall_frac": round(stall_frac, 4),
            "queue_high_frac": round(queue_high_frac, 3),
            "n_steps": n_steps,
        })
        self.n = new
        return new


class MembershipController:
    """Stall-driven sizing of the WORKER SET — LoaderController's policy
    one level up: where that one adds loader threads inside a process,
    this one asks the scheduler for whole worker processes. Inputs are
    the cluster-merged gauges the tracker already aggregates
    (``queue.depth``, ``loader.stall_s``); the output is a target worker
    count the scheduler publishes through its membership machinery
    (Scheduler.set_elastic_target -> retire flags / launcher spawns).

    Policy, deliberately conservative (a worker join costs a process
    spawn + PS init, so flapping is worse than lagging):
    - sustained stall (``grow_after`` consecutive starved observations)
      => grow by 1, up to ``hi``;
    - sustained idle (stall ~ 0 AND a well-stocked queue for
      ``shrink_after`` observations) => shrink by 1, down to ``lo``;
    - anything mixed resets the streaks (hysteresis).
    Every decision is recorded like LoaderController's, so the run
    report can show WHY the worker set moved."""

    def __init__(self, initial: int, lo: int = 1, hi: Optional[int] = None,
                 grow_stall: float = 0.5, shrink_stall: float = 0.05,
                 grow_after: int = 3, shrink_after: int = 6):
        self.target = max(int(initial), lo)
        self.lo = max(int(lo), 1)
        self.hi = hi if hi is not None else 2 * self.target
        self.grow_stall = grow_stall
        self.shrink_stall = shrink_stall
        self.grow_after = max(int(grow_after), 1)
        self.shrink_after = max(int(shrink_after), 1)
        self._starved = 0
        self._idle = 0
        self.decisions: list[dict] = []

    def record(self, queue_depth: float, stall_s: float,
               live: Optional[int] = None) -> int:
        """Fold one observation window in; returns the worker-count
        target. `live` (the currently registered worker count) re-bases
        the target so a crash-shrunk cluster is grown back toward the
        target rather than the controller shrinking to match it."""
        new = self.target
        why = "steady"
        if stall_s > self.grow_stall:
            self._starved += 1
            self._idle = 0
            if self._starved >= self.grow_after:
                new = min(self.target + 1, self.hi)
                why = "starved"
                self._starved = 0
        elif stall_s < self.shrink_stall and queue_depth >= 1.0:
            self._idle += 1
            self._starved = 0
            if self._idle >= self.shrink_after:
                new = max(self.target - 1, self.lo)
                why = "overfed"
                self._idle = 0
        else:
            self._starved = 0
            self._idle = 0
        if new != self.target or why != "steady":
            self.decisions.append({
                "from": self.target, "to": new, "why": why,
                "stall_s": round(float(stall_s), 3),
                "queue_depth": round(float(queue_depth), 1),
                "live": live,
            })
        self.target = new
        return new


_QDEPTH = REGISTRY.gauge("queue.depth")
_STALL = REGISTRY.gauge("loader.stall_s")
_POOL = REGISTRY.gauge("loader.pool_size")

# training-step stage decomposition (the serve.stage.* contract for
# the train plane — obs/report.train_stage_table): the train thread's
# wall per batch splits into load (queue wait) + step (jitted call) +
# metrics (merge/print); pack and h2d run in loader threads overlapped
# with compute, and sync_s is observed by the PS client sync paths.
_ST_LOAD = REGISTRY.histogram("train.stage.load_s")
_ST_PACK = REGISTRY.histogram("train.stage.pack_s")
_ST_H2D = REGISTRY.histogram("train.stage.h2d_s")
_ST_STEP = REGISTRY.histogram("train.stage.step_s")
_ST_METRICS = REGISTRY.histogram("train.stage.metrics_s")
_ST_TOTAL = REGISTRY.histogram("train.stage.total_s")


class MinibatchSolver:
    """Drives a learner (train_batch/eval_batch/predict_batch/store) over
    sharded files with pooled loading and failure re-queue. The pool's
    straggler watchdog is NOT started here: within one process, a
    re-queued part would be read twice and its examples double-trained;
    the watchdog is for the multi-host scheduler (launcher/dmlc_tpu.py)
    where a straggling host's parts move to another host."""

    def __init__(self, learner, cfg, num_loaders: int | None = None,
                 max_queued: int = 8, verbose: bool = True):
        src = "arg"
        pinned = num_loaders is not None
        if num_loaders is None:
            env = os.environ.get("WH_NUM_LOADERS")
            if env:
                # hardware sweeps pin the pool without config edits
                num_loaders = max(1, int(env))
                src = "WH_NUM_LOADERS"
                pinned = True
            else:
                # the reference's max_concurrency knob (minibatch_solver.h:
                # 215-242): concurrently-prepared in-flight minibatches
                num_loaders = getattr(cfg, "max_concurrency", 2)
                src = "cfg.max_concurrency"
        self.learner = learner
        self.cfg = cfg
        self.num_loaders = num_loaders
        self.max_queued = max_queued
        self.verbose = verbose
        self.t0 = time.time()
        # adaptive sizing defaults on, but a pinned count (explicit arg or
        # env) means the operator chose — stay fixed unless they also set
        # WH_ADAPTIVE_LOADERS=1
        self.controller: Optional[LoaderController] = (
            LoaderController(num_loaders)
            if _env_flag("WH_ADAPTIVE_LOADERS", default=not pinned)
            else None)
        self.pack_cache = _pc.from_env()
        # loader-side device staging (double-buffer): batch N+1's arrays
        # go to the device while the main thread steps batch N
        self.device_feed = _env_flag("WH_DEVICE_FEED", True)
        # early-stop hook: (pass progress, data_pass, type) -> bool
        self.stop_hook: Optional[Callable] = None
        # PS barrier hook (SyncedStore.flush): called before eval,
        # checkpoint saves, and predict so an async in-flight sync can't
        # leave those reading a half-merged model; None in single-process
        # runs (no PS plane) and the distributed runner wires it up
        self.sync_flush: Optional[Callable] = None
        # per-op perf accounting (reference minibatch_solver.h:246-275 +
        # difacto async_sgd.h:108-127 style)
        self.perf = Perf(log=self._log)
        cache_desc = "off"
        if self.pack_cache is not None:
            cache_desc = f"mem={self.pack_cache.mem_bytes >> 20}MB"
            if self.pack_cache.disk_dir:
                cache_desc += f" disk={self.pack_cache.disk_dir}"
        self._log(f"[loader] {num_loaders} loader thread(s) ({src}), "
                  f"adaptive={'on' if self.controller else 'off'}, "
                  f"pack_cache={cache_desc}")

    @property
    def _ckpt_store(self):
        # learners with multiple KV stores expose a combined adapter
        return getattr(self.learner, "ckpt_store", None) or self.learner.store

    # ----------------------------------------------------------------- run
    def run(self) -> dict:
        cfg = self.cfg
        if cfg.model_in:
            ckpt.load_model(self._ckpt_store, cfg.model_in,
                            cfg.load_iter if cfg.load_iter >= 0 else None)
        result = {}
        with maybe_trace("minibatch_solver"):
            result = self._run_passes(cfg)
        if _report.enabled() and not os.environ.get("WH_ROLE"):
            # single-process run: no scheduler to aggregate, so this
            # process's registry IS the whole job — write the report
            # directly (distributed runs get it from apps/_runner.py)
            path = _report.write(_report.build_local())
            self._log(f"[obs] run report written: {path}")
        return result

    def _flush(self) -> None:
        if self.sync_flush is not None:
            self.sync_flush()

    def _run_passes(self, cfg) -> dict:
        result = {}
        for dp in range(cfg.max_data_pass):
            tr = self.iterate(cfg.train_data, WorkType.TRAIN, dp)
            result["train"] = tr
            self._flush()  # pass boundary: all of this pass is merged
            if cfg.val_data:
                vl = self.iterate(cfg.val_data, WorkType.VAL, dp)
                result["val"] = vl
            if cfg.model_out and cfg.save_iter > 0 and (
                (dp + 1) % cfg.save_iter == 0 and dp + 1 < cfg.max_data_pass
            ):
                self._flush()
                ckpt.save_model(self._ckpt_store, cfg.model_out, dp)
            if self._should_stop(result, dp):
                self._log(f"early stop after pass {dp}")
                break
        self._flush()
        if cfg.model_out:
            ckpt.save_model(self._ckpt_store, cfg.model_out)
        if getattr(cfg, "predict_out", None):
            self.predict(cfg.val_data or cfg.train_data, cfg.predict_out)
        return result

    def _should_stop(self, result: dict, dp: int) -> bool:
        if self.stop_hook is None:
            return False
        key = "val" if "val" in result else "train"
        return bool(self.stop_hook(result[key], dp, key))

    # ------------------------------------------------------------- iterate
    def _pass_cache_token(self, train: bool):
        """The learner's pack version for this pass, or None when this
        pass's batch stream cannot be replayed bit-identically: shuffle
        and negative sampling draw from a seed that changes per pass, so
        a cached pack from pass 0 would be the wrong batch in pass 1."""
        if self.pack_cache is None:
            return None
        tok_fn = getattr(self.learner, "pack_cache_token", None)
        if tok_fn is None:
            return None
        if train and (self.cfg.rand_shuffle
                      or self.cfg.neg_sampling < 1.0):
            return None
        return tok_fn(train=train)

    def iterate(self, data: str, wtype: WorkType, data_pass: int = 0) -> Progress:
        cfg = self.cfg
        hook = getattr(self.learner, "on_pass_start", None)
        if hook:
            hook()
        pool = WorkloadPool()
        nfiles = pool.add(data, cfg.num_parts_per_file, cfg.data_format)
        if nfiles == 0:
            raise FileNotFoundError(f"no files match {data}")
        prog = Progress()
        if hasattr(self.learner, "nnz"):
            # seed the pass with the model's standing |w|_0 so the row's
            # sparsity column is cumulative across passes like the
            # reference log (progress.h:10-35), not per-pass deltas;
            # one host reduction per pass, not per row
            prog.merge({"new_w": float(self.learner.nnz())})
            prog.take_increment()
        q: queue.Queue = queue.Queue(maxsize=self.max_queued)
        _END = object()
        errors: list[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            """Bounded put that gives up when the consumer is gone, so a
            failed main-thread step can't park loaders on a full queue."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        train = wtype == WorkType.TRAIN
        token = self._pass_cache_token(train)
        prepare = getattr(self.learner, "prepare_batch", None)
        stage = (getattr(self.learner, "stage_batch", None)
                 if self.device_feed else None)

        def loader(node_id: int):
            _pyprof.tag_thread("loader")
            try:
                while not stop.is_set():
                    got = pool.get(f"loader-{node_id}")
                    if got is None:
                        return
                    part_id, f = got

                    def raw_iter(f=f, part_id=part_id):
                        return MinibatchIter(
                            f.filename, f.part, f.num_parts, f.format,
                            minibatch_size=cfg.minibatch,
                            shuf_buf=(cfg.rand_shuffle * cfg.minibatch
                                      if train else 0),
                            neg_sampling=(cfg.neg_sampling
                                          if train else 1.0),
                            seed=data_pass * 7919 + part_id,
                        )

                    def prep(blk):
                        # host-side batch prep (padding + pallas
                        # tile-sort) happens here in the loader thread,
                        # overlapped with the main thread's device steps
                        if prepare is None:
                            return blk
                        t0p = time.perf_counter()
                        with self.perf.timer("prepare"):
                            out = prepare(blk, train=train)
                        if train:
                            _ST_PACK.observe(time.perf_counter() - t0p)
                        return out

                    # identical (token, part, file bytes, batch geometry)
                    # => identical pack; anything else misses
                    part_key = None
                    if token is not None:
                        part_key = (
                            "train" if train else "eval", token,
                            f.filename, f.part, f.num_parts, f.format,
                            cfg.minibatch, _pc.file_stamp(f.filename))
                    for b in _pc.iter_part_cached(
                            self.pack_cache, part_key, raw_iter, prep):
                        if stage is not None:
                            t0h = time.perf_counter()
                            b = stage(b, train=train)
                            if train:
                                _ST_H2D.observe(
                                    time.perf_counter() - t0h)
                        if not _put(b):
                            return
                    pool.finish(part_id)
            except BaseException as e:
                # CPython list.append is atomic; main thread reads only
                # after every loader posted its _END sentinel
                errors.append(e)  # wormlint: disable=lock-discipline
            finally:
                _put(_END)

        n_loaders = self.controller.n if self.controller else self.num_loaders
        _POOL.set(n_loaders)
        threads = [
            threading.Thread(target=loader, args=(i,), daemon=True)
            for i in range(n_loaders)
        ]
        for t in threads:
            t.start()

        mode = ("train" if wtype == WorkType.TRAIN else "eval")
        step = (self.learner.train_batch if mode == "train"
                else self.learner.eval_batch)
        done_loaders = 0
        last_print = time.time()
        n_steps = 0
        t_step = 0.0
        stall_s = 0.0
        gets = 0
        high = 0
        t_pass0 = time.perf_counter()
        _pyprof.tag_thread("train")
        if self.verbose:
            self._log(f"{mode} pass {data_pass}: {data}")
            self._log(Progress.header())
        try:
            with _trace.span(f"solver.{mode}_pass", cat="solver",
                             data_pass=data_pass):
                while done_loaders < len(threads):
                    depth = q.qsize()
                    _QDEPTH.set(depth)
                    gets += 1
                    if depth >= max(1, self.max_queued // 2):
                        high += 1
                    t_w = time.perf_counter()
                    item = q.get()
                    dw = time.perf_counter() - t_w
                    self.perf.add("wait", dw)
                    stall_s += dw
                    _STALL.set(stall_s)
                    if item is _END:
                        done_loaders += 1
                        continue
                    t_s = time.perf_counter()
                    with _trace.span(f"solver.{mode}_step", cat="solver"):
                        out = step(item)
                    dt = time.perf_counter() - t_s
                    self.perf.add(f"{mode}_step", dt)
                    t_step += dt
                    n_steps += 1
                    t_m = time.perf_counter()
                    prog.merge(out)
                    if self.verbose \
                            and time.time() - last_print >= cfg.print_sec:
                        self._log(prog.row(self.t0))
                        last_print = time.time()
                    if train:
                        dm = time.perf_counter() - t_m
                        _ST_LOAD.observe(dw)
                        _ST_STEP.observe(dt)
                        _ST_METRICS.observe(dm)
                        _ST_TOTAL.observe(dw + dt + dm)
        finally:
            stop.set()
            for t in threads:
                t.join()
        if errors:
            raise errors[0]
        if self.verbose:
            self._log(prog.row(self.t0))
        wall = time.perf_counter() - t_pass0
        self.last_pass_stall_s = stall_s
        self.last_pass_wall_s = wall
        if n_steps:
            # FinishMinibatch-style pass summary (minibatch_solver.h:
            # 246-275): average device-step time and the share of wall
            # time spent outside compute (I/O + parse + any PS sync)
            overhead = max(0.0, 100.0 * (1.0 - t_step / max(wall, 1e-9)))
            self._log(
                f"{mode} pass {data_pass}: {n_steps} minibatches, "
                f"avg {1e3 * t_step / n_steps:.1f}ms/step, "
                f"{overhead:.0f}% io/comm overhead, "
                f"wall {wall:.2f}s")
        if self.pack_cache is not None:
            s = self.pack_cache.stats()
            self._log(
                f"[loader] pack cache: {s['hits']} hits / "
                f"{s['misses']} misses ({100 * s['hit_rate']:.0f}%), "
                f"mem {s['mem_bytes'] >> 20}MB/{s['mem_entries']} entries")
        if self.controller is not None:
            self.controller.record_pass(
                stall_s, wall, n_steps, high / max(gets, 1))
            d = self.controller.decisions[-1]
            if d["from"] != d["to"]:
                self._log(
                    f"[loader] controller: {d['from']} -> {d['to']} "
                    f"loaders ({d['why']}, stall "
                    f"{100 * d['stall_frac']:.0f}% of wall, queue "
                    f">=half-full {100 * d['queue_high_frac']:.0f}% "
                    f"of gets)")
        return prog

    # ------------------------------------------------------------- predict
    def predict(self, data: str, out_base: str) -> list[str]:
        """One PRED pass; margins written one file per part
        (iter_solver.h:140-156; users concatenate, criteo_kaggle.rst:97)."""
        cfg = self.cfg
        pool = WorkloadPool()
        if pool.add(data, cfg.num_parts_per_file, cfg.data_format) == 0:
            raise FileNotFoundError(f"no files match {data}")
        os.makedirs(os.path.dirname(out_base) or ".", exist_ok=True)
        out_files = []
        while True:
            got = pool.get("predictor")
            if got is None:
                break
            part_id, f = got
            path = f"{out_base}_part-{part_id}"
            with open(path, "w") as fh:
                for blk in MinibatchIter(
                    f.filename, f.part, f.num_parts, f.format,
                    minibatch_size=cfg.minibatch,
                ):
                    for m in self.learner.predict_batch(blk):
                        fh.write(f"{m:.6g}\n")
            out_files.append(path)
            pool.finish(part_id)
        return out_files

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(msg, flush=True)
