"""The train/val/predict pass loop: scheduler + streaming workers.

Parity with reference learn/solver/minibatch_solver.h + iter_solver.h:
- `run()` drives `max_data_pass` passes of TRAIN then VAL, with model
  load before (model_in / load_iter) and saves during (save_iter) and
  after (model_out) — minibatch_solver.h:85-137.
- each pass dispatches virtual file parts from a WorkloadPool to loader
  workers (data_parallel.h:93-115); here workers are host threads that
  parse minibatches into a bounded queue (the max_concurrency
  backpressure of minibatch_solver.h:284-329) while the main thread runs
  the jitted device steps — async I/O under synchronous XLA steps.
- a progress row prints every print_sec (minibatch_solver.h:169-192) and
  a `stop()` hook supports early stopping (minibatch_solver.h:47-59).
- predict writes one output file per part (iter_solver.h:140-156).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from wormhole_tpu.data.minibatch import MinibatchIter
from wormhole_tpu.obs import report as _report
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.solver.progress import Progress
from wormhole_tpu.solver.workload import WorkloadPool, WorkType
from wormhole_tpu.utils import checkpoint as ckpt
from wormhole_tpu.utils.perf import Perf, maybe_trace


class MinibatchSolver:
    """Drives a learner (train_batch/eval_batch/predict_batch/store) over
    sharded files with pooled loading and failure re-queue. The pool's
    straggler watchdog is NOT started here: within one process, a
    re-queued part would be read twice and its examples double-trained;
    the watchdog is for the multi-host scheduler (launcher/dmlc_tpu.py)
    where a straggling host's parts move to another host."""

    def __init__(self, learner, cfg, num_loaders: int | None = None,
                 max_queued: int = 8, verbose: bool = True):
        if num_loaders is None:
            # the reference's max_concurrency knob (minibatch_solver.h:
            # 215-242): concurrently-prepared in-flight minibatches
            num_loaders = getattr(cfg, "max_concurrency", 2)
        self.learner = learner
        self.cfg = cfg
        self.num_loaders = num_loaders
        self.max_queued = max_queued
        self.verbose = verbose
        self.t0 = time.time()
        # early-stop hook: (pass progress, data_pass, type) -> bool
        self.stop_hook: Optional[Callable] = None
        # per-op perf accounting (reference minibatch_solver.h:246-275 +
        # difacto async_sgd.h:108-127 style)
        self.perf = Perf(log=self._log)

    @property
    def _ckpt_store(self):
        # learners with multiple KV stores expose a combined adapter
        return getattr(self.learner, "ckpt_store", None) or self.learner.store

    # ----------------------------------------------------------------- run
    def run(self) -> dict:
        cfg = self.cfg
        if cfg.model_in:
            ckpt.load_model(self._ckpt_store, cfg.model_in,
                            cfg.load_iter if cfg.load_iter >= 0 else None)
        result = {}
        with maybe_trace("minibatch_solver"):
            result = self._run_passes(cfg)
        if _report.enabled() and not os.environ.get("WH_ROLE"):
            # single-process run: no scheduler to aggregate, so this
            # process's registry IS the whole job — write the report
            # directly (distributed runs get it from apps/_runner.py)
            path = _report.write(_report.build_local())
            self._log(f"[obs] run report written: {path}")
        return result

    def _run_passes(self, cfg) -> dict:
        result = {}
        for dp in range(cfg.max_data_pass):
            tr = self.iterate(cfg.train_data, WorkType.TRAIN, dp)
            result["train"] = tr
            if cfg.val_data:
                vl = self.iterate(cfg.val_data, WorkType.VAL, dp)
                result["val"] = vl
            if cfg.model_out and cfg.save_iter > 0 and (
                (dp + 1) % cfg.save_iter == 0 and dp + 1 < cfg.max_data_pass
            ):
                ckpt.save_model(self._ckpt_store, cfg.model_out, dp)
            if self._should_stop(result, dp):
                self._log(f"early stop after pass {dp}")
                break
        if cfg.model_out:
            ckpt.save_model(self._ckpt_store, cfg.model_out)
        if getattr(cfg, "predict_out", None):
            self.predict(cfg.val_data or cfg.train_data, cfg.predict_out)
        return result

    def _should_stop(self, result: dict, dp: int) -> bool:
        if self.stop_hook is None:
            return False
        key = "val" if "val" in result else "train"
        return bool(self.stop_hook(result[key], dp, key))

    # ------------------------------------------------------------- iterate
    def iterate(self, data: str, wtype: WorkType, data_pass: int = 0) -> Progress:
        cfg = self.cfg
        hook = getattr(self.learner, "on_pass_start", None)
        if hook:
            hook()
        pool = WorkloadPool()
        nfiles = pool.add(data, cfg.num_parts_per_file, cfg.data_format)
        if nfiles == 0:
            raise FileNotFoundError(f"no files match {data}")
        prog = Progress()
        if hasattr(self.learner, "nnz"):
            # seed the pass with the model's standing |w|_0 so the row's
            # sparsity column is cumulative across passes like the
            # reference log (progress.h:10-35), not per-pass deltas;
            # one host reduction per pass, not per row
            prog.merge({"new_w": float(self.learner.nnz())})
            prog.take_increment()
        q: queue.Queue = queue.Queue(maxsize=self.max_queued)
        _END = object()
        errors: list[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            """Bounded put that gives up when the consumer is gone, so a
            failed main-thread step can't park loaders on a full queue."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def loader(node_id: int):
            try:
                while not stop.is_set():
                    got = pool.get(f"loader-{node_id}")
                    if got is None:
                        return
                    part_id, f = got
                    it = MinibatchIter(
                        f.filename, f.part, f.num_parts, f.format,
                        minibatch_size=cfg.minibatch,
                        shuf_buf=(cfg.rand_shuffle * cfg.minibatch
                                  if wtype == WorkType.TRAIN else 0),
                        neg_sampling=(cfg.neg_sampling
                                      if wtype == WorkType.TRAIN else 1.0),
                        seed=data_pass * 7919 + part_id,
                    )
                    prepare = getattr(self.learner, "prepare_batch", None)
                    for blk in it:
                        # host-side batch prep (padding + pallas tile-sort)
                        # happens here in the loader thread, overlapped with
                        # the main thread's device steps
                        if prepare:
                            with self.perf.timer("prepare"):
                                blk = prepare(
                                    blk, train=(wtype == WorkType.TRAIN))
                        if not _put(blk):
                            return
                    pool.finish(part_id)
            except BaseException as e:
                errors.append(e)
            finally:
                _put(_END)

        threads = [
            threading.Thread(target=loader, args=(i,), daemon=True)
            for i in range(self.num_loaders)
        ]
        for t in threads:
            t.start()

        mode = ("train" if wtype == WorkType.TRAIN else "eval")
        step = (self.learner.train_batch if mode == "train"
                else self.learner.eval_batch)
        done_loaders = 0
        last_print = time.time()
        n_steps = 0
        t_step = 0.0
        t_pass0 = time.perf_counter()
        if self.verbose:
            self._log(f"{mode} pass {data_pass}: {data}")
            self._log(Progress.header())
        try:
            with _trace.span(f"{mode}_pass", cat="solver",
                             data_pass=data_pass):
                while done_loaders < len(threads):
                    t_w = time.perf_counter()
                    item = q.get()
                    self.perf.add("wait", time.perf_counter() - t_w)
                    if item is _END:
                        done_loaders += 1
                        continue
                    t_s = time.perf_counter()
                    with _trace.span(f"{mode}_step", cat="solver"):
                        prog.merge(step(item))
                    dt = time.perf_counter() - t_s
                    self.perf.add(f"{mode}_step", dt)
                    t_step += dt
                    n_steps += 1
                    if self.verbose \
                            and time.time() - last_print >= cfg.print_sec:
                        self._log(prog.row(self.t0))
                        last_print = time.time()
        finally:
            stop.set()
            for t in threads:
                t.join()
        if errors:
            raise errors[0]
        if self.verbose:
            self._log(prog.row(self.t0))
        if n_steps:
            # FinishMinibatch-style pass summary (minibatch_solver.h:
            # 246-275): average device-step time and the share of wall
            # time spent outside compute (I/O + parse + any PS sync)
            wall = time.perf_counter() - t_pass0
            overhead = max(0.0, 100.0 * (1.0 - t_step / max(wall, 1e-9)))
            self._log(
                f"{mode} pass {data_pass}: {n_steps} minibatches, "
                f"avg {1e3 * t_step / n_steps:.1f}ms/step, "
                f"{overhead:.0f}% io/comm overhead, "
                f"wall {wall:.2f}s")
        return prog

    # ------------------------------------------------------------- predict
    def predict(self, data: str, out_base: str) -> list[str]:
        """One PRED pass; margins written one file per part
        (iter_solver.h:140-156; users concatenate, criteo_kaggle.rst:97)."""
        cfg = self.cfg
        pool = WorkloadPool()
        if pool.add(data, cfg.num_parts_per_file, cfg.data_format) == 0:
            raise FileNotFoundError(f"no files match {data}")
        os.makedirs(os.path.dirname(out_base) or ".", exist_ok=True)
        out_files = []
        while True:
            got = pool.get("predictor")
            if got is None:
                break
            part_id, f = got
            path = f"{out_base}_part-{part_id}"
            with open(path, "w") as fh:
                for blk in MinibatchIter(
                    f.filename, f.part, f.num_parts, f.format,
                    minibatch_size=cfg.minibatch,
                ):
                    for m in self.learner.predict_batch(blk):
                        fh.write(f"{m:.6g}\n")
            out_files.append(path)
            pool.finish(part_id)
        return out_files

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(msg, flush=True)
