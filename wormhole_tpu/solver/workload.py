"""Workload descriptors and the scheduler's work-pool.

Parity with reference learn/base/workload.h + workload_pool.h: a Workload
is a serializable list of (file, part k of n, format) with a pass number
and TRAIN/VAL/PRED type; the WorkloadPool is the scheduler's thread-safe
queue of virtual file parts with per-part state (available / assigned /
done), node affinity for worker-local data, failure re-queue, and a
straggler watchdog that re-assigns jobs running longer than
max(2 x mean, 5s) once enough samples exist (workload_pool.h:29-34,176-197).

On TPU the "workers" this pool feeds are host-side data-loading tasks
(one per device group or per prefetch thread); the pool semantics —
elastic work stealing, straggler kill, failure re-queue — are unchanged.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from enum import IntEnum
from typing import Callable, Optional

from wormhole_tpu.data.match_file import match_file


class WorkType(IntEnum):
    TRAIN = 1
    VAL = 2
    PRED = 3


@dataclasses.dataclass
class File:
    """One virtual part of one file (workload.h:40-52)."""

    filename: str
    format: str = "libsvm"
    part: int = 0
    num_parts: int = 1

    def __str__(self) -> str:  # debug parity with workload.h ShortDebugString
        return f"{self.filename} {self.part}/{self.num_parts} ({self.format})"


@dataclasses.dataclass
class Workload:
    """A unit of work sent to a worker (workload.h:15-38)."""

    files: list = dataclasses.field(default_factory=list)
    type: WorkType = WorkType.TRAIN
    data_pass: int = 0

    @property
    def empty(self) -> bool:
        return not self.files


def iter_rowblocks(pattern: str, num_parts_per_file: int = 1,
                   fmt: str = "libsvm", minibatch_size: int = 65536,
                   node: str = "loader", seed: int = 0):
    """Drain a one-shot WorkloadPool over `pattern`, yielding RowBlocks —
    the shared pool.add -> get -> MinibatchIter -> finish protocol used by
    every batch learner (the reference's RowBlockIter(rank, world) path,
    kmeans.cc:149-154, lbfgs.cc:229-234)."""
    from wormhole_tpu.data.minibatch import MinibatchIter

    for f in iter_parts(pattern, num_parts_per_file, fmt, node):
        yield from MinibatchIter(f.filename, f.part, f.num_parts, f.format,
                                 minibatch_size=minibatch_size, seed=seed)


def iter_parts(pattern: str, num_parts_per_file: int = 1,
               fmt: str = "libsvm", node: str = "loader"):
    """Yield the File parts `pattern` expands to, through the same
    one-shot pool.add -> get -> finish protocol — for callers that need
    the part boundary itself (e.g. per-part pack-cache keys) rather
    than a flat RowBlock stream."""
    pool = WorkloadPool()
    if pool.add(pattern, num_parts_per_file, fmt) == 0:
        raise FileNotFoundError(f"no files match {pattern}")
    while (got := pool.get(node)) is not None:
        part_id, f = got
        yield f
        pool.finish(part_id)


_STRAGGLER_MIN_SAMPLES = 10
_STRAGGLER_FLOOR_SEC = 5.0


class WorkloadPool:
    """Thread-safe pool of file parts (workload_pool.h).

    States per part: 0 = available, 1 = assigned, 2 = done. Supports
    - Add(pattern/files, num_parts_per_file): regex-match + split
    - Get(node): hand one part to a node (random pick among available)
    - Finish(part_id): mark done, record duration
    - Reset(node): re-queue everything a failed node held
      (the ps-lite node-failure hook path, data_parallel.h:131-135)
    - straggler watchdog thread (start_straggler_killer)
    """

    def __init__(self, straggler: bool = False):
        self._lock = threading.Lock()
        self._parts: list[dict] = []  # {file, state, node, t_start, time}
        self._durations: list[float] = []
        self._straggler = straggler
        self._watchdog: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.num_finished = 0
        # Journal hook: called with the list of part ids the straggler
        # watchdog just re-queued, OUTSIDE the pool lock (the callback
        # may take other locks — e.g. append to the scheduler journal).
        self.on_requeue: Optional[Callable[[list], None]] = None

    # -- filling ------------------------------------------------------------
    def add(self, pattern: str, num_parts_per_file: int, fmt: str = "libsvm",
            shuffle: bool = False, seed: int = 0,
            node: Optional[str] = None) -> int:
        return self.add_files(match_file(pattern), num_parts_per_file, fmt,
                              shuffle, seed, node)

    def add_files(self, files: list, num_parts_per_file: int,
                  fmt: str = "libsvm", shuffle: bool = False, seed: int = 0,
                  node: Optional[str] = None) -> int:
        """Add concrete files. With `node`, the parts get node affinity —
        only that node may be handed them; a file reported by several
        nodes accumulates all of them in its capable set (worker-local
        data, reference workload_pool.h:49-61 Add(id) + :141,155 Get
        filtering)."""
        with self._lock:
            existing = {(p["file"].filename, p["file"].part): p
                        for p in self._parts}
            for f in files:
                for k in range(num_parts_per_file):
                    p = existing.get((f, k))
                    if p is not None:
                        if node:
                            p["affinity"].add(node)
                        continue
                    self._parts.append(
                        dict(file=File(f, fmt, k, num_parts_per_file),
                             state=0, node=None, t_start=0.0,
                             affinity=({node} if node else set()),
                             pin=None, mepoch=None)
                    )
            if shuffle:
                random.Random(seed).shuffle(self._parts)
            return len(files)

    def assign_stable(self, nodes: list) -> None:
        """Batch dispatch mode (reference data_parallel.h:54-60): give
        every part a single fixed owner, round-robin over `nodes` in part
        order — the same stable n/num_workers assignment each pass. Pins
        are preferences (any node CAN read the data), so a dead owner's
        pins are cleared by drop_node rather than stranding the parts."""
        with self._lock:
            for i, p in enumerate(self._parts):
                p["pin"] = nodes[i % len(nodes)]

    def clear(self) -> None:
        with self._lock:
            self._parts.clear()
            self._durations.clear()
            self.num_finished = 0

    # -- dispatch -----------------------------------------------------------
    def get(self, node: str,
            mepoch: Optional[int] = None) -> Optional[tuple[int, File]]:
        """Assign one available part to `node`; None when nothing avail.
        Parts with a non-empty capable set only go to nodes in it
        (workload_pool.h:141,155). `mepoch` stamps the assignment with
        the membership epoch it was made under — the fence finish()
        checks."""
        with self._lock:
            avail = [i for i, p in enumerate(self._parts)
                     if p["state"] == 0
                     and (not p["affinity"] or node in p["affinity"])
                     and (p["pin"] is None or p["pin"] == node)]
            if not avail:
                return None
            i = random.choice(avail)
            p = self._parts[i]
            p.update(state=1, node=node, t_start=time.monotonic(),
                     mepoch=mepoch)
            return i, p["file"]

    def assign_part(self, part_id: int, node: str,
                    mepoch: Optional[int] = None) -> None:
        """Re-apply a journaled assignment during scheduler replay.
        `get` picks randomly, so replay applies the recorded choice
        instead of re-rolling. Idempotent: a part already done (the
        snapshot raced ahead of the journal record) is left alone."""
        with self._lock:
            p = self._parts[part_id]
            if p["state"] == 2:
                return
            p.update(state=1, node=node, t_start=time.monotonic(),
                     mepoch=mepoch)

    def requeue_parts(self, part_ids: list) -> None:
        """Re-apply a journaled straggler re-queue during replay: owner
        cleared but the membership stamp KEPT, so the slow owner's late
        finish can still land (mirrors remove_stragglers)."""
        with self._lock:
            for i in part_ids:
                p = self._parts[i]
                if p["state"] == 1:
                    p.update(state=0, node=None)

    def export_state(self) -> dict:
        """Serializable pool state for the scheduler journal/snapshot."""
        with self._lock:
            return {
                "parts": [
                    dict(file=dataclasses.asdict(p["file"]),
                         state=p["state"], node=p["node"],
                         affinity=sorted(p["affinity"]), pin=p["pin"],
                         mepoch=p["mepoch"])
                    for p in self._parts
                ],
                "durations": list(self._durations),
                "num_finished": self.num_finished,
                "num_skipped": getattr(self, "num_skipped", 0),
            }

    def load_state(self, state: dict) -> None:
        """Restore export_state() output. Assigned parts get a fresh
        t_start so a long scheduler outage does not trip the straggler
        watchdog the instant the pool comes back."""
        now = time.monotonic()
        with self._lock:
            self._parts = [
                dict(file=File(**p["file"]), state=p["state"],
                     node=p["node"], t_start=now,
                     affinity=set(p["affinity"]), pin=p["pin"],
                     mepoch=p["mepoch"])
                for p in state.get("parts", [])
            ]
            self._durations = [float(d) for d in state.get("durations", [])]
            self.num_finished = int(state.get("num_finished", 0))
            if state.get("num_skipped"):
                self.num_skipped = int(state["num_skipped"])

    def finish(self, part_id: int, node: Optional[str] = None,
               mepoch: Optional[int] = None) -> bool:
        """Mark done; False if a straggler twin already finished it (the
        caller must not double-count its progress).

        With `node`, the completion is FENCED: it only counts if the
        part still belongs to this node — or was merely re-queued by
        the straggler watchdog (owner cleared but the membership stamp
        intact, in which case the original owner's late finish is the
        work arriving). A node declared DEAD had its parts reset with
        the stamp cleared, so its late completions are rejected even
        though the part sits unassigned — the double-apply hole the
        membership epoch closes. Callers without node/mepoch keep the
        legacy accept-any semantics (in-process pools)."""
        with self._lock:
            p = self._parts[part_id]
            if p["state"] == 2:
                return False
            if node is not None:
                owned = p["node"] == node
                requeued_twin = (p["node"] is None
                                 and p["mepoch"] is not None
                                 and p["mepoch"] == mepoch)
                if not (owned or requeued_twin):
                    return False
            p["state"] = 2
            self._durations.append(time.monotonic() - p["t_start"])
            self.num_finished += 1
            return True

    def reset(self, node: str) -> int:
        """Re-queue parts assigned to a dead node; returns count. The
        membership stamp is cleared: a reset part's original assignment
        is fenced for good (unlike a straggler re-queue, which keeps
        the stamp so the slow owner's work can still land)."""
        n = 0
        with self._lock:
            for p in self._parts:
                if p["state"] == 1 and p["node"] == node:
                    p.update(state=0, node=None, mepoch=None)
                    n += 1
        return n

    def repin(self, nodes: list) -> int:
        """Membership changed: re-pin batch-mode pinned parts round-robin
        over the surviving/new node set. Idempotent — pin follows part
        order, so a repeat call with the same set changes nothing.
        Online-mode pools (no pins) are untouched. Returns the number of
        pins that moved."""
        if not nodes:
            return 0
        moved = 0
        with self._lock:
            k = 0
            for p in self._parts:
                if p["pin"] is None:
                    continue
                want = nodes[k % len(nodes)]
                k += 1
                if p["pin"] != want:
                    p["pin"] = want
                    moved += 1
        return moved

    def drop_node(self, node: str) -> tuple[int, int]:
        """A node left for good: release its batch-mode pins (anyone can
        take those parts) and remove it from capability sets; parts ONLY
        it could read become unreachable and are marked skipped so the
        round can still end — the reference loses a dead node's local
        disk the same way. Returns (pins_released, parts_skipped)."""
        released = skipped = 0
        with self._lock:
            for p in self._parts:
                if p["pin"] == node:
                    p["pin"] = None
                    released += 1
                if node in p["affinity"]:
                    p["affinity"].discard(node)
                    if not p["affinity"] and p["state"] != 2:
                        p.update(state=2, node=None)
                        skipped += 1
            self.num_skipped = getattr(self, "num_skipped", 0) + skipped
        return released, skipped

    def is_finished(self) -> bool:
        """An empty pool is NOT finished — it is a pool that has not been
        filled (or was just cleared mid-round-change); callers polling it
        must keep waiting rather than conclude the round is over."""
        with self._lock:
            return bool(self._parts) and all(
                p["state"] == 2 for p in self._parts)

    def size(self) -> int:
        with self._lock:
            return len(self._parts)

    def pending(self) -> int:
        with self._lock:
            return sum(1 for p in self._parts if p["state"] != 2)

    # -- straggler watchdog -------------------------------------------------
    def remove_stragglers(self) -> int:
        """Re-queue assigned parts running > max(2 x mean, 5s); only when
        >= 10 finished samples exist (workload_pool.h:176-197)."""
        requeued: list[int] = []
        with self._lock:
            if len(self._durations) < _STRAGGLER_MIN_SAMPLES:
                return 0
            mean = sum(self._durations) / len(self._durations)
            limit = max(2 * mean, _STRAGGLER_FLOOR_SEC)
            now = time.monotonic()
            for i, p in enumerate(self._parts):
                if p["state"] == 1 and now - p["t_start"] > limit:
                    p.update(state=0, node=None)
                    requeued.append(i)
        if requeued and self.on_requeue is not None:
            self.on_requeue(requeued)
        return len(requeued)

    def start_straggler_killer(self, interval: float = 2.0) -> None:
        if self._watchdog is not None:
            return

        def loop():
            while not self._stop.wait(interval):
                self.remove_stragglers()

        self._watchdog = threading.Thread(target=loop, daemon=True)
        self._watchdog.start()

    def stop_straggler_killer(self) -> None:
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)
            self._watchdog = None
        self._stop = threading.Event()
