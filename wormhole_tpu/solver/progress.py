"""Mergeable progress reporting.

The reference pushes std::vector<double> progress from workers/servers to
the scheduler's monitor channel, which sums them since the last read and
prints a row every print_sec (ps::Root/Slave, reference iter_solver.h:62,
120,164; minibatch_solver.h:169-192). Here the "channel" is in-process:
learner steps return per-batch metric dicts that merge by summation, and
the solver prints the same style of row.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class Progress:
    """Summed metric vector with reference-style row formatting
    (linear progress.h:10-35: #ex, logloss, acc, auc columns).

    Thread-safe: the scheduler merges from concurrent RPC handler threads
    while its main thread reads rows (ps::Root monitor parity)."""

    def __init__(self):
        self.tot: dict[str, float] = {}
        self._last: dict[str, float] = {}
        self._lock = threading.Lock()

    def merge(self, p: dict) -> None:  # wormlint: thread-entry
        with self._lock:
            for k, v in p.items():
                self.tot[k] = self.tot.get(k, 0.0) + float(v)

    def value(self, key: str) -> float:
        with self._lock:
            return self.tot.get(key, 0.0)

    def mean(self, key: str) -> float:
        with self._lock:
            n = self.tot.get("nex", 0.0)
            return self.tot.get(key, 0.0) / n if n else 0.0

    # incremental view: metrics since last row (the reference prints
    # per-interval increments, criteo_kaggle.rst:66-75)
    def take_increment(self) -> dict[str, float]:
        with self._lock:
            inc = {k: v - self._last.get(k, 0.0)
                   for k, v in self.tot.items()}
            self._last = dict(self.tot)
            return inc

    def take_row_snapshot(self) -> tuple[dict, dict]:
        """Consistent (increment, totals) pair under ONE lock hold.
        row() needs both; taking the increment and then reading
        self.tot unlocked let RPC handler threads merge in between, so
        a row could show totals that include examples its own increment
        did not — inc sums across rows would never reconcile with the
        final totals."""
        with self._lock:
            inc = {k: v - self._last.get(k, 0.0)
                   for k, v in self.tot.items()}
            self._last = dict(self.tot)
            return inc, dict(self.tot)

    @staticmethod
    def header() -> str:
        # column parity with the reference training log (linear
        # progress.h:10-35; criteo_kaggle.rst:66-75): |w|_0 is the running
        # model sparsity (cumulative new_w deltas the train step reports
        # device-side), COPC = clicks over expected clicks
        # (binary_class_evaluation.h:76-85)
        return (f"{'time':>8} {'#total_ex':>12} {'#inc_ex':>10} "
                f"{'|w|_0':>10} {'logloss':>9} {'accuracy':>9} "
                f"{'auc':>9} {'copc':>7}")

    def row(self, t0: float) -> str:
        inc, tot = self.take_row_snapshot()
        n = inc.get("nex", 0.0)
        def m(k):
            return inc.get(k, 0.0) / n if n else 0.0
        pclk = inc.get("pclk", 0.0)
        copc = inc.get("clk", 0.0) / pclk if pclk else 0.0
        return (f"{time.time() - t0:8.1f} {tot.get('nex', 0):12.0f} "
                f"{n:10.0f} {tot.get('new_w', 0):10.0f} "
                f"{m('logloss'):9.5f} {m('acc'):9.5f} "
                f"{m('auc'):9.5f} {copc:7.4f}")
