"""Batch L-BFGS / OWL-QN solver, TPU-native.

Parity target: reference learn/solver/lbfgs.h — vector-free L-BFGS with
backtracking line search and OWL-QN L1 handling: the weight vector and its
2m+1 history basis are partitioned across ranks (lbfgs.h:127-136,557-645),
global quantities are reconstructed from allreduced dot products
(:235-303), the line search evaluates the objective via allreduce per
trial (:321-356), and rabit checkpoints make iterations elastic
(:120,194).

TPU design: one process drives the whole mesh, so "partitioned across
ranks" becomes sharding the flat weight/history arrays over all devices
(models/batch_objectives.py pads num_dim to an even split). Each
iteration fetches ONE Gram matrix of the [S..., Y..., pg] basis — local
partial dots + an XLA psum, the same math as the reference's single
Allreduce<Sum> of the 5n dot-product vector (lbfgs.h:235-252) — then
runs the two-loop recursion on (2m+1)-sized host vectors and forms the
direction as one device linear combination. The objective accumulates
over device-resident data batches sharded on the data axis. Host Python
drives the outer iteration and the data-dependent line search (a host
loop of jitted evals, the analog of the reference's rank-coordinated
trials).

OWL-QN specifics (lbfgs.h:358-407): pseudo-gradient at w=0, direction
sign-fix against the pseudo-gradient, and orthant projection of each
line-search trial point.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Optional, Protocol

import jax
import jax.numpy as jnp
import numpy as np


class ObjFunction(Protocol):
    """The IObjFunction surface (reference lbfgs.h:23-52)."""

    num_dim: int

    def init_model(self) -> jax.Array: ...
    def eval(self, w: jax.Array) -> float: ...          # sum loss over data
    def grad(self, w: jax.Array) -> jax.Array: ...
    def l1_mask(self) -> jax.Array: ...  # 1 where L1 applies (not bias/V)


@dataclasses.dataclass
class LBFGSConfig:
    max_iter: int = 30
    m: int = 10                 # history pairs
    reg_l1: float = 0.0         # OWL-QN when > 0
    reg_l2: float = 0.0
    c1: float = 1e-4            # sufficient-decrease constant
    rho: float = 0.5            # backtracking factor
    alpha0: float = 1.0
    max_linesearch: int = 20
    min_rel_decrease: float = 1e-7  # convergence: relative objv decrease
    checkpoint_dir: Optional[str] = None


class LBFGSSolver:
    """Host-driven L-BFGS over device-sharded vectors.

    With `comm` set (a runtime/allreduce.py BspWorker), the solver runs
    the reference's distributed layout: parameters and history are
    REPLICATED per rank, data is partitioned, and the two data-dependent
    quantities — the gradient and the raw objective — allreduce over the
    worker ring (lbfgs.h:235-303,321-356). Every other scalar (Gram
    matrix, dots, line-search decisions) is computed from those reduced,
    bit-identical-across-ranks values, so all ranks drive the identical
    host loop in lockstep. Checkpoints ride the ring's version protocol
    (rabit CheckPoint parity): the state includes g and the objective
    history so a resumed worker SKIPS the init grad/eval recompute —
    which is what keeps its per-version collective counters aligned with
    the survivors'."""

    def __init__(self, obj: ObjFunction, cfg: LBFGSConfig, comm=None):
        self.obj = obj
        self.cfg = cfg
        self.comm = comm
        self.S: list[jax.Array] = []   # s_k = w_{k+1} - w_k
        self.Y: list[jax.Array] = []   # y_k = g_{k+1} - g_k
        self.iter = 0
        self.objv_history: list[float] = []

        l2 = cfg.reg_l2

        @jax.jit
        def full_obj(w, raw_loss):
            o = raw_loss + 0.5 * l2 * jnp.vdot(w, w)
            if cfg.reg_l1 > 0:
                o = o + cfg.reg_l1 * jnp.sum(
                    jnp.abs(w) * self.obj.l1_mask())
            return o

        @jax.jit
        def pseudo_gradient(w, g):
            """OWL-QN pseudo-gradient of reg_l1*|w| at w (SetL1Dir parity,
            lbfgs.h:358-378): at w=0 the subgradient closest to zero."""
            g = g + l2 * w
            if cfg.reg_l1 <= 0:
                return g
            m_ = self.obj.l1_mask()
            l1 = cfg.reg_l1
            gp = g + l1 * m_
            gm = g - l1 * m_
            pg_zero = jnp.where(gm > 0, gm, jnp.where(gp < 0, gp, 0.0))
            return jnp.where(
                (w == 0) & (m_ > 0), pg_zero,
                g + l1 * jnp.sign(w) * m_)

        @jax.jit
        def fix_dir_sign(d, pg):
            """Restrict direction to the descent orthant
            (FixDirL1Sign, lbfgs.h:380-389)."""
            return jnp.where(d * -pg > 0, d, 0.0) if cfg.reg_l1 > 0 else d

        @jax.jit
        def orthant_project(w_new, orthant):
            """Clip the trial point to the chosen orthant
            (FixWeightL1Sign, lbfgs.h:391-407)."""
            if cfg.reg_l1 <= 0:
                return w_new
            keep = w_new * orthant >= 0
            m_ = self.obj.l1_mask()
            return jnp.where(keep | (m_ == 0), w_new, 0.0)

        @jax.jit
        def gram(*vs):
            """B Bᵀ for the stacked basis [S..., Y..., pg]: every dot
            product the two-loop recursion needs, in ONE device program /
            ONE host fetch (the reference's single Allreduce<Sum> of the
            5n dot-product vector, lbfgs.h:235-252)."""
            B = jnp.stack(vs)
            return B @ B.T

        @jax.jit
        def combine(coef, *vs):
            return jnp.einsum("i,in->n", coef, jnp.stack(vs))

        self._gram = gram
        self._combine = combine
        self._full_obj = full_obj
        self._pseudo_gradient = pseudo_gradient
        self._fix_dir_sign = fix_dir_sign
        self._orthant_project = orthant_project
        # host-sync counter: every device->host scalar/array fetch the
        # solver makes (the quantity the reference minimizes by batching
        # dots into one allreduce; tests assert the fused path stays lean)
        self.host_syncs = 0

    def _fetch(self, x) -> float:
        self.host_syncs += 1
        return float(x)

    # -- two-loop recursion in basis coordinates (lbfgs.h:216-318) ----------
    def _direction(self, pg: jax.Array):
        """Returns (d, pg_dot_d_or_None). The search direction is computed
        vector-free: one Gram matrix of the [S..., Y..., pg] basis comes
        back to the host (ONE sync per iteration instead of ~4m), the
        two-loop recursion runs on (2m+1)-sized host vectors, and the
        result is a single device linear combination of the basis."""
        if not self.S:
            return -pg, None
        k = len(self.S)
        basis = self.S + self.Y + [pg]
        G = np.asarray(self._gram(*basis))
        self.host_syncs += 1
        coef = np.zeros(2 * k + 1)
        coef[2 * k] = -1.0  # q = -pg
        alphas = np.zeros(k)
        rhos = np.zeros(k)
        for i in range(k - 1, -1, -1):
            rhos[i] = 1.0 / G[i, k + i]            # 1 / (s_i . y_i)
            alphas[i] = rhos[i] * float(G[i] @ coef)   # rho (s_i . q)
            coef[k + i] -= alphas[i]               # q -= a y_i
        gamma = G[k - 1, 2 * k - 1] / G[2 * k - 1, 2 * k - 1]
        coef *= gamma
        for i in range(k):
            b = rhos[i] * float(G[k + i] @ coef)   # rho (y_i . q)
            coef[i] += alphas[i] - b               # q += (a - b) s_i
        d = self._combine(jnp.asarray(coef, jnp.float32), *basis)
        # pg . d is free from the same Gram: d = sum coef_i B_i
        return d, float(G[2 * k] @ coef)

    # -- one iteration (UpdateOneIter, lbfgs.h:168-196) ----------------------
    def _eval_full(self, w) -> float:
        """Full objective at w. The RAW data loss reduces over the ring
        BEFORE regularization: the reg terms are functions of the
        replicated w and must be added exactly once, not `world` times
        (the reference reduces sum_loss the same way, lbfgs.h:321-340)."""
        raw = self.obj.eval(w)
        if self.comm is not None:
            raw = np.float32(self.comm.allreduce(np.float32(raw)))
        return self._fetch(self._full_obj(w, raw))

    def _grad(self, w):
        """Gradient of the data loss: local accumulation, then one ring
        allreduce, re-placed under the objective's sharding (the single
        Allreduce<Sum> per iteration of lbfgs.h:194)."""
        g = self.obj.grad(w)
        if self.comm is not None:
            g = np.asarray(self.comm.allreduce(np.asarray(g)))
            place = getattr(self.obj, "place", None)
            g = place(jnp.asarray(g, jnp.float32)) if place else (
                jnp.asarray(g, jnp.float32))
        return g

    def run(self, verbose: bool = True) -> tuple[jax.Array, float]:
        cfg = self.cfg
        w, g, objv = self._try_resume()
        resumed = w is not None
        if not resumed:
            w = self.obj.init_model()
        # a full (comm/new-format) checkpoint carries g and the
        # objective history, so the resumed run skips both recomputes —
        # required in BSP mode for counter alignment, a free speedup
        # otherwise. Old file checkpoints (no g) just recompute.
        if g is None:
            g = self._grad(w)
        if objv is None:
            objv = self._eval_full(w)
        if not resumed:  # resumed history already ends with this objv
            self.objv_history.append(objv)
        if verbose:
            print(f"lbfgs {'resume' if resumed else 'init'}: "
                  f"objv {objv:.6f}", flush=True)

        while self.iter < cfg.max_iter:
            # convergence is judged from the (checkpointed) history at
            # the loop TOP, so a worker that died after the final
            # checkpoint resumes, observes the same convergence fact the
            # survivors did, and exits instead of ringing alone
            if len(self.objv_history) >= 2:
                prev, cur = self.objv_history[-2], self.objv_history[-1]
                rel = (prev - cur) / max(abs(prev), 1e-12)
                if 0 <= rel < cfg.min_rel_decrease:
                    if verbose:
                        print("lbfgs: converged", flush=True)
                    break
            pg = self._pseudo_gradient(w, g)
            d_raw, gd_raw = self._direction(pg)
            d = self._fix_dir_sign(d_raw, pg)

            # orthant for this step: sign(w), or -sign(pg) where w == 0
            orthant = jnp.where(w != 0, jnp.sign(w), -jnp.sign(pg))

            # backtracking line search (lbfgs.h:321-356). pg.d falls out
            # of the direction's Gram matrix except when the OWL-QN
            # sign-fix altered d
            if cfg.reg_l1 > 0 or gd_raw is None:
                gd = self._fetch(jnp.vdot(pg, d))
            else:
                gd = gd_raw
            if gd >= 0:  # not a descent direction: reset history
                self.S.clear()
                self.Y.clear()
                d = -pg
                gd = self._fetch(jnp.vdot(pg, d))
            alpha = cfg.alpha0
            w_new, objv_new, ok = w, objv, False
            for _ in range(cfg.max_linesearch):
                trial = self._orthant_project(w + alpha * d, orthant)
                o = self._eval_full(trial)
                if o <= objv + cfg.c1 * alpha * gd:
                    w_new, objv_new, ok = trial, o, True
                    break
                alpha *= cfg.rho
            if not ok:
                if verbose:
                    print("lbfgs: line search failed, stopping", flush=True)
                break

            g_new = self._grad(w_new)
            s = w_new - w
            y = (g_new + cfg.reg_l2 * w_new) - (g + cfg.reg_l2 * w)
            if self._fetch(jnp.vdot(s, y)) > 1e-10:
                self.S.append(s)
                self.Y.append(y)
                if len(self.S) > cfg.m:
                    self.S.pop(0)
                    self.Y.pop(0)
            w, g, objv = w_new, g_new, objv_new
            self.iter += 1
            self.objv_history.append(objv)
            if verbose:
                print(f"lbfgs iter {self.iter}: objv {objv:.6f} "
                      f"alpha {alpha:.3g}", flush=True)
            self._checkpoint(w, g)
        return w, objv

    # -- elastic state (rabit CheckPoint parity, lbfgs.h:120,194) -----------
    def _state(self, w, g) -> dict:
        dim = getattr(self.obj, "num_dim_padded", self.obj.num_dim)
        return dict(
            w=np.asarray(w),
            g=np.asarray(g),
            iter=np.int64(self.iter),
            objv=np.asarray(self.objv_history, dtype=np.float64),
            S=np.stack([np.asarray(s) for s in self.S])
            if self.S else np.zeros((0, dim)),
            Y=np.stack([np.asarray(y) for y in self.Y])
            if self.Y else np.zeros((0, dim)),
        )

    def _checkpoint(self, w, g) -> None:
        if self.comm is not None:
            # version-stamped ring checkpoint: bumps (version, seq) on
            # every rank in lockstep and persists under the launcher's
            # snapshot dir for the respawned incarnation
            self.comm.checkpoint(self._state(w, g))
            return
        cdir = self.cfg.checkpoint_dir
        if not cdir:
            return
        from wormhole_tpu.utils.checkpoint import atomic_savez

        os.makedirs(cdir, exist_ok=True)
        atomic_savez(os.path.join(cdir, "lbfgs_state.npz"),
                     **self._state(w, g))

    def _restore_vec(self, v):
        """Re-place a checkpointed vector under the CURRENT objective:
        strip any old sharding padding (padding is provably zero) and
        let place() re-pad and shard for this mesh, so a checkpoint
        moves between device counts and resumed state keeps the
        non-replicated sharding."""
        v = np.asarray(v)[: self.obj.num_dim]
        place = getattr(self.obj, "place", None)
        return place(jnp.asarray(v, jnp.float32)) if place else (
            jnp.asarray(v, jnp.float32))

    def _try_resume(self):
        """Returns (w, g, objv) — g/objv None when the checkpoint
        predates them (old file format) and must be recomputed."""
        if self.comm is not None:
            st = self.comm.load_checkpoint()
        else:
            cdir = self.cfg.checkpoint_dir
            if not cdir:
                return None, None, None
            path = os.path.join(cdir, "lbfgs_state.npz")
            if not os.path.exists(path):
                return None, None, None
            st = dict(np.load(path))
        if st is None:
            return None, None, None
        self.iter = int(st["iter"])
        self.objv_history = list(st["objv"])
        self.S = [self._restore_vec(s) for s in st["S"]]
        self.Y = [self._restore_vec(y) for y in st["Y"]]
        g = self._restore_vec(st["g"]) if "g" in st else None
        objv = self.objv_history[-1] if (
            "g" in st and self.objv_history) else None
        return self._restore_vec(st["w"]), g, objv
