"""KVStore: the parameter server, TPU-native.

ps-lite's server group (reference: OnlineServer + per-key Handle state,
learn/linear/async_sgd.h:200-226; key sharding across `-s` servers) becomes
a set of fixed-capacity hashed tables living as named-sharded jax Arrays in
HBM, bucket dimension sharded over the mesh "model" axis:

- ZPull (worker pulls weights for its minibatch's keys,
  async_sgd.h:277-287)  -> `jnp.take` of bucket rows inside the jitted
  step; XLA turns the cross-shard gather into ICI collectives.
- ZPush (worker pushes gradients, key-sharded scatter)  -> segment-sum of
  per-nonzero contributions into table layout + a sharding constraint, so
  XLA reduce-scatters gradients onto the owning model shard before the
  update runs shard-local.
- server Handle (FTRL/AdaGrad per-key update logic)  -> a functional
  update step over the state pytree, written by each learner.
- message filters (fixed-point/compressing transfer,
  async_sgd.h:290-301)  -> dtype quantization of the pushed gradient.

State is functional: learners thread `store.state` (a dict of arrays)
through jitted steps and assign back. Save/load uses one npz per model
shard with the reference's part naming (see utils/checkpoint.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.parallel.mesh import table_sharding

_GATHER_S = _obs.REGISTRY.histogram("kv.gather_s")
_SCATTER_S = _obs.REGISTRY.histogram("kv.scatter_s")
_GATHER_ROWS = _obs.REGISTRY.counter("kv.gather_rows")
_SCATTER_ROWS = _obs.REGISTRY.counter("kv.scatter_rows")
_JIT_MISSES = _obs.REGISTRY.counter("kv.jit_cache_misses")


@dataclasses.dataclass
class TableSpec:
    """One named state table: shape = (num_buckets, *tail).

    `wire_cap` floors the wire encoding of this table's PUSH deltas:
    "bf16" means WH_WIRE=int8/int4 still ships this table at bf16.
    Second-moment / count accumulators (FTRL n, difacto n/cnt/nV) need
    it: their per-sync deltas are nonnegative with huge dynamic range
    (a hot bucket's n grows ~minibatch per sync while a cold bucket's
    grows ~1), so an absmax group code quantizes the cold buckets at
    the hot neighbor's granularity — mis-scaling their per-coordinate
    learning rates, which error feedback cannot undo (EF repairs the
    accumulated STATE over rounds, not the optimizer trajectory already
    taken at the wrong rate). bf16's per-element relative precision
    (~0.4%) is safe at any magnitude. Sign-mixed gradient-like streams
    (z, V) keep the full int8/int4+EF treatment."""

    tail: tuple = ()
    dtype: object = jnp.float32
    init: Optional[Callable] = None  # (key, shape, dtype) -> array; 0 if None
    wire_cap: str = ""  # "" (no floor) or "bf16"


class KVStore:
    """Hashed, mesh-sharded parameter/optimizer state tables."""

    def __init__(
        self,
        mesh: Mesh,
        num_buckets: int,
        specs: dict[str, TableSpec],
        seed: int = 0,
    ):
        self.mesh = mesh
        self.num_buckets = int(num_buckets)
        self.specs = dict(specs)
        nshards = mesh.shape.get("model", 1)
        assert self.num_buckets % max(nshards, 1) == 0, (
            f"num_buckets {num_buckets} must divide over {nshards} model shards"
        )
        key = jax.random.PRNGKey(seed)
        self.state: dict[str, jax.Array] = {}
        for name, spec in self.specs.items():
            shape = (self.num_buckets, *spec.tail)
            sh = table_sharding(mesh, ndim=len(shape))
            key, sub = jax.random.split(key)
            if spec.init is None:
                arr = jax.jit(
                    lambda: jnp.zeros(shape, spec.dtype), out_shardings=sh
                )()
            else:
                init = spec.init
                arr = jax.jit(
                    lambda sub=sub, init=init: init(sub, shape, spec.dtype),
                    out_shardings=sh,
                )()
            self.state[name] = arr
        # jitted gather/scatter caches, keyed by the _pad_pow2 padded
        # length (and table set / name). jax.jit caches per shape
        # internally, but an explicit per-size entry makes the compile
        # set countable: kv.jit_cache_misses stays flat once every
        # padded size in the touched-row distribution has been seen, so
        # the lab can show steady-state compilation is zero.
        self._gather_fns: dict[int, Callable] = {}
        self._multi_gather_fns: dict[tuple, Callable] = {}
        self._scatter_fns: dict[tuple, Callable] = {}

    # -- helpers used inside learner-jitted steps ---------------------------
    def sharding(self, name: str):
        return table_sharding(
            self.mesh, ndim=1 + len(self.specs[name].tail)
        )

    def constrain(self, name: str, arr):
        """Pin an intermediate (e.g. a dense gradient in table layout) to
        the table's sharding so XLA reduce-scatters it to the owning shard
        (the ZPush key-routing)."""
        return jax.lax.with_sharding_constraint(arr, self.sharding(name))

    def update(self, new_state: dict[str, jax.Array]) -> None:
        assert set(new_state) == set(self.state), "state keys changed"
        self.state = new_state

    # -- sparse host<->device row access (the PS data plane's unit) ---------
    # Row-index lengths vary per sync; padding to the next power of two
    # bounds XLA retraces to O(log max-touched) compiled shapes.
    @staticmethod
    def _pad_pow2(idx: np.ndarray, fill: int) -> tuple[np.ndarray, int]:
        n = int(idx.shape[0])
        m = 8
        while m < n:
            m <<= 1
        out = np.full(m, fill, dtype=np.int64)
        out[:n] = idx
        return out, n

    def gather_rows(self, name: str, idx: np.ndarray) -> np.ndarray:
        """Fetch rows `idx` of a table to host — a device gather plus an
        O(touched) transfer, never a full-table copy (the ZPush side of
        the sparse PS wire reads current values this way)."""
        if idx.size == 0:
            tail = self.state[name].shape[1:]
            return np.empty((0, *tail), np.float32)
        t0 = time.perf_counter()
        pad, n = self._pad_pow2(np.asarray(idx), 0)
        m = pad.shape[0]
        fn = self._gather_fns.get(m)
        if fn is None:
            fn = jax.jit(lambda a, i: a[i])
            self._gather_fns[m] = fn
            _JIT_MISSES.inc()
        out = fn(self.state[name], jnp.asarray(pad))
        out = np.asarray(out[:n], dtype=np.float32)
        _GATHER_S.observe(time.perf_counter() - t0)
        _GATHER_ROWS.inc(n)
        return out

    def gather_rows_multi(self, names: list[str],
                          idx: np.ndarray) -> dict[str, np.ndarray]:
        """gather_rows for several same-height tables sharing one index
        set (FTRL's z and n always do): one index transfer and one
        jitted dispatch for the whole group instead of per-table
        round-trips — the sync-snapshot path's gather cost halves."""
        if idx.size == 0:
            return {k: np.empty((0, *self.state[k].shape[1:]), np.float32)
                    for k in names}
        t0 = time.perf_counter()
        pad, n = self._pad_pow2(np.asarray(idx), 0)
        names_key = tuple(names)
        key = (names_key, pad.shape[0])
        fn = self._multi_gather_fns.get(key)
        if fn is None:
            fn = jax.jit(lambda st, i: {k: st[k][i] for k in names_key})
            self._multi_gather_fns[key] = fn
            _JIT_MISSES.inc()
        outs = fn({k: self.state[k] for k in names}, jnp.asarray(pad))
        res = {k: np.asarray(v[:n], dtype=np.float32)
               for k, v in outs.items()}
        _GATHER_S.observe(time.perf_counter() - t0)
        _GATHER_ROWS.inc(n * len(names))
        return res

    def scatter_rows(self, name: str, idx: np.ndarray,
                     vals: np.ndarray) -> None:
        """Overwrite rows `idx` with `vals` in place on device (the
        sparse pull apply). Padding rows use an out-of-range index and
        mode='drop', so they never land."""
        if idx.size == 0:
            return
        t0 = time.perf_counter()
        pad, n = self._pad_pow2(np.asarray(idx), self.state[name].shape[0])
        key = (name, pad.shape[0])
        fn = self._scatter_fns.get(key)
        if fn is None:
            sh = self.sharding(name)
            fn = jax.jit(
                lambda a, i, v: jax.lax.with_sharding_constraint(
                    a.at[i].set(v, mode="drop"), sh),
                donate_argnums=0)
            self._scatter_fns[key] = fn
            _JIT_MISSES.inc()
        tail = self.state[name].shape[1:]
        v = np.zeros((pad.shape[0], *tail), np.float32)
        v[:n] = vals
        self.state[name] = fn(self.state[name], jnp.asarray(pad),
                              jnp.asarray(v))
        _SCATTER_S.observe(time.perf_counter() - t0)
        _SCATTER_ROWS.inc(n)

    def zero_init_names(self) -> set[str]:
        """Tables created as zeros (spec.init is None) — the PS plane
        creates these server-side from shape alone, with no array on the
        startup wire (runtime/ps_server.py init_from_specs)."""
        return {k for k, s in self.specs.items() if s.init is None}

    def wire_cap_names(self) -> set[str]:
        """Tables whose push deltas must never drop below bf16 on the
        wire (see TableSpec.wire_cap) — read by SyncedStore's
        _quantize_deltas."""
        return {k for k, s in self.specs.items() if s.wire_cap}

    # -- host-side views ----------------------------------------------------
    def nnz(self, name: str = "w") -> int:
        """|w|_0 — the model-sparsity column of the progress row
        (reference linear progress.h:10-25 'new_w' tracking)."""
        return int(jnp.sum(self.state[name] != 0))

    def to_numpy(self) -> dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.state.items()}

    def from_numpy(self, arrays: dict[str, np.ndarray]) -> None:
        for k, v in arrays.items():
            assert k in self.state, f"unknown table {k}"
            assert tuple(v.shape) == tuple(self.state[k].shape), (
                f"table {k}: loaded shape {v.shape} != {self.state[k].shape}"
            )
            sh = self.sharding(k)
            self.state[k] = jax.device_put(jnp.asarray(v), sh)


def quantize_push(grad, nbytes: int = 0):
    """Transfer-filter parity (fixed_bytes knob, reference
    config.proto:126-133 + FIXING_FLOAT filter): round the pushed gradient
    to a lower-precision dtype before aggregation. 0 = off, 2 = bfloat16,
    1 = int8-scaled."""
    if nbytes == 0:
        return grad
    if nbytes >= 2:
        return grad.astype(jnp.bfloat16).astype(grad.dtype)
    # 1 byte: per-array absmax int8 scaling
    scale = jnp.maximum(jnp.max(jnp.abs(grad)), 1e-12) / 127.0
    q = jnp.clip(jnp.round(grad / scale), -127, 127).astype(jnp.int8)
    return q.astype(grad.dtype) * scale
