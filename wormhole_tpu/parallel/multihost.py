"""Multi-host global mesh: one SPMD program over every chip of a pod.

The PS data plane (runtime/ps_server.py) shares a model across worker
processes through TCP push/pull — the reference's ps-lite architecture.
This module is the OTHER, TPU-native composition (BASELINE.json north
star): the `-n` worker processes call `jax.distributed.initialize` and
form ONE global `jax.sharding.Mesh` over all their devices, so the
jitted train step is a single SPMD program and gradient aggregation
rides ICI/DCN collectives instead of the TCP parameter server —
`rabit::Allreduce` become XLA `psum`s the compiler inserts.

Every process runs the SAME jitted steps in lockstep (SPMD requires
it); each contributes its local rows of every global batch via
`jax.make_array_from_process_local_data`. The workload split is the
stable rank slice of file parts (the reference's batch dispatch /
RowBlockIter(rank, world) pattern, kmeans.cc:149-154) and the
end-of-pass decision is itself a collective: a step whose GLOBAL
example count is zero means every rank has drained (see
apps/_runner._run_worker_global).
"""

from __future__ import annotations

import contextlib

import numpy as np


def init_from_env(env) -> bool:
    """Join the jax.distributed cluster the launcher described
    (WH_COORD_URI; workers only). Idempotent; returns True if this
    process is part of a multi-process cluster."""
    import jax

    if not getattr(env, "coord_uri", ""):
        return False
    if env.num_workers <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=env.coord_uri,
        num_processes=env.num_workers,
        process_id=env.rank,
    )
    return True


def global_batch(sharding, local_np: np.ndarray, global_rows: int):
    """Assemble a global device array from this process's local rows
    (rank-ordered concatenation along axis 0)."""
    import jax

    shape = (global_rows, *local_np.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_np), global_shape=shape)


def load_replicated(store, arrays: dict) -> None:
    """Install host arrays into a store whose tables are replicated over
    a multi-process mesh (every process supplies the full array).
    Combined stores (difacto's two table groups) route each table to the
    sub-store that owns it."""
    import jax

    subs = getattr(store, "stores", None)
    if subs is not None:
        known = set().union(*(s.state for s in subs))
        unknown = set(arrays) - known
        assert not unknown, f"unknown tables {sorted(unknown)}"
        for s in subs:
            own = {k: v for k, v in arrays.items() if k in s.state}
            load_replicated(s, own)
        if getattr(store, "on_load", None) is not None:
            store.on_load()
        return
    for k, v in arrays.items():
        assert k in store.state, f"unknown table {k}"
        sh = store.sharding(k)
        store.state[k] = jax.make_array_from_process_local_data(
            sh, np.ascontiguousarray(v), global_shape=v.shape)


def fetch_replicated(arr) -> np.ndarray:
    """Host copy of a fully-replicated global array (every process holds
    a complete shard set, so this is purely local)."""
    return np.asarray(arr.addressable_data(0))


def fetch_local_rows(arr, lo: int, hi: int) -> np.ndarray:
    """Host copy of rows [lo, hi) of a batch-sharded global array,
    assembled from this process's addressable shards only — the range a
    rank contributed via global_batch is exactly the range its own
    devices hold, so no cross-host transfer happens (global-mesh predict
    reads back its margins this way)."""
    out = np.empty((hi - lo, *arr.shape[1:]), np.float32)
    filled = np.zeros(hi - lo, bool)
    for s in arr.addressable_shards:
        sl = s.index[0] if s.index else slice(None)
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else arr.shape[0]
        a, b = max(start, lo), min(stop, hi)
        if a >= b:
            continue
        data = np.asarray(s.data)
        out[a - lo:b - lo] = data[a - start:b - start]
        filled[a - lo:b - lo] = True
    assert filled.all(), (
        f"rows [{lo}, {hi}) not fully addressable on this process — "
        "the output sharding does not match the rank's contribution")
    return out


def exit_barrier(client=None, world: int = 0,
                 timeout: float = 120.0) -> None:
    """Rendezvous before process exit: the coordination-service leader
    (process 0) tearing down while peers are still running kills them
    with a fatal poll error. A HOST-level barrier (the scheduler's TCP
    barrier — device collectives cannot serialize process exit) gets
    every worker to the same point, then all shut the jax.distributed
    client down together. Bounded: a peer that died before arriving must
    not hang the survivors forever."""
    import jax

    if client is not None and world > 1:
        try:
            client.barrier("gm_exit", world, timeout=timeout)
        except Exception:
            pass
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


@contextlib.contextmanager
def worker_session(env):
    """The global-mesh worker frame shared by every SPMD app: register
    with the control plane and START LIVENESS PINGS before the blocking
    jax.distributed rendezvous (a slow peer must not get this worker
    swept as dead mid-init), and guarantee the coordinated teardown —
    exit barrier, distributed shutdown, deregistration — on every exit
    path, including exceptions (a crashed rank must not strand its peers
    in a collective)."""
    from wormhole_tpu.runtime.tracker import LivenessPinger, SchedulerClient

    client = SchedulerClient(env.scheduler_uri, f"worker-{env.rank}")
    client.register()
    pinger = LivenessPinger(client)
    try:
        assert init_from_env(env), "global_mesh needs WH_COORD_URI"
        yield client
    finally:
        exit_barrier(client, env.num_workers)
        pinger.stop()
        try:
            client.call(op="bye")
        except Exception:
            pass


def rank_parts(pattern: str, num_parts_per_file: int, env) -> list:
    """This rank's stable slice of (file, part) work items — the
    reference's RowBlockIter(rank, world) split (kmeans.cc:149-154)."""
    from wormhole_tpu.data.match_file import match_file

    files = match_file(pattern)
    if not files:
        raise FileNotFoundError(f"no files match {pattern}")
    parts = [(f, k) for f in files for k in range(num_parts_per_file)]
    return parts[env.rank :: env.num_workers]


def empty_rowblock():
    """The masked-empty block a drained rank feeds into lockstep steps."""
    from wormhole_tpu.data.rowblock import RowBlock

    return RowBlock(label=np.zeros(0, np.float32),
                    offset=np.zeros(1, np.int64),
                    index=np.zeros(0, np.uint64), value=None, weight=None)


def global_coo_batch(bsh, db, rank: int, local_rows: int,
                     minibatch: int, nnz_per_row: int,
                     with_label: bool = True):
    """Assemble this rank's local DeviceBatch rows into the global
    sharded batch arrays (seg ids offset into the rank's global row
    range; padding rows carry val=0 so offsets on padding are inert)."""
    cap = minibatch * nnz_per_row
    seg = db.seg + np.int32(rank * local_rows)
    out = [global_batch(bsh, seg, cap),
           global_batch(bsh, db.idx, cap),
           global_batch(bsh, db.val, cap)]
    if with_label:
        out.append(global_batch(bsh, db.label, minibatch))
    out.append(global_batch(bsh, db.row_mask, minibatch))
    return tuple(out)


def _global_scalar(local_per_device: "np.ndarray", reduce_fn) -> int:
    """Reduce a per-local-device int64 vector over every device of the
    global mesh."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("i",))
    sh = NamedSharding(mesh, P("i"))
    garr = jax.make_array_from_process_local_data(
        sh, local_per_device, global_shape=(len(devs),))
    return int(reduce_fn(garr))


def global_scalar_sum(local_value: int) -> int:
    """Sum of a per-process host integer over the global mesh (each
    process's value is counted once, not per device)."""
    import jax
    import jax.numpy as jnp

    per = np.zeros(len(jax.local_devices()), np.int64)
    per[0] = local_value
    return _global_scalar(per, jnp.sum)


def global_scalar_max(local_value: int) -> int:
    """Max of a per-process host integer over the global mesh — the
    Allreduce<Max> of the reference BSP apps (lbfgs.cc:107-113)."""
    import jax
    import jax.numpy as jnp

    per = np.full(len(jax.local_devices()), local_value, np.int64)
    return _global_scalar(per, jnp.max)
