"""Multi-host global mesh: one SPMD program over every chip of a pod.

The PS data plane (runtime/ps_server.py) shares a model across worker
processes through TCP push/pull — the reference's ps-lite architecture.
This module is the OTHER, TPU-native composition (BASELINE.json north
star): the `-n` worker processes call `jax.distributed.initialize` and
form ONE global `jax.sharding.Mesh` over all their devices, so the
jitted train step is a single SPMD program and gradient aggregation
rides ICI/DCN collectives instead of the TCP parameter server —
`rabit::Allreduce` become XLA `psum`s the compiler inserts.

Every process runs the SAME jitted steps in lockstep (SPMD requires
it); each contributes its local rows of every global batch via
`jax.make_array_from_process_local_data`. The workload split is the
stable rank slice of file parts (the reference's batch dispatch /
RowBlockIter(rank, world) pattern, kmeans.cc:149-154) and the
end-of-pass decision is itself a collective: a step whose GLOBAL
example count is zero means every rank has drained (see
apps/_runner._run_worker_global).
"""

from __future__ import annotations

import numpy as np


def init_from_env(env) -> bool:
    """Join the jax.distributed cluster the launcher described
    (WH_COORD_URI; workers only). Idempotent; returns True if this
    process is part of a multi-process cluster."""
    import jax

    if not getattr(env, "coord_uri", ""):
        return False
    if env.num_workers <= 1:
        return False
    jax.distributed.initialize(
        coordinator_address=env.coord_uri,
        num_processes=env.num_workers,
        process_id=env.rank,
    )
    return True


def global_batch(sharding, local_np: np.ndarray, global_rows: int):
    """Assemble a global device array from this process's local rows
    (rank-ordered concatenation along axis 0)."""
    import jax

    shape = (global_rows, *local_np.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_np), global_shape=shape)


def load_replicated(store, arrays: dict) -> None:
    """Install host arrays into a store whose tables are replicated over
    a multi-process mesh (every process supplies the full array)."""
    import jax

    for k, v in arrays.items():
        assert k in store.state, f"unknown table {k}"
        sh = store.sharding(k)
        store.state[k] = jax.make_array_from_process_local_data(
            sh, np.ascontiguousarray(v), global_shape=v.shape)


def fetch_replicated(arr) -> np.ndarray:
    """Host copy of a fully-replicated global array (every process holds
    a complete shard set, so this is purely local)."""
    return np.asarray(arr.addressable_data(0))
