from wormhole_tpu.parallel.mesh import make_mesh, table_sharding, batch_sharding  # noqa: F401
from wormhole_tpu.parallel.kvstore import KVStore  # noqa: F401
