"""Collective primitives: rabit's API surface, XLA-native.

The reference's BSP apps call rabit::Allreduce<Sum/Max>, Broadcast and
checkpoint primitives (reference learn/solver/lbfgs.h:172,252,302,
learn/kmeans/kmeans.cc:160-190). On TPU those are `jax.lax.psum/pmax` under
`shard_map` over a mesh axis; this module wraps them so solver code reads
like the reference while compiling to ICI collectives.

Two call styles:
- inside a shard_map'ped function: `allreduce_sum(x, axis)` etc. — thin
  lax wrappers;
- host-level, eager: `Communicator.allreduce(array)` — runs a tiny jitted
  psum over the mesh for host-orchestrated loops (L-BFGS line search,
  k-means outer iterations), the analog of rabit's blocking calls.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from wormhole_tpu.parallel.mesh import DATA_AXIS


def allreduce_sum(x, axis: str = DATA_AXIS):
    return jax.lax.psum(x, axis_name=axis)


def allreduce_max(x, axis: str = DATA_AXIS):
    return jax.lax.pmax(x, axis_name=axis)


def allreduce_min(x, axis: str = DATA_AXIS):
    return jax.lax.pmin(x, axis_name=axis)


def broadcast(x, root: int = 0, axis: str = DATA_AXIS):
    """Every shard gets root's value (rabit::Broadcast parity)."""
    src = jax.lax.all_gather(x, axis)  # small payloads only
    return jax.tree_util.tree_map(lambda g: g[root], src)


class Communicator:
    """Host-level BSP collectives over one mesh axis.

    Plays rabit's blocking Allreduce/Broadcast for host-orchestrated solver
    loops. Arrays are data-sharded or replicated jax Arrays; the reduction
    compiles once per shape and runs as an ICI collective.
    """

    def __init__(self, mesh: Mesh, axis: str = DATA_AXIS):
        self.mesh = mesh
        self.axis = axis
        self._sum_fns: dict[int, Callable] = {}  # per-instance compile cache

    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]

    def _sum_fn(self, ndim: int):
        fn = self._sum_fns.get(ndim)
        if fn is None:
            from wormhole_tpu.parallel.mesh import shard_map

            spec = P(self.axis, *([None] * (ndim - 1)))

            @jax.jit
            @functools.partial(
                shard_map,
                mesh=self.mesh,
                in_specs=spec,
                out_specs=P(*([None] * (ndim - 1))),
            )
            def reduce_sum(x):
                # each shard holds a (1, *tail) block of the stacked
                # contributions; the psum of the squeezed block is the
                # fully-reduced (*tail) result, replicated everywhere
                return jax.lax.psum(x[0], self.axis)

            fn = self._sum_fns[ndim] = reduce_sum
        return fn

    def allreduce_shards(self, x):
        """Sum per-shard contributions: x's leading dim is the axis size
        (one slice per shard); returns the reduced (*tail) array
        replicated everywhere — rabit::Allreduce<Sum> semantics."""
        x = jnp.asarray(x)
        return self._sum_fn(x.ndim)(x)
