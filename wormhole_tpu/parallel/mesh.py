"""Device mesh: the TPU replacement for Wormhole's worker/server topology.

The reference launches `-n` worker and `-s` server processes (tracker,
reference doc/common/build.rst:57-71). Here the same two launch dimensions
become the two axes of a `jax.sharding.Mesh`:

- axis "data"  — data parallelism: minibatches are split across it
  (the workers);
- axis "model" — parameter sharding: hashed tables are range-sharded
  across it (the servers' key shards, localizer.h byte-reversal spreading
  becomes contiguous range sharding of the hashed bucket space).

Both axes ride ICI within a slice; XLA inserts the collectives (the psum of
gradients plays rabit::Allreduce, the cross-axis gather plays ZPull).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # 0.4.x keeps it in experimental, as check_rep
    from functools import wraps

    from jax.experimental.shard_map import shard_map as _shard_map_04

    @wraps(_shard_map_04)
    def shard_map(f, *, check_vma: Optional[bool] = None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map_04(f, **kw)


def make_mesh(
    num_data: Optional[int] = None,
    num_model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data x model) mesh. Defaults to all devices on the data
    axis — the reference's common shape of many workers and fewer servers
    maps to data-major ordering so neighboring workers share ICI links."""
    devs = list(devices if devices is not None else jax.devices())
    if num_data is None:
        num_data = len(devs) // num_model
    need = num_data * num_model
    assert need <= len(devs), (
        f"mesh {num_data}x{num_model} needs {need} devices, have {len(devs)}"
    )
    assert num_data >= 1 and num_model >= 1, (
        f"mesh {num_data}x{num_model} has an empty axis "
        f"({len(devs)} devices can't fill {num_model} model shards)"
    )
    devs = devs[:need]
    arr = np.array(devs).reshape(num_data, num_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def table_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Parameter tables: bucket dimension sharded over the model axis
    (the PS key-shard layout); trailing dims (embedding k) replicated."""
    return NamedSharding(mesh, P(MODEL_AXIS, *([None] * (ndim - 1))))


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Minibatch arrays: leading dimension split over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def single_device_mesh() -> Mesh:
    """1x1 mesh on the first device — single-chip paths."""
    return make_mesh(1, 1, devices=jax.devices()[:1])
