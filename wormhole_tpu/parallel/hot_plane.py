"""Hot parameter plane: device-resident sharded tables, cold TCP tier.

The TCP plane (`runtime/ps_server.SyncedStore`) round-trips every
`max_delay` minibatches through host memory and sockets — the right
shape when workers are separate processes, and ~170x too slow when they
are data-parallel shards of ONE process sharing a device mesh
(BENCH `linear_ftrl_ps_dist` vs the single-chip row). In that regime the
reference's ps-lite server group maps onto the mesh itself: the model
and optimizer tables already live ONCE, sharded over the mesh "model"
axis in HBM (`parallel/kvstore.KVStore`), and the learners' jitted steps
already express ZPull as a sharded gather (`jnp.take` of bucket rows)
and ZPush as a segment-sum + `store.constrain` sharding constraint, so
XLA lowers per-step aggregation to ICI collectives fused with the
update. There is nothing left for a per-step host round-trip to do.

`HotPlane` therefore keeps the `SyncedStore` surface the solver and
runner already speak (`maybe_sync` / `sync` / `flush` / `pull` /
`wire_stats`) but inverts the authority relation:

- **Hot tier = the device store.** `maybe_sync()` only counts steps —
  aggregation already happened inside the jitted step. No RPC, no host
  copy, no wire bytes on the training path.
- **Cold tier = the TCP server group**, demoted to spill, epoch-stamped
  snapshots (the PR 1 fault-tolerance contract), and cross-pod sync.
  It is reconciled only at `flush()` barriers (part ends, pass
  boundaries, checkpoints, predict — the points `minibatch_solver`
  already fences through `sync_flush`): one sparse delta push of every
  row touched since the last barrier, then a versioned pull that
  refreshes the base mirror. Cold-tier frames are large and rare —
  exactly what the `WH_NET_COMPRESS` hello-negotiated zlib knob is for.
- **Pulls never write the device.** The base mirror tracks the server
  exactly (base == server after every reconcile), so a barrier delta
  (cur - base) drives the cold tier toward the device state: the cold
  tier is a MIRROR of the authoritative device tables, not a merge
  point (merging N peers is the TCP plane's regime — the hot plane
  requires all data-parallel workers in this process, so there are no
  concurrent pushers to merge). After a server restore the rolled-back
  shard is re-zeroed in the mirror (its restored un-stamped rows are
  back at zero init) and the next flush falls back to the full-table
  delta scan, re-uploading the authoritative device rows wholesale —
  one barrier repairs the cold tier completely, on top of PSClient's
  journal replay. Recovery verdicts stay metric-based (chaos_lab
  `--plane hot`).

Adoption at `init()` is the one exception to pull-never-writes: before
any training step the SERVER is authoritative (checkpoint-loaded state,
`model_in` warm starts), so the startup pull scatters into the device
store like the TCP plane. From the first step on, the device is.

Selection is one knob: `WH_PS_PLANE={auto,tcp,hot}` (config.py
registry); `auto` picks `hot` when the job's workers share one process
with >= 2 local devices (`apps/_runner.py`).
"""

from __future__ import annotations

import numpy as np

from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.runtime.ps_server import SyncedStore, shard_range

_HOT_STEPS = _obs.REGISTRY.counter("ps.hot.steps")
_HOT_FLUSHES = _obs.REGISTRY.counter("ps.hot.flushes")


class HotPlane(SyncedStore):
    """SyncedStore with the device store authoritative and the TCP
    server group demoted to a flush-barrier cold tier."""

    def __init__(self, store, client, **kw):
        # the async comms thread exists to hide per-step round-trips the
        # hot plane doesn't make; flush barriers want the synchronous
        # path's "state is settled when this returns" guarantee — so the
        # env default (WH_ASYNC_SYNC, exported by chaos/bench drivers
        # for the TCP plane) must not leak in
        kw["async_sync"] = False
        super().__init__(store, client, **kw)
        self._adopting = False
        self._hot_steps = 0
        # armed by a rollback re-pull: the next flush pushes the FULL
        # cur - base scan instead of the touched-row hints, so server
        # rows that rolled back but weren't touched recently still get
        # repaired from the device at the very next barrier
        self._force_scan = False

    # -- hot tier: the training path ------------------------------------
    def maybe_sync(self) -> bool:
        """Per-minibatch hook: count the step, nothing else — gradient
        aggregation already ran as ICI collectives inside the jitted
        step (store.constrain's reduce-scatter ZPush)."""
        self._steps += 1
        self._hot_steps += 1
        _HOT_STEPS.inc()
        return False

    # -- cold tier: flush-barrier reconciliation ------------------------
    def init(self) -> None:
        # startup is the one server-authoritative moment: adopt
        # checkpoint-loaded / warm-start rows into the device store
        self._adopting = True
        try:
            super().init()
        finally:
            self._adopting = False

    def pull(self) -> None:
        if self._clocks is None:
            # dense re-adoption (cold -> hot handoff); server is
            # authoritative here by construction
            self._adopting = True
            try:
                super().pull()
            finally:
                self._adopting = False
            return
        super().pull()

    def _apply_pull(self) -> None:
        """Versioned pull into the BASE MIRROR ONLY (the device store is
        authoritative once training started). Keeps the invariant
        base == server: a rolled-back (snapshot-restored) shard is
        re-zeroed in the mirror before adopting its since=0 re-pull —
        rows first stamped after the snapshot are back at zero init on
        the server and absent from the pull — so the next flush's
        cur - base delta re-uploads the device's authoritative rows
        wholesale. (Non-zero-init tables can't be re-zeroed; for them a
        rolled-back row keeps the TCP plane's bounded-loss behavior:
        the next delta restores progress since the last barrier only.)"""
        if self._adopting:
            super()._apply_pull()
            return
        c = self.client
        # pull_sparse consumes the rollback flags (they force since=0);
        # capture them first so we know which shards to re-zero
        rolled = [r for r in range(c.world) if c._rolled_back[r]]
        clocks, groups, tables = c.pull_sparse(
            self._clocks, compress=self.compress)
        if rolled:
            zero = (self.store.zero_init_names()
                    if hasattr(self.store, "zero_init_names")
                    else set())
            for k in self._base:
                if k not in zero:
                    continue
                n = c.full_rows[k]
                for r in rolled:
                    lo, hi = shard_range(n, r, c.world)
                    self._base[k][lo:hi] = 0.0
            self._force_scan = True
        for k, rows in tables.items():
            idx = groups[c.full_rows[k]]
            if idx.size == 0:
                continue
            self._base[k][idx] = np.asarray(rows, np.float32)
        self._clocks = clocks

    def _touched_groups(self):
        if self._force_scan:
            # rollback repair: push the full-table delta once so server
            # rows outside the recent touched set re-adopt the device
            self._force_scan = False
            if self.touched_fn is not None:
                self.touched_fn()  # drain the accumulator; the scan
            return None            # covers everything it named
        return super()._touched_groups()

    def _sync_now(self) -> None:
        super()._sync_now()
        _HOT_FLUSHES.inc()

    def wire_stats(self) -> dict:
        d = super().wire_stats()
        d["plane"] = "hot"
        mesh = getattr(self.store, "mesh", None)
        d["devices"] = (int(mesh.devices.size) if mesh is not None else 1)
        d["hot_steps"] = self._hot_steps
        d["flushes"] = self.num_syncs
        return d
