"""Deterministic, env-driven fault injection for the runtime planes.

The recovery machinery (server respawn, fenced RPC retry, rollback
replay) is only trustworthy if it can be exercised on demand, so every
plane exposes a hook that consults this module:

- `net.send_frame` / `net.recv_frame` call `ACTIVE.frame(op)` /
  `ACTIVE.recv()` (worker-side network faults),
- `ServerNode._dispatch` calls `ACTIVE.server_op(op)` (server crashes),
- `BspWorker` collectives call `ACTIVE.worker_op(op)` (BSP worker
  crashes mid-round),
- `Scheduler._dispatch` calls `ACTIVE.sched_op(op)` (control-plane
  faults).

Faults are armed by the `WH_FAULT_SPEC` env var, parsed once at import.
Every hook site guards with `if faults.ACTIVE is not None:` — a single
module-level None check — so an unfaulted process pays nothing on the
hot path (the zero-overhead contract `tools/ps_sync_micro.py` checks).

Spec grammar (comma-separated specs; all counters are deterministic):

    server:<rank>:kill@<op>:<nth>[:always]
        the server process of rank <rank> hard-exits (os._exit — no
        cleanup, like SIGKILL) on its <nth> dispatch of <op> ('any'
        matches every op). By default the fault arms only in the
        FIRST incarnation (WH_RESTORE_EPOCH unset/0) so a respawned
        server survives; ':always' re-arms it in every incarnation
        (respawn-cap exhaustion tests).
    worker:<rank>:kill@<op>:<nth>[:always]
        same, for BSP worker processes: <op> is a collective entry
        point of runtime/allreduce.py ('allreduce', 'broadcast',
        'checkpoint', or 'any'), so a worker can be killed
        deterministically mid-round to exercise ring recovery.
    net:reset:after_frames=<N>
        after N request frames have been sent, the next send raises
        ConnectionResetError (fires once). Arms in worker/role-less
        processes only.
    net:delay:ms=<K>
        sleep K ms before every request frame send (latency injection).
        Arms in worker/role-less processes only.
    net:partition@<op>:<secs>
        link-level partition: starting at the FIRST send of <op> ('any'
        matches every op), every matching send raises OSError for
        <secs> seconds, then the link heals and traffic flows again —
        the shape a retry budget must ride out (bounded retries, no
        hang, zero give-ups if the budget outlives the partition).
        Arms in worker/role-less processes only.
    net:slow@<op>:<ms>
        slow link: every send of <op> ('any' = all) sleeps <ms> ms
        first — degraded-but-alive, unlike partition's hard failures.
        Arms in worker/role-less processes only.
    sched:drop@<op>:<nth>
        the scheduler answers the <nth> request of <op> with an error
        (a dropped/garbled control message). Arms in the scheduler.
    sched:kill@<op>:<nth>[:always]
        the scheduler process hard-exits (os._exit) on its <nth>
        dispatch of <op> ('any' matches every op), BEFORE the op's
        effect is applied or journaled — so the dying request is the
        client retry's problem, never a double-applied one. Mirrors
        the server/worker kill grammar: arms only in the first
        incarnation unless ':always'. Pair with the launcher's
        --max-scheduler-restarts to exercise journal replay.

Example: WH_FAULT_SPEC="server:1:kill@push:200" kills server rank 1 on
its 200th push.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

KILL_EXIT = 137  # the exit code of a SIGKILLed process (128 + 9)


def _flight_note(kind: str, **detail) -> None:
    """A fault is firing: record it into (and dump) the flight
    recorder, so the black box names the injected cause. Imported
    lazily at fire time — faults.py is in the bare `import wormhole_tpu`
    closure and must not pull the obs plane in (tests/test_obs.py pins
    that); fault ARMS are rare, so the import cost is off the hot
    path. Kill faults dump before os._exit — an exiting process gets
    no second chance to flush its rings."""
    try:
        from wormhole_tpu.obs import flight
        if flight.ACTIVE is None:
            return
        flight.record_decision("fault", kind, **detail)
        flight.dump(f"fault: {kind}", force=True)
    except Exception:
        pass  # the fault must fire even if the black box cannot


class FaultSpecError(ValueError):
    pass


def _parse_at(tok: str, what: str) -> tuple[str, int, bool]:
    """Parse '<op>:<nth>[:always]' out of 'kill@<op>:<nth>[:always]'."""
    if "@" not in tok:
        raise FaultSpecError(f"{what}: expected '{what}@<op>:<nth>'")
    _, rest = tok.split("@", 1)
    parts = rest.split(":")
    always = False
    if parts and parts[-1] == "always":
        always = True
        parts = parts[:-1]
    if len(parts) != 2:
        raise FaultSpecError(
            f"{what}: expected '<op>:<nth>', got {rest!r}")
    op, nth = parts[0], int(parts[1])
    if nth < 1:
        raise FaultSpecError(f"{what}: nth must be >= 1, got {nth}")
    return op, nth, always


class Faults:
    """A parsed WH_FAULT_SPEC, scoped to one process's role/rank.

    Specs that do not apply to this process (wrong role or rank) parse
    but never fire, so one spec string can be exported job-wide by the
    launcher and each process arms only its own faults."""

    def __init__(self, spec: str, role: Optional[str] = None,
                 rank: int = 0, epoch: int = 0):
        self.spec = spec
        self.role = role
        self.rank = int(rank)
        self.epoch = int(epoch)
        self.kill_fn = os._exit  # patchable for in-process tests
        self._lock = threading.Lock()
        self._frames = 0
        self._op_counts: dict[str, int] = {}
        self._wop_counts: dict[str, int] = {}
        self._sched_counts: dict[str, int] = {}
        # armed faults
        self._kills: list[tuple[str, int]] = []   # (op, nth)
        self._wkills: list[tuple[str, int]] = []  # (op, nth) worker kills
        self._delay_s = 0.0
        self._reset_after: Optional[int] = None
        self._drops: list[tuple[str, int]] = []   # (op, nth)
        self._skills: list[tuple[str, int]] = []  # (op, nth) sched kills
        self._partitions: dict[str, float] = {}   # op -> secs
        self._partition_t0: dict[str, float] = {}  # op -> first-send time
        self._slows: dict[str, float] = {}        # op -> sleep seconds
        self._slow_fired = False                  # first-sleep print latch
        net_ok = role not in ("server", "scheduler")
        for raw in spec.split(","):
            s = raw.strip()
            if not s:
                continue
            f = s.split(":")
            if f[0] == "server":
                if len(f) < 3:
                    raise FaultSpecError(
                        f"bad server fault {s!r}: expected "
                        "'server:<rank>:kill@<op>:<nth>[:always]'")
                want_rank = int(f[1])
                op, nth, always = _parse_at(":".join(f[2:]), "kill")
                if (role == "server" and self.rank == want_rank
                        and (always or self.epoch == 0)):
                    self._kills.append((op, nth))
            elif f[0] == "worker":
                if len(f) < 3:
                    raise FaultSpecError(
                        f"bad worker fault {s!r}: expected "
                        "'worker:<rank>:kill@<op>:<nth>[:always]'")
                want_rank = int(f[1])
                op, nth, always = _parse_at(":".join(f[2:]), "kill")
                if (role == "worker" and self.rank == want_rank
                        and (always or self.epoch == 0)):
                    self._wkills.append((op, nth))
            elif f[0] == "net":
                if len(f) != 3:
                    raise FaultSpecError(f"bad net fault {s!r}")
                if f[1] == "delay":
                    if not f[2].startswith("ms="):
                        raise FaultSpecError(
                            f"net:delay: expected 'ms=<K>', got {f[2]!r}")
                    if net_ok:
                        self._delay_s = float(f[2][3:]) / 1000.0
                elif f[1] == "reset":
                    if not f[2].startswith("after_frames="):
                        raise FaultSpecError(
                            "net:reset: expected 'after_frames=<N>', "
                            f"got {f[2]!r}")
                    if net_ok:
                        self._reset_after = int(f[2][len("after_frames="):])
                elif f[1].startswith("partition@"):
                    fop = f[1].split("@", 1)[1]
                    secs = float(f[2])
                    if not fop or secs <= 0:
                        raise FaultSpecError(
                            f"net:partition: expected "
                            f"'partition@<op>:<secs>', got {s!r}")
                    if net_ok:
                        self._partitions[fop] = secs
                elif f[1].startswith("slow@"):
                    fop = f[1].split("@", 1)[1]
                    ms = float(f[2])
                    if not fop or ms <= 0:
                        raise FaultSpecError(
                            f"net:slow: expected 'slow@<op>:<ms>', got {s!r}")
                    if net_ok:
                        self._slows[fop] = ms / 1000.0
                else:
                    raise FaultSpecError(f"unknown net fault {f[1]!r}")
            elif f[0] == "sched":
                rest = ":".join(f[1:])
                if rest.startswith("kill@"):
                    op, nth, always = _parse_at(rest, "kill")
                    if (role == "scheduler"
                            and (always or self.epoch == 0)):
                        self._skills.append((op, nth))
                else:
                    op, nth, _ = _parse_at(rest, "drop")
                    if role == "scheduler":
                        self._drops.append((op, nth))
            else:
                raise FaultSpecError(f"unknown fault kind {f[0]!r} in {s!r}")

    # -- hooks (call sites guard on ACTIVE is not None) ---------------------
    def frame(self, op) -> None:
        """Before every request frame send (net faults)."""
        if self._delay_s:
            time.sleep(self._delay_s)
        if self._slows:
            d = self._slows.get(op, 0.0) or self._slows.get("any", 0.0)
            if d:
                if not self._slow_fired:
                    self._slow_fired = True
                    print(f"[faults] injecting net slow on {op!r} "
                          f"({d * 1000:g}ms/send)", flush=True)
                    _flight_note("net:slow", op=op, ms=d * 1000)
                time.sleep(d)
        if self._partitions:
            self._partition_check(op)
        if self._reset_after is None:
            return
        with self._lock:
            self._frames += 1
            fire = self._frames > self._reset_after
            if fire:
                self._reset_after = None  # fires once
        if fire:
            print(f"[faults] injecting connection reset after "
                  f"{self._frames - 1} frames (op {op!r})", flush=True)
            _flight_note("net:reset", op=op, frames=self._frames - 1)
            raise ConnectionResetError(
                f"fault injected: net:reset after {self._frames - 1} frames")

    def _partition_check(self, op) -> None:
        """Partition window: armed lazily by the first matching send, so
        '<secs>' measures from when the link is actually exercised, not
        from process start. While open every matching send fails with
        OSError; after <secs> the spec is disarmed (healed) and traffic
        flows again."""
        with self._lock:
            for want in list(self._partitions):
                if want != "any" and want != op:
                    continue
                secs = self._partitions[want]
                t0 = self._partition_t0.get(want)
                if t0 is None:
                    t0 = self._partition_t0[want] = time.monotonic()
                    print(f"[faults] injecting net partition on {want!r} "
                          f"for {secs:g}s", flush=True)
                    _flight_note("net:partition", op=want, secs=secs)
                elapsed = time.monotonic() - t0
                if elapsed < secs:
                    raise OSError(
                        f"fault injected: net:partition@{want} "
                        f"({elapsed:.2f}s/{secs:g}s)")
                del self._partitions[want]
                print(f"[faults] net partition on {want!r} healed after "
                      f"{secs:g}s", flush=True)

    def recv(self) -> None:
        """Before every frame receive (reserved for recv-side faults)."""

    def server_op(self, op) -> None:
        """At every ServerNode dispatch; may hard-exit the process."""
        if not self._kills:
            return
        with self._lock:
            self._op_counts[op] = self._op_counts.get(op, 0) + 1
            n_op = self._op_counts[op]
            n_any = sum(self._op_counts.values())
        for want, nth in self._kills:
            n = n_any if want == "any" else (n_op if want == op else 0)
            if n == nth:
                print(f"[faults] server rank {self.rank} killing itself at "
                      f"{want!r} #{nth} (epoch {self.epoch})", flush=True)
                _flight_note("server:kill", op=want, nth=nth,
                             rank=self.rank)
                self.kill_fn(KILL_EXIT)

    def worker_op(self, op) -> None:
        """At every BSP collective entry; may hard-exit the process."""
        if not self._wkills:
            return
        with self._lock:
            self._wop_counts[op] = self._wop_counts.get(op, 0) + 1
            n_op = self._wop_counts[op]
            n_any = sum(self._wop_counts.values())
        for want, nth in self._wkills:
            n = n_any if want == "any" else (n_op if want == op else 0)
            if n == nth:
                print(f"[faults] worker rank {self.rank} killing itself at "
                      f"{want!r} #{nth} (epoch {self.epoch})", flush=True)
                _flight_note("worker:kill", op=want, nth=nth,
                             rank=self.rank)
                self.kill_fn(KILL_EXIT)

    def sched_op(self, op) -> None:
        """At every Scheduler dispatch; may raise to drop the request,
        or hard-exit the process (sched:kill). The hook runs BEFORE the
        op's effect/journal append, so a killed request was never
        applied — the client's retry re-executes it in the next
        incarnation, still exactly-once."""
        if not self._drops and not self._skills:
            return
        with self._lock:
            self._sched_counts[op] = self._sched_counts.get(op, 0) + 1
            n_op = self._sched_counts[op]
            n_any = sum(self._sched_counts.values())
        for want, nth in self._skills:
            n = n_any if want == "any" else (n_op if want == op else 0)
            if n == nth:
                print(f"[faults] scheduler killing itself at "
                      f"{want!r} #{nth} (epoch {self.epoch})", flush=True)
                _flight_note("sched:kill", op=want, nth=nth)
                self.kill_fn(KILL_EXIT)
        for want, nth in self._drops:
            if want in (op, "any") and n_op == nth:
                _flight_note("sched:drop", op=op, nth=nth)
                raise ConnectionError(
                    f"fault injected: sched:drop {op!r} #{nth}")


ACTIVE: Optional[Faults] = None


def init_from_env() -> Optional[Faults]:
    """(Re)parse WH_FAULT_SPEC; called once at import. Tests may call it
    again after mutating the env, or install a Faults into ACTIVE
    directly."""
    global ACTIVE
    spec = os.environ.get("WH_FAULT_SPEC", "").strip()
    if not spec:
        ACTIVE = None
        return None
    ACTIVE = Faults(
        spec,
        role=os.environ.get("WH_ROLE") or None,
        rank=int(os.environ.get("WH_RANK", "0") or 0),
        epoch=int(os.environ.get("WH_RESTORE_EPOCH", "0") or 0),
    )
    print(f"[faults] armed: {spec!r} (role={ACTIVE.role} "
          f"rank={ACTIVE.rank} epoch={ACTIVE.epoch})", flush=True)
    return ACTIVE


init_from_env()
