"""Native fault-tolerant BSP allreduce/broadcast over the frame protocol.

This is the second Wormhole comm stack from PAPER.md's layer map: the
rabit-style synchronous collective runtime, sibling to the async PS
plane (runtime/ps_server.py). The design reproduces rabit's recovery
semantics on top of this repo's own pieces — `runtime/net.py` frames for
the data plane, the tracker (`runtime/tracker.py`) for rendezvous, and
the launcher's respawn supervision (PR 1) for process resurrection:

- **Ring allreduce via mailbox RPC.** Every worker runs a small frame
  server (the ps_server handler idiom). One ring step = a `bsp_step`
  frame PUSHED to the successor's server; the handler deposits the chunk
  into a mailbox keyed (gen, version, seq, step) and acks immediately —
  handlers never block on other ranks, so the RPC graph cannot deadlock.
  The main loop sends to its successor then waits on its own mailbox for
  the predecessor's chunk. Reduce-scatter then allgather, 2(W-1) steps,
  with a FIXED accumulation order (local-then-incoming at each hop) so a
  replayed round is bit-identical.

- **(version, counter) sequencing, rabit-style.** Every collective
  consumes one monotone counter; `checkpoint()` bumps the version and
  resets the counter to 0. Completed results are cached per
  (version, counter) — and only completed results, written BEFORE the
  counter advances, so a peer observing `next > wanted` can rely on
  cached-or-pruned. `checkpoint()` prunes versions `< current - 1`:
  since no collective can complete without every rank, live skew is at
  most one version.

- **Recovery.** A dead worker is respawned by the launcher (with
  WH_RESTORE_EPOCH bumped), re-registers with the tracker under a new
  URI, which bumps the group **generation**. Survivors blocked mid-round
  time out on a mailbox wait, observe the gen bump, abort the round and
  retry it at the new gen — but FETCH-FIRST: a survivor one step ahead
  may already hold the completed result (adjacent ranks can differ by
  one step at the instant of a crash), and re-running a round some rank
  completed would deadlock. The respawned worker loads its own
  version-stamped checkpoint, replays its post-checkpoint collectives by
  fetching peers' cached results (bit-identical, no re-reduction), and
  falls back into the live ring once fetches miss everywhere.

- **Wire codec (WH_WIRE).** Reduce-scatter chunk sends are quantized
  STATELESSLY per chunk (bf16/int8/int4, per-64-element group scales —
  a pure function of the chunk values, never of round history): cross-round EF state
  cannot survive the fetch-replay contract, because a respawned rank
  replays completed rounds from peers' result caches without advancing
  any residuals while survivors' would have advanced. The allgather
  phase always ships bf16 — bf16 rounding is IDEMPOTENT, so after the
  owning rank self-rounds its reduced chunk once, every forwarding hop
  re-encodes the same 16 bits and all ranks reconstruct bit-identical
  results; recovered runs therefore stay bit-identical to fault-free
  runs with the codec on. Chunks below _WIRE_MIN_ELEMS (solver-loss
  scalars, small vectors) stay raw f32.

Knobs (declared in config.py, group "bsp"): WH_BSP_STEP_TIMEOUT bounds
one mailbox wait before re-polling the tracker generation;
WH_BSP_RETRY_SEC bounds how long a blocked collective waits overall for
a dead peer's respawn before failing the job. WH_WIRE (group "ps")
selects the chunk encoding above.
"""

from __future__ import annotations

import os
import socket
import socketserver
import threading
import time
from typing import Callable, Optional

import numpy as np

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.runtime import faults
from wormhole_tpu.runtime import retry as _retrylib
from wormhole_tpu.runtime.net import (connect_with_retry, quantize_rows,
                                      recv_frame, send_frame)

_ROUNDS = _obs.REGISTRY.counter("bsp.rounds")
_RING_RETRIES = _obs.REGISTRY.counter("bsp.ring_retries")
_FETCHES = _obs.REGISTRY.counter("bsp.result_fetches")
_CHECKPOINTS = _obs.REGISTRY.counter("bsp.checkpoints")
_CKPT_BYTES = _obs.REGISTRY.counter("bsp.checkpoint_bytes")
_ALLREDUCE_S = _obs.REGISTRY.histogram("bsp.allreduce_s")
_CKPT_S = _obs.REGISTRY.histogram("bsp.checkpoint_s")

_OPS: dict[str, Callable] = {"sum": np.add, "max": np.maximum,
                             "min": np.minimum}

# chunks smaller than this ship raw f32: quantizing a solver-loss
# scalar would be all error and no savings (headers dominate anyway)
_WIRE_MIN_ELEMS = 1024


class _RoundAbort(Exception):
    """The group generation changed mid-round: membership rolled, every
    in-flight step of the old generation is void."""


class _BspHandler(socketserver.StreamRequestHandler):
    def handle(self):
        self.connection.setsockopt(socket.IPPROTO_TCP,
                                   socket.TCP_NODELAY, 1)
        worker = self.server.worker  # type: ignore
        with worker._conns_lock:
            worker._srv_conns.add(self.connection)
        try:
            self._serve(worker)
        except (OSError, ValueError):
            pass  # peer vanished mid-frame; it will reconnect or respawn
        finally:
            with worker._conns_lock:
                worker._srv_conns.discard(self.connection)

    def _serve(self, worker):
        while True:
            got = recv_frame(self.rfile)
            if got is None:
                return
            header, arrays, _ = got
            # a sampled BSP round's trace context rides bsp_step/fetch
            # frames; adopting it stitches this peer's handler work
            # under the initiating rank's round span
            with _trace.bind_wire(header):
                with _trace.request_span(
                        f"bsp.peer.{header.get('op')}", cat="bsp",
                        rank=worker.rank):
                    resp = worker._handle(header, arrays)
            send_frame(self.wfile, *resp)


class _BspServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class BspWorker:
    """One member of a tracker-coordinated BSP allreduce group.

    All collective entry points (`allreduce`, `broadcast`, `checkpoint`)
    are called from the worker's MAIN thread only; the embedded frame
    server's handler threads touch just the mailbox and the result cache
    (both lock-guarded).

    Constructor arguments are explicit (no env reads beyond knob
    defaults) so in-process tests can stand up a group without a
    launcher."""

    def __init__(self, rank: int, world: int, client,
                 snapshot_dir: Optional[str] = None,
                 host: str = "127.0.0.1",
                 step_timeout: Optional[float] = None,
                 retry_sec: Optional[float] = None,
                 wire: Optional[str] = None):
        self.rank = int(rank)
        self.world = int(world)
        self.client = client
        self.snapshot_dir = snapshot_dir or os.environ.get(
            "WH_SNAPSHOT_DIR") or None
        self.step_timeout = (step_timeout if step_timeout is not None
                             else knob_value("WH_BSP_STEP_TIMEOUT"))
        self.retry_sec = (retry_sec if retry_sec is not None
                          else knob_value("WH_BSP_RETRY_SEC"))
        # chunk wire encoding (WH_WIRE; see the module docstring for
        # why the BSP plane quantizes statelessly and allgathers bf16)
        w = (wire if wire is not None
             else os.environ.get("WH_WIRE") or "raw").strip().lower()
        self.wire_enc = w if w in ("bf16", "int8", "int4") else "raw"
        self.version = 0   # checkpoints completed
        self.seq = 0       # next collective's counter within the version
        self.gen = 0       # group membership generation (tracker-owned)
        self._uris: list[str] = []
        # replaying after load_checkpoint / a ring retry. A respawned
        # incarnation (WH_RESTORE_EPOCH > 0) starts behind even when it
        # died BEFORE its first checkpoint: version-0 results are still
        # in the survivors' caches (nothing pruned them), and ringing
        # seq 0 against survivors blocked at a later seq would deadlock.
        self._behind = int(os.environ.get("WH_RESTORE_EPOCH", "0")
                           or 0) > 0
        # mailbox: (gen, version, seq, step) -> chunk, deposited by
        # handler threads, consumed by the main loop
        self._mail: dict[tuple, np.ndarray] = {}
        self._mail_cv = threading.Condition()
        # completed collective results, (version, seq) -> array
        self._results: dict[tuple[int, int], np.ndarray] = {}
        self._results_lock = threading.Lock()
        self._conns: dict[int, object] = {}  # rank -> socket file (ours)
        self._srv_conns: set = set()         # accepted peer connections
        self._conns_lock = threading.Lock()
        self._closed = False
        self._srv = _BspServer((host, 0), _BspHandler)
        self._srv.worker = self  # type: ignore
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()
        h, p = self._srv.server_address[:2]
        self.uri = f"{h}:{p}"
        r = self.client.call(op="register_bsp", rank=self.rank,
                             world=self.world, uri=self.uri)
        self.gen = int(r.get("gen", 0))
        self._wait_group()

    # -- group membership ---------------------------------------------------
    def _wait_group(self) -> None:
        deadline = time.monotonic() + self.retry_sec
        while True:
            r = self.client.call(op="bsp_peers", world=self.world)
            if r["ready"]:
                self._adopt(int(r["gen"]), list(r["uris"]))
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"bsp group never reached {self.world} workers "
                    f"({r.get('num_known')} known)")
            time.sleep(0.1)

    def _adopt(self, gen: int, uris: list[str]) -> None:
        """Switch to a new membership generation: drop cached peer
        connections and every mailbox entry of an older generation.
        ELASTIC membership makes the peer list authoritative — a grown
        or shrunk group re-indexes the ring by list position, so world
        and rank follow the list (chunk boundaries are functions of
        (shape, world, rank), so the rebuilt ring is deterministic for
        the new set). A worker absent from the list has been retired or
        evicted; it keeps its old identity just long enough to exit."""
        self._uris = uris
        # re-index BEFORE the same-gen early return: an elastic joiner
        # learns its (already-bumped) gen from register_bsp's reply, so
        # its first _wait_group adopt arrives gen-equal but still needs
        # the authoritative world/rank
        if self.uri in uris and (len(uris) != self.world
                                 or uris.index(self.uri) != self.rank):
            old_r, old_w = self.rank, self.world
            self.world = len(uris)
            self.rank = uris.index(self.uri)
            print(f"[bsp] ring rebuilt at gen {gen}: rank/world "
                  f"{old_r}/{old_w} -> {self.rank}/{self.world}",
                  flush=True)
        if gen == self.gen:
            return
        self.gen = gen
        with self._conns_lock:
            conns, self._conns = dict(self._conns), {}
        for f in conns.values():
            try:
                f.close()
            except OSError:
                pass
        with self._mail_cv:
            for k in [k for k in self._mail if k[0] < gen]:
                del self._mail[k]

    def _poll_gen(self) -> bool:
        """Re-read the tracker's membership; True if the generation
        advanced (the signal that a peer died and respawned)."""
        try:
            r = self.client.call(op="bsp_peers", world=self.world)
        except OSError:
            return False
        if r["ready"] and int(r["gen"]) > self.gen:
            self._adopt(int(r["gen"]), list(r["uris"]))
            return True
        return False

    # -- frame server side --------------------------------------------------
    def _handle(self, header: dict, arrays: dict):
        op = header.get("op")
        if op == "bsp_step":
            key = (int(header["gen"]), int(header["ver"]),
                   int(header["seq"]), int(header["t"]))
            with self._mail_cv:
                self._mail[key] = arrays["x"]
                self._mail_cv.notify_all()
            return {"op": "ok"}, None
        if op == "bsp_fetch":
            want = (int(header["ver"]), int(header["seq"]))
            with self._results_lock:
                got = self._results.get(want)
            if got is not None:
                _FETCHES.inc()
                return ({"op": "ok", "hit": True,
                         "next": [self.version, self.seq]}, {"x": got})
            return ({"op": "ok", "hit": False,
                     "next": [self.version, self.seq]}, None)
        return {"op": "error", "error": f"unknown bsp op {op!r}"}, None

    # -- peer RPC -----------------------------------------------------------
    def _peer_file(self, rank: int):
        with self._conns_lock:
            f = self._conns.get(rank)
        if f is None:
            host, port = self._uris[rank].rsplit(":", 1)
            sock = connect_with_retry((host, int(port)),
                                      deadline_s=self.step_timeout,
                                      timeout=self.retry_sec)
            f = sock.makefile("rwb")
            with self._conns_lock:
                self._conns[rank] = f
        return f

    def _rpc(self, rank: int, header: dict, arrays=None):
        """One request/response frame to a peer's server. Any failure
        poisons the cached connection (a partial frame corrupts the
        stream), so it is dropped before the error propagates."""
        f = self._peer_file(rank)
        try:
            send_frame(f, header, arrays)
            got = recv_frame(f)
        except OSError:
            self._drop_conn(rank, f)
            raise
        if got is None:
            self._drop_conn(rank, f)
            raise ConnectionResetError(f"bsp peer {rank} closed mid-rpc")
        return got[0], got[1]

    def _drop_conn(self, rank: int, f) -> None:
        with self._conns_lock:
            if self._conns.get(rank) is f:
                del self._conns[rank]
        try:
            f.close()
        except OSError:
            pass

    # -- ring ----------------------------------------------------------------
    def _send_step(self, to: int, gen: int, key: tuple[int, int],
                   t: int, chunk, deadline: float) -> None:
        # `chunk` is an ndarray or a pre-quantized net.QuantRows; every
        # retry re-sends the SAME object, so the bytes never vary
        header = {"op": "bsp_step", "gen": gen, "ver": key[0],
                  "seq": key[1], "t": t, "src": self.rank}
        pace = min(0.2, self.step_timeout)
        budget = _retrylib.RetryBudget(
            max(deadline - time.monotonic(), 0.0),
            base_s=pace, cap_s=pace, op="bsp.step")
        while True:
            try:
                self._rpc(to, header, {"x": chunk})
                budget.succeeded()
                return
            except OSError:
                # successor unreachable: either transient or it died. A
                # death surfaces as a generation bump once its respawn
                # (or the survivors' shrunk ring) re-registers; until
                # then keep retrying within budget.
                if self._poll_gen():
                    raise _RoundAbort()
                if budget.expired:
                    budget.give_up(TimeoutError(
                        f"bsp rank {self.rank}: peer {to} unreachable for "
                        f"{self.retry_sec:.0f}s (step {t} of {key})"))
                budget.sleep()

    def _wait_step(self, gen: int, key: tuple[int, int], t: int,
                   deadline: float) -> np.ndarray:
        mkey = (gen, key[0], key[1], t)
        while True:
            with self._mail_cv:
                got = self._mail.pop(mkey, None)
                if got is None:
                    self._mail_cv.wait(self.step_timeout)
                    got = self._mail.pop(mkey, None)
            if got is not None:
                return got
            if self._poll_gen():
                raise _RoundAbort()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"bsp rank {self.rank}: no step {t} of {key} from "
                    f"predecessor within {self.retry_sec:.0f}s")

    def _wire_rs(self, chunk: np.ndarray):
        """Reduce-scatter wire form of a chunk: the configured encoding
        with grouped scales — a pure function of the chunk values, so a
        retried round re-sends identical bytes. Small chunks stay raw."""
        if self.wire_enc == "raw" or chunk.size < _WIRE_MIN_ELEMS:
            return chunk
        return quantize_rows(chunk, self.wire_enc)

    def _wire_ag(self, chunk: np.ndarray):
        """Allgather wire form: always bf16 when the codec is on. bf16
        rounding is idempotent, so every forwarding hop re-encodes the
        same 16 bits and all ranks reconstruct identical values."""
        if self.wire_enc == "raw" or chunk.size < _WIRE_MIN_ELEMS:
            return chunk
        return quantize_rows(chunk, "bf16")

    def _ring_round(self, key: tuple[int, int], flat: np.ndarray,
                    combine: Callable) -> np.ndarray:
        """One ring reduce-scatter + allgather at the current generation.
        Chunk boundaries (np.array_split) and the local-then-incoming
        accumulation order are functions of (shape, world, rank) only, so
        any retry or replay reproduces the result bit-for-bit. With the
        wire codec on, the rank that finishes reducing a chunk rounds
        its OWN copy to bf16 before the allgather — the same values
        every other rank will decode off the wire — so the concatenated
        result is bit-identical on all ranks."""
        gen0 = self.gen
        w, r = self.world, self.rank
        chunks = list(np.array_split(flat, w))
        succ = (r + 1) % w
        deadline = time.monotonic() + self.retry_sec
        for t in range(w - 1):  # reduce-scatter
            si = (r - t) % w
            ri = (r - t - 1) % w
            self._send_step(succ, gen0, key, t, self._wire_rs(chunks[si]),
                            deadline)
            got = self._wait_step(gen0, key, t, deadline)
            chunks[ri] = combine(chunks[ri], got)
        own = (r + 1) % w  # the chunk this rank finished reducing
        if (self.wire_enc != "raw"
                and chunks[own].size >= _WIRE_MIN_ELEMS):
            chunks[own] = quantize_rows(chunks[own], "bf16").dequant()
        for t in range(w - 1):  # allgather
            si = (r + 1 - t) % w
            ri = (r - t) % w
            self._send_step(succ, gen0, key, w - 1 + t,
                            self._wire_ag(chunks[si]), deadline)
            chunks[ri] = self._wait_step(gen0, key, w - 1 + t, deadline)
        return np.concatenate(chunks)

    # -- replay fetch --------------------------------------------------------
    def _fetch_result(self, key: tuple[int, int]) -> Optional[np.ndarray]:
        """Ask every peer for the cached result of `key`. Returns the
        array on a hit; None when the group provably has not completed
        `key` yet (we are live — join the ring). Peers whose counter is
        PAST `key` but miss the cache mean the window was pruned: the
        group ran a full version ahead while we were gone, which the
        checkpoint protocol rules out for any recoverable death."""
        ahead = False
        reached = 0
        for peer in range(self.world):
            if peer == self.rank:
                continue
            try:
                h, arrs = self._rpc(peer, {"op": "bsp_fetch",
                                           "ver": key[0], "seq": key[1]})
            except OSError:
                continue
            reached += 1
            if h.get("hit"):
                return np.array(arrs["x"])  # own writable copy
            if tuple(h.get("next", (0, 0))) > key:
                ahead = True
        if ahead:
            raise RuntimeError(
                f"bsp rank {self.rank}: result {key} was pruned by peers "
                "(recovery window is one version)")
        if reached == 0 and self.world > 1:
            raise ConnectionError("no bsp peer reachable for replay fetch")
        return None

    def _collective(self, key: tuple[int, int], flat: np.ndarray,
                    combine: Callable) -> np.ndarray:
        attempt_fetch = self._behind
        pace = min(0.2, self.step_timeout)
        budget = _retrylib.RetryBudget(self.retry_sec, base_s=pace,
                                       cap_s=pace, op="bsp.fetch")
        while True:
            if attempt_fetch:
                try:
                    got = self._fetch_result(key)
                except ConnectionError as e:
                    if budget.expired:
                        budget.give_up(e)
                    budget.sleep()
                    self._poll_gen()
                    continue
                if got is not None:
                    return got
                self._behind = False  # caught up: this round is live
            if self.world == 1:
                return flat.copy()
            try:
                return self._ring_round(key, flat, combine)
            except _RoundAbort:
                # membership rolled mid-round. Fetch-first on retry: a
                # survivor one step ahead may have completed this round,
                # and re-ringing a completed round would deadlock.
                _RING_RETRIES.inc()
                attempt_fetch = True
                budget = _retrylib.RetryBudget(self.retry_sec, base_s=pace,
                                               cap_s=pace, op="bsp.fetch")

    # -- public API ----------------------------------------------------------
    def allreduce(self, x, op: str = "sum") -> np.ndarray:
        """Reduce `x` elementwise across the group; every rank returns
        the bit-identical reduced array (float32 on the wire)."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.worker_op("allreduce")
        t0 = time.perf_counter()
        # asarray, not ascontiguousarray: the latter promotes 0-d to 1-d
        # and solver scalars (raw losses) must round-trip shape ()
        x = np.asarray(x, np.float32)
        key = (self.version, self.seq)
        with _trace.bind(_trace.start_request()), \
                _trace.request_span("bsp.round", cat="bsp",
                                    ver=key[0], seq=key[1]):
            out = self._collective(key, np.ascontiguousarray(x.ravel()),
                                   _OPS[op]).reshape(x.shape)
        with self._results_lock:
            self._results[key] = out
        self.seq += 1  # AFTER the cache write: next>key implies cached
        _ROUNDS.inc()
        _ALLREDUCE_S.observe(time.perf_counter() - t0)
        return out

    def broadcast(self, x, root: int = 0) -> np.ndarray:
        """Every rank returns root's array. Consumes one counter of the
        same (version, seq) sequence as allreduce, so it replays the
        same way: non-roots fetch the value from root's result cache."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.worker_op("broadcast")
        key = (self.version, self.seq)
        if self.rank == root:
            out = np.ascontiguousarray(
                np.asarray(x, np.float32).ravel()).reshape(np.shape(x))
        else:
            pace = min(0.1, self.step_timeout)
            budget = _retrylib.RetryBudget(self.retry_sec, base_s=pace,
                                           cap_s=pace, op="bsp.broadcast")
            while True:
                try:
                    h, arrs = self._rpc(root, {"op": "bsp_fetch",
                                               "ver": key[0],
                                               "seq": key[1]})
                    if h.get("hit"):
                        out = np.array(arrs["x"])
                        budget.succeeded()
                        break
                except OSError:
                    self._poll_gen()
                if budget.expired:
                    budget.give_up(TimeoutError(
                        f"bsp rank {self.rank}: broadcast {key} never "
                        f"published by root {root}"))
                budget.sleep()
        with self._results_lock:
            self._results[key] = out
        self.seq += 1
        _ROUNDS.inc()
        return out

    def checkpoint(self, state: dict) -> None:
        """End a synchronized round: bump the version, reset the counter,
        persist `state` (a dict of arrays) version-stamped and atomic,
        and prune the result cache to the one-version recovery window."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.worker_op("checkpoint")
        t0 = time.perf_counter()
        self.version += 1
        self.seq = 0
        if self.snapshot_dir:
            from wormhole_tpu.utils.checkpoint import atomic_savez

            path = self._ckpt_path()
            atomic_savez(path, __version=np.int64(self.version), **state)
            _CKPT_BYTES.inc(os.path.getsize(path))
        with self._results_lock:
            floor = self.version - 1
            for k in [k for k in self._results if k[0] < floor]:
                del self._results[k]
        _CHECKPOINTS.inc()
        _CKPT_S.observe(time.perf_counter() - t0)

    def load_checkpoint(self) -> Optional[dict]:
        """Restore this rank's last checkpoint (None if none exists).
        Rewinds (version, seq) to the checkpoint boundary and switches
        the worker into replay mode: until its collectives stop hitting
        peers' caches, results are fetched instead of re-reduced."""
        if not self.snapshot_dir:
            return None
        path = self._ckpt_path()
        if not os.path.exists(path):
            return None
        with np.load(path, allow_pickle=False) as z:
            state = {k: z[k] for k in z.files}
        self.version = int(state.pop("__version"))
        self.seq = 0
        self._behind = True
        return state

    def leave(self) -> None:
        """Resign from the BSP group (elastic retire): bump the tracker
        generation so survivors rebuild the ring without this rank at
        their next round boundary. Best-effort — a crash reaches the
        same end state through liveness eviction; sends both rank and
        uri because a re-indexed survivor's rank may no longer match
        its tracker registration."""
        try:
            self.client.call(op="bsp_leave", rank=self.rank, uri=self.uri)
        except (OSError, ConnectionError):
            pass

    def _ckpt_path(self) -> str:
        return os.path.join(self.snapshot_dir, f"bsp_rank{self.rank}.npz")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._srv.shutdown()
        self._srv.server_close()
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns = {}
            srv_conns = list(self._srv_conns)
        for f in conns:
            try:
                f.close()
            except OSError:
                pass
        for c in srv_conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
