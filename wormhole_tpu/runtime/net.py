"""Shared TCP plumbing for the runtime control/data planes.

Connection establishment retries with backoff (the launcher spawns all
node processes concurrently, so clients routinely race ahead of a
server's bind); once a connection exists, request/response failures are
NOT retried here — the ops they carry (barrier entry, part assignment)
are not idempotent, so replay policy belongs to the caller.
"""

from __future__ import annotations

import socket
import time


def connect_with_retry(addr: tuple[str, int], deadline_s: float = 30.0,
                       timeout: float = 60.0) -> socket.socket:
    """Dial `addr`, retrying refused/unreachable connections with
    exponential backoff until `deadline_s` elapses."""
    deadline = time.monotonic() + deadline_s
    backoff = 0.05
    while True:
        try:
            return socket.create_connection(addr, timeout=timeout)
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
