"""Shared TCP plumbing for the runtime control/data planes.

Connection establishment retries with backoff (the launcher spawns all
node processes concurrently, so clients routinely race ahead of a
server's bind); once a connection exists, request/response failures are
NOT retried here — the ops they carry (barrier entry, part assignment)
are not idempotent, so replay policy belongs to the caller. (The PS
data plane layers a fenced, idempotent retry on top: PSClient stamps
pushes with per-sender sequence numbers the servers deduplicate, which
is what makes ITS replay safe — see runtime/ps_server.py.)

This module also owns the PS wire format. Frame = 4-byte big-endian
header length | JSON header | raw payload. header = {"op": str, ...meta,
"arrays": [{"name", "shape", "enc", "scale", "nbytes"}, ...]}; payload =
buffers concatenated in array order. Integer arrays (sparse-push/pull
row indices) ride the same frame with enc="i32"/"i64" — under the
negotiated bshuf mode a sorted 1-D index array additionally ships
delta-encoded ("dlt": 1 — first value + gaps, cumsum on decode), which
zeroes its high byte planes for the shuffle; float payloads
may additionally ship quantized (enc="bf16"/"int8"/"int8r"/"int4"/
"int4r" — the r-suffixed forms carry per-row f32 scales appended to the
code bytes; int4 packs two biased nibbles per byte). "comp": "zlib" (or
"bshuf+zlib", the byte-plane-shuffled form) marks a compressed buffer
("nbytes" is then the compressed size, "rawbytes" the original). Key-list caching (the reference's KEY_CACHING
filter) rides the JSON header as `key_digest()` fingerprints — a frame
whose digest the receiver has cached omits the index array entirely
(runtime/ps_server.py owns the cache + miss/full-resend protocol).

Decoded arrays are zero-copy views over the received buffer and may be
READ-ONLY (raw/i32/i64 encodings); callers that mutate a decoded array
in place must copy it first.

Fault injection (runtime/faults.py) hooks frame send/recv; the guards
are module-level None checks so an unfaulted process pays nothing.
Wire accounting (frames/bytes in+out, encode/decode latency, connect
retries) lands in the process-wide metrics registry (wormhole_tpu/obs)
via handles cached at import.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import sys
import struct
import threading
import time
import zlib
from typing import Optional

import numpy as np

from wormhole_tpu.obs import flight as _flight
from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.runtime import faults
from wormhole_tpu.runtime import overload as _overload
from wormhole_tpu.runtime import retry as _retry

_COMPRESS_MIN = 512  # don't bother compressing tiny buffers

# wire codec v2: value encodings a peer may negotiate (WH_WIRE). "raw"
# ships f32; the rest quantize float payloads (never index arrays).
WIRE_ENCODINGS = ("raw", "bf16", "int8", "int4")
# frame compression modes (WH_WIRE_COMP / WH_NET_COMPRESS): "bshuf"
# byte-plane-shuffles multi-byte payloads before zlib-1 so the
# same-significance bytes (exponents especially) group into long runs
WIRE_COMP_MODES = ("", "zlib", "bshuf")

# Central declaration table for every top-level header key the frame
# protocol (and the scheduler's newline-JSON RPC) carries. The
# `frame-header` wormlint checker parses this dict literal statically
# (never importing the module) and flags any undeclared key read or
# written at a header site — the wire vocabulary equivalent of the
# obs/names.py metric registry. Per-array metadata (the entries of the
# "arrays" list: name/shape/enc/scale/nbytes/comp/rawbytes/dlt/gs/goff)
# is the codec's own and is not declared here.
# fmt: off
HEADER_KEYS: dict[str, str] = {
    # -- every frame / every plane
    "op": "request verb (push/pull/fetch/score/hello/bsp_step/...)",
    "arrays": "per-payload array metadata list (codec-owned fields)",
    "sender": "stable client identity for seq dedup and reply caching",
    "seq": "per-sender request sequence number (exactly-once retries)",
    "error": "reply-side failure message; absence means success",
    "ok": "reply-side success marker",
    "tctx": "sampled request-trace context (obs/trace.py bind_wire)",
    "dl": "relative deadline budget in seconds, stamped at send",
    "dl_mono": "receiver-anchored absolute deadline (overload.arm)",
    "shed": "reply marker: the deadline expired before dispatch",
    "busy": "reply marker: the admission gate bounced this frame",
    "retry_ms": "suggested client backoff attached to a busy reply",
    # -- hello negotiation (PS + serving)
    "net_compress": "both ends agree to zlib frame compression",
    "wire": "negotiated value encoding (WIRE_ENCODINGS) for payloads",
    "wire_comp": "negotiated frame compression mode (WIRE_COMP_MODES)",
    "wire_ef": "client uses error-feedback residuals on quantized pushes",
    "comp_reply": "server will compress its replies to this client",
    "world": "shard-group size echoed in hello (config cross-check)",
    # -- PS data plane (runtime/ps_server.py)
    "epoch": "server restore epoch stamped on every PS reply",
    "full_rows": "table name -> row count map (init / hello replies)",
    "specs": "table name -> dtype/shape spec map (init_spec)",
    "derived": "derived-table expressions shipped with init_spec",
    "since": "client clock for incremental pulls",
    "skip": "pull reply: rows unchanged since `since`, payload omitted",
    "clock": "server logical clock stamped on pull replies",
    "last_seq": "highest per-sender push seq the server has applied",
    "dup": "push reply: seq already applied, delta dropped (dedup)",
    "kc": "client requests key-list digest caching for this push",
    "kdig": "group -> key-list digest map (key cache probe)",
    "kfull": "group -> digest map acknowledging a full key resend",
    "known": "digest probe reply: all digests matched the cache",
    "need": "digest probe reply: groups needing a full key resend",
    "need_keys": "push reply: digest missed, client must resend keys",
    "base": "snapshot base path for save/load ops",
    "iter": "snapshot iteration label for save/load ops",
    # -- serving plane (serving/server.py, serving/router.py)
    "version": "model snapshot version stamped on serving replies",
    "kind": "score-op model kind (linear/difacto)",
    "rows": "live row count of a score round's fold target",
    "tables": "table names requested by a fetch",
    "rep": "fetch wants replicated (full) tables, not range slices",
    "queue_s": "shard-side recv-to-dispatch queue wait (stage attribution)",
    "served_s": "shard-side handler service time (stage attribution)",
    "degraded": "reply served under degraded mode (bounded staleness)",
    "threshold": "difacto admission threshold for the score op",
    "vb": "difacto V-table hash buckets for the score op",
    "l1_shrk": "difacto l1-shrink admission flag for the score op",
    # -- BSP collective plane (runtime/allreduce.py)
    "gen": "group membership generation (tracker-owned fencing)",
    "ver": "BSP checkpoint version of the collective",
    "t": "ring step index within one allreduce round",
    "src": "sending rank of a bsp_step frame",
    "hit": "bsp_fetch reply: the cached reduced result was present",
    "next": "bsp_fetch reply: (ver, seq) the peer advanced to",
    # -- scheduler control plane (runtime/tracker.py, newline-JSON RPC)
    "inc": "scheduler incarnation stamped on every reply (restart fence)",
    "fgen": "flight-recorder trigger generation piggybacked on replies",
    "fwhy": "flight-recorder trigger reason piggybacked on replies",
    "node": "reporting node's name (heartbeats, registrations)",
    "rank": "role-group rank of the registering node",
    "uri": "RPC endpoint the registering node listens on",
    "part_id": "workload part id assigned by get / finished by finish",
    "mepoch": "membership epoch stamped on part grants and completions",
    "metrics": "heartbeat-piggybacked metrics snapshot",
    "format": "workload pattern format argument of add_local",
    "files": "workload file list argument of add_local",
    "progress": "progress blob attached to a finish/report op",
    "data": "blob payload of blob_put",
    "key": "blob name of blob_put/blob_get/blob_del",
    "name": "barrier name of a barrier/barrier_wait op",
    "target": "desired worker count in an elastic reply",
    "history": "metrics verb: client wants the telemetry ring, not a spot",
    "slo": "metrics verb: client wants SLO burn judgments included",
    "reason": "flight-trigger op: why the cluster dump fired",
}
# fmt: on

# handles cached at import: per-frame cost is an inc, never a dict walk
_FRAMES_SENT = _obs.REGISTRY.counter("net.frames_sent")
_FRAMES_RECV = _obs.REGISTRY.counter("net.frames_recv")
_BYTES_SENT = _obs.REGISTRY.counter("net.bytes_sent")
_BYTES_RECV = _obs.REGISTRY.counter("net.bytes_recv")
_CONNECT_RETRIES = _obs.REGISTRY.counter("net.connect_retries")
_ENCODE_S = _obs.REGISTRY.histogram("net.encode_s")
_DECODE_S = _obs.REGISTRY.histogram("net.decode_s")
# frame-compression accounting (WH_NET_COMPRESS / per-call compress=):
# compressed payload bytes that actually crossed the wire, both
# directions, so the run report can state the codec's measured effect
_COMPRESS_OUT = _obs.REGISTRY.counter("net.compress.bytes_out")
_COMPRESS_IN = _obs.REGISTRY.counter("net.compress.bytes_in")
_BUSY_REJECTIONS = _obs.REGISTRY.counter("net.busy.rejections")
_BUSY_RETRIES = _obs.REGISTRY.counter("net.busy.retries")
# value-codec accounting: f32-equivalent bytes a quantized float payload
# WOULD have cost vs what it actually cost on the wire (savings =
# bytes_raw / bytes_wire); index arrays and raw floats are not counted
_WIRE_RAW = _obs.REGISTRY.counter("wire.codec.bytes_raw")
_WIRE_BYTES = _obs.REGISTRY.counter("wire.codec.bytes_wire")
_WIRE_EF_NORM = _obs.REGISTRY.gauge("wire.codec.ef_resid_norm")
# byte-shuffle framing: payload bytes that crossed the wire under
# comp="bshuf+zlib", both directions
_BSHUF_OUT = _obs.REGISTRY.counter("net.bshuf.bytes_out")
_BSHUF_IN = _obs.REGISTRY.counter("net.bshuf.bytes_in")


class InflightGate:
    """Server-side admission gate: at most WH_NET_MAX_INFLIGHT requests
    may be in their handler concurrently; the overflow gets a structured
    `busy` reply (see `busy_reply`) instead of queueing behind a
    saturated thread pool. 0 (the default) admits everything — existing
    PS deployments see no behavior change unless they opt in. The knob
    is read once at server construction; per-request cost at the default
    is a single None check."""

    def __init__(self, limit: Optional[int] = None):
        if limit is None:
            limit = int(os.environ.get("WH_NET_MAX_INFLIGHT", "0") or 0)
        self.limit = max(int(limit), 0)
        self._sem = (threading.BoundedSemaphore(self.limit)
                     if self.limit else None)

    def try_enter(self) -> bool:
        """Admit one request; False means the caller must send
        `busy_reply()` and NOT dispatch (and must not `leave()`)."""
        if self._sem is None:
            return True
        ok = self._sem.acquire(blocking=False)
        if not ok:
            _BUSY_REJECTIONS.inc()
        return ok

    def leave(self) -> None:
        if self._sem is not None:
            self._sem.release()


def busy_reply(retry_ms: float = 25.0) -> dict:
    """Header of the structured backpressure reply. Not an `error`:
    nothing was dispatched, the client should back off `retry_ms`
    (jittered) and resend the SAME frame — for seq-fenced ops the fence
    stamp is reused, so the eventual apply is still exactly-once.
    Servers pass `AdmissionController.busy_hint_ms()` here so the hint
    scales with observed reject pressure instead of pinning every
    bounced client to the same fixed 25 ms re-arrival."""
    return {"busy": 1, "retry_ms": float(retry_ms)}


def busy_backoff(header: dict, budget: Optional[_retry.RetryBudget] = None
                 ) -> bool:
    """Client side of the gate: True when `header` is a busy reply, after
    sleeping its hint under the unified full-jitter policy — the caller
    just retries its frame.  With a `budget` the sleep is additionally
    capped to the remaining retry window (and counted against it), so a
    storm of busy replies can't walk an op past its own deadline."""
    if not header.get("busy"):
        return False
    _BUSY_RETRIES.inc()
    hint = float(header.get("retry_ms", 25.0)) / 1000.0
    if budget is not None:
        budget.sleep(hint_s=hint)
    else:
        _retry.jitter_sleep(hint)
    return True


def connect_with_retry(addr: tuple[str, int], deadline_s: float = 30.0,
                       timeout: float = 60.0) -> socket.socket:
    """Dial `addr`, retrying refused/unreachable connections until
    `deadline_s` elapses.  The loop itself lives in runtime/retry.py
    (the unified deadline-budgeted policy); this wrapper keeps the
    historical `net.connect_retries` per-failure counter."""
    return _retry.connect(addr, deadline_s, timeout,
                          on_retry=_CONNECT_RETRIES.inc)


def _bf16_round(a: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even truncation of f32 to the high 16 bits."""
    u = a.view(np.uint32)
    return ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(np.uint16)


def _row_scales(a: np.ndarray, qmax: int) -> np.ndarray:
    """Per-row (axis-0) absmax scales for a 2-D+ array — one outlier row
    no longer flattens every other row's resolution (the historical
    global-absmax int8 bug)."""
    absmax = np.abs(a).reshape(a.shape[0], -1).max(axis=1)
    return np.maximum(absmax, 1e-30).astype(np.float32) / qmax


# scale-group width for 1-D arrays: one f32 absmax scale per GROUP of
# contiguous elements (4/64 = 6.25% overhead on int8). A scalar scale
# over a whole compacted touched-row vector is catastrophic for skewed
# tables — one hot FTRL z/n row flattens the resolution of the other
# ~10^5 rows in the same payload to zero and the model diverges (the
# same failure per-row scales fix for 2-D); groups keep the outlier's
# blast radius to 63 neighbors.
_GROUP = 64


def _group_scales(a: np.ndarray, qmax: int) -> np.ndarray:
    """Per-group absmax scales of a 1-D array (last group may be
    short)."""
    n = a.size
    ng = -(-n // _GROUP)
    absmax = np.abs(a)
    if ng * _GROUP != n:
        absmax = np.concatenate(
            [absmax, np.zeros(ng * _GROUP - n, np.float32)])
    gmax = absmax.reshape(ng, _GROUP).max(axis=1)
    return np.maximum(gmax, 1e-30).astype(np.float32) / qmax


def _expand_gscales(scale: np.ndarray, gs: int, goff: int,
                    n: int) -> np.ndarray:
    """Per-element scale vector of a (possibly sliced) grouped array:
    element i belongs to group (goff + i) // gs. Used identically by
    QuantRows.dequant and _decode so both ends multiply the same
    floats."""
    return np.repeat(scale, gs)[goff:goff + n]


def _pack4(q: np.ndarray) -> bytes:
    """Pack int8 values in [-7, 7] into nibbles, two per byte (bias +8
    so the packed range is 1..15; a trailing odd value pads with 0)."""
    b = (q.reshape(-1).astype(np.int16) + 8).astype(np.uint8)
    if b.size % 2:
        b = np.concatenate([b, np.zeros(1, np.uint8)])
    return (b[0::2] | (b[1::2] << 4)).tobytes()


def _unpack4(buf: bytes, n: int) -> np.ndarray:
    """Inverse of _pack4: n int8 values in [-7, 7]."""
    b = np.frombuffer(buf, np.uint8)
    out = np.empty(2 * b.size, np.int8)
    out[0::2] = (b & 0x0F).astype(np.int8) - 8
    out[1::2] = (b >> 4).astype(np.int8) - 8
    return out[:n]


class QuantRows:
    """An array quantized ONCE, client-side, ahead of the frame layer.

    The EF push path quantizes a sync round's delta rows exactly once
    (at snapshot time) and hands the quantized form through push_sparse;
    row-range slicing for the per-server split and journal replay both
    operate on this object, so every (re)send of the same logical rows
    serializes to the same bytes — that determinism is what keeps the
    seq-fenced retry exactly-once under quantization.

    `q` holds the integer codes (int8 for int8/int4, uint16 for bf16);
    `scale` is a scalar (legacy peers), a per-row f32 vector aligned to
    axis 0 (2-D+), or — when `gs` is set — one f32 per `gs`-element
    GROUP of a 1-D array, with `goff` the phase of element 0 within the
    group grid (a contiguous slice keeps the parent's group boundaries,
    so per-server splits stay cheap views)."""

    __slots__ = ("enc", "q", "scale", "gs", "goff")

    def __init__(self, enc: str, q: np.ndarray, scale,
                 gs: Optional[int] = None, goff: int = 0):
        self.enc = enc
        self.q = q
        self.scale = scale
        self.gs = gs
        self.goff = goff

    @property
    def shape(self):
        return self.q.shape

    def __len__(self):
        return len(self.q)

    def __getitem__(self, sel) -> "QuantRows":
        if self.gs is not None:
            if not isinstance(sel, slice) or sel.step not in (None, 1):
                raise TypeError(
                    "grouped QuantRows supports contiguous slices only")
            a, b, _ = sel.indices(self.q.size)
            ga, gb = (self.goff + a) // self.gs, -(-(self.goff + b)
                                                   // self.gs)
            return QuantRows(self.enc, self.q[sel], self.scale[ga:gb],
                             self.gs, (self.goff + a) % self.gs)
        s = (self.scale[sel] if isinstance(self.scale, np.ndarray)
             else self.scale)
        return QuantRows(self.enc, self.q[sel], s)

    def dequant(self) -> np.ndarray:
        """The f32 values a receiver will decode — EXACTLY: the same
        integer-to-float multiply _decode performs, so the sender can
        account residuals against what the peer really applied."""
        if self.enc == "bf16":
            return (self.q.astype(np.uint32) << 16).view(np.float32)
        f = self.q.astype(np.float32)
        if self.gs is not None:
            return f * _expand_gscales(self.scale, self.gs, self.goff,
                                       f.size)
        if isinstance(self.scale, np.ndarray):
            return f * self.scale.reshape((-1,) + (1,) * (f.ndim - 1))
        return f * self.scale

    def wire_nbytes(self) -> int:
        """Pre-compression payload size _encode_quant will emit (the
        wire-savings accounting unit for wire_stats)."""
        n = int(self.q.size)
        if self.enc == "bf16":
            body = 2 * n
        elif self.enc == "int8":
            body = n
        else:  # int4: two codes per byte
            body = (n + 1) // 2
        if isinstance(self.scale, np.ndarray):
            body += 4 * int(self.scale.size)
        return body


def quantize_rows(a: np.ndarray, enc: str,
                  per_row: bool = True) -> QuantRows:
    """Quantize a float array under wire encoding `enc`. Per-row scales
    are used for 2-D+ arrays and per-_GROUP-element scales for 1-D
    arrays (unless `per_row` is False — the legacy / old-peer form,
    one scalar absmax scale)."""
    a = np.ascontiguousarray(a, np.float32)
    if enc == "bf16":
        return QuantRows("bf16", _bf16_round(a), None)
    qmax = 127 if enc == "int8" else 7
    if enc not in ("int8", "int4"):
        raise ValueError(f"unknown wire encoding {enc!r}")
    if per_row and a.ndim >= 2:
        scale = _row_scales(a, qmax)
        x = a / scale.reshape((-1,) + (1,) * (a.ndim - 1))
    elif per_row and a.ndim == 1 and a.size:
        scale = _group_scales(a, qmax)
        x = a / _expand_gscales(scale, _GROUP, 0, a.size)
        q = np.clip(np.round(x), -qmax, qmax).astype(np.int8)
        return QuantRows(enc, q, scale, _GROUP, 0)
    else:
        scale = float(max(np.max(np.abs(a), initial=0.0), 1e-30) / qmax)
        x = a / scale
    q = np.clip(np.round(x), -qmax, qmax).astype(np.int8)
    return QuantRows(enc, q, scale)


class EFQuant:
    """Sender-side error-feedback accumulator over a sparse row space:
    transmit Q(x + r), keep r <- (x + r) - Q(.) so the quantization
    error of every row is re-injected the next time that row ships,
    making int8/int4 value streams unbiased over time.

    Residual support is the set of rows ever sent and not yet fully
    corrected, stored as a sorted index vector + aligned value rows
    (vectorized searchsorted merge — no per-row Python). `cap` bounds
    the support; overflow drops the smallest-magnitude residuals (the
    ones that matter least) and counts them.

    Used on both halves of the PS plane: SyncedStore's push path (one
    accumulator per table, advanced ONCE per logical sync — journal
    replays and need_keys resends reuse the returned QuantRows, so a
    seq-fenced retry can never double-apply a residual) and the PS
    server's pull side (one accumulator per sender per table; pulls are
    absolute-value refreshes, so a lost reply self-corrects on the next
    pull instead of double-counting)."""

    def __init__(self, enc: str, per_row: bool = True,
                 cap: int = 1 << 22):
        self.enc = enc
        self.per_row = per_row
        self.cap = int(cap)
        self.dropped = 0
        self._idx = np.empty(0, np.int64)
        self._val: Optional[np.ndarray] = None

    def apply(self, idx: np.ndarray, values: np.ndarray) -> QuantRows:
        """Quantize `values` (rows aligned to sorted-unique global ids
        `idx`) with this state's residuals folded in; advances the
        residuals. Call ONCE per logical send — replays must reuse the
        returned QuantRows, never re-apply."""
        idx = np.asarray(idx, np.int64)
        x = np.array(values, np.float32, copy=True)
        if self._idx.size and idx.size:
            pos = np.minimum(np.searchsorted(self._idx, idx),
                             self._idx.size - 1)
            hit = self._idx[pos] == idx
            if hit.any():
                x[hit] += self._val[pos[hit]]
        qr = quantize_rows(x, self.enc, self.per_row)
        r = x - qr.dequant()
        if self._idx.size:
            if idx.size:
                pos = np.minimum(np.searchsorted(idx, self._idx),
                                 idx.size - 1)
                keep = idx[pos] != self._idx
            else:
                keep = np.ones(self._idx.size, bool)
            new_idx = np.concatenate([self._idx[keep], idx])
            new_val = np.concatenate([self._val[keep], r])
            order = np.argsort(new_idx, kind="stable")
            self._idx, self._val = new_idx[order], new_val[order]
        else:
            self._idx = idx.copy()
            self._val = r
        if self._idx.size > self.cap:
            norm = np.abs(self._val).reshape(self._idx.size, -1).max(axis=1)
            keep_i = np.sort(np.argpartition(norm, -self.cap)[-self.cap:])
            self.dropped += self._idx.size - self.cap
            self._idx, self._val = self._idx[keep_i], self._val[keep_i]
        _WIRE_EF_NORM.set(self.resid_norm())
        if os.environ.get("WH_WIRE_DEBUG"):
            dq = qr.dequant()
            print(f"[efq] n={idx.size} |d|max={np.abs(values).max():.3g}"
                  f" |x|max={np.abs(x).max():.3g}"
                  f" |r|max={np.abs(r).max():.3g}"
                  f" |err|={np.linalg.norm(x - dq):.3g}"
                  f" resid_norm={self.resid_norm():.3g}",
                  file=sys.stderr, flush=True)
        return qr

    def resid_norm(self) -> float:
        if self._val is None or not self._idx.size:
            return 0.0
        return float(np.linalg.norm(self._val))

    def reset(self) -> None:
        """Drop all residual state (restore / reconnect invalidation:
        the peer's adopted values rolled back, so the accumulated error
        no longer describes anything)."""
        self._idx = np.empty(0, np.int64)
        self._val = None


def _bshuf(buf: bytes, itemsize: int) -> bytes:
    """Byte-plane shuffle: transpose the N x itemsize byte view so the
    k-th byte of every element lands contiguously. Float exponent bytes
    are near-constant across a table, so the shuffled stream compresses
    both better and FASTER under zlib-1 (long literal runs)."""
    b = np.frombuffer(buf, np.uint8)
    return b.reshape(-1, itemsize).T.tobytes()


def _unbshuf(buf: bytes, itemsize: int) -> bytes:
    b = np.frombuffer(buf, np.uint8)
    return b.reshape(itemsize, -1).T.tobytes()


_ENC_ITEMSIZE = {"raw": 4, "bf16": 2, "i32": 4, "i64": 8}


def _compress_buf(meta: dict, buf: bytes, mode: str) -> bytes:
    """Apply the negotiated frame compression to one encoded buffer.
    `mode` is "zlib" or "bshuf" (bshuf composes the byte-plane shuffle
    with zlib-1 and falls back to plain zlib for single-byte or
    mixed-layout encodings, where there is nothing to transpose)."""
    if len(buf) < _COMPRESS_MIN:
        return buf
    isz = _ENC_ITEMSIZE.get(meta["enc"], 1)
    if mode == "bshuf" and isz > 1 and len(buf) % isz == 0:
        # level 6 here, not 1: the shuffle concentrates the stream's
        # redundancy into long same-plane runs (near-constant exponent
        # bytes, zeroed high planes of delta-coded indices) where the
        # deeper match search keeps paying; the noisy mantissa planes
        # fall out as stored blocks either way. Plain zlib below stays
        # at 1 — it only ever sees unshuffled int8/mixed buffers where
        # level 6 buys ~nothing and costs the whole deflate budget.
        c = zlib.compress(_bshuf(buf, isz), 6)
        tag = "bshuf+zlib"
    else:
        c = zlib.compress(buf, 1)
        tag = "zlib"
    if len(c) < len(buf):
        meta.update(comp=tag, rawbytes=meta["nbytes"], nbytes=len(c))
        return c
    return buf


def _encode(a, fixed_bytes: int = 0,
            compress=False) -> tuple[dict, bytes]:
    """Encode one array for the wire. Float arrays honor fixed_bytes:
    0 = raw f32, 2 = bfloat16 bit-truncation (round-to-nearest-even),
    1 = absmax int8. Integer arrays always go raw (they are row indices;
    rounding them would corrupt the scatter). A QuantRows input is
    already quantized (the EF paths) and serializes deterministically.
    `compress` may be False, True/"zlib", or "bshuf"."""
    if isinstance(a, QuantRows):
        meta, buf = _encode_quant(a)
    else:
        meta = {"shape": list(a.shape)}
        if np.issubdtype(a.dtype, np.integer):
            a = np.ascontiguousarray(
                a, dtype=np.int64 if a.dtype.itemsize > 4 else np.int32)
            enc = "i64" if a.dtype == np.int64 else "i32"
            if compress == "bshuf" and a.ndim == 1 and a.size >= 128:
                # delta-encode sorted key lists (the classic PS wire
                # trick): sorted-unique row indices become first value +
                # gaps, whose high byte planes are ~all zero — bshuf+zlib
                # then collapses them, where the absolute values' low
                # bytes are incompressible noise. Lossless (cumsum on
                # decode), gated on the negotiated bshuf mode so old
                # peers never see the form.
                d = np.diff(a)
                if d.size == 0 or bool((d >= 0).all()):
                    out = np.empty_like(a)
                    if a.size:
                        out[0] = a[0]
                        out[1:] = d
                    a = out
                    meta["dlt"] = 1
            buf = a.tobytes()
            meta.update(enc=enc, nbytes=len(buf))
        else:
            a = np.ascontiguousarray(a, dtype=np.float32)
            if fixed_bytes == 0:
                buf = a.tobytes()
                meta.update(enc="raw", nbytes=len(buf))
            elif fixed_bytes >= 2:
                buf = _bf16_round(a).tobytes()
                meta.update(enc="bf16", nbytes=len(buf))
            else:
                scale = float(
                    max(np.max(np.abs(a), initial=0.0), 1e-30) / 127.0)
                q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
                buf = q.tobytes()
                meta.update(enc="int8", scale=scale, nbytes=len(buf))
    if meta["enc"] not in ("raw", "i32", "i64"):
        _WIRE_RAW.inc(4 * int(np.prod(meta["shape"], dtype=np.int64)))
        _WIRE_BYTES.inc(meta["nbytes"])
    if compress:
        mode = compress if isinstance(compress, str) else "zlib"
        buf = _compress_buf(meta, buf, mode)
        if meta.get("comp") == "bshuf+zlib":
            _BSHUF_OUT.inc(meta["nbytes"])
    return meta, buf


def _encode_quant(a: QuantRows) -> tuple[dict, bytes]:
    """Serialize a pre-quantized array. Wire forms:
    bf16   — identical to the fixed_bytes=2 encoding;
    int8   — scalar scale (the legacy form old peers decode);
    int8r  — per-row scales: q bytes then shape[0] f32 scales;
    int8g  — grouped 1-D: q bytes then per-group f32 scales, group
             size and slice phase in meta (gs/goff);
    int4   — nibble-packed, scalar scale;
    int4r / int4g — nibble-packed per-row / grouped forms."""
    meta: dict = {"shape": list(a.shape)}
    per_row = isinstance(a.scale, np.ndarray)
    grouped = a.gs is not None
    if a.enc == "bf16":
        buf = np.ascontiguousarray(a.q).tobytes()
        meta.update(enc="bf16", nbytes=len(buf))
    elif a.enc == "int8":
        buf = np.ascontiguousarray(a.q).tobytes()
        if grouped:
            buf += np.ascontiguousarray(a.scale, np.float32).tobytes()
            meta.update(enc="int8g", gs=a.gs, goff=a.goff,
                        nbytes=len(buf))
        elif per_row:
            buf += np.ascontiguousarray(a.scale, np.float32).tobytes()
            meta.update(enc="int8r", nbytes=len(buf))
        else:
            meta.update(enc="int8", scale=float(a.scale), nbytes=len(buf))
    elif a.enc == "int4":
        buf = _pack4(a.q)
        if grouped:
            buf += np.ascontiguousarray(a.scale, np.float32).tobytes()
            meta.update(enc="int4g", gs=a.gs, goff=a.goff,
                        nbytes=len(buf))
        elif per_row:
            buf += np.ascontiguousarray(a.scale, np.float32).tobytes()
            meta.update(enc="int4r", nbytes=len(buf))
        else:
            meta.update(enc="int4", scale=float(a.scale), nbytes=len(buf))
    else:
        raise ValueError(f"unknown quantized encoding {a.enc!r}")
    return meta, buf


def key_digest(idx: np.ndarray) -> str:
    """Content fingerprint of a key (row-index) vector, the unit of the
    KEY_CACHING filter: two frames whose sorted-unique index arrays hash
    equal carry the same key list, so the second can ship digest-only.
    blake2b like the pack cache's fingerprints — fast and collision-safe
    at 12 bytes for the per-sender cache sizes involved."""
    a = np.ascontiguousarray(idx, np.int64)
    return hashlib.blake2b(a.tobytes(), digest_size=12).hexdigest()


def _decode(meta: dict, buf: bytes) -> np.ndarray:
    shape = tuple(meta["shape"])
    enc = meta["enc"]
    comp = meta.get("comp")
    if comp == "zlib":
        buf = zlib.decompress(buf)
    elif comp == "bshuf+zlib":
        buf = _unbshuf(zlib.decompress(buf), _ENC_ITEMSIZE[enc])
    if enc == "raw":
        return np.frombuffer(buf, np.float32).reshape(shape)
    if enc == "i32":
        a = np.frombuffer(buf, np.int32).reshape(shape)
        return np.cumsum(a, dtype=np.int32) if meta.get("dlt") else a
    if enc == "i64":
        a = np.frombuffer(buf, np.int64).reshape(shape)
        return np.cumsum(a, dtype=np.int64) if meta.get("dlt") else a
    if enc == "bf16":
        u = np.frombuffer(buf, np.uint16).astype(np.uint32) << 16
        return u.view(np.float32).reshape(shape)
    if enc == "int8":
        q = np.frombuffer(buf, np.int8).astype(np.float32)
        return (q * meta["scale"]).reshape(shape)
    n = int(np.prod(shape, dtype=np.int64))
    nrows = shape[0] if shape else 1
    if enc == "int8r":
        q = np.frombuffer(buf, np.int8, count=n).astype(np.float32)
        s = np.frombuffer(buf, np.float32, offset=n)
        return q.reshape(shape) * s.reshape((nrows,) + (1,) * (len(shape) - 1))
    if enc == "int8g":
        q = np.frombuffer(buf, np.int8, count=n).astype(np.float32)
        s = np.frombuffer(buf, np.float32, offset=n)
        return (q * _expand_gscales(s, meta["gs"], meta.get("goff", 0),
                                    n)).reshape(shape)
    if enc == "int4":
        q = _unpack4(buf, n).astype(np.float32)
        return (q * meta["scale"]).reshape(shape)
    if enc == "int4r":
        npk = (n + 1) // 2
        q = _unpack4(buf[:npk], n).astype(np.float32)
        s = np.frombuffer(buf, np.float32, offset=npk)
        return q.reshape(shape) * s.reshape((nrows,) + (1,) * (len(shape) - 1))
    if enc == "int4g":
        npk = (n + 1) // 2
        q = _unpack4(buf[:npk], n).astype(np.float32)
        s = np.frombuffer(buf, np.float32, offset=npk)
        return (q * _expand_gscales(s, meta["gs"], meta.get("goff", 0),
                                    n)).reshape(shape)
    raise ValueError(f"unknown encoding {enc!r}")


def _read_exact(sock_file, n: int) -> Optional[bytes]:
    chunks = []
    while n > 0:
        c = sock_file.read(n)
        if not c:
            return None
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def send_frame(sock_file, header: dict,
               arrays: Optional[dict[str, np.ndarray]] = None,
               fixed_bytes: int = 0, compress=False) -> int:
    """Write one frame; returns the number of payload+header bytes sent
    (the wire-accounting unit PSClient reports). `compress` is False,
    True/"zlib", or "bshuf" (the negotiated frame compression mode);
    array values may be plain ndarrays or pre-quantized QuantRows."""
    if faults.ACTIVE is not None:
        faults.ACTIVE.frame(header.get("op"))
    t0 = time.perf_counter()
    metas, bufs = [], []
    for name, a in (arrays or {}).items():
        m, b = _encode(a, fixed_bytes, compress)
        m["name"] = name
        metas.append(m)
        bufs.append(b)
    header = dict(header, arrays=metas)
    if _trace.ACTIVE is not None:
        # a sampled request's trace context rides the header (the
        # key_digest piggyback pattern) so the receiver's spans stitch
        # to the sender's in tools/trace_viewer.py
        tc = _trace.wire_ctx()
        if tc is not None:
            header["tctx"] = tc
    # the ambient deadline rides the same way: remaining seconds at
    # send time (`dl`), re-anchored to the receiver's monotonic clock
    # in recv_frame — clock skew between hosts never touches it
    dl = _overload.wire_deadline()
    if dl is not None:
        header["dl"] = dl
    h = json.dumps(header).encode()
    _ENCODE_S.observe(time.perf_counter() - t0)
    if os.environ.get("WH_WIRE_DEBUG") == "2" and metas:
        print("[wire]", header.get("op"),
              [(m["name"], m["enc"], m.get("comp", "-"), m["nbytes"])
               for m in metas], file=sys.stderr, flush=True)
    comp = sum(m["nbytes"] for m in metas if "comp" in m)
    if comp:
        _COMPRESS_OUT.inc(comp)
    sock_file.write(struct.pack(">I", len(h)))
    sock_file.write(h)
    total = 4 + len(h)
    for b in bufs:
        sock_file.write(b)
        total += len(b)
    sock_file.flush()
    _FRAMES_SENT.inc()
    _BYTES_SENT.inc(total)
    return total


def recv_frame(sock_file) -> Optional[tuple[dict, dict[str, np.ndarray], int]]:
    if faults.ACTIVE is not None:
        faults.ACTIVE.recv()
    raw = _read_exact(sock_file, 4)
    if raw is None:
        return None
    (hlen,) = struct.unpack(">I", raw)
    h = _read_exact(sock_file, hlen)
    if h is None:
        return None
    # decode latency excludes the socket reads (network wait is not
    # deserialization cost): time json.loads + _decode only
    t0 = time.perf_counter()
    header = json.loads(h)
    decode_s = time.perf_counter() - t0
    _overload.arm(header)  # anchor a carried deadline: dl -> dl_mono
    if _flight.ACTIVE is not None and header.get("dl") is not None:
        # per-hop deadline audit: budget this frame arrived with
        _flight.record_hop(header.get("op"), float(header["dl"]))
    total = 4 + hlen
    arrays = {}
    for m in header.get("arrays", []):
        buf = _read_exact(sock_file, m["nbytes"])
        if buf is None:
            return None
        total += m["nbytes"]
        t0 = time.perf_counter()
        arrays[m["name"]] = _decode(m, buf)
        decode_s += time.perf_counter() - t0
        if "comp" in m:
            _COMPRESS_IN.inc(m["nbytes"])
            if m["comp"] == "bshuf+zlib":
                _BSHUF_IN.inc(m["nbytes"])
    _DECODE_S.observe(decode_s)
    _FRAMES_RECV.inc()
    _BYTES_RECV.inc(total)
    return header, arrays, total
