"""Shared TCP plumbing for the runtime control/data planes.

Connection establishment retries with backoff (the launcher spawns all
node processes concurrently, so clients routinely race ahead of a
server's bind); once a connection exists, request/response failures are
NOT retried here — the ops they carry (barrier entry, part assignment)
are not idempotent, so replay policy belongs to the caller. (The PS
data plane layers a fenced, idempotent retry on top: PSClient stamps
pushes with per-sender sequence numbers the servers deduplicate, which
is what makes ITS replay safe — see runtime/ps_server.py.)

This module also owns the PS wire format. Frame = 4-byte big-endian
header length | JSON header | raw payload. header = {"op": str, ...meta,
"arrays": [{"name", "shape", "enc", "scale", "nbytes"}, ...]}; payload =
buffers concatenated in array order. Integer arrays (sparse-push/pull
row indices) ride the same frame with enc="i32"/"i64"; "comp": "zlib"
marks a compressed buffer ("nbytes" is then the compressed size,
"rawbytes" the original). Key-list caching (the reference's KEY_CACHING
filter) rides the JSON header as `key_digest()` fingerprints — a frame
whose digest the receiver has cached omits the index array entirely
(runtime/ps_server.py owns the cache + miss/full-resend protocol).

Decoded arrays are zero-copy views over the received buffer and may be
READ-ONLY (raw/i32/i64 encodings); callers that mutate a decoded array
in place must copy it first.

Fault injection (runtime/faults.py) hooks frame send/recv; the guards
are module-level None checks so an unfaulted process pays nothing.
Wire accounting (frames/bytes in+out, encode/decode latency, connect
retries) lands in the process-wide metrics registry (wormhole_tpu/obs)
via handles cached at import.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Optional

import numpy as np

from wormhole_tpu.obs import flight as _flight
from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.runtime import faults
from wormhole_tpu.runtime import overload as _overload
from wormhole_tpu.runtime import retry as _retry

_COMPRESS_MIN = 512  # don't bother compressing tiny buffers

# handles cached at import: per-frame cost is an inc, never a dict walk
_FRAMES_SENT = _obs.REGISTRY.counter("net.frames_sent")
_FRAMES_RECV = _obs.REGISTRY.counter("net.frames_recv")
_BYTES_SENT = _obs.REGISTRY.counter("net.bytes_sent")
_BYTES_RECV = _obs.REGISTRY.counter("net.bytes_recv")
_CONNECT_RETRIES = _obs.REGISTRY.counter("net.connect_retries")
_ENCODE_S = _obs.REGISTRY.histogram("net.encode_s")
_DECODE_S = _obs.REGISTRY.histogram("net.decode_s")
# frame-compression accounting (WH_NET_COMPRESS / per-call compress=):
# compressed payload bytes that actually crossed the wire, both
# directions, so the run report can state the codec's measured effect
_COMPRESS_OUT = _obs.REGISTRY.counter("net.compress.bytes_out")
_COMPRESS_IN = _obs.REGISTRY.counter("net.compress.bytes_in")
_BUSY_REJECTIONS = _obs.REGISTRY.counter("net.busy.rejections")
_BUSY_RETRIES = _obs.REGISTRY.counter("net.busy.retries")


class InflightGate:
    """Server-side admission gate: at most WH_NET_MAX_INFLIGHT requests
    may be in their handler concurrently; the overflow gets a structured
    `busy` reply (see `busy_reply`) instead of queueing behind a
    saturated thread pool. 0 (the default) admits everything — existing
    PS deployments see no behavior change unless they opt in. The knob
    is read once at server construction; per-request cost at the default
    is a single None check."""

    def __init__(self, limit: Optional[int] = None):
        if limit is None:
            limit = int(os.environ.get("WH_NET_MAX_INFLIGHT", "0") or 0)
        self.limit = max(int(limit), 0)
        self._sem = (threading.BoundedSemaphore(self.limit)
                     if self.limit else None)

    def try_enter(self) -> bool:
        """Admit one request; False means the caller must send
        `busy_reply()` and NOT dispatch (and must not `leave()`)."""
        if self._sem is None:
            return True
        ok = self._sem.acquire(blocking=False)
        if not ok:
            _BUSY_REJECTIONS.inc()
        return ok

    def leave(self) -> None:
        if self._sem is not None:
            self._sem.release()


def busy_reply(retry_ms: float = 25.0) -> dict:
    """Header of the structured backpressure reply. Not an `error`:
    nothing was dispatched, the client should back off `retry_ms`
    (jittered) and resend the SAME frame — for seq-fenced ops the fence
    stamp is reused, so the eventual apply is still exactly-once.
    Servers pass `AdmissionController.busy_hint_ms()` here so the hint
    scales with observed reject pressure instead of pinning every
    bounced client to the same fixed 25 ms re-arrival."""
    return {"busy": 1, "retry_ms": float(retry_ms)}


def busy_backoff(header: dict, budget: Optional[_retry.RetryBudget] = None
                 ) -> bool:
    """Client side of the gate: True when `header` is a busy reply, after
    sleeping its hint under the unified full-jitter policy — the caller
    just retries its frame.  With a `budget` the sleep is additionally
    capped to the remaining retry window (and counted against it), so a
    storm of busy replies can't walk an op past its own deadline."""
    if not header.get("busy"):
        return False
    _BUSY_RETRIES.inc()
    hint = float(header.get("retry_ms", 25.0)) / 1000.0
    if budget is not None:
        budget.sleep(hint_s=hint)
    else:
        _retry.jitter_sleep(hint)
    return True


def connect_with_retry(addr: tuple[str, int], deadline_s: float = 30.0,
                       timeout: float = 60.0) -> socket.socket:
    """Dial `addr`, retrying refused/unreachable connections until
    `deadline_s` elapses.  The loop itself lives in runtime/retry.py
    (the unified deadline-budgeted policy); this wrapper keeps the
    historical `net.connect_retries` per-failure counter."""
    return _retry.connect(addr, deadline_s, timeout,
                          on_retry=_CONNECT_RETRIES.inc)


def _encode(a: np.ndarray, fixed_bytes: int = 0,
            compress: bool = False) -> tuple[dict, bytes]:
    """Encode one array for the wire. Float arrays honor fixed_bytes:
    0 = raw f32, 2 = bfloat16 bit-truncation (round-to-nearest-even),
    1 = absmax int8. Integer arrays always go raw (they are row indices;
    rounding them would corrupt the scatter)."""
    meta: dict = {"shape": list(a.shape)}
    if np.issubdtype(a.dtype, np.integer):
        a = np.ascontiguousarray(
            a, dtype=np.int64 if a.dtype.itemsize > 4 else np.int32)
        buf = a.tobytes()
        meta.update(enc="i64" if a.dtype == np.int64 else "i32",
                    nbytes=len(buf))
    else:
        a = np.ascontiguousarray(a, dtype=np.float32)
        if fixed_bytes == 0:
            buf = a.tobytes()
            meta.update(enc="raw", nbytes=len(buf))
        elif fixed_bytes >= 2:
            u = a.view(np.uint32)
            # round-to-nearest-even to the high 16 bits (bfloat16)
            rounded = (u + 0x7FFF + ((u >> 16) & 1)) >> 16
            buf = rounded.astype(np.uint16).tobytes()
            meta.update(enc="bf16", nbytes=len(buf))
        else:
            scale = float(max(np.max(np.abs(a), initial=0.0), 1e-30) / 127.0)
            q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
            buf = q.tobytes()
            meta.update(enc="int8", scale=scale, nbytes=len(buf))
    if compress and len(buf) >= _COMPRESS_MIN:
        c = zlib.compress(buf, 1)
        if len(c) < len(buf):
            meta.update(comp="zlib", rawbytes=meta["nbytes"], nbytes=len(c))
            buf = c
    return meta, buf


def key_digest(idx: np.ndarray) -> str:
    """Content fingerprint of a key (row-index) vector, the unit of the
    KEY_CACHING filter: two frames whose sorted-unique index arrays hash
    equal carry the same key list, so the second can ship digest-only.
    blake2b like the pack cache's fingerprints — fast and collision-safe
    at 12 bytes for the per-sender cache sizes involved."""
    a = np.ascontiguousarray(idx, np.int64)
    return hashlib.blake2b(a.tobytes(), digest_size=12).hexdigest()


def _decode(meta: dict, buf: bytes) -> np.ndarray:
    shape = tuple(meta["shape"])
    enc = meta["enc"]
    if meta.get("comp") == "zlib":
        buf = zlib.decompress(buf)
    if enc == "raw":
        return np.frombuffer(buf, np.float32).reshape(shape)
    if enc == "i32":
        return np.frombuffer(buf, np.int32).reshape(shape)
    if enc == "i64":
        return np.frombuffer(buf, np.int64).reshape(shape)
    if enc == "bf16":
        u = np.frombuffer(buf, np.uint16).astype(np.uint32) << 16
        return u.view(np.float32).reshape(shape)
    if enc == "int8":
        q = np.frombuffer(buf, np.int8).astype(np.float32)
        return (q * meta["scale"]).reshape(shape)
    raise ValueError(f"unknown encoding {enc!r}")


def _read_exact(sock_file, n: int) -> Optional[bytes]:
    chunks = []
    while n > 0:
        c = sock_file.read(n)
        if not c:
            return None
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def send_frame(sock_file, header: dict,
               arrays: Optional[dict[str, np.ndarray]] = None,
               fixed_bytes: int = 0, compress: bool = False) -> int:
    """Write one frame; returns the number of payload+header bytes sent
    (the wire-accounting unit PSClient reports)."""
    if faults.ACTIVE is not None:
        faults.ACTIVE.frame(header.get("op"))
    t0 = time.perf_counter()
    metas, bufs = [], []
    for name, a in (arrays or {}).items():
        m, b = _encode(a, fixed_bytes, compress)
        m["name"] = name
        metas.append(m)
        bufs.append(b)
    header = dict(header, arrays=metas)
    if _trace.ACTIVE is not None:
        # a sampled request's trace context rides the header (the
        # key_digest piggyback pattern) so the receiver's spans stitch
        # to the sender's in tools/trace_viewer.py
        tc = _trace.wire_ctx()
        if tc is not None:
            header["tctx"] = tc
    # the ambient deadline rides the same way: remaining seconds at
    # send time (`dl`), re-anchored to the receiver's monotonic clock
    # in recv_frame — clock skew between hosts never touches it
    dl = _overload.wire_deadline()
    if dl is not None:
        header["dl"] = dl
    h = json.dumps(header).encode()
    _ENCODE_S.observe(time.perf_counter() - t0)
    comp = sum(m["nbytes"] for m in metas if "comp" in m)
    if comp:
        _COMPRESS_OUT.inc(comp)
    sock_file.write(struct.pack(">I", len(h)))
    sock_file.write(h)
    total = 4 + len(h)
    for b in bufs:
        sock_file.write(b)
        total += len(b)
    sock_file.flush()
    _FRAMES_SENT.inc()
    _BYTES_SENT.inc(total)
    return total


def recv_frame(sock_file) -> Optional[tuple[dict, dict[str, np.ndarray], int]]:
    if faults.ACTIVE is not None:
        faults.ACTIVE.recv()
    raw = _read_exact(sock_file, 4)
    if raw is None:
        return None
    (hlen,) = struct.unpack(">I", raw)
    h = _read_exact(sock_file, hlen)
    if h is None:
        return None
    # decode latency excludes the socket reads (network wait is not
    # deserialization cost): time json.loads + _decode only
    t0 = time.perf_counter()
    header = json.loads(h)
    decode_s = time.perf_counter() - t0
    _overload.arm(header)  # anchor a carried deadline: dl -> dl_mono
    if _flight.ACTIVE is not None and header.get("dl") is not None:
        # per-hop deadline audit: budget this frame arrived with
        _flight.record_hop(header.get("op"), float(header["dl"]))
    total = 4 + hlen
    arrays = {}
    for m in header.get("arrays", []):
        buf = _read_exact(sock_file, m["nbytes"])
        if buf is None:
            return None
        total += m["nbytes"]
        t0 = time.perf_counter()
        arrays[m["name"]] = _decode(m, buf)
        decode_s += time.perf_counter() - t0
        if "comp" in m:
            _COMPRESS_IN.inc(m["nbytes"])
    _DECODE_S.observe(decode_s)
    _FRAMES_RECV.inc()
    _BYTES_RECV.inc(total)
    return header, arrays, total
