from wormhole_tpu.runtime.tracker import (  # noqa: F401
    Scheduler, SchedulerClient, RemotePool, node_env, Role,
)
