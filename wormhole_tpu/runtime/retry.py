"""Unified deadline-budgeted retry policy for every dial/redial loop.

Before this module each retrying subsystem hand-rolled its own loop:
`net.connect_with_retry` (jittered exponential dial backoff),
`PSClient._recover` (0.25s-doubling reconnect), the serving Router's
fast-dial `_rpc` loop (fixed 0.1s), and the tracker client's `blob_get`
busy-poll (fixed 0.1s).  They disagreed on jitter, caps, and — worse —
on whether a deadline bounded the loop at all, so a partitioned peer
could spin one plane while hanging another.  This module is the single
policy: every retry loop draws sleeps from a `RetryBudget` whose
deadline is fixed at construction, backs off exponentially with full
jitter, and either succeeds or *gives up* at the deadline with the
failure counted (`retry.give_ups`) — bounded degradation instead of a
hang, which is what lets a partitioned node resign from the job cleanly
(see docs/distributed.md, elasticity section).

The wormlint `retry-policy` checker enforces adoption: hand-rolled
sleep-in-except retry loops outside this file are findings.
"""

from __future__ import annotations

import dataclasses
import random
import socket
import time
from typing import Optional

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.runtime import overload as _overload

_ATTEMPTS = _obs.REGISTRY.counter("retry.attempts")
_GIVE_UPS = _obs.REGISTRY.counter("retry.give_ups")
_SUCCESSES = _obs.REGISTRY.counter("retry.successes")
_BACKOFF_S = _obs.REGISTRY.histogram("retry.backoff_s")


def _default_base() -> float:
    return float(knob_value("WH_RETRY_BASE_SEC"))


def _default_cap() -> float:
    return float(knob_value("WH_RETRY_CAP_SEC"))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How a class of operations retries: a total deadline plus backoff
    shape.  Policies are cheap immutable descriptions; each *use* mints a
    `RetryBudget` whose clock starts then."""

    deadline_s: float
    base_s: float = 0.0  # 0 = WH_RETRY_BASE_SEC
    cap_s: float = 0.0  # 0 = WH_RETRY_CAP_SEC
    op: str = ""

    def budget(self, deadline_s: Optional[float] = None) -> "RetryBudget":
        return RetryBudget(
            deadline_s=self.deadline_s if deadline_s is None else deadline_s,
            base_s=self.base_s or _default_base(),
            cap_s=self.cap_s or _default_cap(),
            op=self.op)


class RetryBudget:
    """One operation's live retry state: a monotonic deadline set at
    construction and an exponentially growing, fully jittered backoff.
    The contract every converted loop follows:

        budget = policy.budget()
        while True:
            try:
                return attempt()
            except OSError as e:
                if budget.expired:
                    budget.give_up(e)   # counts retry.give_ups, raises
                budget.sleep()          # jittered, capped to remaining
    """

    def __init__(self, deadline_s: float, base_s: float = 0.0,
                 cap_s: float = 0.0, op: str = ""):
        self.op = op
        self.deadline = time.monotonic() + max(float(deadline_s), 0.0)
        self._base = base_s or _default_base()
        self._cap = cap_s or _default_cap()
        self._backoff = self._base
        self.attempts = 0

    @property
    def remaining(self) -> float:
        return self.deadline - time.monotonic()

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.deadline

    def sleep(self, hint_s: Optional[float] = None) -> float:
        """Back off before the next attempt: full jitter over the current
        exponential step (or the caller's `hint_s`, e.g. a busy reply's
        retry_ms), never sleeping past the deadline.  Returns the actual
        sleep taken.  Jitter matters here for the same reason it does in
        connect_with_retry: synchronized retries from every peer of a
        respawned/healed node arrive as a thundering herd."""
        self.attempts += 1
        _ATTEMPTS.inc()
        step = self._backoff if hint_s is None else hint_s
        dur = min(step * (0.5 + random.random()), max(self.remaining, 0.0))
        self._backoff = min(self._backoff * 2, self._cap)
        if dur > 0:
            _BACKOFF_S.observe(dur)
            time.sleep(dur)
        return dur

    def bind(self):
        """Bind this budget's deadline as the thread's ambient deadline
        for a block: every frame sent inside it carries the remaining
        budget on the wire, and servers shed the work if it expires in
        transit (runtime/overload.py). This is how budgets MINT the
        propagated deadline — the op's retry window and its wire
        deadline are one number."""
        return _overload.bind(self.deadline)

    def succeeded(self) -> None:
        """Record a success that needed at least one retry (callers that
        succeed first try never touch the budget's counters)."""
        if self.attempts:
            _SUCCESSES.inc()

    def give_up(self, err: Optional[BaseException] = None) -> None:
        """The deadline is spent: count the give-up and re-raise `err`
        (or a TimeoutError naming the op).  Give-ups are the metric the
        chaos drills pin to zero — a healed partition must never have
        pushed any plane past its budget."""
        _GIVE_UPS.inc()
        if err is not None:
            raise err
        raise TimeoutError(
            f"retry budget exhausted after {self.attempts} attempts"
            + (f" ({self.op})" if self.op else ""))


def jitter_sleep(hint_s: float) -> float:
    """One full-jitter backoff sleep for paths that carry no
    RetryBudget (e.g. a busy-reply hint on a first-try RPC).  Same
    jitter law and `retry.*` accounting as `RetryBudget.sleep`, and
    still capped to the thread's ambient deadline so a budgeted caller
    higher up the stack can't be slept past its own deadline."""
    _ATTEMPTS.inc()
    dur = hint_s * (0.5 + random.random())
    rem = _overload.remaining()
    if rem is not None:
        dur = min(dur, max(rem, 0.0))
    if dur > 0:
        _BACKOFF_S.observe(dur)
        time.sleep(dur)
    return dur


def connect(addr: tuple[str, int], deadline_s: float = 30.0,
            timeout: float = 60.0, op: str = "connect",
            on_retry=None) -> socket.socket:
    """Dial `addr` under the unified policy: refused/unreachable
    connections retry with jittered exponential backoff until
    `deadline_s` elapses, then the last OSError propagates (counted as a
    give-up).  `timeout` is the established socket's I/O timeout;
    `on_retry` lets a caller keep its own per-failure counter (net.py's
    `net.connect_retries`) next to the policy-wide `retry.*` ones.

    Both windows are clamped to the thread's ambient propagated
    deadline when one is bound: a dial may never outlive the budget of
    the operation it serves (a caller with 2s left must not sit in a
    30s dial loop or a 60s blocking connect)."""
    rem = _overload.remaining()
    if rem is not None:
        # expired: one fast attempt, then give up.  The floor must
        # still cover a localhost round-trip — the shed reply ("deadline
        # expired before dispatch") travels back over this same socket,
        # and a sub-millisecond I/O timeout turns every expired-budget
        # call into an opaque socket timeout instead of the typed shed
        # error the caller is supposed to see
        rem = max(rem, 0.05)
        deadline_s = min(deadline_s, rem)
        timeout = min(timeout, rem)
    budget = RetryBudget(deadline_s, op=op)
    while True:
        try:
            sock = socket.create_connection(addr, timeout=timeout)
            # request/response framing on a Nagle'd socket interacts
            # with delayed ACK: the tail segment of every frame can sit
            # ~40ms waiting for the peer's ACK, which dwarfs the actual
            # PS sync work (tools/ps_lab.py measures the difference)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            budget.succeeded()
            return sock
        except OSError as e:
            if on_retry is not None:
                on_retry()
            if budget.expired:
                budget.give_up(e)
            budget.sleep()
