"""Overload protection: deadlines, adaptive admission, hedging, degrade.

Four cooperating mechanisms keep the serving/PS planes useful when
offered load exceeds capacity (docs/serving.md "Overload protection"):

**Deadline propagation.** A client operation binds its remaining budget
into a thread-local (``bind()``, the trace-context pattern of
obs/trace.py); every ``net.send_frame`` under the binding stamps the
remaining seconds onto the frame header as ``dl`` and ``recv_frame``
anchors it to the receiver's monotonic clock (``dl_mono``). Handlers
call ``should_shed(header)`` BEFORE dispatch: a frame whose budget is
already spent is answered with a structured shed reply instead of
computing a result nobody is waiting for (``net.deadline.shed``).
Deadlines ride relative (remaining seconds, the gRPC convention) so
cross-process clock skew cannot corrupt them; a nested ``bind`` can
only tighten the ambient deadline, never extend it.

**Adaptive admission (AIMD).** ``AdmissionController`` subsumes the
fixed ``WH_NET_MAX_INFLIGHT`` gate of runtime/net.py. With
``WH_ADMIT_AIMD`` on, the concurrency limit walks between
``WH_ADMIT_MIN`` and ``WH_ADMIT_MAX`` by the classic AIMD law driven by
measured handler latency (and, when published, the ``slo.*_burn``
gauges of obs/slo.py): sustained service latency above
``WH_ADMIT_LATENCY_MS`` multiplies the limit by ``WH_ADMIT_BACKOFF``;
a window that ran at the limit without violating adds one. Ops in
``CONTROL_OPS`` (hellos, inits, membership/manifest/control traffic)
are NEVER shed — only bulk push/pull/fetch work is gated — and the
busy-reply hint scales with the observed reject pressure so retries
from many clients spread out instead of synchronizing.

**Hedged fan-out.** ``HedgeTracker`` owns the rolling-quantile hedge
delay and the hedge budget: a fan-out leg still unanswered after the
``WH_HEDGE_QUANTILE`` of recent latencies may issue ONE backup request,
provided total hedges stay under ``WH_HEDGE_BUDGET_PCT`` percent of
primaries. The duplicate reuses the primary's (sender, seq), so the
receiving shard's reply cache keeps it exactly-once — pure tail
insurance, bounded extra load (``serve.hedge.*``).

**Degraded mode.** ``DegradeController`` watches per-request latency
against the serving SLO; when the violation fraction burns past
``WH_DEGRADE_BURN`` times the SLO allowance for ``WH_DEGRADE_AFTER_SEC``
straight, it flips active and the router stops paying for strict
version consistency (serving bounded-staleness mixed-version replies
stamped ``degraded=1``), flipping back once the burn stays clear for
``WH_DEGRADE_CLEAR_SEC`` (``serve.degraded.*``).

This module sits below runtime/net.py and runtime/retry.py in the
import graph (it imports neither), so every wire/retry layer can use it.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import flight as _flight
from wormhole_tpu.obs import metrics as _obs

_DEADLINE_SHED = _obs.REGISTRY.counter("net.deadline.shed")
_ADMIT_SHEDS = _obs.REGISTRY.counter("admit.sheds")
_ADMIT_LIMIT = _obs.REGISTRY.gauge("admit.limit")
_ADMIT_INFLIGHT = _obs.REGISTRY.gauge("admit.inflight")
_HEDGE_ISSUED = _obs.REGISTRY.counter("serve.hedge.issued")
_HEDGE_WINS = _obs.REGISTRY.counter("serve.hedge.wins")
_HEDGE_SUPPRESSED = _obs.REGISTRY.counter("serve.hedge.suppressed")
_HEDGE_DELAY_MS = _obs.REGISTRY.gauge("serve.hedge.delay_ms")
_DEGRADED_ACTIVE = _obs.REGISTRY.gauge("serve.degraded.active")
_DEGRADED_REPLIES = _obs.REGISTRY.counter("serve.degraded.replies")
_DEGRADED_ENTERS = _obs.REGISTRY.counter("serve.degraded.enters")
_DEGRADED_EXITS = _obs.REGISTRY.counter("serve.degraded.exits")

#: Ops that may never be shed — liveness, membership, handshake,
#: manifest/control and snapshot traffic. Shedding a heartbeat or a
#: hello under load converts an overload into a spurious eviction /
#: failed recovery, the exact spiral admission control exists to stop.
#: Bulk data ops (push / pull / fetch) are the ONLY sheddable class.
CONTROL_OPS = frozenset({
    "hello", "init", "init_spec", "init_arrays", "stats", "shutdown",
    "save", "load", "epoch", "register", "register_serve",
})

# ------------------------------------------------------------ deadlines

_TLS = threading.local()  # .deadline = absolute monotonic deadline


class _BindDeadline:
    """Install an absolute (monotonic) deadline on this thread for a
    block. Nesting only tightens: an inner bind past the ambient
    deadline keeps the ambient one, so a sub-operation can never grant
    itself more budget than its caller holds. ``bind(None)`` is a
    no-op that still restores, mirroring trace.bind()."""

    __slots__ = ("deadline", "_saved")

    def __init__(self, deadline: Optional[float]):
        self.deadline = deadline

    def __enter__(self):
        self._saved = getattr(_TLS, "deadline", None)
        if self.deadline is not None:
            cur = self._saved
            _TLS.deadline = (self.deadline if cur is None
                             else min(cur, self.deadline))
        return self

    def __exit__(self, *exc):
        _TLS.deadline = self._saved
        return False


def bind(deadline: Optional[float]) -> _BindDeadline:
    """Bind an absolute ``time.monotonic()`` deadline (or None: no-op)."""
    return _BindDeadline(deadline)


def bind_in(remaining_s: float) -> _BindDeadline:
    """Bind a deadline ``remaining_s`` seconds from now."""
    return _BindDeadline(time.monotonic() + float(remaining_s))


def current() -> Optional[float]:
    """The ambient absolute deadline on this thread, if any — hand it
    to a worker thread's ``bind()`` (pools don't inherit thread-locals,
    the trace ``current_ctx`` pattern)."""
    return getattr(_TLS, "deadline", None)


def remaining() -> Optional[float]:
    """Seconds left in the ambient budget (may be negative); None when
    no deadline is bound."""
    d = getattr(_TLS, "deadline", None)
    return None if d is None else d - time.monotonic()


def wire_deadline() -> Optional[float]:
    """The ambient budget as a frame-header field: remaining seconds,
    floored at 0 so an already-expired budget still travels (and is
    shed at the far end rather than silently dropped here)."""
    d = getattr(_TLS, "deadline", None)
    if d is None:
        return None
    return round(max(d - time.monotonic(), 0.0), 6)


def arm(header: dict) -> None:
    """Receiver side: anchor a frame's relative ``dl`` to this
    process's monotonic clock (``dl_mono``). Called by
    ``net.recv_frame`` on every frame that carries a deadline; transit
    time is not charged (the sender stamped REMAINING budget at send)."""
    dl = header.get("dl")
    if dl is not None:
        header["dl_mono"] = time.monotonic() + float(dl)


def header_deadline(header: dict) -> Optional[float]:
    """The anchored monotonic deadline a received frame carried."""
    return header.get("dl_mono")


def should_shed(header: dict) -> bool:
    """True when this frame's budget is already spent and the server
    should answer ``shed_reply()`` instead of dispatching. Control ops
    are never shed regardless of their deadline; WH_DEADLINE_SHED=0
    disables shedding entirely (the deadline still rides the wire for
    observability)."""
    d = header.get("dl_mono")
    if d is None or time.monotonic() < d:
        return False
    if header.get("op") in CONTROL_OPS:
        return False
    if not knob_value("WH_DEADLINE_SHED"):
        return False
    _DEADLINE_SHED.inc()
    _flight.record_decision(
        "shed", "deadline expired in transit", op=header.get("op"),
        budget_ms=round((d - time.monotonic()) * 1e3, 3))
    return True


class Shed(TimeoutError):
    """A request bounced by overload protection BEFORE any work was
    done on it — an expired budget caught at the client edge, or a
    saturated admission gate. Subclasses TimeoutError so every caller
    that already classifies deadline misses (labs, chaos drivers)
    handles a shed the same way without new plumbing."""


def shed_reply(header: dict) -> dict:
    """Header of the structured shed reply. Carries ``error`` so every
    existing client raises instead of mis-parsing, and ``shed=1`` so
    callers that care (labs, tests) can tell a shed from a real
    failure. Nothing was dispatched: a seq-stamped frame's fence was
    not consumed, so a (hypothetical) retry under a fresh budget would
    still apply exactly once."""
    op = header.get("op", "?")
    return {"shed": 1,
            "error": f"deadline expired before dispatch of {op!r}"}


# ------------------------------------------------------------ admission


class AdmissionController:
    """Server-side admission gate, subsuming net.InflightGate.

    Fixed mode (default): identical contract to the historical gate —
    at most ``WH_NET_MAX_INFLIGHT`` bulk requests in their handlers
    concurrently, overflow bounced with a busy reply, 0 admits all.

    Adaptive mode (``WH_ADMIT_AIMD``): the limit walks between
    ``WH_ADMIT_MIN`` and ``WH_ADMIT_MAX`` under the AIMD law, driven by
    the measured per-request service latency the handler reports to
    ``leave()`` (queue wait + dispatch) against ``WH_ADMIT_LATENCY_MS``
    — and, when some plane published SLO burn gauges into this
    process's registry, a burning ``slo.serve.latency_burn`` /
    ``slo.ps.rpc.latency_burn`` also counts as a violation. Every
    ``_ADJUST_EVERY`` completions: latency over target multiplies the
    limit by ``WH_ADMIT_BACKOFF``; a full window at the limit without
    violation adds 1.

    Priority classes: ``CONTROL_OPS`` bypass the gate entirely (never
    shed, not counted against the limit) — under overload the bulk
    plane starves before a heartbeat or hello does."""

    _ADJUST_EVERY = 16

    def __init__(self, limit: Optional[int] = None,
                 adaptive: Optional[bool] = None,
                 target_ms: Optional[float] = None):
        if limit is None:
            limit = int(knob_value("WH_NET_MAX_INFLIGHT"))
        self.adaptive = (bool(knob_value("WH_ADMIT_AIMD"))
                         if adaptive is None else bool(adaptive))
        self.lo = max(int(knob_value("WH_ADMIT_MIN")), 1)
        self.hi = max(int(knob_value("WH_ADMIT_MAX")), self.lo)
        self.target_ms = (float(knob_value("WH_ADMIT_LATENCY_MS"))
                          if target_ms is None else float(target_ms))
        self.backoff = min(max(float(knob_value("WH_ADMIT_BACKOFF")),
                               0.1), 0.99)
        if self.adaptive:
            # start from the fixed knob when set (operator intent),
            # else from the ceiling and let violations walk it down
            limit = min(max(limit or self.hi, self.lo), self.hi)
        self.limit = max(int(limit), 0)
        self.enabled = self.limit > 0
        self._lock = threading.Lock()
        self._inflight = 0
        self._ewma_ms: Optional[float] = None
        self._completions = 0
        self._hit_limit = False   # window saw a reject/full admit
        self._violated = False    # window saw latency over target
        self._reject_streak = 0   # consecutive bounces since last admit
        if self.enabled:
            _ADMIT_LIMIT.set(float(self.limit))

    # the historical counter rides along so dashboards and existing
    # drills keep one continuous series across the gate upgrade
    _BUSY_REJECTIONS = _obs.REGISTRY.counter("net.busy.rejections")

    def try_enter(self, op: Optional[str] = None) -> bool:
        """Admit one request; False means the caller must answer
        ``busy_reply(self.busy_hint_ms())`` and NOT dispatch (and must
        not ``leave()``). Control ops are always admitted."""
        if not self.enabled or (op is not None and op in CONTROL_OPS):
            return True
        with self._lock:
            if self._inflight >= self.limit:
                self._reject_streak += 1
                self._hit_limit = True
                self._BUSY_REJECTIONS.inc()
                _ADMIT_SHEDS.inc()
                _flight.record_decision(
                    "admit_shed",
                    f"inflight {self._inflight} >= limit {self.limit}",
                    op=op)
                return False
            self._inflight += 1
            self._reject_streak = 0
            if self._inflight >= self.limit:
                self._hit_limit = True
            _ADMIT_INFLIGHT.set(float(self._inflight))
        return True

    def leave(self, op: Optional[str] = None,
              service_s: Optional[float] = None) -> None:
        """Release one admitted request; ``service_s`` (recv-to-reply
        wall) feeds the AIMD controller."""
        if not self.enabled or (op is not None and op in CONTROL_OPS):
            return
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            _ADMIT_INFLIGHT.set(float(self._inflight))
            if not self.adaptive or service_s is None:
                return
            ms = service_s * 1e3
            self._ewma_ms = (ms if self._ewma_ms is None
                             else 0.8 * self._ewma_ms + 0.2 * ms)
            if self._ewma_ms > self.target_ms:
                self._violated = True
            self._completions += 1
            if self._completions < self._ADJUST_EVERY:
                return
            self._completions = 0
            # the SLO-burn check snapshots the whole metric registry —
            # far too heavy per completion, cheap once per window
            if not self._violated and self._burning():
                self._violated = True
            if self._violated:
                self.limit = max(self.lo,
                                 int(self.limit * self.backoff))
            elif self._hit_limit:
                self.limit = min(self.hi, self.limit + 1)
            self._violated = False
            self._hit_limit = False
            _ADMIT_LIMIT.set(float(self.limit))

    @staticmethod
    def _burning() -> float:
        """Max published SLO latency burn in this process's registry
        (0.0 when none published — the gauges appear only where
        obs/slo.evaluate ran with publish=True)."""
        gauges = _obs.REGISTRY.snapshot().get("gauges", {})
        return max((v for k, v in gauges.items()
                    if k.startswith("slo.") and k.endswith("_burn")
                    and v > 1.0), default=0.0)

    def busy_hint_ms(self, base_ms: float = 25.0) -> float:
        """Load-aware retry hint for the busy reply: grows with the
        reject streak per unit of limit, so the backoff clients take
        scales with how oversubscribed the gate actually is instead of
        every bounced client re-arriving 25 ms later in lockstep."""
        with self._lock:
            streak, limit = self._reject_streak, max(self.limit, 1)
        return min(base_ms * (1.0 + streak / limit), 250.0)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight


def router_gate() -> Optional["AdmissionController"]:
    """The CLIENT-edge admission gate for a serving router, iff
    WH_ADMIT_AIMD is on (None keeps the ungated hot path one attribute
    check). Overload queues form at the router — its fan-out slots
    serialize ahead of any shard gate — so a saturated FIFO there turns
    every request into a doomed one that expires mid-queue and sheds at
    dispatch (goodput -> 0 under sustained overload, the classic
    collapse). Bouncing at ENTRY instead keeps admitted queueing
    bounded. The gate adapts on whole-request latency against the
    shared WH_ADMIT_LATENCY_MS target — deliberately TIGHT (well under
    the request deadline): past the efficient concurrency the service
    rate FALLS with queue depth (lock/scheduler thrash), so a loose
    target would converge on a deep, slow, low-goodput equilibrium
    that still technically meets the deadline."""
    if not knob_value("WH_ADMIT_AIMD"):
        return None
    return AdmissionController(limit=0, adaptive=True)


# -------------------------------------------------------------- hedging


class HedgeTracker:
    """Rolling-quantile hedge delay + hedge budget for tail-tolerant
    fan-out. ``observe()`` records primary-request latencies;
    ``delay_s()`` is the WH_HEDGE_QUANTILE of the last window (floored
    at WH_HEDGE_MIN_MS), None until ``warmup`` samples exist so cold
    caches never trigger a hedge storm. ``try_issue()`` enforces the
    budget: issued hedges stay under WH_HEDGE_BUDGET_PCT percent of
    primaries (a delay that fires but finds the budget spent counts
    ``serve.hedge.suppressed``)."""

    def __init__(self, quantile: Optional[float] = None,
                 budget_pct: Optional[float] = None,
                 min_ms: Optional[float] = None,
                 warmup: int = 32, window: int = 256):
        self.quantile = (float(knob_value("WH_HEDGE_QUANTILE"))
                         if quantile is None else float(quantile))
        self.budget_pct = (float(knob_value("WH_HEDGE_BUDGET_PCT"))
                           if budget_pct is None else float(budget_pct))
        self.min_s = (float(knob_value("WH_HEDGE_MIN_MS"))
                      if min_ms is None else float(min_ms)) / 1e3
        self.warmup = int(warmup)
        self._lock = threading.Lock()
        self._lat: list[float] = []
        self._window = int(window)
        self._pos = 0
        self._primaries = 0
        self._issued = 0
        self._cached: Optional[float] = None  # quantile of the window
        self._since_sort = 0

    def observe(self, latency_s: float) -> None:
        with self._lock:
            self._primaries += 1
            self._since_sort += 1
            if len(self._lat) < self._window:
                self._lat.append(latency_s)
            else:  # ring overwrite: O(1), no deque churn on the hot path
                self._lat[self._pos] = latency_s
                self._pos = (self._pos + 1) % self._window

    def delay_s(self) -> Optional[float]:
        with self._lock:
            if len(self._lat) < self.warmup:
                return None
            # delay_s runs per fetch: re-sorting the window every call
            # is measurable at serving rates, and the quantile moves
            # slowly — recompute every 16 observations
            if self._cached is None or self._since_sort >= 16:
                s = sorted(self._lat)
                self._cached = max(
                    s[min(len(s) - 1, int(self.quantile * len(s)))],
                    self.min_s)
                self._since_sort = 0
                _HEDGE_DELAY_MS.set(self._cached * 1e3)
            return self._cached

    def try_issue(self) -> bool:
        """Claim budget for one hedge; False counts a suppression."""
        with self._lock:
            allowed = (self._issued + 1) <= (
                self.budget_pct / 100.0 * max(self._primaries, 1))
            if allowed:
                self._issued += 1
        if allowed:
            _HEDGE_ISSUED.inc()
            _flight.record_decision("hedge", "delay quantile elapsed")
        else:
            _HEDGE_SUPPRESSED.inc()
            _flight.record_decision("hedge_suppressed",
                                    "hedge budget spent")
        return allowed

    @staticmethod
    def won() -> None:
        """The backup answered first (the shard reply cache absorbed
        the duplicate — see router._attempt)."""
        _HEDGE_WINS.inc()
        _flight.record_decision("hedge_win", "backup answered first")


def hedge_tracker() -> Optional[HedgeTracker]:
    """A HedgeTracker iff WH_HEDGE is on (None keeps every hedge hook
    a single attribute check)."""
    return HedgeTracker() if knob_value("WH_HEDGE") else None


# -------------------------------------------------------------- degrade


class DegradeController:
    """Sustained-burn detector behind degraded-mode serving.

    ``observe(latency_s)`` classifies each request against
    ``target_ms`` (the serving latency SLO); the violation fraction
    over the last ``window`` requests, divided by the SLO allowance
    (obs/slo.py's 1%), is the burn rate. Burn above WH_DEGRADE_BURN
    continuously for WH_DEGRADE_AFTER_SEC activates degraded mode;
    burn clear for WH_DEGRADE_CLEAR_SEC deactivates it. Mixed-version
    fan-out replays (``observe_replay``) count as violations too —
    replay storms under a swap are precisely the consistency cost
    degraded mode sheds."""

    _ALLOWANCE = 0.01  # mirrors obs/slo.py's latency allowance

    def __init__(self, target_ms: Optional[float] = None,
                 window: int = 128):
        self.enabled = bool(knob_value("WH_DEGRADE"))
        self.target_ms = (float(knob_value("WH_SLO_SERVE_P99_MS"))
                          if target_ms is None else float(target_ms))
        self.burn_thr = float(knob_value("WH_DEGRADE_BURN"))
        self.after_s = float(knob_value("WH_DEGRADE_AFTER_SEC"))
        self.clear_s = float(knob_value("WH_DEGRADE_CLEAR_SEC"))
        self._lock = threading.Lock()
        self._window = int(window)
        self._hits: list[bool] = []
        self._pos = 0
        self._over_since: Optional[float] = None
        self._under_since: Optional[float] = None
        self._active = False

    def _record(self, violated: bool) -> None:
        now = time.monotonic()
        with self._lock:
            if len(self._hits) < self._window:
                self._hits.append(violated)
            else:
                self._hits[self._pos] = violated
                self._pos = (self._pos + 1) % self._window
            frac = sum(self._hits) / len(self._hits)
            burn = frac / self._ALLOWANCE
            if burn > self.burn_thr:
                self._under_since = None
                if self._over_since is None:
                    self._over_since = now
                if (not self._active
                        and now - self._over_since >= self.after_s):
                    self._active = True
                    _DEGRADED_ENTERS.inc()
                    _DEGRADED_ACTIVE.set(1.0)
                    _flight.record_decision(
                        "brownout_enter",
                        f"burn {burn:.1f} > {self.burn_thr:.1f} "
                        f"for {self.after_s:.0f}s")
            else:
                self._over_since = None
                if self._under_since is None:
                    self._under_since = now
                if (self._active
                        and now - self._under_since >= self.clear_s):
                    self._active = False
                    _DEGRADED_EXITS.inc()
                    _DEGRADED_ACTIVE.set(0.0)
                    _flight.record_decision(
                        "brownout_exit",
                        f"burn clear for {self.clear_s:.0f}s")

    def observe(self, latency_s: float) -> None:
        if self.enabled:
            self._record(latency_s * 1e3 > self.target_ms)

    def observe_replay(self) -> None:
        """A mixed-version fan-out replay burned budget."""
        if self.enabled:
            self._record(True)

    def active(self) -> bool:
        """Serve bounded-staleness (mixed-version) replies right now?"""
        if not self.enabled:
            return False
        with self._lock:
            return self._active

    def served_degraded(self) -> None:
        _DEGRADED_REPLIES.inc()
