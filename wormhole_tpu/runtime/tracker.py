"""Distributed control plane: scheduler RPC + remote workload pool.

The reference's control plane is ps-lite Task messages between the
scheduler and worker/server processes (reference learn/solver/
data_parallel.h:93-206: StartDispatch / SendWorkload / ProcessResponse,
node-failure re-queue at :131-135) plus the rabit tracker's rendezvous.
On TPU the DATA plane is XLA collectives over ICI/DCN (SURVEY.md §5), so
what remains host-side is exactly this thin control protocol:

- workload dispatch: workers ask for file parts, the scheduler hands out
  parts from a WorkloadPool (elastic: straggler re-queue, failure reset);
- progress: workers push mergeable metric vectors, the scheduler sums and
  prints rows (the ps::Root/Slave monitor channel, iter_solver.h:62-164);
- barrier: BSP phase sync for the rabit-style apps (kmeans, L-BFGS);
- liveness: nodes that stop polling past a timeout get their assigned
  parts re-queued (AddNodeFailureHandler parity).

Transport is newline-delimited JSON over TCP, one connection per request
— control traffic is per-file-part (seconds), not per-minibatch, so
simplicity beats throughput here. The launcher (launcher/dmlc_tpu.py)
spawns the node processes and wires the env vars.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import socket
import socketserver
import threading
import time
from enum import Enum
from typing import Optional

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import flight as _flight
from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.obs import prom as _prom
from wormhole_tpu.obs import slo as _slo
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.runtime import faults
from wormhole_tpu.runtime import overload as _overload
from wormhole_tpu.runtime import retry as _retry
from wormhole_tpu.runtime.net import connect_with_retry
from wormhole_tpu.runtime.sched_journal import SchedulerJournal
from wormhole_tpu.solver.progress import Progress
from wormhole_tpu.solver.workload import File, WorkloadPool, WorkType

_EVICTIONS = _obs.REGISTRY.counter("sched.liveness_evictions")
_SRV_RECOVERIES = _obs.REGISTRY.counter("sched.server_recoveries")
_SERVE_RECOVERIES = _obs.REGISTRY.counter("sched.serve_recoveries")
_BSP_RECOVERIES = _obs.REGISTRY.counter("bsp.recoveries")
_BARRIER_WAIT_S = _obs.REGISTRY.histogram("sched.barrier_wait_s")
_SCRAPES = _obs.REGISTRY.counter("obs.scrape.requests")
_RING_DEPTH = _obs.REGISTRY.gauge("obs.ring.depth")
_MEPOCHS = _obs.REGISTRY.counter("sched.membership_epochs")
_JOINS = _obs.REGISTRY.counter("sched.joins")
_LEAVES = _obs.REGISTRY.counter("sched.leaves")
_RECOVERIES = _obs.REGISTRY.counter("sched.recoveries")
_DEDUP_HITS = _obs.REGISTRY.counter("sched.rpc.dedup_hits")
_INCARNATION = _obs.REGISTRY.gauge("sched.incarnation")

# Client ops that mutate scheduler state: these carry a per-sender
# sequence number so a retried RPC (lost reply, scheduler restart)
# deduplicates against the reply cache instead of re-executing.
_MUTATING_OPS = frozenset({
    "join", "leave", "register", "register_server", "register_serve",
    "register_bsp", "bsp_leave", "get", "add_local", "finish", "report",
    "blob_put", "blob_del", "barrier", "bye",
})

# Server-side: which ops append an RPC record to the write-ahead
# journal.  `get` is special-cased — only journaled when it actually
# assigned a part (the assignment is replayed verbatim; `get` picks
# randomly so re-dispatching it would re-roll the choice).  Pure reads
# (epoch, servers, bsp_peers, serve_nodes, blob_get, barrier_wait,
# metrics, elastic) are never journaled.
_JOURNALED_OPS = frozenset({
    "join", "leave", "register", "register_server", "register_serve",
    "register_bsp", "bsp_leave", "add_local", "finish", "report",
    "blob_put", "blob_del", "barrier", "bye",
})

# Ops an overloaded scheduler may shed when their propagated deadline
# expired in transit.  Deliberately tiny: everything else the tracker
# handles IS the control plane (membership, barriers, heartbeats,
# registration) whose loss converts overload into spurious evictions.
# `metrics` is pure telemetry pull — dropping a stale one is free.
_SHEDDABLE_SCHED_OPS = frozenset({"metrics"})


def _worker_rank(node: str) -> int:
    """Numeric rank of a `worker-<r>` node name (for retire ordering);
    unparsable names sort first so they are retired last."""
    try:
        return int(node.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return -1


def _parse_elastic_plan(spec: str) -> list[tuple[float, int]]:
    """`join@<sec>,leave@<sec>,...` -> [(at_sec, +1/-1), ...] sorted by
    time. Unknown verbs raise — a typo'd drill plan must fail loudly."""
    plan = []
    for tok in (t.strip() for t in spec.split(",") if t.strip()):
        verb, _, at = tok.partition("@")
        if verb not in ("join", "leave") or not at:
            raise ValueError(f"bad WH_ELASTIC_PLAN token {tok!r} "
                             "(want join@<sec> or leave@<sec>)")
        plan.append((float(at), 1 if verb == "join" else -1))
    return sorted(plan)


class Role(str, Enum):
    SCHEDULER = "scheduler"
    WORKER = "worker"
    SERVER = "server"
    SERVE = "serve"  # online serving shard (serving/server.py)


@dataclasses.dataclass
class NodeEnv:
    """Role/rank/addressing as the launcher exports it (the reference
    discovers these via ps-lite/rabit env vars, linear.cc:13-20)."""

    role: Optional[Role]
    rank: int
    num_workers: int
    num_servers: int
    scheduler_uri: str
    coord_uri: str = ""  # jax.distributed coordinator (global-mesh mode)
    num_serve: int = 0   # online serving shards (--serve group)

    @property
    def is_distributed(self) -> bool:
        return self.role is not None


def node_env() -> NodeEnv:
    role = os.environ.get("WH_ROLE")
    return NodeEnv(
        role=Role(role) if role else None,
        rank=int(os.environ.get("WH_RANK", "0")),
        num_workers=int(os.environ.get("WH_NUM_WORKERS", "1")),
        num_servers=int(os.environ.get("WH_NUM_SERVERS", "1")),
        scheduler_uri=os.environ.get("WH_SCHEDULER_URI", ""),
        coord_uri=os.environ.get("WH_COORD_URI", ""),
        num_serve=int(os.environ.get("WH_NUM_SERVE", "0")),
    )


# --------------------------------------------------------------- scheduler
class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        line = self.rfile.readline()
        if not line:
            return
        try:
            req = json.loads(line)
            resp = self.server.scheduler._dispatch(req)  # type: ignore
        except Exception as e:  # malformed request must not kill the server
            resp = {"error": repr(e)}
        self.wfile.write((json.dumps(resp) + "\n").encode())


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class Scheduler:
    """The scheduler node: owns the WorkloadPool, the summed Progress, and
    the liveness table. Start with serve(); stop() shuts down."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 node_timeout: float = 30.0, straggler: bool = True,
                 num_servers: int = 0, num_workers: int = 0,
                 journal_dir: Optional[str] = None):
        self.pool = WorkloadPool()
        self.num_workers = num_workers
        self._collect: "Optional[dict]" = None  # worker-local-data round
        self._round: "Optional[dict]" = None     # current dispatch round
        self.progress = Progress()
        self.node_timeout = node_timeout
        self.num_servers = num_servers
        self._server_uris: dict[int, str] = {}   # ps server rank -> uri
        self._serve_uris: dict[int, str] = {}    # serving shard rank -> uri
        self.num_serve_recoveries = 0            # shards that re-registered
        self._bsp_uris: dict[int, str] = {}      # bsp worker rank -> uri
        self._bsp_gen = 0                        # membership generation
        self._bsp_ready = False                  # group fully formed once
        self.num_bsp_recoveries = 0              # workers that re-registered
        self._lock = threading.Lock()
        self._nodes: dict[str, float] = {}       # node -> last seen
        # elastic membership: the epoch fences stale assignments across
        # join/leave/eviction; _members guards join idempotence (a
        # retried join must not double-bump); _retiring holds workers
        # the controller asked to drain and leave; _elastic_target is
        # the controller's published worker-count goal
        self._mepoch = 0
        self._members: set[str] = set()
        self._retiring: set[str] = set()
        self._elastic_target: Optional[int] = None
        self._elastic_thread: Optional[threading.Thread] = None
        self._barriers: dict[str, set] = {}      # name -> arrived nodes
        self._barrier_gen: dict[str, int] = {}   # name -> generation
        self._epoch = 0                          # bumped per dispatch round
        self._shutdown = False                   # job end; workers exit
        self._seen_workers: set[str] = set()     # workers ever registered
        self._blobs: dict[str, str] = {}         # rendezvous KV payloads
        # latest metrics snapshot each node piggybacked on a heartbeat
        # (keyed by node name, so a respawned server's snapshot replaces
        # its dead incarnation's — surviving-incarnation semantics, same
        # as PSClient.stats())
        self._node_metrics: dict[str, dict] = {}
        # flight-recorder control plane: a trigger bumps _flight_gen and
        # every subsequent RPC reply carries it (fgen/fwhy), so clients
        # dump their own rings around the same moment — the multi-node
        # black box. _burning_slos tracks which SLOs were already over
        # budget so only fresh crossings trigger (scrape thread only).
        self._flight_gen = 0
        self._flight_why = ""
        self._burning_slos: set[str] = set()
        self.num_server_recoveries = 0           # servers that re-registered
        self._done = False
        self._stop_evt = threading.Event()
        # metrics-over-time: a periodic sampler (WH_OBS_SCRAPE_SEC)
        # appends the aggregated cluster snapshot to this ring; the
        # `metrics` verb serves it as `history`
        self._snap_ring = _obs.SnapshotRing(int(knob_value("WH_OBS_RING")))
        self._scrape_sec = float(knob_value("WH_OBS_SCRAPE_SEC"))
        self._scrape_port = int(knob_value("WH_OBS_SCRAPE_PORT"))
        self._scrape_srv = None  # Prometheus HTTP endpoint, if enabled
        self._srv = _Server((host, port), _Handler)
        self._srv.scheduler = self  # type: ignore
        self._threads: list[threading.Thread] = []
        # exactly-once RPC: last (seq, reply) per sender — a retried op
        # whose reply was lost returns the cached reply instead of
        # re-executing; an OLDER seq is fenced as a pre-restart ghost
        self._replies: dict[str, tuple[int, dict]] = {}
        # durable control plane: write-ahead journal + replay (see
        # runtime/sched_journal.py). Replay runs BEFORE the straggler
        # killer starts so restored assignments cannot be re-queued
        # while the journal is still being applied.
        self._replaying = False
        self.incarnation = 0
        self._served_at = time.monotonic()
        self._compact_every = int(knob_value("WH_SCHED_JOURNAL_COMPACT"))
        self._journal: Optional[SchedulerJournal] = None
        if journal_dir:
            self._journal = SchedulerJournal(journal_dir)
            self._replay_journal()
            self.pool.on_requeue = self._journal_requeue
        _INCARNATION.set(float(self.incarnation))
        if straggler:
            self.pool.start_straggler_killer()

    # -- lifecycle ----------------------------------------------------------
    @property
    def uri(self) -> str:
        h, p = self._srv.server_address[:2]
        return f"{h}:{p}"

    def serve(self) -> None:
        self._served_at = time.monotonic()
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        w = threading.Thread(target=self._liveness_loop, daemon=True)
        w.start()
        self._threads.append(w)
        if self._scrape_sec > 0:
            s = threading.Thread(target=self._scrape_loop, daemon=True)
            s.start()
            self._threads.append(s)
        if self._scrape_port > 0:
            self._start_scrape_server()

    def announce_shutdown(self) -> None:
        """Mark the job finished; workers see it on their next epoch poll
        and exit their dispatch loop. Journaled — a scheduler respawned
        after a crash-during-drain resumes already shut down instead of
        restarting the pass loop."""
        with self._lock:
            self._shutdown = True
        if self._journal is not None:
            self._journal.record({"k": "shutdown"})

    def stop(self) -> None:
        self._done = True
        self._stop_evt.set()
        self.pool.stop_straggler_killer()
        if self._scrape_srv is not None:
            self._scrape_srv.shutdown()
            self._scrape_srv.server_close()
            self._scrape_srv = None
        self._srv.shutdown()
        self._srv.server_close()
        if self._journal is not None:
            self._journal.close()

    @staticmethod
    def from_env(env) -> "Scheduler":
        """Bind the scheduler on the URI the launcher allocated
        (WH_SCHEDULER_URI). When the launcher provides a snapshot dir
        (and WH_SCHED_JOURNAL is not disabled), the control plane
        journals there — a respawned scheduler replays it and resumes
        the job instead of restarting it."""
        host, port = env.scheduler_uri.rsplit(":", 1)
        jdir = os.environ.get("WH_SNAPSHOT_DIR") or None
        if jdir and not knob_value("WH_SCHED_JOURNAL"):
            jdir = None
        return Scheduler(
            host=host, port=int(port),
            node_timeout=float(os.environ.get("WH_NODE_TIMEOUT", "30")),
            num_servers=env.num_servers,
            num_workers=env.num_workers,
            journal_dir=jdir,
        )

    # -- durable control plane (journal + replay) ---------------------------
    def _replay_journal(self) -> None:
        """Restore state from the snapshot + journal tail. Called from
        __init__ (before any RPC thread exists); a corrupt record is
        skipped with a warning rather than bricking the respawn."""
        snap, records, max_inc = self._journal.load()
        had_state = snap is not None or bool(records)
        self._replaying = True
        try:
            if snap is not None:
                self._restore_state(snap)
            for rec in records:
                try:
                    self._apply_record(rec)
                except Exception as e:
                    print(f"[sched-journal] skipping bad "
                          f"{rec.get('k')!r} record: {e!r}", flush=True)
        finally:
            self._replaying = False
        self.incarnation = (max_inc + 1) if had_state else 0
        self._journal.record({"k": "inc", "inc": self.incarnation})
        if self.incarnation > 0:
            _RECOVERIES.inc()
            _trace.event("sched.resumed", cat="recovery",
                         inc=self.incarnation, records=len(records),
                         snapshot=snap is not None)
            self._flight_trigger(f"sched.resumed inc={self.incarnation}")
            print(f"[recovery] scheduler resumed at incarnation "
                  f"{self.incarnation} (snapshot="
                  f"{'yes' if snap else 'no'}, {len(records)} journal "
                  f"records replayed; epoch {self._epoch}, mepoch "
                  f"{self._mepoch})", flush=True)

    def _apply_record(self, rec: dict) -> None:
        """Re-apply one journal record during replay (chronological)."""
        k = rec.get("k")
        if k == "inc":
            return
        if k == "rpc":
            req = rec["req"]
            op = req.get("op")
            resp = rec.get("resp", {})
            if op == "get":
                # `get` picks randomly — apply the journaled choice
                # instead of re-rolling a different assignment
                if "part_id" in resp:
                    self.pool.assign_part(int(resp["part_id"]),
                                          req.get("node", "?"),
                                          resp.get("mepoch"))
            else:
                self._dispatch_op(op, req)
            sender, seq = req.get("sender"), req.get("seq")
            if sender is not None and seq is not None:
                # the cache holds the JOURNALED reply, not a recomputed
                # one — a post-restart retry must see the original
                with self._lock:
                    prev = self._replies.get(sender)
                    if prev is None or int(seq) >= prev[0]:
                        self._replies[sender] = (int(seq), resp)
            return
        if k == "round":
            self._apply_round_record(rec)
            return
        if k == "evict":
            n = rec["node"]
            _EVICTIONS.inc()
            with self._lock:
                self._nodes.pop(n, None)
            self._handle_dead_node(n)
            return
        if k == "requeue":
            self.pool.requeue_parts([int(i) for i in rec.get("parts", [])])
            return
        if k == "shutdown":
            with self._lock:
                self._shutdown = True
            return
        if k == "blob":
            with self._lock:
                self._blobs[rec["key"]] = rec["data"]
            return
        print(f"[sched-journal] unknown record kind {k!r}; skipped",
              flush=True)

    def _apply_round_record(self, rec: dict) -> None:
        self.pool.clear()
        with self._lock:
            self.progress = Progress()
            self._epoch = int(rec["epoch"])
            self._round = rec["round"]
            c = rec.get("collect")
            self._collect = (dict(pattern=c["pattern"], npp=c["npp"],
                                  fmt=c["fmt"],
                                  reported=set(c.get("reported", [])))
                             if c else None)
        if rec.get("parts") is not None:
            self.pool.load_state(rec["parts"])

    def _journal_round(self) -> None:
        """Append the round record (epoch, round, collect, pool fill)
        right after a round becomes visible. Also the compaction hook:
        round starts are the only quiescent point where no non-idempotent
        record (report/finish progress) can straddle the snapshot."""
        if self._journal is None:
            return
        if (self._compact_every > 0
                and self._journal.appends_since_compact
                >= self._compact_every):
            self._journal.compact(self._durable_state)
            print(f"[sched-journal] compacted into snapshot "
                  f"(epoch {self._epoch})", flush=True)
        with self._lock:
            rec = {"k": "round", "epoch": self._epoch,
                   "round": dict(self._round),
                   "collect": (dict(pattern=self._collect["pattern"],
                                    npp=self._collect["npp"],
                                    fmt=self._collect["fmt"],
                                    reported=sorted(
                                        self._collect["reported"]))
                               if self._collect is not None else None)}
        rec["parts"] = self.pool.export_state()
        self._journal.record(rec)

    def _journal_requeue(self, part_ids: list) -> None:
        """pool.on_requeue hook: the straggler watchdog re-queued parts;
        journal it so a replayed pool agrees about ownership (owner
        cleared, membership stamp kept)."""
        if self._journal is not None and not self._replaying:
            self._journal.record({"k": "requeue", "parts": list(part_ids)})

    def _record_op(self, op, req: dict, resp: dict,
                   sender, seq) -> None:
        """Cache the reply (exactly-once dedup) and append the RPC
        record. WAL order is effect -> journal -> reply: a crash between
        effect and journal loses the effect, but the reply was never
        sent, so the client's retry re-executes it — still exactly
        once."""
        if "error" in resp:
            return
        with self._lock:
            self._replies[sender] = (int(seq), resp)
        if self._journal is None or self._replaying:
            return
        if op not in _JOURNALED_OPS and not (op == "get"
                                             and "part_id" in resp):
            return
        jreq = dict(req)
        if op not in ("bye", "leave"):
            # heartbeat-piggybacked metrics snapshots are bulky and
            # refresh within seconds of a respawn; only the FINAL
            # snapshot a departing node sends is worth replaying
            jreq.pop("metrics", None)
        self._journal.record({"k": "rpc", "req": jreq, "resp": resp})

    def _durable_state(self) -> dict:
        """Everything a respawned scheduler needs, as one JSON-able
        snapshot (the compaction target). URI maps are stored as
        [rank, uri] pairs — JSON would silently turn int keys into
        strings. Counter values ride along so the end-of-run report
        adds up across incarnations."""
        with self._lock:
            state = {
                "inc": self.incarnation,
                "epoch": self._epoch,
                "round": self._round,
                "collect": (dict(pattern=self._collect["pattern"],
                                 npp=self._collect["npp"],
                                 fmt=self._collect["fmt"],
                                 reported=sorted(
                                     self._collect["reported"]))
                            if self._collect is not None else None),
                "mepoch": self._mepoch,
                "members": sorted(self._members),
                "retiring": sorted(self._retiring),
                "seen_workers": sorted(self._seen_workers),
                "blobs": dict(self._blobs),
                "server_uris": [[r, u] for r, u
                                in sorted(self._server_uris.items())],
                "serve_uris": [[r, u] for r, u
                               in sorted(self._serve_uris.items())],
                "bsp_uris": [[r, u] for r, u
                             in sorted(self._bsp_uris.items())],
                "bsp_gen": self._bsp_gen,
                "bsp_ready": self._bsp_ready,
                "barrier_gen": dict(self._barrier_gen),
                "barriers": {k: sorted(v)
                             for k, v in self._barriers.items()},
                "shutdown": self._shutdown,
                "replies": {s: [q, r]
                            for s, (q, r) in self._replies.items()},
                "recoveries": [self.num_server_recoveries,
                               self.num_serve_recoveries,
                               self.num_bsp_recoveries],
                "node_metrics": dict(self._node_metrics),
                "progress": dict(self.progress.tot),
            }
        counters = _obs.REGISTRY.snapshot()["counters"]
        state["counters"] = {
            n: v for n, v in counters.items()
            if v and (n.startswith("sched.") or n == "bsp.recoveries")
        }
        state["pool"] = self.pool.export_state()
        return state

    def _restore_state(self, s: dict) -> None:
        with self._lock:
            self._epoch = int(s.get("epoch", 0))
            self._round = s.get("round")
            c = s.get("collect")
            self._collect = (dict(pattern=c["pattern"], npp=c["npp"],
                                  fmt=c["fmt"],
                                  reported=set(c.get("reported", [])))
                             if c else None)
            self._mepoch = int(s.get("mepoch", 0))
            self._members = set(s.get("members", []))
            self._retiring = set(s.get("retiring", []))
            self._seen_workers = set(s.get("seen_workers", []))
            self._blobs = dict(s.get("blobs", {}))
            self._server_uris = {int(r): u
                                 for r, u in s.get("server_uris", [])}
            self._serve_uris = {int(r): u
                                for r, u in s.get("serve_uris", [])}
            self._bsp_uris = {int(r): u
                              for r, u in s.get("bsp_uris", [])}
            self._bsp_gen = int(s.get("bsp_gen", 0))
            self._bsp_ready = bool(s.get("bsp_ready", False))
            self._barrier_gen = {k: int(v) for k, v
                                 in s.get("barrier_gen", {}).items()}
            self._barriers = {k: set(v) for k, v
                              in s.get("barriers", {}).items()}
            self._shutdown = bool(s.get("shutdown", False))
            self._replies = {snd: (int(q), r) for snd, (q, r)
                             in s.get("replies", {}).items()}
            rec = s.get("recoveries", [0, 0, 0])
            self.num_server_recoveries = int(rec[0])
            self.num_serve_recoveries = int(rec[1])
            self.num_bsp_recoveries = int(rec[2])
            self._node_metrics = dict(s.get("node_metrics", {}))
            self.progress.merge(s.get("progress", {}))
        for name, v in (s.get("counters") or {}).items():
            if v:
                _obs.REGISTRY.counter(name).inc(int(v))
        if s.get("pool"):
            self.pool.load_state(s["pool"])

    def publish_blob(self, key: str, data: str) -> None:
        """Scheduler-side blob publish, journaled (unlike a direct
        _blobs poke) so it survives a restart — e.g. the runner's
        model-loaded marker must not cause a respawned scheduler to
        re-load the input model over live training state."""
        with self._lock:
            self._blobs[key] = data
        if self._journal is not None:
            self._journal.record({"k": "blob", "key": key, "data": data})

    def has_blob(self, key: str) -> bool:
        with self._lock:
            return key in self._blobs

    # -- dispatch round management -----------------------------------------
    def start_round(self, pattern: str, num_parts_per_file: int,
                    fmt: str, wtype: WorkType, data_pass: int,
                    local_data: bool = False,
                    dispatch: str = "online") -> int:
        """Load a pass's file parts into the pool (StartDispatch parity,
        data_parallel.h:93-115). Ordering matters both ways: the epoch is
        bumped BEFORE the pool refills so a worker still polling the old
        round can never be handed a new-round part under the old round's
        semantics (its stale-epoch `get` returns {wait}), and a new-epoch
        worker polling mid-fill sees the empty pool as not-finished
        (WorkloadPool.is_finished) rather than as an instantly-over
        round."""
        self.pool.clear()
        # worker-local data (reference data_parallel.h:82,96-100):
        # workers match the pattern against THEIR filesystems and
        # report; parts then carry node affinity
        collect = (dict(pattern=pattern, npp=num_parts_per_file,
                        fmt=fmt, reported=set())
                   if local_data else None)
        with self._lock:
            # rebind under the lock: handler threads merge() into the
            # current Progress and must not see a half-published swap
            self.progress = Progress()
            self._epoch += 1
            self._round = dict(type=int(wtype), data_pass=data_pass)
            self._collect = collect
        n = 0
        if not local_data:
            n = self.pool.add(pattern, num_parts_per_file, fmt)
            if n == 0:
                raise FileNotFoundError(f"no files match {pattern}")
            if dispatch == "batch" and self.num_workers > 0:
                # stable n/num_workers assignment, unchanged between
                # passes (reference batch mode, data_parallel.h:54-60)
                self.pool.assign_stable(
                    [f"worker-{r}" for r in range(self.num_workers)])
        self._journal_round()
        return n

    def _round_finished(self) -> bool:
        """A worker-local-data round is only over when every expected
        worker has reported its files AND all reported parts are done —
        otherwise a fast worker draining its own parts would end the
        round before a slow worker's files ever entered the pool. A
        collect round where every worker reported zero files terminates
        (as an empty round) instead of spinning; wait_round raises the
        same FileNotFoundError the non-local path does."""
        with self._lock:
            if self._collect is not None and self.num_workers > 0:
                if len(self._collect["reported"]) < self.num_workers:
                    return False
                if self.pool.size() == 0:
                    return True
        return self.pool.is_finished()

    def wait_round(self, print_sec: float = 1.0, t0: Optional[float] = None,
                   verbose: bool = True) -> Progress:
        """Block until every part is done, printing progress rows
        (ShowProgress parity, minibatch_solver.h:169-192). Completion is
        polled every ~0.2s regardless of print_sec — print_sec controls
        only row cadence. (Sleeping print_sec between completion checks
        stalled every job whose conf quieted output with a large
        print_sec: a round that drained in 100s held the scheduler for
        the full print interval — the r3 PS bench timeout.)"""
        t0 = t0 or time.time()
        if verbose:
            print(Progress.header(), flush=True)
        next_print = time.time() + print_sec
        none_live_since: Optional[float] = None
        while not self._round_finished():
            time.sleep(min(0.2, print_sec))
            live = self.live_workers()
            if self._seen_workers and not live:
                # every worker gone from the liveness table. Workers run
                # a LivenessPinger, so eviction means real death — but
                # grant one extra node_timeout of grace before aborting
                # so a transient stall (GC pause, ping thread descheduled)
                # can never kill a healthy job. After that, abort with a
                # clear error instead of waiting forever for parts nobody
                # will finish; the job is resumable from the last
                # save_iter snapshot.
                now = time.monotonic()
                if none_live_since is None:
                    none_live_since = now
                elif now - none_live_since > self.node_timeout:
                    raise RuntimeError(
                        "all workers lost mid-round; aborting the job "
                        "(resume from the last _iter-K checkpoint with "
                        "model_in/load_iter)")
            else:
                none_live_since = None
            if verbose and time.time() >= next_print:
                print(self.progress.row(t0), flush=True)
                next_print = time.time() + print_sec
        with self._lock:
            empty_collect = (self._collect is not None
                             and self.pool.size() == 0)
            pattern = self._collect["pattern"] if empty_collect else None
        if empty_collect:
            raise FileNotFoundError(
                f"no worker matched any file for {pattern!r}")
        if verbose:
            print(self.progress.row(t0), flush=True)
        return self.progress

    # -- RPC ops ------------------------------------------------------------
    def _dispatch(self, req: dict) -> dict:  # wormlint: thread-entry
        op = req.get("op")
        t0 = time.perf_counter()
        try:
            # deadline shed, telemetry ops only (control ops always
            # dispatch): anchor the carried relative deadline and bounce
            # the request if its budget was spent in transit
            _overload.arm(req)
            if op in _SHEDDABLE_SCHED_OPS and _overload.should_shed(req):
                return dict(_overload.shed_reply(req),
                            inc=self.incarnation)
            sender, seq = req.get("sender"), req.get("seq")
            if sender is not None and seq is not None:
                with self._lock:
                    cached = self._replies.get(sender)
                if cached is not None:
                    if seq == cached[0]:
                        # duplicate of this sender's last applied op (a
                        # retry whose reply was lost, possibly across a
                        # restart): return the recorded reply instead
                        # of re-executing — exactly-once
                        _DEDUP_HITS.inc()
                        resp = dict(cached[1])
                        resp["inc"] = self.incarnation
                        return resp
                    if seq < cached[0]:
                        # incarnation fence: an older seq can only be a
                        # ghost from before a restart
                        return {"error": f"stale scheduler seq {seq} < "
                                         f"{cached[0]} from {sender}",
                                "inc": self.incarnation}
            resp = self._dispatch_op(op, req)
            resp["inc"] = self.incarnation
            if self._flight_gen:
                # piggyback the flight generation + trigger reason so
                # every client learns of a cluster trigger on its next
                # RPC (heartbeats flow constantly) and dumps its rings
                with self._lock:
                    resp["fgen"] = self._flight_gen
                    resp["fwhy"] = self._flight_why
            if sender is not None and seq is not None:
                self._record_op(op, req, resp, sender, seq)
            return resp
        finally:
            _obs.REGISTRY.histogram(f"sched.op.{op}_s").observe(
                time.perf_counter() - t0)

    def _dispatch_op(self, op, req: dict) -> dict:
        if faults.ACTIVE is not None and not self._replaying:
            # journal replay re-runs recorded ops; armed faults (drops,
            # kills) must not fire on historical traffic
            faults.ACTIVE.sched_op(op)
        node = req.get("node", "?")
        snap = req.get("metrics")
        with self._lock:
            self._nodes[node] = time.monotonic()
            if node.startswith("worker"):
                self._seen_workers.add(node)
            if isinstance(snap, dict):
                # heartbeat-piggybacked metrics snapshot (any op may
                # carry one; LivenessPinger/heartbeat loops do, and a
                # final one rides the worker's `bye`)
                self._node_metrics[node] = snap
        if op == "metrics":
            got = self.aggregate_metrics()
            if req.get("format") == "prom":
                # Prometheus text exposition over the RPC channel, for
                # scrapers that bridge the newline-JSON protocol (the
                # WH_OBS_SCRAPE_PORT endpoint serves the same body)
                return {"ok": True, "nodes": got["nodes"],
                        "prom": _prom.render_snapshot(got["aggregate"])}
            out = {"ok": True, **got}
            if req.get("history"):
                out["history"] = [{"ts": ts, "aggregate": snap}
                                  for ts, snap in self._snap_ring.items()]
            if req.get("slo"):
                out["slos"] = _slo.evaluate(got["aggregate"],
                                            publish=False)
            return out
        if op == "register":
            return {"ok": True, "epoch": self._epoch,
                    "mepoch": self._mepoch}
        if op == "join":
            # a worker joining a RUNNING job (elastic membership): admit
            # it and bump the membership epoch so both planes observe the
            # change. Idempotent — a joiner retrying its join RPC bumps
            # only once.
            with self._lock:
                fresh = node not in self._members
                self._members.add(node)
            if fresh:
                _JOINS.inc()
                _trace.event("sched.member_join", cat="membership",
                             node=node)
                self.progress.merge({"member_joins": 1.0})
                self._member_change("join", node)
            return {"ok": True, "epoch": self._epoch,
                    "mepoch": self._mepoch}
        if op == "leave":
            # a worker resigning cleanly (retired by the controller, or
            # degrading out of a partition after bounded retries): drop
            # it from liveness NOW instead of burning a node_timeout,
            # re-queue anything it still held, and bump the epoch.
            with self._lock:
                self._nodes.pop(node, None)
                self._members.discard(node)
                self._retiring.discard(node)
            requeued = self.pool.reset(node)
            self.pool.drop_node(node)
            if requeued:
                print(f"[membership] {node} left holding {requeued} "
                      "parts; re-queued", flush=True)
            _LEAVES.inc()
            _trace.event("sched.member_leave", cat="membership", node=node)
            with self._lock:
                self.progress.merge({"member_leaves": 1.0})
            self._member_change("leave", node)
            return {"ok": True, "mepoch": self._mepoch}
        if op == "elastic":
            # the elastic supervisor's poll (launcher --elastic): read
            # the controller's current worker-count target and the live
            # set; a caller may also publish a target here (drills).
            if req.get("target") is not None:
                self.set_elastic_target(int(req["target"]))
            with self._lock:
                live = sorted(n for n in self._nodes
                              if n.startswith("worker"))
                return {"ok": True, "target": self._elastic_target,
                        "live": live, "retiring": sorted(self._retiring),
                        "mepoch": self._mepoch,
                        "shutdown": self._shutdown}
        if op == "register_server":
            # a ps server announces its push/pull endpoint (the ps-lite
            # node-manager rendezvous role). A rank re-registering under
            # a NEW uri is a respawned server rejoining — a first-class
            # recovery event: log it and count it into progress so the
            # job's output records that a failover happened.
            with self._lock:
                rank = int(req["rank"])
                prev = self._server_uris.get(rank)
                self._server_uris[rank] = req["uri"]
                recovered = prev is not None and prev != req["uri"]
                if recovered:
                    self.num_server_recoveries += 1
                    self.progress.merge({"server_recoveries": 1.0})
            if recovered:
                _SRV_RECOVERIES.inc()
                _trace.event("sched.server_recovered", cat="recovery",
                             rank=rank, uri=req["uri"], prev=prev)
                self._flight_trigger(f"server-{rank} recovered")
                print(f"[recovery] ps server-{rank} re-registered at "
                      f"{req['uri']} (was {prev})", flush=True)
            return {"ok": True}
        if op == "register_serve":
            # a serving shard announces its predict endpoint. A rank
            # re-registering under a NEW uri is a respawned shard
            # rejoining after death — routers following the serve_nodes
            # resolver pick the new address up on their next retry.
            with self._lock:
                rank = int(req["rank"])
                prev = self._serve_uris.get(rank)
                self._serve_uris[rank] = req["uri"]
                recovered = prev is not None and prev != req["uri"]
                if recovered:
                    self.num_serve_recoveries += 1
                    self.progress.merge({"serve_recoveries": 1.0})
            if recovered:
                _SERVE_RECOVERIES.inc()
                _trace.event("sched.serve_recovered", cat="recovery",
                             rank=rank, uri=req["uri"], prev=prev)
                self._flight_trigger(f"serve-shard-{rank} recovered")
                print(f"[recovery] serve shard-{rank} re-registered at "
                      f"{req['uri']} (was {prev})", flush=True)
            return {"ok": True}
        if op == "serve_nodes":
            # routers poll until the full --serve group is up, and
            # re-poll after a socket error to chase a respawned shard
            world = int(req.get("world", 0))
            with self._lock:
                known = len(self._serve_uris)
                ready = known >= world > 0
                uris = [self._serve_uris[r]
                        for r in sorted(self._serve_uris)] if ready else []
            return {"ready": ready, "uris": uris, "num_known": known}
        if op == "register_bsp":
            # a BSP worker announces its ring endpoint. A rank
            # re-registering under a NEW uri is a respawned worker
            # rejoining: bump the membership GENERATION — the signal
            # survivors blocked mid-round poll for (runtime/allreduce.py
            # aborts and replays the round at the new generation).
            with self._lock:
                rank = int(req["rank"])
                prev = self._bsp_uris.get(rank)
                self._bsp_uris[rank] = req["uri"]
                recovered = prev is not None and prev != req["uri"]
                # a rank the formed group has never seen is an ELASTIC
                # JOIN: bump the generation so survivors rebuild the
                # ring over the grown peer set at their next version
                # boundary (before the group first forms, new ranks are
                # just the initial rendezvous filling up)
                joined = prev is None and self._bsp_ready
                if recovered:
                    self._bsp_gen += 1
                    self.num_bsp_recoveries += 1
                    self.progress.merge({"bsp_recoveries": 1.0})
                elif joined:
                    self._bsp_gen += 1
                gen = self._bsp_gen
            if recovered:
                _BSP_RECOVERIES.inc()
                _trace.event("sched.bsp_recovered", cat="recovery",
                             rank=rank, uri=req["uri"], prev=prev)
                self._flight_trigger(f"bsp-worker-{rank} recovered")
                print(f"[recovery] bsp worker-{rank} re-registered at "
                      f"{req['uri']} (was {prev}); generation -> {gen}",
                      flush=True)
            elif joined:
                print(f"[membership] bsp worker-{rank} joined at "
                      f"{req['uri']}; generation -> {gen}", flush=True)
            return {"ok": True, "gen": gen}
        if op == "bsp_peers":
            # BSP workers poll until the full group is up, and re-poll
            # mid-round to detect membership changes. Once the group
            # has formed ONCE, the reply reports the CURRENT set even
            # when it is smaller than the caller's world — that is how
            # survivors of a leave adopt the shrunk ring instead of
            # waiting forever for a peer that resigned.
            world = int(req.get("world", self.num_workers))
            with self._lock:
                full = len(self._bsp_uris) >= world > 0
                if full:
                    self._bsp_ready = True
                ready = full or (self._bsp_ready and bool(self._bsp_uris))
                uris = [self._bsp_uris[r]
                        for r in sorted(self._bsp_uris)] if ready else []
                gen = self._bsp_gen
            return {"ready": ready, "gen": gen, "uris": uris,
                    "num_known": len(self._bsp_uris)}
        if op == "bsp_leave":
            # a BSP worker resigning for good (not a respawn): shrink
            # the peer set and bump the generation; survivors rebuild
            # the ring without it.
            with self._lock:
                rank = int(req["rank"])
                uri = req.get("uri")
                # key by rank when it still maps to this worker's uri;
                # otherwise fall back to a uri scan — an elastic
                # survivor may have RE-INDEXED its rank since it
                # registered (allreduce.py _adopt), so the uri is the
                # stable identity
                if uri is None or self._bsp_uris.get(rank) == uri:
                    left = self._bsp_uris.pop(rank, None) is not None
                else:
                    left = False
                    for r, u in list(self._bsp_uris.items()):
                        if u == uri:
                            del self._bsp_uris[r]
                            rank, left = r, True
                            break
                if left:
                    self._bsp_gen += 1
                gen = self._bsp_gen
            if left:
                print(f"[membership] bsp worker-{rank} left; "
                      f"generation -> {gen}", flush=True)
            return {"ok": True, "gen": gen}
        if op == "servers":
            # workers poll until the full `-s` group is up
            with self._lock:
                ready = len(self._server_uris) >= self.num_servers
                uris = [self._server_uris[r]
                        for r in sorted(self._server_uris)] if ready else []
            return {"ready": ready, "uris": uris,
                    "num_known": len(self._server_uris),
                    "num_servers": self.num_servers}
        if op == "get":
            with self._lock:
                retire = node in self._retiring
                mepoch = self._mepoch
            if retire:
                # a retiring worker gets no new parts: it drains what it
                # holds, flushes, and leaves
                return {"wait": True, "retire": True, "epoch": self._epoch,
                        "mepoch": mepoch}
            if req.get("epoch") != self._epoch:
                # worker is in an older round; tell it to resync
                return {"wait": True, "epoch": self._epoch,
                        "mepoch": mepoch}
            with self._lock:
                if (self._collect is not None
                        and node not in self._collect["reported"]):
                    # worker-local-data round: this node must first match
                    # the pattern locally and report its files
                    return {"match": self._collect["pattern"],
                            "epoch": self._epoch}
            got = self.pool.get(node, mepoch=mepoch)
            if got is None:
                done = self._round_finished()
                return {"done": done, "wait": not done,
                        "epoch": self._epoch, "mepoch": mepoch}
            part_id, f = got
            return {
                "part_id": part_id,
                "file": dataclasses.asdict(f),
                "round": self._round,
                "epoch": self._epoch,
                "mepoch": mepoch,
            }
        if op == "add_local":
            with self._lock:
                c = self._collect
                if c is None or req.get("epoch") != self._epoch:
                    return {"ok": False}
                c["reported"].add(node)
                npp, fmt = c["npp"], c["fmt"]
            n = self.pool.add_files(req.get("files", []), npp, fmt,
                                    node=node)
            return {"ok": True, "num_files": n}
        if op == "finish":
            # fenced completion: besides the round epoch, the pool
            # rejects a finish whose sender no longer owns the part — a
            # node declared dead (assignment reset, membership epoch
            # bumped) that comes BACK cannot double-apply its stale
            # assignment; the part's re-execution by a live owner is
            # what counts
            counted = (req.get("epoch") == self._epoch
                       and self.pool.finish(req["part_id"], node=node,
                                            mepoch=req.get("mepoch")))
            # a straggler twin's duplicate finish is dropped so its
            # progress is not double-counted (at-least-once execution,
            # exactly-once accounting); merges run under the lock since
            # handler threads are concurrent
            if counted and req.get("progress"):
                with self._lock:
                    self.progress.merge(req["progress"])
            return {"ok": True, "counted": counted}
        if op == "report":  # pure progress push (ps::Slave channel)
            with self._lock:
                self.progress.merge(req.get("progress", {}))
            return {"ok": True}
        if op == "blob_put":
            # tiny rendezvous KV (host-side rabit::Broadcast payloads,
            # e.g. the k-means centroid init from rank 0)
            with self._lock:
                self._blobs[req["key"]] = req["data"]
            return {"ok": True}
        if op == "blob_del":
            # consumed rendezvous payloads should not sit in scheduler
            # memory for the job's lifetime
            with self._lock:
                self._blobs.pop(req["key"], None)
            return {"ok": True}
        if op == "blob_get":
            with self._lock:
                data = self._blobs.get(req["key"])
            return {"ok": data is not None, "data": data}
        if op == "bye":
            # explicit deregistration (global-mesh workers) so liveness
            # does not have to time the node out
            with self._lock:
                self._nodes.pop(node, None)
            return {"ok": True}
        if op == "epoch":
            with self._lock:
                retire = node in self._retiring
            return {"epoch": self._epoch,
                    "round": getattr(self, "_round", None),
                    "shutdown": self._shutdown,
                    "mepoch": self._mepoch,
                    "retire": retire}
        if op == "barrier":
            return self._barrier_enter(req["name"], node, req["world"])
        if op == "barrier_wait":
            with self._lock:
                gen = self._barrier_gen.get(req["name"], 0)
            return {"released": gen > req["gen"]}
        if op == "flight":
            # explicit black-box dump: dump this node's rings NOW and
            # bump the generation so every client dumps on its next RPC
            reason = str(req.get("reason") or "flight-verb")
            path = self._flight_trigger(reason)
            with self._lock:
                gen = self._flight_gen
            return {"ok": True, "enabled": _flight.ACTIVE is not None,
                    "path": path, "fgen": gen}
        return {"error": f"unknown op {op!r}"}

    def _barrier_enter(self, name: str, node: str, world: int) -> dict:
        """A node arrives at the named barrier. Returns the generation it
        belongs to; the barrier releases (generation increments) when
        `world` distinct nodes of that generation have arrived."""
        with self._lock:
            gen = self._barrier_gen.setdefault(name, 0)
            arrived = self._barriers.setdefault(name, set())
            arrived.add(node)
            if len(arrived) >= world:
                self._barrier_gen[name] = gen + 1
                self._barriers[name] = set()
                return {"released": True, "gen": gen}
            return {"released": False, "gen": gen}

    # -- elastic membership -------------------------------------------------
    @property
    def membership_epoch(self) -> int:
        return self._mepoch

    def _member_change(self, why: str, node: str) -> None:
        """The worker set changed (join/leave/eviction): bump the
        membership epoch and rebalance pinned parts over the live set.
        Must be called WITHOUT the lock held."""
        with self._lock:
            self._mepoch += 1
            mepoch = self._mepoch
            live = sorted((n for n in self._nodes
                           if n.startswith("worker")), key=_worker_rank)
        _MEPOCHS.inc()
        repinned = self.pool.repin(live) if live else 0
        print(f"[membership] epoch -> {mepoch} ({why}: {node}); "
              f"{len(live)} live workers"
              + (f", {repinned} parts re-pinned" if repinned else ""),
              flush=True)

    def set_elastic_target(self, target: int) -> None:
        """Publish the controller's worker-count goal. Growing is the
        launcher's half (spawn processes; they `join`); shrinking is
        decided HERE — the highest-ranked live workers are marked
        retiring, drain their current part, flush, and `leave`."""
        with self._lock:
            self._elastic_target = int(target)
            live = sorted((n for n in self._nodes
                           if n.startswith("worker")), key=_worker_rank)
            active = [n for n in live if n not in self._retiring]
            excess = len(active) - self._elastic_target
            newly = []
            if excess > 0:
                for n in sorted(active, key=_worker_rank,
                                reverse=True)[:excess]:
                    self._retiring.add(n)
                    newly.append(n)
        for n in newly:
            print(f"[membership] retiring {n} (target "
                  f"{target} < {len(active)} active)", flush=True)

    def start_membership_controller(self, initial_workers: int,
                                    controller=None) -> None:
        """WH_ELASTIC decision loop: every WH_ELASTIC_SEC either follow
        the scripted WH_ELASTIC_PLAN (`join@<sec>,leave@<sec>` offsets
        from start — deterministic churn for drills) or feed the
        cluster-aggregated `queue.depth` / `loader.stall_s` gauges to a
        MembershipController (solver/minibatch_solver.py) and publish
        its target."""
        if self._elastic_thread is not None:
            return
        cadence = float(knob_value("WH_ELASTIC_SEC"))
        plan = _parse_elastic_plan(str(knob_value("WH_ELASTIC_PLAN") or ""))
        if controller is None and not plan:
            from wormhole_tpu.solver.minibatch_solver import (
                MembershipController,
            )

            lo = int(knob_value("WH_ELASTIC_MIN"))
            hi = int(knob_value("WH_ELASTIC_MAX")) or 2 * initial_workers
            controller = MembershipController(initial_workers, lo=lo, hi=hi)
        t0 = time.monotonic()

        def loop():  # wormlint: thread-entry
            while not self._stop_evt.wait(max(cadence, 0.2)):
                try:
                    if plan:
                        target = initial_workers + sum(
                            delta for at, delta in plan
                            if time.monotonic() - t0 >= at)
                    else:
                        agg = self.aggregate_metrics()["aggregate"]
                        gauges = agg.get("gauges", {})
                        target = controller.record(
                            float(gauges.get("queue.depth") or 0.0),
                            float(gauges.get("loader.stall_s") or 0.0),
                            live=len(self.live_workers()))
                    if target is not None:
                        self.set_elastic_target(target)
                except Exception:
                    pass  # a malformed snapshot must not kill the loop

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._elastic_thread = t
        self._threads.append(t)

    # -- telemetry ----------------------------------------------------------
    def _flight_trigger(self, reason: str) -> Optional[str]:
        """An anomaly fired: dump this node's flight rings and bump the
        generation every RPC reply piggybacks, so the whole cluster
        dumps its recent past around the same moment. No-op (and no
        generation bump — replies stay byte-identical) when the flight
        recorder is disabled."""
        if _flight.ACTIVE is None:
            return None
        with self._lock:
            self._flight_gen += 1
            self._flight_why = reason
        return _flight.dump(reason, force=True)

    def _scrape_loop(self) -> None:  # wormlint: thread-entry
        """WH_OBS_SCRAPE_SEC sampler: append the aggregated cluster
        snapshot to the ring every tick (metrics over time, not just
        final values) and refresh the slo.*_burn gauges so burn rates
        ride heartbeats and scrapes like any other metric. A FRESH
        SLO-burn crossing (an objective newly over budget this tick)
        triggers a cluster-wide flight dump."""
        while not self._stop_evt.wait(self._scrape_sec):
            try:
                got = self.aggregate_metrics()
            except Exception:
                continue  # a malformed node snapshot must not kill it
            slos = _slo.evaluate(got["aggregate"])
            burning = {v["name"] for v in slos if not v.get("ok", True)}
            with self._lock:
                fresh = burning - self._burning_slos
                self._burning_slos = burning
            if fresh:
                self._flight_trigger(
                    "slo-burn: " + ",".join(sorted(fresh)))
            self._snap_ring.add(time.time(), got["aggregate"])
            _RING_DEPTH.set(float(len(self._snap_ring)))

    def _start_scrape_server(self) -> None:
        """Prometheus text-exposition endpoint (WH_OBS_SCRAPE_PORT):
        GET /metrics renders the live aggregated snapshot."""
        import http.server

        sched = self

        class _MetricsHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                _SCRAPES.inc()
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = _prom.render_snapshot(
                    sched.aggregate_metrics()["aggregate"]).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass  # scrapes are periodic; don't spam stderr

        host = self._srv.server_address[0]
        self._scrape_srv = http.server.ThreadingHTTPServer(
            (host, self._scrape_port), _MetricsHandler)
        t = threading.Thread(target=self._scrape_srv.serve_forever,
                             daemon=True)
        t.start()
        self._threads.append(t)

    def aggregate_metrics(self) -> dict:
        """Cluster-wide metrics view: this process's registry merged
        with the latest snapshot each node piggybacked on a heartbeat.
        The payload of the `metrics` dispatch verb and the raw material
        of the end-of-run report (obs/report.py)."""
        with self._lock:
            snaps = dict(self._node_metrics)
        merged = _obs.merge_snapshots(
            [_obs.REGISTRY.snapshot(), *snaps.values()])
        return {"nodes": sorted(snaps), "aggregate": merged}

    # -- liveness -----------------------------------------------------------
    def live_workers(self) -> list[str]:
        """Workers currently in the liveness table."""
        with self._lock:
            return [n for n in self._nodes if n.startswith("worker")]

    def workers_drained(self, expect: int) -> bool:
        """True once `expect` distinct workers have registered AND none
        remain live — the shutdown-drain condition (a fast worker's
        deregistration must not read as 'everyone finished' while a
        slow-starting peer has yet to register)."""
        if (self.incarnation > 0
                and time.monotonic() - self._served_at < 6.0):
            # a respawned scheduler's liveness table starts from the
            # replayed journal, which may be empty of live workers; let
            # the LivenessPinger cadence (2s) repopulate it before
            # trusting emptiness as "drained"
            return False
        with self._lock:
            if len(self._seen_workers) < expect:
                return False
            return not any(n.startswith("worker") for n in self._nodes)

    def workers_ever_seen(self) -> int:
        """How many distinct workers have registered so far (the drain
        fast-path: a mis-launched job where NO worker ever arrives
        should exit after one liveness window, not the full drain
        bound — VERDICT r4 weak #6)."""
        with self._lock:
            return len(self._seen_workers)

    def _liveness_loop(self) -> None:
        while not self._done:
            time.sleep(min(self.node_timeout / 3, 5.0))
            now = time.monotonic()
            with self._lock:
                dead = [n for n, seen in self._nodes.items()
                        if now - seen > self.node_timeout]
                for n in dead:
                    del self._nodes[n]
            if dead:
                _EVICTIONS.inc(len(dead))
            for n in dead:
                if self._journal is not None:
                    self._journal.record({"k": "evict", "node": n})
                self._handle_dead_node(n)

    def _handle_dead_node(self, n: str) -> None:
        """Evict one node that dropped off the liveness plane (shared
        between the watchdog and journal replay of `evict` records)."""
        _trace.event("sched.liveness_evict", cat="recovery", node=n)
        if not self._replaying:
            self._flight_trigger(f"liveness-evict {n}")
        if n.startswith("server"):
            # servers carry no pool parts; their loss is its own
            # first-class event (the launcher's respawn loop — if
            # enabled — brings the process back; workers ride it
            # out through the PSClient retry path)
            print(f"[recovery] ps {n} lost from the liveness "
                  "plane (no epoch ping for "
                  f"{self.node_timeout:.0f}s); awaiting respawn "
                  "or worker-side retry failure", flush=True)
            return
        requeued = self.pool.reset(n)
        if requeued:
            print(f"node {n} lost; re-queued {requeued} parts",
                  flush=True)
        released, skipped = self.pool.drop_node(n)
        if skipped:
            print(f"node {n} lost; {skipped} parts only it could "
                  "read are skipped", flush=True)
        if n.startswith("worker"):
            # a declared-dead worker is a membership change: the
            # epoch bump (plus the assignment reset above, which
            # clears the parts' owner/epoch stamps) fences any
            # late completion the node sends if it comes back
            with self._lock:
                self._members.discard(n)
                self._retiring.discard(n)
            self._member_change("evict", n)
        with self._lock:
            if (self._collect is not None
                    and n not in self._collect["reported"]):
                # a dead worker will never report its local files;
                # count it as reported-empty so the round can end
                # (its data is unreachable, like the reference
                # losing a node's local disk)
                self._collect["reported"].add(n)
                print(f"node {n} lost before reporting local "
                      "files; its data is skipped", flush=True)


# ------------------------------------------------------------------ client
_CLIENT_NONCE = itertools.count()


class SchedulerClient:
    """Worker-side RPC stub.

    Mutating ops carry a per-sender sequence number; the scheduler
    caches the last reply per sender (journaled), so a retried op whose
    reply was lost — or that straddled a scheduler restart — returns
    the ORIGINAL reply instead of re-executing. That is what makes
    retrying safe here: without it, ops like barrier entry and part
    assignment would double-apply. `retry_deadline` (default: the
    launcher-exported WH_SCHED_RETRY_SEC; 0 = legacy fail-fast) bounds
    how long a lost connection/reply is retried under the unified
    retry budget."""

    def __init__(self, uri: str, node: str, timeout: float = 60.0,
                 connect_deadline: float = 30.0,
                 retry_deadline: Optional[float] = None):
        host, port = uri.rsplit(":", 1)
        self.addr = (host, int(port))
        self.node = node
        self.timeout = timeout
        self.connect_deadline = connect_deadline
        if retry_deadline is None:
            retry_deadline = float(
                os.environ.get("WH_SCHED_RETRY_SEC", "0") or 0.0)
        self.retry_deadline = retry_deadline
        # per-INSTANCE sender id: a client re-created in the same
        # process (an in-process respawn, e.g. a BSP rank rejoining)
        # is a new logical sender with a fresh seq space — it must not
        # be fenced by its dead predecessor's cached seq.
        self._sender = f"{node}:{os.getpid()}.{next(_CLIENT_NONCE)}"
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._inc: Optional[int] = None  # last incarnation seen
        self._fgen = 0  # last flight generation seen (fgen piggyback)

    def call(self, **req) -> dict:
        """One exactly-once RPC. Connection establishment always
        retries under `connect_deadline` (the launcher spawns workers
        concurrently with the scheduler, ADVICE r1). With a positive
        `retry_deadline`, a lost reply retries the SAME (sender, seq)
        — the scheduler's reply cache deduplicates it — so clients
        ride out a scheduler outage/restart instead of crashing."""
        req.setdefault("node", self.node)
        if req.get("op") in _MUTATING_OPS:
            # mint the seq ONCE so every retry of this op carries it
            with self._seq_lock:
                self._seq += 1
                req["sender"], req["seq"] = self._sender, self._seq
        budget = None
        while True:
            # (re)stamp the remaining ambient budget per ATTEMPT — a
            # retry after backoff has less budget left than the first
            # send did, and the scheduler sheds on what the frame says
            dl = _overload.wire_deadline()
            if dl is not None:
                req["dl"] = dl
            payload = json.dumps(req) + "\n"
            try:
                with connect_with_retry(self.addr, self.connect_deadline,
                                        self.timeout) as s:
                    f = s.makefile("rw")
                    f.write(payload)
                    f.flush()
                    line = f.readline()
                if not line:
                    raise ConnectionResetError("empty scheduler reply")
                break
            except (OSError, ConnectionError) as e:
                if self.retry_deadline <= 0:
                    raise  # legacy fail-fast (no retry window granted)
                if budget is None:
                    budget = _retry.RetryBudget(
                        self.retry_deadline,
                        op=f"sched.{req.get('op')}")
                if budget.expired:
                    budget.give_up(e)
                budget.sleep()
        if budget is not None:
            budget.succeeded()
        resp = json.loads(line)
        inc = resp.get("inc")
        if inc is not None:
            with self._seq_lock:
                prev, self._inc = self._inc, inc
            if prev is not None and inc != prev:
                print(f"[sched-client] {self.node}: scheduler restarted "
                      f"(incarnation {prev} -> {inc}); resumed from its "
                      "journal", flush=True)
        fgen = resp.get("fgen")
        if fgen is not None:
            # cluster flight trigger: the scheduler bumped the flight
            # generation — dump THIS node's rings too (multi-node black
            # box; a no-op when the local recorder is off)
            with self._seq_lock:
                fresh_gen = int(fgen) > self._fgen
                if fresh_gen:
                    self._fgen = int(fgen)
            if fresh_gen:
                _flight.dump(f"cluster: {resp.get('fwhy') or '?'}",
                             force=True)
        if "error" in resp:
            raise RuntimeError(f"scheduler error: {resp['error']}")
        return resp

    def register(self) -> dict:
        return self.call(op="register")

    def blob_put(self, key: str, arr) -> None:
        """Broadcast a small host payload (one array, or a dict of
        arrays) through the scheduler — the rabit::Broadcast host path
        for BSP init payloads like centroid seeds and quantile-sketch
        summaries."""
        import base64
        import io

        import numpy as np

        buf = io.BytesIO()
        if isinstance(arr, dict):
            np.savez(buf, **arr)
        else:
            np.save(buf, np.asarray(arr))
        self.call(op="blob_put", key=key,
                  data=base64.b64encode(buf.getvalue()).decode())

    def blob_get(self, key: str, timeout: float = 60.0, poll: float = 0.1):
        """Fetch a rendezvous payload, waiting for the publisher under
        the unified retry policy: jittered backoff growing from `poll`
        instead of a fixed-interval busy-poll (which spun the scheduler
        whenever a partition fault delayed the publisher), bounded by
        the caller's `timeout`."""
        import base64
        import io

        import numpy as np

        budget = _retry.RetryBudget(timeout, base_s=poll, op="blob_get")
        while True:
            r = self.call(op="blob_get", key=key)
            if r.get("ok"):
                budget.succeeded()
                got = np.load(io.BytesIO(base64.b64decode(r["data"])))
                if hasattr(got, "files"):  # npz: dict payload
                    return {k: got[k] for k in got.files}
                return got
            if budget.expired:
                budget.give_up(
                    TimeoutError(f"blob {key!r} never published"))
            budget.sleep()

    def report(self, progress: dict) -> None:
        self.call(op="report", progress=progress)

    def barrier(self, name: str, world: int, poll: float = 0.1,
                timeout: Optional[float] = None) -> None:
        """Block until `world` distinct nodes reach the named barrier
        (rabit tracker rendezvous parity for the BSP apps). With a
        timeout, raises TimeoutError instead of waiting forever for a
        peer that died before arriving."""
        deadline = (time.monotonic() + timeout) if timeout else None
        t_enter = time.monotonic()
        with _trace.span(f"barrier.{name}", cat="sched", world=world):
            try:
                r = self.call(op="barrier", name=name, world=world)
                if r["released"]:
                    return
                gen = r["gen"]
                while True:
                    time.sleep(poll)
                    if self.call(op="barrier_wait", name=name,
                                 gen=gen)["released"]:
                        return
                    if deadline is not None and time.monotonic() > deadline:
                        raise TimeoutError(
                            f"barrier {name!r} never released")
            finally:
                _BARRIER_WAIT_S.observe(time.monotonic() - t_enter)


class LivenessPinger:
    """Background liveness pings for workers whose main thread runs long
    device computations (global-mesh BSP loops): without them the
    scheduler's sweep would declare the worker dead mid-solve."""

    def __init__(self, client: SchedulerClient, interval: float = 2.0):
        import threading

        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(interval):
                try:
                    # piggyback this process's metrics snapshot on the
                    # liveness ping — the scheduler-aggregation channel
                    client.call(op="epoch",
                                metrics=_obs.REGISTRY.snapshot())
                except Exception:
                    pass

        self._t = threading.Thread(target=loop, daemon=True)
        self._t.start()

    def stop(self) -> None:
        self._stop.set()
        self._t.join(timeout=5)


class RemotePool:
    """WorkloadPool-shaped adapter over the scheduler RPC, so the same
    solver code runs single-process (local pool) or distributed (this).
    get() returns None only when the whole round is finished; while other
    workers still hold parts it blocks-and-polls (online mode semantics,
    data_parallel.h:54-72)."""

    def __init__(self, client: SchedulerClient, poll: float = 0.2):
        self.client = client
        self.poll = poll
        self.epoch = 0  # joins whatever round is live on first sync_round
        self.round: Optional[dict] = None
        # elastic membership state observed on replies: the membership
        # epoch (the worker's store absorbs bumps between parts) and
        # the retire flag (the scheduler asked this worker to drain,
        # flush, and leave)
        self.mepoch = 0
        self.retire = False
        self._part_mepoch: dict[int, int] = {}

    def _observe(self, r: dict) -> None:
        if "mepoch" in r:
            self.mepoch = r["mepoch"]
        if r.get("retire"):
            self.retire = True

    def sync_round(self, wait: bool = True) -> Optional[dict]:
        """Adopt the scheduler's next dispatch round (type/data_pass).
        Returns None on job shutdown (or once this worker is marked
        retiring — the caller leaves instead of joining a new round).
        Blocks until the epoch advances past the one this pool last
        worked."""
        while True:
            r = self.client.call(op="epoch")
            self._observe(r)
            if r.get("shutdown") or self.retire:
                return None
            if r.get("round") is not None and r["epoch"] > self.epoch:
                self.epoch = r["epoch"]
                self.round = r["round"]
                return self.round
            if not wait:
                return None
            time.sleep(self.poll)

    def get(self, node: str = "") -> Optional[tuple[int, File]]:
        while True:
            r = self.client.call(op="get", epoch=self.epoch)
            self._observe(r)
            if self.retire:
                # drain stops here; the part we were handed (if any)
                # was not: retire replies never carry part_ids
                return None
            if "part_id" in r:
                # remember the membership epoch the assignment was made
                # under; finish() echoes it so the scheduler can fence
                # completions that straddled a membership change
                self._part_mepoch[r["part_id"]] = r.get("mepoch", 0)
                f = File(**r["file"])
                return r["part_id"], f
            if "match" in r:
                # worker-local-data round: match the pattern against THIS
                # node's filesystem and report (data_parallel.h:96-100,
                # 143-150)
                from wormhole_tpu.data.match_file import match_file

                try:
                    files = match_file(r["match"])
                except FileNotFoundError:
                    files = []
                self.client.call(op="add_local", files=files,
                                 epoch=self.epoch)
                continue
            if r.get("done"):
                return None
            if r.get("epoch", self.epoch) != self.epoch:
                # the scheduler has moved on to a newer round: this round
                # is over for us — fall back to sync_round (a worker
                # descheduled across the round change must not spin here
                # forever, ADVICE r1)
                return None
            time.sleep(self.poll)

    def finish(self, part_id: int, progress: Optional[dict] = None) -> None:
        self.client.call(op="finish", part_id=part_id, epoch=self.epoch,
                         mepoch=self._part_mepoch.pop(part_id, None),
                         progress=progress or {})

    def join(self) -> dict:
        """Announce this worker as an elastic joiner of a running job
        (bumps the membership epoch scheduler-side) and adopt the
        current state."""
        r = self.client.call(op="join")
        self._observe(r)
        return r

    def leave(self) -> None:
        """Resign from the job cleanly (retirement, or degradation out
        of a partition): the scheduler drops us from liveness NOW and
        re-queues anything we still held."""
        try:
            self.client.call(op="leave",
                             metrics=_obs.REGISTRY.snapshot())
        except Exception:
            pass  # leaving best-effort: liveness eviction is the backstop
