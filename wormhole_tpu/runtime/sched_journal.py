"""Durable write-ahead journal for the scheduler control plane.

The scheduler keeps all control-plane state (workload assignments,
membership epoch, BSP generation, server/serve URIs, barriers, blobs)
in memory.  This module makes that state durable so a respawned
scheduler resumes with exactly-once workload accounting intact:

- ``sched.journal`` — append-only JSONL; every state-mutating op
  appends one fsync'd record *after* applying its effect and *before*
  the reply is sent (WAL order: effect -> journal -> reply, so a lost
  effect implies a lost reply and the client's retry re-executes it).
- ``sched.snapshot`` — periodic compaction target, written atomically
  (tmp + fsync + os.replace) so a crash mid-compaction leaves the
  previous snapshot + journal intact.

The reader tolerates a torn tail: a partially written final line (the
scheduler died mid-append) is dropped and the file is truncated back
to the last good record so subsequent appends do not follow garbage.

Record envelope: one JSON object per line with a ``"k"`` kind tag.
Kinds are interpreted by the scheduler's replay loop, not here; the
journal itself only knows about ``{"k": "inc", "inc": N}`` records and
the snapshot's ``"inc"`` field, which carry the incarnation number
used for restart fencing.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from wormhole_tpu.obs import metrics as _obs

_APPENDS = _obs.REGISTRY.counter("sched.journal.appends")
_BYTES = _obs.REGISTRY.counter("sched.journal.bytes")
_REPLAYS = _obs.REGISTRY.counter("sched.journal.replays")
_COMPACTIONS = _obs.REGISTRY.counter("sched.journal.compactions")

JOURNAL_NAME = "sched.journal"
SNAPSHOT_NAME = "sched.snapshot"


class SchedulerJournal:
    """fsync'd JSONL journal + atomic snapshot for scheduler state.

    Thread-safe: ``record`` may be called from any dispatch thread;
    ``compact`` holds the same lock across the whole snapshot build so
    no record can land between the state capture and the truncation
    (callers pass a ``state_fn`` that is invoked *inside* the lock —
    the lock ordering is therefore journal -> scheduler/pool locks,
    and no caller may hold those locks while appending).
    """

    def __init__(self, dirpath: str):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self.journal_path = os.path.join(dirpath, JOURNAL_NAME)
        self.snapshot_path = os.path.join(dirpath, SNAPSHOT_NAME)
        self._lock = threading.Lock()
        self._fh = None  # type: ignore[assignment]
        self._appends_since_compact = 0

    # -- load / replay ------------------------------------------------

    def load(self) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]], int]:
        """Read (snapshot, tail_records, max_incarnation_seen).

        Truncates a torn tail in place.  Returns ``(None, [], -1)``
        when neither file exists (fresh start — incarnation 0 with no
        recovery accounting).
        """
        snap: Optional[Dict[str, Any]] = None
        max_inc = -1
        if os.path.exists(self.snapshot_path):
            try:
                with open(self.snapshot_path, "r") as f:
                    snap = json.load(f)
                if snap is not None:
                    max_inc = max(max_inc, int(snap.get("inc", 0)))
            except (OSError, ValueError) as e:
                print(f"[sched-journal] unreadable snapshot "
                      f"{self.snapshot_path}: {e!r}; ignoring", flush=True)
                snap = None
        records: List[Dict[str, Any]] = []
        if os.path.exists(self.journal_path):
            good = 0
            with open(self.journal_path, "rb") as f:
                data = f.read()
            pos = 0
            while pos < len(data):
                nl = data.find(b"\n", pos)
                if nl < 0:
                    break  # torn tail: no terminating newline
                line = data[pos:nl]
                if line.strip():
                    try:
                        rec = json.loads(line.decode("utf-8"))
                    except (ValueError, UnicodeDecodeError):
                        break  # torn/corrupt line: stop at good prefix
                    records.append(rec)
                    if rec.get("k") == "inc":
                        max_inc = max(max_inc, int(rec.get("inc", 0)))
                pos = nl + 1
                good = pos
            if good < len(data):
                print(f"[sched-journal] truncating torn tail: "
                      f"{len(data) - good} bytes after offset {good}",
                      flush=True)
                with open(self.journal_path, "r+b") as f:
                    f.truncate(good)
            _REPLAYS.inc(len(records))
        return snap, records, max_inc

    # -- append -------------------------------------------------------

    def record(self, rec: Dict[str, Any]) -> None:
        """Append one record and fsync it before returning."""
        line = (json.dumps(rec, separators=(",", ":"), sort_keys=True)
                + "\n").encode("utf-8")
        with self._lock:
            if self._fh is None:
                self._fh = open(self.journal_path, "ab")
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._appends_since_compact += 1
        _APPENDS.inc()
        _BYTES.inc(len(line))

    @property
    def appends_since_compact(self) -> int:
        with self._lock:
            return self._appends_since_compact

    # -- compaction ---------------------------------------------------

    def compact(self, state_fn) -> None:
        """Atomically replace snapshot+journal with ``state_fn()``.

        ``state_fn`` is called with the journal lock held, so no append
        can land between the state capture and the journal truncation.
        """
        with self._lock:
            state = state_fn()
            tmp = self.snapshot_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f, separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            with open(self.journal_path, "wb") as f:
                f.flush()
                os.fsync(f.fileno())
            self._appends_since_compact = 0
        _COMPACTIONS.inc()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
