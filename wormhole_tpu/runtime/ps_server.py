"""Parameter-server data plane: shared model across worker processes.

The reference's ps-lite servers hold THE model: every worker ZPulls the
same server-resident weights and ZPushes gradients back, so N workers
train one set of statistics (reference learn/linear/async_sgd.h:240-288,
servers at :200-226; key-range sharding across `-s` server processes).
This module is the TPU build's cross-process equivalent:

- `-s` server processes each own a contiguous bucket-range shard of every
  state table (the ps-lite key-shard layout; rows n*r//S .. n*(r+1)//S of
  each array, matching utils/checkpoint.py's part split so server part
  files ARE checkpoint part files).
- Workers train on their local device mesh and synchronize through the
  servers with **bounded staleness**: every `max_delay` minibatches a
  worker pushes the additive delta of its state tables since its last
  pull and pulls the merged state back. For FTRL the (z, n) tables are
  exactly additive in the pushed gradients, so delta-merging reproduces
  async-SGD semantics with staleness <= max_delay minibatches per worker
  (the reference's max_delay knob, difacto guide/criteo.conf:21, bounds
  the same quantity in units of in-flight minibatches).
- The wire is a length-prefixed binary protocol over TCP; pushes are
  optionally quantized on the wire (fixed_bytes: 2 = bfloat16 bits,
  1 = int8 + scale — the FIXING_FLOAT/TRUNCATE filter parity,
  async_sgd.h:290-301) so the filter actually reduces bandwidth, not
  just rounding.

Server discovery rides the scheduler control plane: servers register
their URI (op=register_server), workers poll op=servers until all `-s`
URIs are known.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
from typing import Optional

import numpy as np

from wormhole_tpu.runtime.net import connect_with_retry

# ------------------------------------------------------------ wire format
# Frame = 4-byte big-endian header length | JSON header | raw payload.
# header = {"op": str, ...meta, "arrays": [{"name", "shape", "enc",
#           "scale", "nbytes"}, ...]}; payload = buffers concatenated in
# array order.


def _encode(a: np.ndarray, fixed_bytes: int = 0) -> tuple[dict, bytes]:
    """Encode one f32 array for the wire. fixed_bytes: 0 = raw f32,
    2 = bfloat16 bit-truncation (round-to-nearest-even), 1 = absmax int8."""
    a = np.ascontiguousarray(a, dtype=np.float32)
    meta = {"shape": list(a.shape)}
    if fixed_bytes == 0:
        buf = a.tobytes()
        meta.update(enc="raw", nbytes=len(buf))
        return meta, buf
    if fixed_bytes >= 2:
        u = a.view(np.uint32)
        # round-to-nearest-even to the high 16 bits (bfloat16)
        rounded = (u + 0x7FFF + ((u >> 16) & 1)) >> 16
        buf = rounded.astype(np.uint16).tobytes()
        meta.update(enc="bf16", nbytes=len(buf))
        return meta, buf
    scale = float(max(np.max(np.abs(a), initial=0.0), 1e-30) / 127.0)
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    buf = q.tobytes()
    meta.update(enc="int8", scale=scale, nbytes=len(buf))
    return meta, buf


def _decode(meta: dict, buf: bytes) -> np.ndarray:
    shape = tuple(meta["shape"])
    enc = meta["enc"]
    if enc == "raw":
        return np.frombuffer(buf, np.float32).reshape(shape).copy()
    if enc == "bf16":
        u = np.frombuffer(buf, np.uint16).astype(np.uint32) << 16
        return u.view(np.float32).reshape(shape).copy()
    if enc == "int8":
        q = np.frombuffer(buf, np.int8).astype(np.float32)
        return (q * meta["scale"]).reshape(shape)
    raise ValueError(f"unknown encoding {enc!r}")


def _read_exact(sock_file, n: int) -> Optional[bytes]:
    chunks = []
    while n > 0:
        c = sock_file.read(n)
        if not c:
            return None
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def send_frame(sock_file, header: dict,
               arrays: Optional[dict[str, np.ndarray]] = None,
               fixed_bytes: int = 0) -> None:
    metas, bufs = [], []
    for name, a in (arrays or {}).items():
        m, b = _encode(a, fixed_bytes)
        m["name"] = name
        metas.append(m)
        bufs.append(b)
    header = dict(header, arrays=metas)
    h = json.dumps(header).encode()
    sock_file.write(struct.pack(">I", len(h)))
    sock_file.write(h)
    for b in bufs:
        sock_file.write(b)
    sock_file.flush()


def recv_frame(sock_file) -> Optional[tuple[dict, dict[str, np.ndarray]]]:
    raw = _read_exact(sock_file, 4)
    if raw is None:
        return None
    (hlen,) = struct.unpack(">I", raw)
    h = _read_exact(sock_file, hlen)
    if h is None:
        return None
    header = json.loads(h)
    arrays = {}
    for m in header.get("arrays", []):
        buf = _read_exact(sock_file, m["nbytes"])
        if buf is None:
            return None
        arrays[m["name"]] = _decode(m, buf)
    return header, arrays


def shard_range(n: int, rank: int, world: int) -> tuple[int, int]:
    """Row range of server `rank`: the same even split checkpoint part
    files use (utils/checkpoint.py), so parts reassemble by rank order."""
    return n * rank // world, n * (rank + 1) // world


# ---------------------------------------------------------------- server
class _PSHandler(socketserver.StreamRequestHandler):
    def handle(self):
        while True:
            got = recv_frame(self.rfile)
            if got is None:
                return
            header, arrays = got
            resp_header, resp_arrays = self.server.node._dispatch(  # type: ignore
                header, arrays)
            send_frame(self.wfile, resp_header, resp_arrays)
            if header.get("op") == "shutdown":
                self.server.node._shutdown.set()  # type: ignore
                return


class _PSServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ServerNode:
    """One `-s` server process: owns its bucket-range slice of every state
    table. Tables are created by the first `init` push (set-if-absent;
    workers init deterministically so any winner is equivalent); `push`
    adds deltas; `pull` returns current slices; `save` writes this
    server's shard as a checkpoint part file."""

    def __init__(self, rank: int, world: int,
                 host: str = "127.0.0.1", port: int = 0):
        self.rank = rank
        self.world = world
        self.tables: dict[str, np.ndarray] = {}
        self.full_rows: dict[str, int] = {}  # full-table row counts
        # derived-table specs ({name: {"kind": "ftrl_prox", ...}}): tables
        # that are NOT additive in worker pushes but are pure functions of
        # additive ones (FTRL's w = prox(z, n)); recomputed server-side
        # after merges so pulls/saves never expose an inconsistent pair
        self.derived: dict[str, dict] = {}
        self._derived_dirty = False
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._srv = _PSServer((host, port), _PSHandler)
        self._srv.node = self  # type: ignore
        self.num_push = 0
        self.num_pull = 0

    @property
    def uri(self) -> str:
        h, p = self._srv.server_address[:2]
        return f"{h}:{p}"

    def serve(self) -> None:
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()

    def wait_shutdown(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown.wait(timeout)

    def stop(self) -> None:
        self._shutdown.set()
        self._srv.shutdown()
        self._srv.server_close()

    # -- ops ----------------------------------------------------------------
    def _dispatch(self, header: dict, arrays: dict) -> tuple[dict, dict]:
        op = header.get("op")
        if op == "init":
            with self._lock:
                known = bool(self.tables)
                if not known:
                    for k, v in arrays.items():
                        self.tables[k] = v.astype(np.float32)
                    self.full_rows = {
                        k: int(n) for k, n in header["full_rows"].items()}
                    self.derived = header.get("derived") or {}
            return {"ok": True, "known": known}, {}
        if op == "pull":
            with self._lock:
                self.num_pull += 1
                self._recompute_derived()
                out = {k: v.copy() for k, v in self.tables.items()}
            return {"ok": True}, out
        if op == "push":
            with self._lock:
                self.num_push += 1
                for k, d in arrays.items():
                    if k not in self.tables:
                        return {"error": f"push to unknown table {k}"}, {}
                    if k in self.derived:
                        # non-additive derived tables ignore pushed deltas;
                        # they are recomputed from their additive sources
                        continue
                    self.tables[k] += d
                self._derived_dirty = True
            return {"ok": True}, {}
        if op == "save":
            path = self._save(header["base"], header.get("iter"))
            return {"ok": True, "path": path}, {}
        if op == "stats":
            with self._lock:
                return {"ok": True, "num_push": self.num_push,
                        "num_pull": self.num_pull,
                        "tables": {k: list(v.shape)
                                   for k, v in self.tables.items()}}, {}
        if op == "shutdown":
            return {"ok": True}, {}
        return {"error": f"unknown op {op!r}"}, {}

    def _recompute_derived(self) -> None:
        """Recompute derived tables from their additive sources (caller
        holds the lock). FTRL's w is soft-threshold-nonlinear in (z, n),
        so additively merged worker deltas cannot represent it: a key
        whose merged z crosses the L1 threshold must re-solve the prox
        even though every worker pushed delta-w = 0."""
        if not self._derived_dirty:
            return
        for k, spec in self.derived.items():
            if spec["kind"] == "ftrl_prox":
                z, n = self.tables["z"], self.tables["n"]
                eta = (spec["lr_beta"] + np.sqrt(n)) / spec["lr_eta"]
                mag = np.maximum(np.abs(z) - spec["lambda_l1"], 0.0)
                self.tables[k] = (np.sign(-z) * mag
                                  / (eta + spec["lambda_l2"])
                                  ).astype(np.float32)
            else:
                raise ValueError(f"unknown derived kind {spec['kind']!r}")
        self._derived_dirty = False

    def _save(self, base: str, it: Optional[int]) -> str:
        import glob
        import re

        from wormhole_tpu.utils.checkpoint import (atomic_savez, part_name,
                                                   save_prefix)

        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        with self._lock:
            self._recompute_derived()
            tables = {k: v.copy() for k, v in self.tables.items()}
        prefix = save_prefix(base, it)
        if self.rank == 0:
            # remove stale files from a previous save with a different
            # shard count (the invariant utils/checkpoint.save_model
            # keeps): only rank 0 cleans, and only files NO current
            # server writes, so concurrent part writes are never raced
            if self.world > 1 and os.path.exists(prefix + ".npz"):
                os.remove(prefix + ".npz")
            for old in glob.glob(prefix + "_part-*.npz"):
                r = int(re.search(r"_part-(\d+)\.npz$", old).group(1))
                if r >= self.world or self.world <= 1:
                    os.remove(old)
        if self.world <= 1:
            path = prefix + ".npz"
        else:
            path = part_name(base, it, self.rank) + ".npz"
        atomic_savez(path, compressed=True, **tables)
        return path


# ---------------------------------------------------------------- client
class PSClient:
    """Worker-side stub over all servers: splits each table by the
    servers' row ranges, keeps one persistent connection per server."""

    def __init__(self, uris: list[str], connect_deadline: float = 30.0):
        self.uris = list(uris)
        self.world = len(uris)
        self._socks: list[Optional[socket.socket]] = [None] * self.world
        self._files = [None] * self.world
        self.connect_deadline = connect_deadline

    def _file(self, r: int):
        if self._files[r] is None:
            host, port = self.uris[r].rsplit(":", 1)
            s = connect_with_retry((host, int(port)), self.connect_deadline)
            self._socks[r] = s
            self._files[r] = s.makefile("rwb")
        return self._files[r]

    def _rpc(self, r: int, header: dict, arrays=None, fixed_bytes: int = 0):
        f = self._file(r)
        try:
            send_frame(f, header, arrays, fixed_bytes)
            got = recv_frame(f)
        except OSError:
            self.close(r)
            raise
        if got is None:
            self.close(r)
            raise ConnectionResetError(f"server {self.uris[r]} closed")
        h, arrs = got
        if "error" in h:
            raise RuntimeError(f"ps server error: {h['error']}")
        return h, arrs

    def close(self, r: Optional[int] = None) -> None:
        ranks = range(self.world) if r is None else [r]
        for i in ranks:
            try:
                if self._socks[i] is not None:
                    self._socks[i].close()
            except OSError:
                pass
            self._socks[i] = None
            self._files[i] = None

    # -- table ops ----------------------------------------------------------
    def _slices(self, tables: dict[str, np.ndarray], r: int):
        out = {}
        for k, v in tables.items():
            lo, hi = shard_range(v.shape[0], r, self.world)
            out[k] = v[lo:hi]
        return out

    def init(self, tables: dict[str, np.ndarray],
             derived: Optional[dict] = None) -> None:
        full_rows = {k: int(v.shape[0]) for k, v in tables.items()}
        for r in range(self.world):
            self._rpc(r, {"op": "init", "full_rows": full_rows,
                          "derived": derived or {}},
                      self._slices(tables, r))

    def pull(self) -> dict[str, np.ndarray]:
        parts = [self._rpc(r, {"op": "pull"})[1] for r in range(self.world)]
        return {
            k: np.concatenate([p[k] for p in parts], axis=0)
            if self.world > 1 else parts[0][k]
            for k in parts[0]
        }

    def push(self, deltas: dict[str, np.ndarray],
             fixed_bytes: int = 0) -> None:
        for r in range(self.world):
            self._rpc(r, {"op": "push"}, self._slices(deltas, r),
                      fixed_bytes=fixed_bytes)

    def save(self, base: str, it: Optional[int] = None) -> list[str]:
        return [self._rpc(r, {"op": "save", "base": base, "iter": it})[0]
                ["path"] for r in range(self.world)]

    def stats(self, r: int = 0) -> dict:
        return self._rpc(r, {"op": "stats"})[0]

    def shutdown(self) -> None:
        for r in range(self.world):
            try:
                self._rpc(r, {"op": "shutdown"})
            except (OSError, ConnectionError):
                pass
        self.close()


class SyncedStore:
    """Bounded-staleness synchronization of a learner's KV store against
    the server group: tracks the state at last pull and pushes additive
    deltas (cur - base). `maybe_sync` counts minibatches and syncs every
    `max_delay` (the reference's bounded-async knob)."""

    def __init__(self, store, client: PSClient, max_delay: int = 16,
                 fixed_bytes: int = 0, derived: Optional[dict] = None,
                 perf=None):
        self.store = store
        self.client = client
        self.perf = perf  # optional utils.perf.Perf: times push/pull ops
        self.max_delay = max(int(max_delay), 1)
        self.fixed_bytes = fixed_bytes
        # non-additive derived-table specs forwarded to the servers (e.g.
        # FTRL's w = prox(z, n); see ServerNode._recompute_derived)
        self.derived = derived or {}
        self._base: dict[str, np.ndarray] = {}
        self._steps = 0
        self.num_syncs = 0

    def init(self) -> None:
        """Offer this worker's (deterministic) init state, then adopt the
        authoritative server state."""
        self.client.init(self.store.to_numpy(), derived=self.derived)
        self.pull()

    def pull(self) -> None:
        pulled = self.client.pull()
        self.store.from_numpy(pulled)
        self._base = pulled

    def sync(self) -> None:
        import time as _time

        t0 = _time.perf_counter()
        cur = self.store.to_numpy()
        # derived tables (e.g. FTRL's w) are recomputed server-side from
        # their additive sources; shipping their deltas would be dead
        # payload the servers discard
        deltas = {k: cur[k] - self._base[k] for k in cur
                  if k not in self.derived}
        self.client.push(deltas, fixed_bytes=self.fixed_bytes)
        t1 = _time.perf_counter()
        self.pull()
        if self.perf is not None:
            self.perf.add("ps_push", t1 - t0)
            self.perf.add("ps_pull", _time.perf_counter() - t1)
        self._steps = 0
        self.num_syncs += 1

    def maybe_sync(self) -> bool:
        self._steps += 1
        if self._steps >= self.max_delay:
            self.sync()
            return True
        return False
