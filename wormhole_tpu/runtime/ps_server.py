"""Parameter-server data plane: shared model across worker processes.

The reference's ps-lite servers hold THE model: every worker ZPulls the
same server-resident weights and ZPushes gradients back, so N workers
train one set of statistics (reference learn/linear/async_sgd.h:240-288,
servers at :200-226; key-range sharding across `-s` server processes).
This module is the TPU build's cross-process equivalent:

- `-s` server processes each own a contiguous bucket-range shard of every
  state table (the ps-lite key-shard layout; rows n*r//S .. n*(r+1)//S of
  each array, matching utils/checkpoint.py's part split so server part
  files ARE checkpoint part files).
- Workers train on their local device mesh and synchronize through the
  servers with **bounded staleness**: every `max_delay` minibatches a
  worker pushes the additive delta of its state tables since its last
  pull and pulls the merged state back. For FTRL the (z, n) tables are
  exactly additive in the pushed gradients, so delta-merging reproduces
  async-SGD semantics with staleness <= max_delay minibatches per worker
  (the reference's max_delay knob, difacto guide/criteo.conf:21, bounds
  the same quantity in units of in-flight minibatches).
- **The wire is sparse**: a push carries only the rows the worker
  touched since its last sync — (indices, delta-rows) per table — the
  ZPush-of-the-minibatch's-keys semantic (async_sgd.h:270-287). Pulls
  are versioned: servers stamp every pushed row with a monotonically
  increasing clock, and `pull since=c` returns only rows stamped after
  `c` — so a worker's pull traffic is proportional to what ANY worker
  changed since it last looked, never to the table size. Together these
  make wire bytes/sync O(globally touched keys), which is what lets the
  multi-process path run at the 2^26-bucket Criteo-1TB operating point
  (a dense (z, n) sync there would be ~0.5 GB per worker per sync).
- Pushes are optionally quantized on the wire (fixed_bytes: 2 = bfloat16
  bits, 1 = int8 + scale — the FIXING_FLOAT/TRUNCATE filter parity,
  async_sgd.h:290-301) and optionally zlib-compressed (the
  msg_compression filter, linear config.proto:123-133).
- **Wire codec v2** (`WH_WIRE={raw,bf16,int8,int4}`, `WH_WIRE_EF`,
  `WH_WIRE_COMP={,zlib,bshuf}`): value quantization on BOTH directions
  with sender-side error feedback. Pushes quantize each sync's delta
  rows ONCE (SyncedStore snapshot time) into `net.QuantRows` — per-row
  scales for 2-D tables, per-64-element group scales for 1-D (a scalar
  scale over a skewed compacted row vector flattens everything but the
  hottest row to zero and diverges FTRL) — with an `EFQuant` residual
  accumulator per table (transmit Q(delta + r), keep
  r <- (delta + r) - Q(.)), so low-bit value streams stay unbiased over
  time; journal replays and need_keys resends reuse the SAME QuantRows,
  so the seq-fenced retry can never re-advance (double-apply) a
  residual. Versioned pull replies are quantized server-side with a
  per-(sender, table) EFQuant — pulls are absolute refreshes, so a lost
  reply self-corrects on the next one — and invalidated with the key
  caches on restore; pull replies cap at bf16 (absolute-state refreshes
  need per-element relative precision — absmax codes err relative to
  the hottest group neighbor and diverge skewed FTRL tables). Everything is hello-negotiated per connection: the
  client offers `wire`/`wire_comp`, the server acks what it can decode,
  and an un-acked (older) peer silently degrades to the legacy scalar
  fixed_bytes forms and raw framing. `wire_comp=bshuf` frames eligible
  buffers with a byte-plane shuffle + zlib-1 (`comp="bshuf+zlib"`).
- The reference's third filter, KEY_CACHING, avoids resending
  identical key lists; `WH_KEYCACHE=1` enables its analog here: frames carry a blake2b
  digest of each group's sorted key vector, servers cache key lists per
  (sender, digest), and a repeated touched set (the common case on
  epoch 2+ under the pack cache) ships digest + values only, with a
  miss-reply -> full-resend fallback. Caches are invalidated by the
  recovery path (server restore/reload, client reconnect), counted in
  `ps.keycache.{hits,misses,invalidations}`.
- **Async sync** (`WH_ASYNC_SYNC=1`): `SyncedStore.sync()` snapshots the
  touched rows + deltas and hands the push+pull round-trip to a
  background comms thread (ps-lite's ZPush/ZPull-return-immediately
  semantics), folding the pull result in at the NEXT sync boundary —
  device compute overlaps the wire, and effective staleness grows to at
  most 2*max_delay minibatches. `flush()` is the barrier (part ends,
  eval, checkpoints): it drains the in-flight round-trip and runs one
  synchronous sync so results stay well-defined. With the knob off the
  sync path is bit-identical to the original synchronous one.
- Multi-server pushes/pulls fan their per-server slices out on a small
  thread pool (one socket per server), so a sync against `-s` servers
  costs max-of-shards, not sum-of-shards.

Server discovery rides the scheduler control plane: servers register
their URI (op=register_server), workers poll op=servers until all `-s`
URIs are known.
"""

from __future__ import annotations

import collections
import concurrent.futures
import json
import os
import queue
import time
import socket
import socketserver
import threading
from typing import Callable, Optional

import numpy as np

from wormhole_tpu.obs import metrics as _obs
from wormhole_tpu.obs import pyprof as _pyprof
from wormhole_tpu.obs import trace as _trace
from wormhole_tpu.runtime import faults
from wormhole_tpu.runtime import overload as _overload
from wormhole_tpu.runtime import retry as _retrylib
from wormhole_tpu.runtime.net import (  # noqa: F401  (re-exported: the wire
    _COMPRESS_MIN, _decode, _encode, _read_exact, EFQuant, InflightGate,
    QuantRows, WIRE_COMP_MODES, WIRE_ENCODINGS, busy_backoff, busy_reply,
    connect_with_retry, key_digest, quantize_rows,
    recv_frame, send_frame)  # format moved to net.py so fault
# injection can hook frame send/recv for every net user; tests and tools
# keep importing the names from here.

# registry handles cached at import (see wormhole_tpu/obs/metrics.py)
_NUM_PUSH = _obs.REGISTRY.counter("ps.server.num_push")
_NUM_PULL = _obs.REGISTRY.counter("ps.server.num_pull")
_DEDUP_HITS = _obs.REGISTRY.counter("ps.server.dedup_hits")
_SNAPSHOTS = _obs.REGISTRY.counter("ps.server.snapshots")
_SNAPSHOT_S = _obs.REGISTRY.histogram("ps.server.snapshot_s")
_RESTORES = _obs.REGISTRY.counter("ps.server.restores")
_RESTORE_EPOCH = _obs.REGISTRY.gauge("ps.server.restore_epoch")
_RPC_S = _obs.REGISTRY.histogram("ps.client.rpc_s")
_BYTES_PUSH = _obs.REGISTRY.counter("ps.client.bytes_push")
_BYTES_PULL = _obs.REGISTRY.counter("ps.client.bytes_pull")
_RETRIES = _obs.REGISTRY.counter("ps.client.retries")
_REPLAYS = _obs.REGISTRY.counter("ps.client.replays")
_REPLAY_DEDUP = _obs.REGISTRY.counter("ps.client.replay_dedup")
_ROLLBACKS = _obs.REGISTRY.counter("ps.client.rollback_repulls")
# membership-epoch absorption: re-handshakes run against the (stable)
# server group after the WORKER set changed (see PSClient.rehello)
_REHELLOS = _obs.REGISTRY.counter("ps.client.rehellos")
_SYNCS = _obs.REGISTRY.counter("ps.client.syncs")
_SYNC_PUSH_S = _obs.REGISTRY.histogram("ps.client.sync_push_s")
_SYNC_PULL_S = _obs.REGISTRY.histogram("ps.client.sync_pull_s")
# async-sync plane: in-flight round-trips (0 or 1 per SyncedStore),
# fraction of round-trip wall hidden behind device compute, and the
# fold-wait the training loop actually paid at sync boundaries
_SYNC_INFLIGHT = _obs.REGISTRY.gauge("ps.sync.inflight")
_SYNC_OVERLAP = _obs.REGISTRY.gauge("ps.sync.overlap_frac")
_SYNC_WAIT_S = _obs.REGISTRY.histogram("ps.client.sync_wait_s")
# train.stage.* mirror: the sync wall the TRAIN THREAD actually pays —
# the full round-trip in synchronous mode, only the fold wait in async
# mode (the overlapped remainder is hidden behind compute)
_ST_SYNC = _obs.REGISTRY.histogram("train.stage.sync_s")
# key-list caching (the KEY_CACHING filter analog): hits = frames that
# shipped digest-only, misses = digest sends the receiver couldn't
# resolve (followed by a full resend), invalidations = cache discards
# on the recovery path (server restore/reload, client reconnect)
_KC_HITS = _obs.REGISTRY.counter("ps.keycache.hits")
_KC_MISSES = _obs.REGISTRY.counter("ps.keycache.misses")
_KC_INVALIDATIONS = _obs.REGISTRY.counter("ps.keycache.invalidations")


def _env_flag(name: str) -> bool:
    v = os.environ.get(name)
    return v is not None and v.lower() not in ("", "0", "false", "off")

# init_spec claim TTL: how long a server waits for a claimant's
# init_arrays before handing the claim to the next poller. Clients wait
# 2x this by default so at least one full re-claim cycle fits inside the
# client deadline (a claimant dying right after claiming stays
# recoverable instead of racing the waiters' own timeout).
INIT_CLAIM_TTL = 300.0


def shard_range(n: int, rank: int, world: int) -> tuple[int, int]:
    """Row range of server `rank`: the same even split checkpoint part
    files use (utils/checkpoint.py), so parts reassemble by rank order."""
    return n * rank // world, n * (rank + 1) // world


def _idx_name(rows: int) -> str:
    """Wire name of the shared index array for the row-space group of
    tables with `rows` full rows (tables with equal row counts share one
    touched-index set per frame — z and n are always touched together)."""
    return f"idx:{rows}"


def ftrl_prox_rows(spec: dict, z: np.ndarray,
                   n: np.ndarray) -> np.ndarray:
    """The 'ftrl_prox' derived-table rule: w = prox(z, n) with the
    spec's lr/elastic-net constants. ONE definition shared by the
    server's dirty-row recompute (_recompute_derived) and the client's
    pull-side reconstruction (SyncedStore._fill_derived), so both ends
    of the wire derive identical values from identical sources."""
    eta = (spec["lr_beta"] + np.sqrt(n)) / spec["lr_eta"]
    mag = np.maximum(np.abs(z) - spec["lambda_l1"], 0.0)
    return (np.sign(-z) * mag / (eta + spec["lambda_l2"])
            ).astype(np.float32)


# ---------------------------------------------------------------- server
class _PSHandler(socketserver.StreamRequestHandler):
    def handle(self):
        # mirror the client side's TCP_NODELAY (net.connect_with_retry):
        # reply frames must not sit out a delayed-ACK window
        self.connection.setsockopt(socket.IPPROTO_TCP,
                                   socket.TCP_NODELAY, 1)
        node = self.server.node  # type: ignore
        with node._conns_lock:
            node._conns.add(self.connection)
        try:
            self._serve(node)
        except (OSError, ConnectionError):
            # a peer that vanished mid-frame (or a client that severed
            # this socket after a hedged pull won) is an ordinary
            # disconnect, not a handler error worth a traceback
            pass
        finally:
            with node._conns_lock:
                node._conns.discard(self.connection)

    def _serve(self, node):
        # frame compression (WH_NET_COMPRESS) is per-connection and
        # hello-negotiated: it turns on only after a hello carrying
        # net_compress=1 lands while this server has the knob set, and
        # the ack in the reply is what arms the client side — either end
        # left at the default keeps the whole connection uncompressed.
        # Wire-codec negotiation rides the same hello: `wire` asks "can
        # you decode QuantRows encodings / quantize pull replies" (acked
        # unconditionally — capability is the codebase, not a knob) and
        # `wire_comp` latches the negotiated frame-compression mode
        # ("zlib" / "bshuf") for every frame both ways; fc holds
        # False / True(zlib) / "zlib" / "bshuf" and feeds send_frame.
        fc = False
        while True:
            got = recv_frame(self.rfile)
            if got is None:
                return
            header, arrays, _ = got
            t_in = time.perf_counter()
            op = header.get("op")
            # deadline shed: a frame whose propagated budget expired in
            # transit is answered without dispatch — the sender's retry
            # window is already spent, and under overload every shed
            # admits work someone is still waiting for. Nothing was
            # applied, so seq fences are untouched.
            if _overload.should_shed(header):
                send_frame(self.wfile, dict(_overload.shed_reply(header),
                                            epoch=node.epoch))
                continue
            # admission gate (fixed WH_NET_MAX_INFLIGHT or WH_ADMIT_AIMD):
            # an over-admitted frame is bounced with a structured busy
            # reply BEFORE dispatch — nothing was applied, so the
            # client's resend of the same seq-stamped frame stays
            # exactly-once. Control ops (hello/init/...) always pass.
            if not node._gate.try_enter(op):
                send_frame(self.wfile,
                           dict(busy_reply(node._gate.busy_hint_ms()),
                                epoch=node.epoch))
                continue
            try:
                # adopt the trace context a sampled sync round carried
                # so this shard's spans stitch under the client's round
                # — and its remaining deadline, for downstream budgets
                with _trace.bind_wire(header), \
                        _overload.bind(_overload.header_deadline(header)):
                    resp_header, resp_arrays = node._dispatch(header,
                                                              arrays)
            finally:
                node._gate.leave(op, time.perf_counter() - t_in)
            if header.get("op") == "hello":
                if header.get("net_compress") and node.net_compress:
                    fc = True
                    resp_header["net_compress"] = 1
                if header.get("wire"):
                    resp_header["wire"] = 1
                wc = header.get("wire_comp")
                if wc in ("zlib", "bshuf"):
                    fc = wc
                    resp_header["wire_comp"] = wc
            # every reply carries the server's restore epoch so clients
            # detect a respawned (rolled-back) server on any op
            resp_header.setdefault("epoch", node.epoch)
            send_frame(self.wfile, resp_header, resp_arrays,
                       compress=bool(header.get("comp_reply")) or fc)
            if header.get("op") == "shutdown":
                self.server.node._shutdown.set()  # type: ignore
                return


class _PSServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ServerNode:
    """One `-s` server process: owns its bucket-range slice of every state
    table. Tables are created by the first `init` push (set-if-absent;
    workers init deterministically so any winner is equivalent); `push`
    adds deltas — sparse (rows at pushed indices) or dense; `pull`
    returns rows stamped after the caller's `since` clock; `save` writes
    this server's shard as a checkpoint part file.

    Versioning: every push advances `clock` and stamps the pushed rows
    in a per-row-space version array (`_ver[full_rows][row] = clock`).
    Tables with the same full row count form one group and share a
    version array — pushing z also makes the derived w's rows dirty,
    which is exactly right since w = prox(z, n).

    Fault tolerance: pushes carrying a (`sender`, `seq`) pair are
    seq-fenced — a seq at or below the sender's last applied one is
    acknowledged but NOT re-applied, so clients may blindly replay their
    push journal after a reconnect. `epoch` counts the process's
    incarnations (0 = first run, N = Nth respawn); it rides every reply
    so clients detect a restored-from-snapshot (rolled-back) server.
    `start_snapshots` takes periodic async shard snapshots off the
    request path; `restore_snapshot` rebuilds the shard from the newest
    one (see docs/distributed.md "Fault tolerance")."""

    def __init__(self, rank: int, world: int,
                 host: str = "127.0.0.1", port: int = 0, epoch: int = 0):
        self.rank = rank
        self.world = world
        self.epoch = int(epoch)
        self.tables: dict[str, np.ndarray] = {}
        self.full_rows: dict[str, int] = {}  # full-table row counts
        # derived-table specs ({name: {"kind": "ftrl_prox", ...}}): tables
        # that are NOT additive in worker pushes but are pure functions of
        # additive ones (FTRL's w = prox(z, n)); recomputed server-side
        # after merges so pulls/saves never expose an inconsistent pair
        self.derived: dict[str, dict] = {}
        self.clock = 0
        self._ver: dict[int, np.ndarray] = {}  # group -> int64[shard rows]
        # rows dirty since the last derived recompute, per group:
        # list of shard-local index arrays, or "all" after a dense push
        self._dirty: dict[int, object] = {}
        # push log for O(pushed) versioned pulls: per group a list of
        # (clock, idx) from sparse pushes, and the clock BEFORE the
        # oldest logged entry. A pull with since >= _log_start[g] takes
        # the union of logged rows newer than `since` instead of the
        # O(shard rows) version-array scan — at the 2^26 operating point
        # that scan walks 64M entries per group per sync and was the
        # dominant term of the measured PS-plane overhead (PERF.md r5).
        # Dense merges / checkpoint stamps reset the log (the scan
        # fallback stays correct); the log is capped so memory stays
        # O(recent pushes).
        self._pushlog: dict[int, list] = {}
        self._log_start: dict[int, int] = {}
        self._log_elems: dict[int, int] = {}
        # spec-init bookkeeping: non-zero-init tables awaiting their
        # arrays, per-table upload claims (name -> deadline), the full
        # table shapes for the divergent-conf cross-check, and the
        # post-checkpoint-load stamping state
        self._pending: set[str] = set()
        self._claims: dict[str, float] = {}
        self._full_shapes: Optional[dict[str, list]] = None
        # per-table zero-init flags, known only when THIS server created
        # the tables from an init_spec (checkpoint loads leave it None —
        # the loaded arrays are ground truth and flags are moot)
        self._zero_flags: Optional[dict[str, bool]] = None
        self._loaded = False
        self._stamped_all: set[int] = set()
        # seq fence: last applied push sequence number per sender, the
        # dedup table that makes client-side replay idempotent
        self._last_seq: dict[str, int] = {}
        # KEY_CACHING filter state (client-driven, see PSClient):
        # per-sender LRU of key lists received in full (digest ->
        # shard-local idx) so repeated pushes can ship digest-only, and
        # per-sender LRU of digests the sender itself is known to hold
        # (adopted from its full pushes / our full pull replies) so pull
        # replies can go digest-only too. The known-cap is smaller than
        # the client's cache, so an omitted reply is nearly always
        # reconstructible; the client's full-re-pull fallback keeps a
        # stale assumption harmless.
        self._kc_idx: dict[str, collections.OrderedDict] = {}
        self._kc_known: dict[str, collections.OrderedDict] = {}
        # pull-side error feedback (wire codec v2): per-sender,
        # per-table residual accumulators for quantized pull replies;
        # invalidated with the key caches on restore (a rolled-back
        # shard's residuals describe values that no longer exist)
        self._efq: dict[str, dict[str, EFQuant]] = {}
        # async snapshot state: base path, cadence, clock of the last
        # written snapshot (skip when nothing changed), writer thread
        self._snap_base: Optional[str] = None
        self._snap_every = 0.0
        self._snap_clock = -1
        self._snap_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        # live handler connections, severed on stop() so a stopped node
        # looks like a dead process to its clients (not a half-open
        # socket that strands them in recv)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # hello-negotiated zlib frame compression (WH_NET_COMPRESS):
        # meant for the hot plane's cold-tier traffic — big, rare flush
        # frames — where the codec cost amortizes; default off
        self.net_compress = _env_flag("WH_NET_COMPRESS")
        # max-in-flight admission gate (WH_NET_MAX_INFLIGHT; default
        # unlimited = a single None check per frame)
        self._gate = _overload.AdmissionController()
        self._srv = _PSServer((host, port), _PSHandler)
        self._srv.node = self  # type: ignore
        self.num_push = 0
        self.num_pull = 0

    @property
    def uri(self) -> str:
        h, p = self._srv.server_address[:2]
        return f"{h}:{p}"

    def serve(self) -> None:
        t = threading.Thread(target=self._srv.serve_forever, daemon=True)
        t.start()

    def wait_shutdown(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown.wait(timeout)

    def stop(self) -> None:
        self._shutdown.set()
        self._srv.shutdown()
        self._srv.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _shard_rows(self, group: int) -> int:
        lo, hi = shard_range(group, self.rank, self.world)
        return hi - lo

    def _create_group_meta(self) -> None:  # wormlint: guarded-by(self._lock)
        """Version/dirty arrays for every row-space group (caller holds
        the lock, full_rows already set). uint32 clock stamps: 4
        bytes/row; push asserts the clock never reaches the wrap point
        so staleness can't silently freeze (ADVICE r3)."""
        for g in {r for r in self.full_rows.values()}:
            self._ver[g] = np.zeros(self._shard_rows(g), np.uint32)
            self._dirty[g] = []
            self._reset_pushlog(g)

    # -- ops ----------------------------------------------------------------
    def _dispatch(self, header: dict,  # wormlint: thread-entry
                  arrays: dict) -> tuple[dict, dict]:
        op = header.get("op")
        t0 = time.perf_counter()
        try:
            with _trace.request_span(f"ps.shard.{op}", cat="ps",
                                     rank=self.rank):
                return self._dispatch_op(op, header, arrays)
        finally:
            # per-op service latency (what the server spent, not what the
            # client waited — that's ps.client.rpc_s)
            _obs.REGISTRY.histogram(f"ps.server.op.{op}_s").observe(
                time.perf_counter() - t0)

    def _dispatch_op(self, op, header: dict,
                     arrays: dict) -> tuple[dict, dict]:
        if faults.ACTIVE is not None:
            faults.ACTIVE.server_op(op)
        if op == "hello":
            # reconnect handshake: tells the client this server's epoch
            # (rollback detection) and the last push seq it applied for
            # the asking sender (journal replay starts after it)
            sender = header.get("sender")
            with self._lock:
                return ({"ok": True, "clock": self.clock,
                         "last_seq": self._last_seq.get(sender, 0)}, {})
        if op == "init":
            with self._lock:
                known = bool(self.tables)
                if not known:
                    for k, v in arrays.items():
                        self.tables[k] = v.astype(np.float32)
                    self.full_rows = {
                        k: int(n) for k, n in header["full_rows"].items()}
                    self.derived = header.get("derived") or {}
                    self._create_group_meta()
                return ({"ok": True, "known": known, "clock": self.clock},
                        {})
        if op == "init_spec":
            # O(spec) table creation: the header carries {shape, zero}
            # per table; zero-init tables (the whole FTRL state) are
            # created server-side with no payload at all. Non-zero-init
            # tables are CLAIMED by the first asker (claims expire so a
            # dead claimant can't wedge startup) and only the claimant
            # ships them via init_arrays — so even N concurrently
            # starting workers put exactly one copy on the wire, not N.
            # A dense init offer at the 2^26 operating point is ~768 MB
            # per worker, which this path never sends.
            with self._lock:
                if not self.tables and not self._pending:
                    self.full_rows = {
                        k: int(s["shape"][0])
                        for k, s in header["specs"].items()}
                    self.derived = header.get("derived") or {}
                    self._full_shapes = {
                        k: [int(d) for d in s["shape"]]
                        for k, s in header["specs"].items()}
                    self._zero_flags = {
                        k: bool(s.get("zero", False))
                        for k, s in header["specs"].items()}
                    for k, s in header["specs"].items():
                        lo, hi = shard_range(int(s["shape"][0]), self.rank,
                                             self.world)
                        if s.get("zero", False):
                            self.tables[k] = np.zeros(
                                (hi - lo, *s["shape"][1:]), np.float32)
                        else:
                            self._pending.add(k)
                    self._create_group_meta()
                else:
                    # cross-check FULL shapes (rows AND tails — e.g. two
                    # difacto confs disagreeing on dim) AND the zero-init
                    # flag (same shapes but disagreeing on which tables
                    # are zero-init means an incoherent base mirror): a
                    # divergent worker must fail here, not later with
                    # misrouted or mis-shaped pushes
                    want = {k: [int(d) for d in s["shape"]]
                            for k, s in header["specs"].items()}
                    have = self._full_shapes
                    if have is not None and want != have:
                        return {"error":
                                f"init spec mismatch: offered {want} vs "
                                f"created {have}"}, {}
                    w_zero = {k: bool(s.get("zero", False))
                              for k, s in header["specs"].items()}
                    if (self._zero_flags is not None
                            and w_zero != self._zero_flags):
                        return {"error":
                                f"init spec mismatch: zero flags "
                                f"{w_zero} vs created "
                                f"{self._zero_flags}"}, {}
                    w_drv = header.get("derived") or {}
                    if self._zero_flags is not None:
                        # tables were created from a worker's spec: the
                        # creator's derived set is authoritative, so the
                        # comparison is exact — a worker adding or
                        # omitting derived tables entirely is just as
                        # divergent as one redefining them
                        if w_drv != self.derived:
                            return {"error":
                                    f"init spec mismatch: derived "
                                    f"tables {w_drv} vs created "
                                    f"{self.derived}"}, {}
                    elif self.derived and w_drv and w_drv != self.derived:
                        # checkpoint-loaded: derived may legitimately be
                        # absent on one side (loads don't carry specs),
                        # so only a conflicting non-empty pair fails
                        return {"error":
                                f"init spec mismatch: derived tables "
                                f"{w_drv} vs created {self.derived}"}, {}
                    if not self.derived:
                        # checkpoint loads don't carry derived-table
                        # specs; adopt them from the first worker
                        self.derived = header.get("derived") or {}
                    self._stamp_nonspec_groups(header["specs"])
                now = time.monotonic()
                # claim TTL must comfortably cover a slow upload of a
                # multi-hundred-MB slice; expiry only matters when the
                # claimant DIED, so generous is safe (a live claimant's
                # init_arrays clears the claim)
                need = sorted(k for k in self._pending
                              if self._claims.get(k, 0.0) <= now)
                for k in need:
                    self._claims[k] = now + INIT_CLAIM_TTL
                return ({"ok": True, "known": not self._pending,
                         "need": need, "clock": self.clock}, {})
        if op == "init_arrays":
            # second phase of init_spec: slices for the `need` tables;
            # first worker's arrays win, duplicates are dropped
            with self._lock:
                for k, v in arrays.items():
                    if k in self._pending:
                        self.tables[k] = v.astype(np.float32)
                        self._pending.discard(k)
                        self._claims.pop(k, None)
                return {"ok": True, "known": not self._pending}, {}
        if op == "pull":
            since = header.get("since")
            if since is None:
                with self._lock:
                    self.num_pull += 1
                    _NUM_PULL.inc()
                    self._recompute_derived()
                    out = {k: v.copy() for k, v in self.tables.items()}
                    return {"ok": True, "clock": self.clock}, out
            with self._lock:
                self.num_pull += 1
                _NUM_PULL.inc()
                out = {}
                if since >= self.clock:
                    # nothing pushed since the caller last looked: skip
                    # both the derived recompute and the O(shard rows)
                    # version scans (ADVICE r3 — at 2^26 buckets each
                    # scan walks a 64M-element array); reply shape
                    # matches the scan path (empty idx + empty rows),
                    # INCLUDING the derived-table skip — a quiet shard
                    # that ships an empty `w` part while a dirty peer
                    # honors the skip would leave the client's merged
                    # `w` shorter than its merged index
                    skip = {k for k in (header.get("skip") or ())
                            if k in self.derived}
                    for g in self._ver:
                        out[_idx_name(g)] = np.empty(0, np.int64)
                    for k in self.tables:
                        if k in skip:
                            continue
                        out[k] = self.tables[k][:0]
                    return {"ok": True, "clock": self.clock}, out
                self._recompute_derived()
                sender = header.get("sender")
                use_kc = bool(header.get("kc")) and sender is not None
                wire = header.get("wire")
                if wire not in ("bf16", "int8", "int4"):
                    wire = None
                # derived-table wire skip: a client that can recompute a
                # derived table from its pulled sources asks us to omit
                # it. Honored ONLY for tables in self.derived — additive
                # state can never be silently dropped by a bad request.
                skip = {k for k in (header.get("skip") or ())
                        if k in self.derived}
                kdig_hit: dict[str, str] = {}
                kdig_full: dict[str, str] = {}
                for g, ver in self._ver.items():
                    if since >= self._log_start.get(g, self.clock):
                        parts = [i for c, i in self._pushlog[g]
                                 if c > since]
                        idx = (np.unique(np.concatenate(parts))
                               if parts else np.empty(0, np.int64))
                    else:
                        idx = np.flatnonzero(ver > since).astype(np.int64)
                    omit = False
                    if use_kc and idx.size:
                        dig, held = self._kc_pull_digest(sender, idx)
                        if held:
                            kdig_hit[str(g)] = dig
                            omit = True
                        else:
                            kdig_full[str(g)] = dig
                    if not omit:
                        out[_idx_name(g)] = idx
                    for k, rows in self.full_rows.items():
                        if rows == g:
                            if k in skip:
                                continue
                            vals = self.tables[k][idx]
                            if wire is not None and idx.size:
                                vals = self._wire_pull(sender, k, idx,
                                                       vals, wire,
                                                       header)
                            out[k] = vals
                resp = {"ok": True, "clock": self.clock}
                if kdig_hit:
                    resp["kdig"] = kdig_hit
                if kdig_full:
                    resp["kfull"] = kdig_full
                return resp, out
        if op == "push":
            with self._lock:
                # seq fence BEFORE the clock advance: a replayed push
                # (client journal re-sent after a reconnect) must be
                # acknowledged without re-applying the delta OR bumping
                # the clock — at-most-once apply is what makes the
                # client's blind replay safe
                sender, seq = header.get("sender"), header.get("seq")
                if sender is not None and seq is not None:
                    if seq <= self._last_seq.get(sender, 0):
                        _DEDUP_HITS.inc()
                        return ({"ok": True, "clock": self.clock,
                                 "dup": True}, {})
                idx_of = {g: arrays[_idx_name(g)]
                          for g in self._ver if _idx_name(g) in arrays}
                # resolve key-list digests BEFORE the fence advances: a
                # miss reply must leave fence and clock untouched so the
                # client's full resend (a fresh seq) is a clean first
                # send, not a dup
                kdig = header.get("kdig") or {}
                if kdig and sender is not None:
                    need = self._kc_resolve(sender, kdig, idx_of)
                    if need:
                        _KC_MISSES.inc(len(need))
                        return ({"ok": True, "clock": self.clock,
                                 "need_keys": need}, {})
                if sender is not None and seq is not None:
                    self._last_seq[sender] = int(seq)
                self.num_push += 1
                _NUM_PUSH.inc()
                self.clock += 1
                # uint32 stamp wrap would silently freeze rows as
                # never-dirty; unreachable in practice, but fail loudly
                # rather than go stale (ADVICE r3). An error REPLY (not
                # an assert): asserts vanish under python -O and an
                # exception here would just kill the connection thread
                # without ever telling the worker why.
                if self.clock >= 2**32 - 1:
                    return {"error":
                            "version clock exhausted (2^32 pushes)"}, {}
                dense_groups = set()
                for k, d in arrays.items():
                    if k.startswith("idx:"):
                        continue
                    if k not in self.tables:
                        return {"error": f"push to unknown table {k}"}, {}
                    if k in self.derived:
                        # non-additive derived tables ignore pushed deltas;
                        # they are recomputed from their additive sources
                        continue
                    g = self.full_rows[k]
                    idx = idx_of.get(g)
                    if idx is None:
                        self.tables[k] += d
                        dense_groups.add(g)
                    else:
                        # worker-side indices are unique (np.unique
                        # output), so fancy += is a correct scatter-add
                        self.tables[k][idx] += d
                for g, idx in idx_of.items():
                    self._ver[g][idx] = self.clock
                    if self._dirty.get(g) != "all":
                        self._dirty.setdefault(g, []).append(idx)
                    self._log_push(g, idx)
                # any dense-merged group is wholly dirty — including in a
                # MIXED frame where other groups carried idx arrays;
                # stamping per merged group (not only when NO idx exists)
                # keeps versioned pulls from missing dense rows
                # (ADVICE r3)
                for g in dense_groups:
                    self._ver[g][:] = self.clock
                    self._dirty[g] = "all"
                    self._reset_pushlog(g)
                return {"ok": True, "clock": self.clock}, {}
        if op == "save":
            path = self._save(header["base"], header.get("iter"))
            return {"ok": True, "path": path}, {}
        if op == "load":
            # IterScheduler::LoadModel parity (iter_solver.h:40-47): the
            # scheduler commands the server group to load a checkpoint;
            # each server takes its bucket-range slice straight from the
            # filesystem — the model never crosses the worker wire.
            with self._lock:
                if self.tables:
                    return {"error": "load into a non-empty server "
                                     "(command load before workers init)"
                            }, {}
                try:
                    self._load(header["base"], header.get("iter"))
                except Exception as e:
                    # an error REPLY, not an escaped exception: a typo'd
                    # model_in must surface as "no such checkpoint" at
                    # the scheduler, not as a dead-connection mystery at
                    # the workers
                    self.tables.clear()
                    return {"error": f"checkpoint load failed: {e}"}, {}
                return {"ok": True, "clock": self.clock}, {}
        if op == "stats":
            with self._lock:
                return {"ok": True, "num_push": self.num_push,
                        "num_pull": self.num_pull, "clock": self.clock,
                        "tables": {k: list(v.shape)
                                   for k, v in self.tables.items()}}, {}
        if op == "shutdown":
            return {"ok": True}, {}
        return {"error": f"unknown op {op!r}"}, {}

    # caps: logged row-indices AND entry count per group; beyond either
    # the oldest entries fall off and pulls older than the floor use the
    # scan (the entry cap stops tiny-push streams from growing the log
    # into an O(total pushes) python walk per pull)
    _LOG_ELEM_CAP = 1 << 23
    _LOG_ENTRY_CAP = 4096

    def _log_push(self, g: int, idx) -> None:  # wormlint: guarded-by(self._lock)
        """Record a sparse push for O(pushed) pulls (lock held)."""
        arr = np.asarray(idx, np.int64)
        if arr.size == 0:
            return  # nothing dirtied in this shard's range
        self._pushlog[g].append((self.clock, arr))
        self._log_elems[g] += arr.size
        while ((self._log_elems[g] > self._LOG_ELEM_CAP
                or len(self._pushlog[g]) > self._LOG_ENTRY_CAP)
               and len(self._pushlog[g]) > 1):
            c, old = self._pushlog[g].pop(0)
            self._log_elems[g] -= old.size
            self._log_start[g] = c

    def _reset_pushlog(self, g: int) -> None:  # wormlint: guarded-by(self._lock)
        """Version stamps changed outside push (load/spec stamp): the
        log no longer covers history before this clock (lock held)."""
        self._pushlog[g] = []
        self._log_start[g] = self.clock
        self._log_elems[g] = 0

    # key-cache caps: key lists cached per sender (push side) and
    # digests assumed still client-held (pull side). The known-cap is
    # deliberately below the client's own LRU cap so digest-only pull
    # replies are nearly always reconstructible client-side; the
    # client's full-re-pull fallback covers the rest.
    _KC_CAP = 32
    _KC_KNOWN_CAP = 8

    def _kc_resolve(self, sender: str, kdig: dict, idx_of: dict) -> list:  # wormlint: guarded-by(self._lock)
        """Adopt/resolve a push's key-list digests (lock held): a group
        whose idx array rode the frame is cached under its digest; a
        digest-only group is resolved from the cache into `idx_of`.
        Returns the groups whose digest is unknown (cache miss — the
        caller replies need_keys without applying anything)."""
        cache = self._kc_idx.setdefault(sender, collections.OrderedDict())
        known = self._kc_known.setdefault(sender, collections.OrderedDict())
        need = []
        for gs, dig in kdig.items():
            g = int(gs)
            if g in idx_of:
                # full send: adopt the key list, and remember the sender
                # holds it (it hashed its own idx) so pull replies with
                # the same key set can go digest-only
                cache[dig] = np.ascontiguousarray(idx_of[g], np.int64)
                cache.move_to_end(dig)
                known[dig] = True
                known.move_to_end(dig)
            else:
                hit = cache.get(dig)
                if hit is None:
                    need.append(gs)
                else:
                    cache.move_to_end(dig)
                    idx_of[g] = hit
                    _KC_HITS.inc()
        while len(cache) > self._KC_CAP:
            cache.popitem(last=False)
        while len(known) > self._KC_KNOWN_CAP:
            known.popitem(last=False)
        return need

    def _kc_pull_digest(self, sender: str,  # wormlint: guarded-by(self._lock)
                        idx: np.ndarray) -> tuple[str, bool]:
        """Pull-reply half of the key cache (lock held): returns
        (digest, held) — `held` means the sender provably has this key
        list, so the reply may omit the idx array; otherwise the reply
        ships idx + digest so the client caches it for next time."""
        dig = key_digest(idx)
        known = self._kc_known.setdefault(sender, collections.OrderedDict())
        if dig in known:
            known.move_to_end(dig)
            _KC_HITS.inc()
            return dig, True
        known[dig] = True
        while len(known) > self._KC_KNOWN_CAP:
            known.popitem(last=False)
        return dig, False

    def _wire_pull(self, sender, k: str, idx: np.ndarray,  # wormlint: guarded-by(self._lock)
                   vals: np.ndarray, wire: str, header: dict) -> QuantRows:
        """Quantize a versioned-pull reply's rows (wire codec v2, lock
        held). With `wire_ef` and a named sender the per-(sender, table)
        EFQuant folds prior quantization error of these rows back in;
        pulls are absolute-value refreshes, so a reply lost on the wire
        is corrected by the sender's next pull, never double-counted."""
        if header.get("wire_ef") and sender is not None:
            efq = self._efq.setdefault(sender, {}).setdefault(
                k, EFQuant(wire))
            return efq.apply(idx, vals)
        return quantize_rows(vals, wire)

    def _kc_invalidate(self) -> None:  # wormlint: guarded-by(self._lock)
        """Recovery-path cache discard (snapshot restore / checkpoint
        load): a rolled-back server must not resolve pre-crash digests
        (lock held)."""
        if self._kc_idx or self._kc_known:
            _KC_INVALIDATIONS.inc()
        self._kc_idx = {}
        self._kc_known = {}
        # pull-EF residuals roll back with the tables they corrected
        self._efq = {}

    def _recompute_derived(self) -> None:  # wormlint: guarded-by(self._lock)
        """Recompute derived tables from their additive sources over the
        rows dirtied since the last recompute (caller holds the lock).
        FTRL's w is soft-threshold-nonlinear in (z, n), so additively
        merged worker deltas cannot represent it: a key whose merged z
        crosses the L1 threshold must re-solve the prox even though
        every worker pushed delta-w = 0. Restricting the prox to dirty
        rows keeps server work O(touched keys), not O(table)."""
        for k, spec in self.derived.items():
            g = self.full_rows[k]
            dirty = self._dirty.get(g)
            if dirty == []:
                continue
            if spec["kind"] != "ftrl_prox":
                raise ValueError(f"unknown derived kind {spec['kind']!r}")
            if dirty == "all":
                u = slice(None)
            else:
                u = np.unique(np.concatenate(dirty))
                if u.size == 0:
                    continue
            self.tables[k][u] = ftrl_prox_rows(
                spec, self.tables["z"][u], self.tables["n"][u])
        for g in self._dirty:
            self._dirty[g] = []

    def _load(self, base: str, it: Optional[int]) -> None:  # wormlint: guarded-by(self._lock)
        """Create this shard's tables from a checkpoint (caller holds the
        lock). When the checkpoint was written by a same-world server
        group, this server reads ONLY its own `_part-<rank>` file (the
        __full_rows__ tag each part carries says the full table sizes);
        on any shard-count mismatch it falls back to concatenating all
        parts and slicing its range. Every loaded row that differs from
        the zero init is version-stamped, so a worker that initializes to
        zeros and pulls since=0 receives exactly the model's nonzero
        rows — O(model nnz) wire, not O(table). Rows of NON-zero-init
        tables (e.g. difacto's seeded V) can differ from the load even
        where the load is zero; init_spec stamps those groups fully when
        a worker's spec names them (see _stamp_nonspec_groups)."""
        import glob
        from wormhole_tpu.utils.checkpoint import (load_parts, part_name,
                                                   save_prefix)

        own = part_name(base, it if (it is not None and it >= 0) else None,
                        self.rank) + ".npz"
        prefix = save_prefix(base, it if (it is not None and it >= 0)
                             else None)
        npeers = len(glob.glob(prefix + "_part-*.npz"))
        shard_arrays = None
        if npeers == self.world and os.path.exists(own):
            got = dict(np.load(own))
            meta = got.pop("__full_rows__", None)
            if meta is not None:
                self.full_rows = {
                    k: int(n) for k, n in
                    json.loads(bytes(meta.tobytes()).decode()).items()}
                shard_arrays = got
        if shard_arrays is None:
            arrays = load_parts(base, it)
            self.full_rows = {k: int(v.shape[0])
                              for k, v in arrays.items()}
            shard_arrays = {}
            for k, v in arrays.items():
                lo, hi = shard_range(v.shape[0], self.rank, self.world)
                shard_arrays[k] = np.ascontiguousarray(v[lo:hi],
                                                       np.float32)
        self._full_shapes = {
            k: [self.full_rows[k], *v.shape[1:]]
            for k, v in shard_arrays.items()}
        self._loaded = True
        self._kc_invalidate()
        # a pre-load init_spec may have left pending/claim state; the
        # checkpoint supersedes it (a late init_arrays must not
        # overwrite loaded tables)
        self._pending = set()
        self._claims = {}
        self._zero_flags = None
        for k, v in shard_arrays.items():
            # np.array (not ascontiguousarray): decoded wire arrays are
            # read-only zero-copy views and tables get merged in place
            self.tables[k] = np.array(v, np.float32)
        self._create_group_meta()
        self.clock = 1
        for g, ver in self._ver.items():
            nz = None
            for k, rows in self.full_rows.items():
                if rows != g:
                    continue
                t_nz = self.tables[k] != 0
                if t_nz.ndim > 1:
                    t_nz = t_nz.any(axis=tuple(range(1, t_nz.ndim)))
                nz = t_nz if nz is None else (nz | t_nz)
            if nz is not None:
                ver[nz] = self.clock
            # stamps bypassed the push log: pulls older than this clock
            # must take the scan path
            self._reset_pushlog(g)

    def _stamp_nonspec_groups(self, specs: dict) -> None:  # wormlint: guarded-by(self._lock)
        """After a checkpoint load, groups holding non-zero-init tables
        must be stamped wholly dirty the first time a worker's init spec
        names them: the worker's seeded init differs from the loaded
        values even at loaded-zero rows, so only a full-group pull makes
        its base mirror coherent (caller holds the lock)."""
        if not self._loaded:
            return
        for k, s in specs.items():
            if s.get("zero", True) or k in self.derived:
                continue
            g = self.full_rows.get(k)
            if g is None or g in self._stamped_all:
                continue
            self._ver[g][:] = self.clock
            self._reset_pushlog(g)
            self._stamped_all.add(g)

    def _save(self, base: str, it: Optional[int]) -> str:
        import glob
        import re

        from wormhole_tpu.utils.checkpoint import (atomic_savez, part_name,
                                                   save_prefix)

        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        with self._lock:
            self._recompute_derived()
            tables = {k: v.copy() for k, v in self.tables.items()}
        prefix = save_prefix(base, it)
        if self.rank == 0:
            # remove stale files from a previous save with a different
            # shard count (the invariant utils/checkpoint.save_model
            # keeps): only rank 0 cleans, and only files NO current
            # server writes, so concurrent part writes are never raced
            if self.world > 1 and os.path.exists(prefix + ".npz"):
                os.remove(prefix + ".npz")
            for old in glob.glob(prefix + "_part-*.npz"):
                r = int(re.search(r"_part-(\d+)\.npz$", old).group(1))
                if r >= self.world or self.world <= 1:
                    os.remove(old)
        if self.world <= 1:
            path = prefix + ".npz"
        else:
            path = part_name(base, it, self.rank) + ".npz"
        # __full_rows__ tag: lets a same-world server reload ONLY its own
        # part (ServerNode._load fast path); load_parts skips "__" keys
        tables["__full_rows__"] = np.frombuffer(
            json.dumps(self.full_rows).encode(), np.uint8).copy()
        atomic_savez(path, compressed=True, **tables)
        return path

    # -- hot-restore snapshots ----------------------------------------------
    def start_snapshots(self, base: str, every_sec: float) -> None:
        """Write `snapshot()` to `<base>_part-<rank>.npz` every
        `every_sec` seconds on a daemon thread — off the request path, so
        the only request-visible cost is the brief copy under the lock."""
        self._snap_base = base
        self._snap_every = float(every_sec)
        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)

        def loop():
            while not self._shutdown.wait(self._snap_every):
                try:
                    self.snapshot()
                except Exception as e:  # keep snapshotting best-effort
                    print(f"[ps server {self.rank}] snapshot failed: {e}",
                          flush=True)

        self._snap_thread = threading.Thread(target=loop, daemon=True)
        self._snap_thread.start()

    def snapshot(self) -> Optional[str]:
        """One epoch-stamped shard snapshot (atomic temp+rename write).
        Unlike `_save` checkpoints this also captures the clock, the seq
        fence, and the table metadata a respawned server needs to resume
        MID-training without a worker re-init. Skips when no push landed
        since the last snapshot or tables aren't fully created yet."""
        t0 = time.perf_counter()
        path = self._snapshot_impl()
        if path is not None:
            dur = time.perf_counter() - t0
            _SNAPSHOT_S.observe(dur)
            _SNAPSHOTS.inc()
            if _trace.ACTIVE is not None:
                _trace.ACTIVE.emit_span(
                    "ps.snapshot", "ps", time.monotonic() - dur, dur,
                    {"rank": self.rank, "clock": self._snap_clock})
        return path

    def _snapshot_impl(self) -> Optional[str]:
        from wormhole_tpu.utils import manifest as _manifest
        from wormhole_tpu.utils.checkpoint import atomic_savez, part_name

        with self._lock:
            if (not self.tables or self._pending
                    or self.clock == self._snap_clock):
                return None
            self._recompute_derived()
            arrays = {k: v.copy() for k, v in self.tables.items()}
            meta = {
                "clock": self.clock,
                "epoch": self.epoch,
                "world": self.world,
                "full_rows": self.full_rows,
                "derived": self.derived,
                "last_seq": self._last_seq,
                "full_shapes": self._full_shapes,
                "zero_flags": self._zero_flags,
            }
            clock = self.clock
            full_rows = dict(self.full_rows)
        arrays["__snap__"] = np.frombuffer(
            json.dumps(meta).encode(), np.uint8).copy()
        base = self._snap_base or "ps_snap"
        path = part_name(base, None, self.rank) + ".npz"
        atomic_savez(path, compressed=True, **arrays)
        # publish the finished part in the snapshot-set manifest so
        # readers (restore on a respawn, the serving watcher) discover a
        # digest-verified consistent set instead of globbing — closing
        # the torn-read window where a reader pairs this rank's fresh
        # part with a half-replaced peer's
        _manifest.update_manifest(base, self.rank, self.world, path,
                                  clock, self.epoch, full_rows)
        # only advance the skip-fence after the write landed; re-take the
        # lock because restore_snapshot writes it from the serving threads
        with self._lock:
            self._snap_clock = clock
        return path

    def restore_snapshot(self, base: str) -> bool:
        """Rebuild this shard from its snapshot file; returns False when
        none exists (a server dying before its first snapshot restarts
        empty and waits for worker init like a fresh process). The
        restored clock is re-stamped onto every nonzero row so a worker
        pulling with a pre-crash `since` below it receives every row the
        snapshot knows (a superset of what it missed — over-delivery is
        safe, under-delivery would desync the base mirror)."""
        from wormhole_tpu.utils import manifest as _manifest
        from wormhole_tpu.utils.checkpoint import part_name

        self._snap_base = base
        path = part_name(base, None, self.rank) + ".npz"
        got = None
        # manifest-first: read the digest-verified part the manifest
        # names (a peer may be mid-replace — retry a couple of times on
        # a torn read, each time against a fresh manifest)
        man = _manifest.read_manifest(base)
        if man is not None and str(self.rank) in man.get("parts", {}):
            for _ in range(3):
                try:
                    got = _manifest.read_part(base, man, self.rank)
                    break
                except _manifest.TornSnapshot as e:
                    print(f"[ps server {self.rank}] torn snapshot read "
                          f"({e}); retrying", flush=True)
                    time.sleep(0.05)
                    man = _manifest.read_manifest(base) or man
        if got is None:
            # pre-manifest snapshot dirs (or a manifest that never saw
            # this rank): fall back to the direct part path
            if not os.path.exists(path):
                return False
            got = dict(np.load(path))
        meta = json.loads(bytes(got.pop("__snap__").tobytes()).decode())
        with self._lock:
            self.tables = {k: np.ascontiguousarray(v, np.float32)
                           for k, v in got.items()}
            self.full_rows = {k: int(n)
                              for k, n in meta["full_rows"].items()}
            self.derived = meta["derived"] or {}
            self._last_seq = {k: int(v)
                              for k, v in (meta["last_seq"] or {}).items()}
            self._full_shapes = meta["full_shapes"]
            self._zero_flags = meta["zero_flags"]
            self._pending = set()
            self._claims = {}
            self._kc_invalidate()
            self._create_group_meta()
            self.clock = int(meta["clock"])
            self._snap_clock = self.clock
            for g, ver in self._ver.items():
                nz = None
                for k, rows in self.full_rows.items():
                    if rows != g:
                        continue
                    t_nz = self.tables[k] != 0
                    if t_nz.ndim > 1:
                        t_nz = t_nz.any(axis=tuple(range(1, t_nz.ndim)))
                    nz = t_nz if nz is None else (nz | t_nz)
                if nz is not None:
                    ver[nz] = self.clock
                self._reset_pushlog(g)
            self._loaded = True
            self._stamped_all = set()
        _RESTORES.inc()
        _RESTORE_EPOCH.set(self.epoch)
        _trace.event("ps.restore", cat="recovery", rank=self.rank,
                     clock=self.clock, epoch=self.epoch)
        print(f"[ps server {self.rank}] restored snapshot {path} "
              f"(clock {self.clock}, epoch {self.epoch})", flush=True)
        return True


# ---------------------------------------------------------------- client
class PSClient:
    """Worker-side stub over all servers: splits each table by the
    servers' row ranges, keeps one persistent connection per server.
    Tracks wire bytes (bytes_push / bytes_pull, both directions) so the
    sparse-wire claim — bytes/sync proportional to touched keys — is a
    measured quantity, not an assumption.

    Recovery (all opt-in; the defaults reproduce the original fail-fast
    behavior exactly): with `retry_deadline > 0` a failed RPC is retried
    with backoff against a (possibly respawned) server instead of
    raising. `sender` names this worker for the servers' seq fence —
    every push is stamped with a per-server sequence number and journaled
    (last `journal_len` pushes per server), so on reconnect the client
    replays the journal entries the server's `hello` reports as
    unapplied; the fence makes over-replay harmless. `resolver`, when
    given, re-resolves the server URI list on each reconnect attempt (a
    respawned server binds a NEW port and re-announces it through the
    scheduler). A reply whose `epoch` exceeds the last seen one marks the
    server rolled-back; the next pull_sparse turns into a since=0 re-pull
    so the base mirror re-adopts the restored state."""

    # client-side key-list LRU cap: above the server's _KC_KNOWN_CAP so
    # a digest-only pull reply is nearly always reconstructible here
    _KC_CLIENT_CAP = 64

    def __init__(self, uris: list[str], connect_deadline: float = 30.0,
                 sender: Optional[str] = None, retry_deadline: float = 0.0,
                 resolver: Optional[Callable[[], Optional[list[str]]]] = None,
                 journal_len: int = 64, keycache: Optional[bool] = None):
        self.uris = list(uris)
        self.world = len(uris)
        self._socks: list[Optional[socket.socket]] = [None] * self.world
        self._files = [None] * self.world
        self.connect_deadline = connect_deadline
        self.full_rows: dict[str, int] = {}
        self.bytes_push = 0
        self.bytes_pull = 0
        self.bytes_init = 0
        self.sender = sender
        self.retry_deadline = float(retry_deadline)
        self.resolver = resolver
        # per-server push seq numbers + journal of recent pushes
        # (seq, header, arrays, fixed_bytes, compress); journaled only
        # when retry is enabled so the default path pays no copies
        self._seq = [0] * self.world
        self._journal: list = [collections.deque(maxlen=max(journal_len, 1))
                               for _ in range(self.world)]
        self._epochs: list[Optional[int]] = [None] * self.world
        self._rolled_back = [False] * self.world
        self.num_retries = 0
        # KEY_CACHING filter, client half (default from WH_KEYCACHE):
        # per-server LRU of digest -> shard-local idx (content-addressed;
        # fed by our own full pushes AND full pull replies) plus the
        # digests each server has ack'd receiving, so repeat pushes ship
        # digest + values only
        self.keycache = (_env_flag("WH_KEYCACHE") if keycache is None
                         else bool(keycache))
        # hello-negotiated frame compression (WH_NET_COMPRESS): when the
        # knob is set here, every fresh connection's hello offers it and
        # _fc[r] latches the server's ack — from then on every frame to
        # that server ships zlib'd (replies ride the server's fc flag).
        # _fc holds False / True(zlib) / "zlib" / "bshuf" — whatever
        # mode the server latched feeds send_frame's `compress` arg.
        self.net_compress = _env_flag("WH_NET_COMPRESS")
        self._fc = [False] * self.world
        # wire codec v2 (WH_WIRE / WH_WIRE_EF / WH_WIRE_COMP): the value
        # encoding pushes carry and pulls request, whether error
        # feedback is on (default yes — low-bit encodings without it
        # bias convergence), and the negotiated frame compression mode.
        # _wc[r] latches the server's `wire` capability ack: only an
        # acked connection receives QuantRows encodings or quantized
        # pull replies; an un-acked (older) peer keeps the legacy
        # scalar fixed_bytes forms (see SyncedStore._quantize_deltas).
        self.wire_enc = (os.environ.get("WH_WIRE") or "raw").strip().lower()
        if self.wire_enc not in WIRE_ENCODINGS:
            raise ValueError(f"WH_WIRE={self.wire_enc!r}: expected one "
                             f"of {WIRE_ENCODINGS}")
        ef = os.environ.get("WH_WIRE_EF")
        self.wire_ef = (True if ef is None
                        else ef.lower() not in ("", "0", "false", "off"))
        self.wire_comp = (os.environ.get("WH_WIRE_COMP") or "").strip().lower()
        if self.wire_comp not in WIRE_COMP_MODES:
            raise ValueError(f"WH_WIRE_COMP={self.wire_comp!r}: expected "
                             f"one of {WIRE_COMP_MODES}")
        self._wc = [False] * self.world
        self._kc_idx = [collections.OrderedDict()
                        for _ in range(self.world)]
        self._kc_pushed = [collections.OrderedDict()
                           for _ in range(self.world)]
        self.kc_hits = 0
        self.kc_misses = 0
        # byte/hit tallies are written from pool threads during fanned
        # pushes/pulls; a plain int += is a load-add-store race
        self._stats_lock = threading.Lock()
        # per-server RPC fan-out pool, created on first multi-server
        # push/pull (one socket per server, per-rank client state — the
        # only shared mutables are behind _stats_lock)
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        # hedged pulls (WH_HEDGE): None when off, so the per-attempt
        # cost of the feature is one attribute check
        self._hedge = _overload.hedge_tracker()

    def _hello_offer(self) -> dict:
        """Per-connection negotiation flags a hello should carry:
        legacy zlib compression, the wire-codec capability ask, and the
        requested frame-compression mode. Empty when every knob is at
        its default (the hello is then skipped on the fast path)."""
        offer: dict = {}
        if self.net_compress:
            offer["net_compress"] = 1
        if self.wire_enc != "raw":
            offer["wire"] = 1
        if self.wire_comp:
            offer["wire_comp"] = self.wire_comp
        return offer

    def _latch_hello(self, r: int, h: dict) -> None:  # wormlint: thread-owned
        """Adopt a hello reply's negotiation acks for connection r: the
        frame-compression mode (string modes win over legacy zlib) and
        the wire-codec capability. An older server acks neither and the
        connection degrades to raw framing + legacy scalar encodings."""
        self._fc[r] = (h.get("wire_comp")
                       if h.get("wire_comp") in ("zlib", "bshuf")
                       else bool(h.get("net_compress")))
        self._wc[r] = bool(h.get("wire"))

    def _file(self, r: int):  # wormlint: thread-owned
        if self._files[r] is None:
            host, port = self.uris[r].rsplit(":", 1)
            s = connect_with_retry((host, int(port)), self.connect_deadline)
            self._socks[r] = s
            self._files[r] = s.makefile("rwb")
            offer = self._hello_offer()
            if offer:
                # negotiate before any payload frame: the server arms
                # its side of the connection on this hello and the ack
                # arms ours; an old/default server simply doesn't ack
                # and the connection stays raw
                f = self._files[r]
                send_frame(f, dict({"op": "hello", "sender": self.sender},
                                   **offer))
                got = recv_frame(f)
                if got is None:
                    raise ConnectionResetError(
                        "connection closed during negotiation hello")
                self._latch_hello(r, got[0])
        return self._files[r]

    def _attempt(self, r: int, header: dict, arrays, fixed_bytes: int,
                 compress: bool) -> tuple[dict, dict, int, int]:
        """One send/recv round against server r; OSError (including the
        ConnectionResetError recv_frame's None maps to) means the
        connection is dead."""
        f = self._file(r)
        sent = send_frame(f, header, arrays, fixed_bytes,
                          compress or self._fc[r])
        got = recv_frame(f)
        if got is None:
            raise ConnectionResetError("connection closed mid-rpc")
        h, arrs, received = got
        return h, arrs, sent, received

    def _attempt_hedged(self, r: int, header: dict, arrays,
                        fixed_bytes: int,
                        compress: bool) -> tuple[dict, dict, int, int]:
        """A pull attempt with tail insurance (WH_HEDGE): after the
        rolling-quantile delay a backup copy of the frame goes out on a
        fresh ephemeral connection. Pulls are idempotent reads with no
        seq fence, so the duplicate is harmless by construction; the
        budget (WH_HEDGE_BUDGET_PCT) bounds the extra load. Gated off
        for non-pull ops and under keycache/compression/wire-codec,
        whose per-connection negotiated state a second connection would
        not share (a hedged wire-codec pull would also advance the
        server's pull-EF residuals twice for the same rows). If the backup answers first it severs the pooled socket
        so the primary's blocked recv turns into the error path, which
        hands back the backup's reply."""
        delay = (self._hedge.delay_s() if self._hedge is not None
                 and header.get("op") == "pull"
                 and not self.keycache and not self.net_compress
                 and self.wire_enc == "raw" and not self.wire_comp
                 and not compress else None)
        if delay is None:
            return self._attempt(r, header, arrays, fixed_bytes, compress)
        done = threading.Event()
        lock = threading.Lock()
        state: dict = {}

        def fire():  # wormlint: thread-entry
            if done.is_set() or not self._hedge.try_issue():
                return
            try:
                host, port = self.uris[r].rsplit(":", 1)
                sock = connect_with_retry((host, int(port)), 1.0)
                try:
                    f = sock.makefile("rwb")
                    sent = send_frame(f, header, arrays, fixed_bytes,
                                      False)
                    got = recv_frame(f)
                    if got is None or got[0].get("busy"):
                        return  # dead or busy backup: primary decides
                    h, arrs, received = got
                    with lock:
                        if not done.is_set():
                            state["reply"] = (h, arrs, sent, received)
                            s = self._socks[r]
                            if s is not None:
                                try:
                                    s.shutdown(socket.SHUT_RDWR)
                                except OSError:
                                    pass
                finally:
                    try:
                        sock.close()
                    except OSError:
                        pass
            except Exception:
                pass  # best-effort tail insurance; the primary decides

        timer = threading.Timer(delay, fire)
        timer.daemon = True
        timer.start()
        try:
            t0 = time.monotonic()
            got = self._attempt(r, header, arrays, fixed_bytes, compress)
            with lock:
                done.set()
            self._hedge.observe(time.monotonic() - t0)
            return got
        except OSError:
            with lock:
                done.set()
                if "reply" in state:
                    self._hedge.won()
                    # the pooled connection was severed to unblock us;
                    # drop it so the next RPC redials cleanly
                    self.close(r)
                    return state["reply"]
            raise
        finally:
            timer.cancel()

    def _note_epoch(self, r: int, h: dict) -> None:  # wormlint: thread-owned
        ep = h.get("epoch")
        if ep is None:
            return
        last = self._epochs[r]
        if last is not None and ep > last:
            # the server restarted and restored a snapshot: its state
            # rolled back to the snapshot clock. Flag it so the next
            # versioned pull re-adopts the full restored state.
            self._rolled_back[r] = True
            _ROLLBACKS.inc()
            _trace.event("ps.rollback", cat="recovery", server=r,
                         epoch_from=last, epoch_to=ep)
            print(f"[ps-retry] server {r} epoch {last} -> {ep}: "
                  "rolled back to its last snapshot; scheduling a "
                  "full re-pull", flush=True)
        self._epochs[r] = ep

    def _rpc(self, r: int, header: dict, arrays=None, fixed_bytes: int = 0,  # wormlint: thread-owned
             compress: bool = False, journal_arrays=None):
        if compress:
            header = dict(header, comp_reply=1)
        op_name = header.get("op", "?")
        if (op_name == "push" and self.sender is not None
                and "seq" not in header):
            # stamp the fence ONCE per logical push (a retried replay
            # reuses the stamp — that's what the dedup keys on)
            self._seq[r] += 1
            header = dict(header, sender=self.sender, seq=self._seq[r])
        t_rpc = time.monotonic()
        recovered = False
        # a saturated server answers `busy` without dispatching;
        # resending the same stamped frame is exactly-once, so back off
        # under the unified full-jitter policy (the budget caps each
        # sleep to the window and counts it) — bounded so a wedged
        # server still fails loudly instead of spinning forever
        busy_budget = None
        while True:
            try:
                h, arrs, sent, received = self._attempt_hedged(
                    r, header, arrays, fixed_bytes, compress)
                if h.get("busy"):
                    if busy_budget is None:  # minted on first bounce only
                        busy_budget = _retrylib.RetryBudget(
                            max(self.retry_deadline, 60.0), op="ps.busy")
                    if busy_budget.expired:
                        raise RuntimeError(
                            f"ps server {self.uris[r]} still busy after "
                            f"{time.monotonic() - t_rpc:.0f}s of backoff "
                            f"during '{op_name}'")
                    busy_backoff(h, busy_budget)
                    continue
                break
            except OSError as e:
                self.close(r)
                if self.retry_deadline <= 0 or op_name == "shutdown":
                    if isinstance(e, ConnectionResetError):
                        raise ConnectionResetError(
                            f"ps server {self.uris[r]} closed the "
                            f"connection during '{op_name}' — the server "
                            "process likely died; the job must be "
                            "restarted (resume from the last _iter-K "
                            "checkpoint)") from e
                    raise ConnectionError(
                        f"ps server {self.uris[r]} unreachable during "
                        f"'{op_name}' ({e}) — the server process likely "
                        "died; the job must be restarted (resume from "
                        "the last _iter-K checkpoint)") from e
                self._recover(r, op_name, e)
                recovered = True
        dur = time.monotonic() - t_rpc
        _RPC_S.observe(dur)
        if _trace.ACTIVE is not None:
            _trace.ACTIVE.emit_span(f"rpc.{op_name}", "rpc", t_rpc, dur,
                                    {"server": r})
        if recovered and op_name == "push" and self.sender is not None:
            # the in-flight push re-sent after a reconnect is itself a
            # replay: count it, and whether the fence absorbed it
            _REPLAYS.inc()
            if h.get("dup"):
                _REPLAY_DEDUP.inc()
        if "error" in h:
            raise RuntimeError(f"ps server error: {h['error']}")
        self._note_epoch(r, h)
        op = header.get("op")
        if op == "push":
            with self._stats_lock:
                self.bytes_push += sent + received
            _BYTES_PUSH.inc(sent + received)
            if (self.retry_deadline > 0 and self.sender is not None
                    and not h.get("need_keys")):
                # journal the FULL-keys form (journal_arrays) so a
                # replay after a reconnect is self-contained even when
                # the original frame shipped digest-only; a need_keys
                # miss reply applied nothing, so the full resend (not
                # this frame) is what gets journaled
                self._journal[r].append(
                    (header["seq"], header, journal_arrays or arrays,
                     fixed_bytes, compress))
        elif op == "pull":
            with self._stats_lock:
                self.bytes_pull += sent + received
            _BYTES_PULL.inc(sent + received)
        elif op in ("init", "init_spec", "init_arrays"):
            with self._stats_lock:
                self.bytes_init += sent + received
        return h, arrs

    def _recover(self, r: int, op_name: str, err: Exception) -> None:  # wormlint: thread-owned
        """Reconnect to server r (re-resolving its URI when a resolver
        is available), fence with `hello`, and replay unacked journaled
        pushes. Raises with the resume guidance once `retry_deadline`
        is exhausted."""
        budget = _retrylib.RetryBudget(self.retry_deadline, base_s=0.25,
                                       cap_s=2.0, op="ps.recover")
        print(f"[ps-retry] server {r} ({self.uris[r]}) failed during "
              f"'{op_name}' ({err}); retrying for up to "
              f"{self.retry_deadline:.0f}s", flush=True)
        while True:
            if budget.expired:
                budget.give_up(ConnectionError(
                    f"ps server {self.uris[r]} unreachable during "
                    f"'{op_name}' and did not come back within "
                    f"{self.retry_deadline:.0f}s — the job must be "
                    "restarted (resume from the last _iter-K checkpoint)"))
            budget.sleep()
            try:
                if self.resolver is not None:
                    uris = self.resolver()
                    if uris and len(uris) == self.world:
                        # atomic rebind of a complete snapshot: racing
                        # fan threads each publish a full resolved list
                        self.uris = list(uris)  # wormsan: allow=race
                self.close(r)
                host, port = self.uris[r].rsplit(":", 1)
                s = connect_with_retry(
                    (host, int(port)),
                    deadline_s=min(2.0, max(budget.remaining, 0.1)))
                self._socks[r] = s
                self._files[r] = s.makefile("rwb")
                hello = dict({"op": "hello", "sender": self.sender},
                             **self._hello_offer())
                h, _, _, _ = self._attempt(r, hello, None, 0, False)
                self._latch_hello(r, h)
                self._note_epoch(r, h)
                with self._stats_lock:  # shared tally; fan threads race
                    self.num_retries += 1
                _RETRIES.inc()
                _trace.event("ps.reconnect", cat="recovery", server=r,
                             uri=self.uris[r], epoch=self._epochs[r])
                if self.keycache and (self._kc_pushed[r]
                                      or self._kc_idx[r]):
                    # the peer may be a fresh/restored process whose key
                    # cache died with the old one: drop both directions
                    # for this rank (correctness never depended on the
                    # cache; the next syncs re-prime it)
                    _KC_INVALIDATIONS.inc()
                    self._kc_pushed[r].clear()
                    self._kc_idx[r].clear()
                applied = int(h.get("last_seq", 0))
                replay = [e for e in self._journal[r] if e[0] > applied]
                # the RPC being retried is re-sent by _rpc after we
                # return; when it is itself an unapplied push, don't
                # count it lost
                in_flight = int(op_name == "push" and self.sender is not None
                                and self._seq[r] > applied)
                if (self.sender is not None
                        and self._seq[r] > applied + len(replay) + in_flight):
                    # pushes older than the journal window were lost with
                    # the dead server and cannot be replayed; the
                    # snapshot bounds the loss — warn, don't die (the
                    # merged model self-corrects like any bounded-
                    # staleness overwrite)
                    print(f"[ps-retry] server {r}: "
                          f"{self._seq[r] - applied - len(replay)} "
                          "pushes predate the journal window and are "
                          "lost to the rollback", flush=True)
                for seq, hdr, arrs, fb, comp in replay:
                    rh, _, _, _ = self._attempt(r, hdr, arrs, fb, comp)
                    if "error" in rh:
                        raise RuntimeError(
                            f"ps server error on replay: {rh['error']}")
                    _REPLAYS.inc()
                    if rh.get("dup"):
                        _REPLAY_DEDUP.inc()
                if replay:
                    print(f"[ps-retry] server {r}: replayed "
                          f"{len(replay)} journaled pushes "
                          f"(server had seq {applied})", flush=True)
                print(f"[ps-retry] server {r} reconnected at "
                      f"{self.uris[r]} (epoch {self._epochs[r]})",
                      flush=True)
                budget.succeeded()
                return
            except (OSError, ConnectionError) as e2:
                self.close(r)
                err = e2

    def rehello(self, mepoch: Optional[int] = None) -> None:  # wormlint: thread-owned
        """Absorb a membership-epoch bump: the WORKER set changed (a
        peer joined or left) while the server group stayed fixed, so the
        shard map is untouched — but this process may be the one that
        just came back from a partition, sitting on half-dead sockets
        whose next frame would ride a stale connection. Re-handshake
        every server: close, reconnect, hello (latching compression +
        the server's restore epoch), and replay any journaled pushes the
        server's `last_seq` reports unapplied. The seq fence makes the
        replay exactly-once, so calling this when nothing was actually
        lost is merely a round of hellos."""
        for r in range(self.world):
            try:
                self.close(r)
                host, port = self.uris[r].rsplit(":", 1)
                s = connect_with_retry((host, int(port)),
                                       self.connect_deadline)
                self._socks[r] = s
                self._files[r] = s.makefile("rwb")
                hello = dict({"op": "hello", "sender": self.sender},
                             **self._hello_offer())
                h, _, _, _ = self._attempt(r, hello, None, 0, False)
                self._latch_hello(r, h)
                self._note_epoch(r, h)
                _REHELLOS.inc()
                applied = int(h.get("last_seq", 0))
                replay = [e for e in self._journal[r] if e[0] > applied]
                for seq, hdr, arrs, fb, comp in replay:
                    rh, _, _, _ = self._attempt(r, hdr, arrs, fb, comp)
                    if "error" in rh:
                        raise RuntimeError(
                            f"ps server error on replay: {rh['error']}")
                    _REPLAYS.inc()
                    if rh.get("dup"):
                        _REPLAY_DEDUP.inc()
                if replay:
                    print(f"[ps-retry] rehello (mepoch {mepoch}): server "
                          f"{r} replayed {len(replay)} journaled pushes "
                          f"(server had seq {applied})", flush=True)
            except (OSError, ConnectionError) as e:
                # a dead server here is the ordinary recovery problem,
                # not a membership one — hand it to the fenced retry
                if self.retry_deadline <= 0:
                    raise
                self._recover(r, "rehello", e)

    def close(self, r: Optional[int] = None) -> None:  # wormlint: thread-owned
        ranks = range(self.world) if r is None else [r]
        for i in ranks:
            try:
                if self._socks[i] is not None:
                    self._socks[i].close()
            except OSError:
                pass
            self._socks[i] = None
            self._files[i] = None
            # compression + wire-codec acks are per-connection state
            self._fc[i] = False
            self._wc[i] = False
        if r is None and self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def _fan(self, fn: Callable[[int], object]) -> list:
        """Run fn(r) against every server. Multi-server clients fan out
        on a small thread pool (one socket per server; all per-rank
        client state is rank-indexed, shared tallies sit behind
        _stats_lock), so a sync costs max-of-shards instead of
        sum-of-shards. Results come back in rank order; the first
        worker exception propagates."""
        if self.world == 1:
            return [fn(0)]
        if self._pool is None:
            # lazy init on the train thread only; close() tears it down
            # after the last fan-out returned
            self._pool = concurrent.futures.ThreadPoolExecutor(  # wormlint: thread-owned
                max_workers=min(self.world, 8),
                thread_name_prefix="ps-rpc")
        ctx = _trace.current_ctx()
        dl = _overload.current()
        if ctx is not None or dl is not None:
            # pool threads don't inherit thread-locals: rebind the
            # sampled sync round's trace context (so each per-rank RPC
            # frame carries it to its server shard) and the round's
            # ambient deadline (so those frames keep their budget)
            inner = fn

            def fn(r, _inner=inner, _ctx=ctx, _dl=dl):
                with _overload.bind(_dl):
                    if _ctx is None:
                        return _inner(r)
                    with _trace.bind(_ctx):
                        return _inner(r)
        futs = [self._pool.submit(fn, r) for r in range(self.world)]
        return [f.result() for f in futs]

    def _kc_cache_idx(self, r: int, dig: str, idx: np.ndarray) -> None:
        """Remember a key list by content digest (per-server LRU) so a
        later digest-only pull reply can be reconstructed locally."""
        lru = self._kc_idx[r]
        lru[dig] = idx
        lru.move_to_end(dig)
        while len(lru) > self._KC_CLIENT_CAP:
            lru.popitem(last=False)

    # -- table ops ----------------------------------------------------------
    def _slices(self, tables: dict[str, np.ndarray], r: int):
        out = {}
        for k, v in tables.items():
            lo, hi = shard_range(v.shape[0], r, self.world)
            out[k] = v[lo:hi]
        return out

    def init(self, tables: dict[str, np.ndarray],
             derived: Optional[dict] = None) -> None:
        """Offer init state to every server (full-array fallback; the
        wire cost is O(table) — prefer init_from_specs when the store
        can describe its init)."""
        self.full_rows = {k: int(v.shape[0]) for k, v in tables.items()}
        for r in range(self.world):
            self._rpc(r, {"op": "init", "full_rows": self.full_rows,
                          "derived": derived or {}},
                      self._slices(tables, r))

    def init_from_specs(self, zero_names: set[str],
                        tables: dict[str, np.ndarray],
                        derived: Optional[dict] = None,
                        timeout: float = 2 * INIT_CLAIM_TTL) -> None:
        """O(spec) table creation: send {shape, zero} per table; servers
        build zero-init tables locally, CLAIM the rest for the first
        asker, and only the claimant ships them via init_arrays — one
        copy on the wire no matter how many workers start at once. A
        non-claimant polls until the claimant's arrays land (claims
        expire server-side, so a dead claimant just hands the claim to
        the next poller). The server cross-checks the offered shapes
        against the created tables, so a divergent-conf worker fails at
        init, not later with misrouted row indices. At the 2^26-bucket
        FTRL operating point this turns a ~768 MB-per-worker startup
        push into a ~1 KB header exchange (VERDICT r3 item 2)."""
        self.full_rows = {k: int(v.shape[0]) for k, v in tables.items()}
        specs = {k: {"shape": list(v.shape), "zero": k in zero_names}
                 for k, v in tables.items()}
        for r in range(self.world):
            deadline = time.monotonic() + timeout
            while True:
                h, _ = self._rpc(r, {"op": "init_spec", "specs": specs,
                                     "derived": derived or {}})
                if h.get("known"):
                    break
                need = h.get("need") or []
                if need:  # we hold the claim for these: ship our slices
                    h2, _ = self._rpc(
                        r, {"op": "init_arrays"},
                        self._slices({k: tables[k] for k in need}, r))
                    if h2.get("known"):
                        break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"server {self.uris[r]} tables never completed "
                        "creation (claimant died repeatedly?)")
                time.sleep(0.1)

    def pull(self) -> dict[str, np.ndarray]:
        """Dense full-table pull (startup / test convenience)."""
        parts = [self._rpc(r, {"op": "pull"})[1] for r in range(self.world)]
        if not self.full_rows:
            self.full_rows = {
                k: sum(p[k].shape[0] for p in parts) for k in parts[0]}
        return {
            k: np.concatenate([p[k] for p in parts], axis=0)
            if self.world > 1 else parts[0][k]
            for k in parts[0]
        }

    def pull_sparse(self, since: list[int], compress: bool = False,
                    skip: Optional[list] = None,
                    ) -> tuple[list[int], dict[int, np.ndarray],
                               dict[str, np.ndarray]]:
        """Versioned pull: rows stamped after `since[r]` on each server.
        Returns (new clocks, {group_rows: global indices},
        {table: rows aligned to its group's indices}).

        `skip` names derived tables the caller will recompute from the
        same reply's source rows (SyncedStore._fill_derived) — the
        server omits their values from the wire. Purely advisory: a
        server that predates the field ships them anyway and the caller
        just uses the shipped rows."""
        kc = self.keycache and self.sender is not None

        def one(r: int) -> tuple[dict, dict]:  # wormlint: thread-entry thread-owned

            s = int(since[r])
            if self._rolled_back[r]:
                # the server restored a snapshot: its clock (and row
                # stamps) rolled back, so our `since` may exceed it and
                # miss rows. since=0 returns every stamped row — a
                # superset of the delta — and re-adopts the restored
                # state wholesale.
                self._rolled_back[r] = False
                s = 0
            def wire_hdr(hdr: dict) -> dict:
                # quantized pull replies (wire codec v2) ride only
                # connections whose hello ack'd the capability; EF is
                # keyed by sender, so anonymous clients get stateless
                # quantization. Pulls cap at bf16 even when WH_WIRE is
                # int8/int4: a pull is an ABSOLUTE-state refresh, and
                # uniform absmax codes give errors relative to the
                # hottest neighbor, not the element itself — on a
                # skewed FTRL table that pushes small z past the L1
                # threshold and diverges training. bf16's per-element
                # relative precision is scale-free; int8/int4 stay on
                # the EF-corrected DELTA streams (pushes), where the
                # residual makes the error unbiased over rounds.
                if self.wire_enc != "raw" and self._wc[r]:
                    hdr["wire"] = ("bf16" if self.wire_enc in
                                   ("int8", "int4") else self.wire_enc)
                    if self.wire_ef and self.sender is not None:
                        hdr.update(sender=self.sender, wire_ef=1)
                return hdr

            header = {"op": "pull", "since": s}
            if skip:
                header["skip"] = list(skip)
            if kc:
                header.update(sender=self.sender, kc=1)
            h, arrs = self._rpc(r, wire_hdr(header), compress=compress)
            if kc:
                for gs, dig in (h.get("kfull") or {}).items():
                    # full reply stamped with its digest: cache the key
                    # list so the server's next same-set reply can omit
                    # it
                    name = _idx_name(int(gs))
                    if name in arrs:
                        self._kc_cache_idx(r, dig, arrs[name])
                kdig = h.get("kdig") or {}
                missing = any(dig not in self._kc_idx[r]
                              for dig in kdig.values())
                if missing:
                    # digest-only reply we can no longer reconstruct
                    # (our LRU evicted it): re-pull this server in full
                    # — correctness never depends on the cache
                    with self._stats_lock:
                        self.kc_misses += 1
                    hdr2 = {"op": "pull", "since": s}
                    if skip:
                        hdr2["skip"] = list(skip)
                    h, arrs = self._rpc(r, wire_hdr(hdr2),
                                        compress=compress)
                elif kdig:
                    for gs, dig in kdig.items():
                        lru = self._kc_idx[r]
                        lru.move_to_end(dig)
                        arrs[_idx_name(int(gs))] = lru[dig]
                    with self._stats_lock:
                        self.kc_hits += len(kdig)
            return h, arrs

        got = self._fan(one)
        clocks = []
        g_idx: dict[int, list] = {}
        t_rows: dict[str, list] = {}
        for r, (h, arrs) in enumerate(got):
            clocks.append(int(h["clock"]))
            for g in {rows for rows in self.full_rows.values()}:
                name = _idx_name(g)
                if name not in arrs:
                    continue
                lo, _ = shard_range(g, r, self.world)
                g_idx.setdefault(g, []).append(arrs[name] + lo)
            for k, rows in self.full_rows.items():
                if k in arrs:
                    t_rows.setdefault(k, []).append(arrs[k])
        groups = {g: np.concatenate(v) if len(v) > 1 else v[0]
                  for g, v in g_idx.items()}
        tables = {k: np.concatenate(v, axis=0) if len(v) > 1 else v[0]
                  for k, v in t_rows.items()}
        return clocks, groups, tables

    def push(self, deltas: dict[str, np.ndarray],
             fixed_bytes: int = 0) -> None:
        """Dense full-table delta push (test convenience / fallback)."""
        for r in range(self.world):
            self._rpc(r, {"op": "push"}, self._slices(deltas, r),
                      fixed_bytes=fixed_bytes)

    def push_sparse(self, groups: dict[int, np.ndarray],
                    deltas: dict[str, np.ndarray],
                    fixed_bytes: int = 0, compress: bool = False) -> None:
        """Sparse delta push. `groups` maps a row-space (full row count)
        to the sorted-unique GLOBAL row indices touched in it;
        `deltas[k]` holds the delta rows of table k aligned to
        `groups[full_rows[k]]`.

        Sortedness makes each server's slice a contiguous searchsorted
        range, so the per-server split is two binary searches and VIEWS
        of the delta rows — no boolean masks, no row copies. With key
        caching on, a slice whose digest the server already holds ships
        digest + values only; a need_keys reply (cache lost — e.g. a
        respawned server) triggers a full resend under a fresh seq."""
        kc = self.keycache and self.sender is not None

        def one(r: int) -> None:  # wormlint: thread-entry thread-owned

            sel: dict[int, slice] = {}
            loc_of: dict[int, np.ndarray] = {}
            kdig: dict[str, str] = {}
            for g, idx in groups.items():
                lo, hi = shard_range(g, r, self.world)
                a, b = np.searchsorted(idx, (lo, hi))
                sel[g] = slice(a, b)
                loc_of[g] = idx[a:b] - lo
                if kc:
                    kdig[str(g)] = key_digest(loc_of[g])
            vals = {k: rows[sel[self.full_rows[k]]]
                    for k, rows in deltas.items()}
            full = {_idx_name(g): v for g, v in loc_of.items()}
            full.update(vals)
            if not kc:
                self._rpc(r, {"op": "push"}, full,
                          fixed_bytes=fixed_bytes, compress=compress)
                return
            header = {"op": "push", "kdig": kdig}
            send = {_idx_name(g): v for g, v in loc_of.items()
                    if kdig[str(g)] not in self._kc_pushed[r]}
            omitted = len(loc_of) - len(send)
            send.update(vals)
            h, _ = self._rpc(r, header, send, fixed_bytes=fixed_bytes,
                             compress=compress, journal_arrays=full)
            need = h.get("need_keys")
            if need:
                # the server lost (or never had) our key lists — a
                # fresh/restored process. The miss reply advanced
                # neither fence nor clock, so resend in full; _rpc
                # stamps a new seq.
                with self._stats_lock:
                    self.kc_misses += len(need)
                self._kc_pushed[r].clear()
                self._rpc(r, {"op": "push", "kdig": kdig}, full,
                          fixed_bytes=fixed_bytes, compress=compress)
            elif omitted:
                with self._stats_lock:
                    self.kc_hits += omitted
            pushed = self._kc_pushed[r]
            for gs, dig in kdig.items():
                pushed[dig] = True
                pushed.move_to_end(dig)
                # the digest space is content-addressed, so our own
                # pushed key lists double as pull-reply reconstructions
                self._kc_cache_idx(r, dig, loc_of[int(gs)])
            while len(pushed) > ServerNode._KC_CAP:
                pushed.popitem(last=False)

        self._fan(one)

    def save(self, base: str, it: Optional[int] = None) -> list[str]:
        return [self._rpc(r, {"op": "save", "base": base, "iter": it})[0]
                ["path"] for r in range(self.world)]

    def load(self, base: str, it: Optional[int] = None) -> None:
        """Command every server to load its shard of a checkpoint
        (IterScheduler::LoadModel parity) — must run before any worker
        init so the loaded state IS the table-creation state."""
        for r in range(self.world):
            self._rpc(r, {"op": "load", "base": base, "iter": it})

    def stats(self, r: int = 0) -> dict:
        return self._rpc(r, {"op": "stats"})[0]

    def shutdown(self) -> None:
        for r in range(self.world):
            try:
                self._rpc(r, {"op": "shutdown"})
            except (OSError, ConnectionError):
                pass
        self.close()


class SyncedStore:
    """Bounded-staleness synchronization of a learner's KV store against
    the server group: tracks the state at last pull and pushes additive
    deltas (cur - base). `maybe_sync` counts minibatches and syncs every
    `max_delay` (the reference's bounded-async knob).

    Sparse wire: when the learner supplies `touched_fn` (returning, per
    additive table, the sorted-unique global rows it touched since the
    last call) AND the store exposes `gather_rows`/`scatter_rows`, the
    sync path never materializes a full table — it gathers the touched
    device rows, pushes (indices, deltas), and scatters back the rows
    the versioned pull reports dirty. Without hints it falls back to a
    full-table delta scan (host O(table), wire still sparse: only rows
    with nonzero delta are sent).

    Async sync (`async_sync=True`, default from `WH_ASYNC_SYNC`):
    `sync()` snapshots the touched rows + deltas, advances the base
    mirror by them ("deltas on the wire ARE part of base"), hands the
    push+pull round-trip to a daemon comms thread, and returns — the
    device trains through the round-trip. At most ONE round-trip is in
    flight; the next sync waits for it and FOLDS the pull in first:
    for every pulled row, store <- pulled + (cur - base) keeps local
    un-pushed progress on top of the adopted merged state (derived
    tables are overwritten — they are not additive), base <- pulled.
    Effective staleness is therefore at most 2*max_delay minibatches.
    `flush()` is the barrier for part ends / eval / checkpoints: drain
    the in-flight round-trip, then one synchronous sync. Recovery
    composes unchanged: the comms thread rides PSClient's fenced retry,
    journal replay, and rollback re-pull. With async off, sync() is the
    original, bit-identical synchronous path."""

    def __init__(self, store, client: PSClient, max_delay: int = 16,
                 fixed_bytes: int = 0, derived: Optional[dict] = None,
                 perf=None, touched_fn: Optional[Callable] = None,
                 compress: bool = False, offer_arrays: bool = False,
                 async_sync: Optional[bool] = None):
        self.store = store
        self.client = client
        self.perf = perf  # optional utils.perf.Perf: times push/pull ops
        self.max_delay = max(int(max_delay), 1)
        self.fixed_bytes = fixed_bytes
        self.compress = bool(compress)
        # warm starts (model_in loaded into the store) MUST offer real
        # arrays: the spec path would create zero tables while this
        # worker's base mirror holds the loaded model, silently erasing
        # the warm start on the first sync
        self.offer_arrays = bool(offer_arrays)
        # non-additive derived-table specs forwarded to the servers (e.g.
        # FTRL's w = prox(z, n); see ServerNode._recompute_derived)
        self.derived = derived or {}
        self.touched_fn = touched_fn
        self._sparse_store = (hasattr(store, "gather_rows")
                              and hasattr(store, "scatter_rows"))
        # wire codec v2 (client half): the encoding/EF/comp operating
        # point lives on the PSClient (it owns the per-connection
        # negotiation); this store quantizes each sync's delta rows
        # once, with one EF accumulator per table, and tallies the
        # f32-equivalent vs on-the-wire bytes for wire_stats
        self.wire_enc = client.wire_enc
        self.wire_ef = client.wire_ef
        # per-table wire floor (TableSpec.wire_cap via the store hook):
        # second-moment / count accumulator deltas never drop below bf16
        cap_fn = getattr(store, "wire_cap_names", None)
        self._wire_cap: set = set(cap_fn()) if cap_fn is not None else set()
        self._efq: dict[str, EFQuant] = {}
        self._wire_raw = 0
        self._wire_bytes = 0
        self._base: dict[str, np.ndarray] = {}
        self._clocks: Optional[list[int]] = None
        self._steps = 0
        self.num_syncs = 0
        self.async_sync = (_env_flag("WH_ASYNC_SYNC") if async_sync is None
                           else bool(async_sync))
        # async comms state: at most one in-flight round-trip job (that
        # bound IS the staleness guarantee) on a lazily started daemon
        # thread; device-row gathers/scatters stay on the training
        # thread (jax dispatch), only wire work moves off it
        self._inflight: Optional[dict] = None
        self._comm_q: Optional[queue.Queue] = None
        self._comm_thread: Optional[threading.Thread] = None
        self._mepoch_seen = 0  # last membership epoch absorbed
        self._rt_wall = 0.0    # round-trip wall summed (comms thread)
        self._wait_wall = 0.0  # fold wait actually paid (train thread)
        self._push_s = 0.0
        self._pull_s = 0.0
        self.max_fold_lag = 0  # observed staleness, in sync rounds

    def init(self) -> None:
        """Offer this worker's (deterministic) init state, then adopt the
        merged server state. INVARIANT: all workers initialize
        identically (the learners construct state from fixed seeds /
        zeros), so the local state IS the table-creation state — which
        is what lets both halves of this be O(touched), not O(table):
        the offer goes as an init SPEC when the store can name its
        zero-init tables (arrays only for the remainder, shipped by the
        single claiming worker), and the startup pull asks only for rows
        pushed since creation (since=0). The server rejects an init spec
        whose shapes disagree with the created tables, so a
        divergent-conf worker fails at init rather than training against
        a wrong base mirror. Warm starts (offer_arrays=True) take the
        full-array path: loaded state is NOT the deterministic init, so
        it must be offered as the table-creation state."""
        snap = self.store.to_numpy()
        zero_names = getattr(self.store, "zero_init_names", None)
        if zero_names is not None and not self.offer_arrays:
            self.client.init_from_specs(set(zero_names()), snap,
                                        derived=self.derived)
        else:
            self.client.init(snap, derived=self.derived)
        # writable host mirror (to_numpy may hand out read-only views of
        # device buffers)
        self._base = {k: np.array(v, np.float32) for k, v in snap.items()}
        self._clocks = [0] * self.client.world
        self._apply_pull()

    def _pull_skip(self) -> Optional[list]:
        """Derived tables to omit from quantized pull replies: w is a
        pure function of (z, n), so shipping it alongside its sources
        is a third bf16 table of pure redundancy — the client derives
        the same rows from the same reply (_fill_derived). Raw-wire
        pulls keep shipping it: there the contract is bit-identical
        adoption of server state, and recomputing would trade exact
        f32 equality for a formula re-evaluation."""
        if self.wire_enc == "raw" or not self._wire_ok():
            return None
        sk = [k for k, s in self.derived.items()
              if s.get("kind") == "ftrl_prox"]
        return sk or None

    def _fill_derived(self, groups: dict, tables: dict) -> dict:
        """Client half of the derived-table wire skip: reconstruct any
        derived table the reply omitted from its pulled source rows
        (same ftrl_prox_rows the server runs, so both ends derive
        identical values). A reply that still carries the table (older
        server, raw wire) is used as-is."""
        for k, spec in self.derived.items():
            if spec.get("kind") != "ftrl_prox":
                continue
            z, n = tables.get("z"), tables.get("n")
            if (z is None or n is None
                    or self.client.full_rows.get("z")
                    != self.client.full_rows.get(k)):
                continue
            if k in tables and tables[k].shape[0] == z.shape[0]:
                # a complete part was shipped (raw wire, or every
                # server predates the skip): adopt it as-is
                continue
            # absent — or PARTIAL: in a mixed world where only some
            # servers honor the skip, the merged part covers only the
            # non-honoring servers' rows and is useless; z/n are never
            # skipped, so recomputing from them always aligns with the
            # merged index
            tables[k] = ftrl_prox_rows(spec, z, n)
        return tables

    def _apply_pull(self) -> None:
        """Versioned pull: fetch rows dirty since our clocks, fold them
        into the base mirror and the device store."""
        clocks, groups, tables = self.client.pull_sparse(
            self._clocks, compress=self.compress, skip=self._pull_skip())
        tables = self._fill_derived(groups, tables)
        for k, rows in tables.items():
            idx = groups[self.client.full_rows[k]]
            if idx.size == 0:
                continue
            self._base[k][idx] = rows
            if self._sparse_store:
                self.store.scatter_rows(k, idx, rows)
        if not self._sparse_store and groups:
            self.store.from_numpy(self._base)
        elif self._sparse_store:
            # host-mirror coherence hook (e.g. difacto's admission-count
            # mirror): the dense path refreshes mirrors via from_numpy;
            # the sparse path hands over exactly the pulled rows
            hook = getattr(self.store, "on_sparse_pull", None)
            if hook is not None:
                hook({k: (groups[self.client.full_rows[k]], rows)
                      for k, rows in tables.items()})
        self._clocks = clocks

    def pull(self) -> None:
        if self.async_sync:
            # adopt any completed (or still-flying) round-trip before a
            # fresh pull overwrites rows — base must stay coherent
            self._fold_pending(wait=True)
        if self._clocks is None:
            pulled = self.client.pull()
            self.store.from_numpy(pulled)
            # decoded arrays can be read-only zero-copy views (net.py);
            # the base mirror gets written by later sparse pulls
            self._base = {k: np.array(v, np.float32)
                          for k, v in pulled.items()}
            return
        self._apply_pull()

    def _touched_groups(self):
        """(groups, deltas) for push_sparse from learner hints, or None
        to use the full-scan fallback."""
        if self.touched_fn is None:
            return None
        touched = self.touched_fn()
        if touched is None:
            return None
        per_g: dict[int, list[np.ndarray]] = {}
        for k, rows in self.client.full_rows.items():
            if k in self.derived:
                continue
            idx = touched.get(k)
            if idx is None:
                return None  # incomplete hint: fall back to the scan
            per_g.setdefault(rows, []).append(idx)
        groups = self._union_groups(per_g)
        snap = None if self._sparse_store else self.store.to_numpy()
        deltas: dict[str, np.ndarray] = {}
        multi = (getattr(self.store, "gather_rows_multi", None)
                 if snap is None else None)
        by_g: dict[int, list[str]] = {}
        for k, rows in self.client.full_rows.items():
            if k not in self.derived:
                by_g.setdefault(rows, []).append(k)
        for rows, names in by_g.items():
            idx = groups[rows]
            if multi is not None and len(names) > 1:
                # one padded index transfer + one device dispatch for
                # the whole group (z, n, ... share the touched set)
                cur = multi(names, idx)
            else:
                cur = {k: (self.store.gather_rows(k, idx) if snap is None
                           else snap[k][idx]) for k in names}
            for k in names:
                deltas[k] = cur[k] - self._base[k][idx]
        return groups, deltas

    @staticmethod
    def _union_groups(per_g: dict[int, list]) -> dict[int, np.ndarray]:
        """Union the per-table touched sets of each row-space group with
        ONE concatenate+unique (repeated pairwise np.union1d re-sorts
        the whole accumulated set per table: O(k * n log n))."""
        groups: dict[int, np.ndarray] = {}
        for rows, parts in per_g.items():
            first = parts[0]
            if all(p is first or np.array_equal(p, first)
                   for p in parts[1:]):
                groups[rows] = first
            else:
                groups[rows] = np.unique(np.concatenate(parts))
        return groups

    def _scan_groups(self):
        """Fallback: full-table delta scan; wire stays sparse (only rows
        whose delta is nonzero ship)."""
        cur = self.store.to_numpy()
        per_g: dict[int, list[np.ndarray]] = {}
        diffs: dict[str, np.ndarray] = {}
        for k, v in cur.items():
            if k in self.derived:
                continue
            d = v - self._base[k]
            nz = d != 0
            if nz.ndim > 1:
                nz = nz.any(axis=tuple(range(1, nz.ndim)))
            idx = np.flatnonzero(nz)
            diffs[k] = d
            per_g.setdefault(self.client.full_rows[k], []).append(idx)
        groups = self._union_groups(per_g)
        deltas = {k: diffs[k][groups[self.client.full_rows[k]]]
                  for k in diffs}
        return groups, deltas

    # -- wire codec v2 (push half) -------------------------------------------
    def _wire_ok(self) -> bool:
        """True when every server connection ack'd the wire codec in
        its hello — QuantRows encodings only ship to peers that can
        decode them (per-server slices come from ONE quantized array,
        so the decision is all-or-nothing per sync)."""
        return all(self.client._wc)

    def _wire_fb(self) -> int:
        """Effective fixed_bytes for this sync's push: when WH_WIRE is
        set but a server didn't ack the codec (older peer), degrade to
        the legacy bf16 truncation form (fixed_bytes=2) for EVERY
        quantized encoding instead of sending frames the peer can't
        decode. Not fixed_bytes=1: that form is one global absmax scale
        over the whole push — exactly the hot-neighbor granularity
        pathology wire_cap exists to avoid, with no EF and no per-table
        escape hatch."""
        if self.wire_enc == "raw" or self._wire_ok():
            return self.fixed_bytes
        return 2

    def _quantize_deltas(self, groups: dict, deltas: dict) -> dict:
        """Quantize a sync round's delta rows ONCE into QuantRows
        (per-row scales for 2-D tables, grouped scales for 1-D), folding in and
        advancing the per-table error-feedback residuals. Everything
        downstream — the per-server searchsorted split, the push
        journal, a need_keys full resend — slices/replays these same
        objects, so every (re)send of a logical sync serializes to
        identical bytes and a residual can never be applied twice.
        Returns the deltas untouched when the codec is off or a peer
        didn't negotiate it (see _wire_fb's legacy fallback)."""
        if self.wire_enc == "raw" or not self._wire_ok():
            return deltas
        out: dict = {}
        for k, d in deltas.items():
            idx = groups[self.client.full_rows[k]]
            if not idx.size:
                out[k] = d
                continue
            # wire_cap floor: accumulator tables (FTRL n, difacto
            # n/cnt/nV) ship at bf16 even under int8/int4 — an absmax
            # group code quantizes a cold bucket's delta at the hot
            # neighbor's granularity, mis-scaling its learning rate in
            # a way EF can't repair (see TableSpec.wire_cap)
            enc = ("bf16" if k in self._wire_cap
                   and self.wire_enc in ("int8", "int4")
                   else self.wire_enc)
            if self.wire_ef:
                efq = self._efq.get(k)
                if efq is None:
                    efq = self._efq[k] = EFQuant(enc)
                qr = efq.apply(idx, d)
            else:
                qr = quantize_rows(d, enc)
            out[k] = qr
            self._wire_raw += 4 * int(qr.q.size)
            self._wire_bytes += qr.wire_nbytes()
        return out

    # -- async comms plane ---------------------------------------------------
    def _ensure_comm_thread(self) -> None:
        if self._comm_thread is None:
            self._comm_q = queue.Queue()
            self._comm_thread = threading.Thread(
                target=self._comm_loop, daemon=True, name="ps-sync-comms")
            self._comm_thread.start()

    def _comm_loop(self) -> None:
        """Comms thread: run each queued round-trip (push then versioned
        pull) against the servers. PSClient is touched ONLY from this
        thread while async mode is live, so the fenced retry / journal
        replay / rollback machinery runs here unchanged."""
        _pyprof.tag_thread("comms")
        while True:
            job = self._comm_q.get()
            if job is None:
                return
            t0 = time.perf_counter()
            try:
                # every WH_TRACE_SAMPLE-th round gets a trace context
                # that rides the push/pull frames, so the PS shards'
                # handler spans stitch under this round cross-node
                with _trace.bind(_trace.start_request()), \
                        _trace.request_span("ps.sync.round", cat="ps"):
                    with _trace.span("ps.sync.push", cat="ps"):
                        self.client.push_sparse(
                            job["groups"], job["deltas"],
                            fixed_bytes=self._wire_fb(),
                            compress=self.compress)
                    t1 = time.perf_counter()
                    with _trace.span("ps.sync.pull", cat="ps"):
                        job["pull"] = self.client.pull_sparse(
                            self._clocks, compress=self.compress,
                            skip=self._pull_skip())
                t2 = time.perf_counter()
                _SYNC_PUSH_S.observe(t1 - t0)
                _SYNC_PULL_S.observe(t2 - t1)
                # duration tallies ride the job dict and are folded by
                # _fold_pending on the train thread (job["done"] is the
                # fence), keeping _push_s/_pull_s/perf single-writer
                job["push_s"] = t1 - t0
                job["pull_s"] = t2 - t1
            except BaseException as e:  # surfaced at the next fold
                job["error"] = e
            finally:
                job["rt"] = time.perf_counter() - t0
                job["done"].set()

    def _fold_pending(self, wait: bool) -> None:
        """Adopt the in-flight round-trip's pull, if any (and, with
        `wait`, block until it lands). Comms-thread errors re-raise
        here, on the training thread."""
        job = self._inflight
        if job is None:
            return
        t0 = time.perf_counter()
        if wait:
            job["done"].wait()
        elif not job["done"].is_set():
            return
        waited = time.perf_counter() - t0
        self._inflight = None
        _SYNC_INFLIGHT.set(0)
        err = job.get("error")
        if err is not None:
            raise err
        self._wait_wall += waited
        self._rt_wall += job["rt"]
        if "push_s" in job:
            self._push_s += job["push_s"]
            self._pull_s += job["pull_s"]
            if self.perf is not None:
                self.perf.add("ps_push", job["push_s"])
                self.perf.add("ps_pull", job["pull_s"])
        _SYNC_WAIT_S.observe(waited)
        _ST_SYNC.observe(waited)
        if self._rt_wall > 0:
            _SYNC_OVERLAP.set(
                max(0.0, 1.0 - self._wait_wall / self._rt_wall))
        self.max_fold_lag = max(self.max_fold_lag,
                                self.num_syncs - job["enq_sync"])
        clocks, groups, tables = job["pull"]
        self._fold_rows(groups, self._fill_derived(groups, tables))
        self._clocks = clocks

    def _fold_rows(self, groups: dict, tables: dict) -> None:
        """Fold a pull that raced local training: by the time the
        round-trip landed, the store holds deltas newer than the pushed
        snapshot. For every pulled row of an additive table,

            store <- pulled + (cur - base);  base <- pulled

        keeps that un-pushed local progress on top of the adopted merged
        state (base is always "adopted server state + deltas already on
        the wire", so cur - base IS the un-pushed part). Derived tables
        (non-additive, e.g. FTRL's w) are overwritten like the sync
        path; their rows re-cohere the next time they are trained or
        pulled — the same bounded-staleness wobble async-SGD already
        accepts."""
        snap = None
        if not self._sparse_store:
            # to_numpy may hand out read-only device views; the fold
            # mutates rows in place
            snap = {k: np.array(v, np.float32)
                    for k, v in self.store.to_numpy().items()}
        scattered: dict[str, tuple] = {}
        for k, rows in tables.items():
            idx = groups[self.client.full_rows[k]]
            if idx.size == 0:
                continue
            if k in self.derived:
                new = rows
            else:
                cur = (self.store.gather_rows(k, idx) if snap is None
                       else snap[k][idx])
                new = rows + (cur - self._base[k][idx])
            self._base[k][idx] = rows
            if self._sparse_store:
                self.store.scatter_rows(k, idx, new)
                scattered[k] = (idx, new)
            else:
                snap[k][idx] = new
        if not self._sparse_store and groups:
            self.store.from_numpy(snap)
        elif scattered:
            # host-mirror coherence hook (see _apply_pull): hand over
            # the FOLDED rows — they are what the device store now holds
            hook = getattr(self.store, "on_sparse_pull", None)
            if hook is not None:
                hook(scattered)

    def sync(self) -> None:
        if not self.async_sync:
            self._sync_now()
            return
        # adopt the previous round-trip first (waiting if it is still in
        # flight — one-in-flight is the staleness bound), then snapshot
        # deltas and hand the next round-trip to the comms thread
        self._fold_pending(wait=True)
        with _trace.span("ps.sync.snapshot", cat="ps"):
            got = self._touched_groups()
            if got is None:
                got = self._scan_groups()
            groups, deltas = got
            # mark the snapshot as pushed NOW: the next delta starts
            # from zero and the fold can tell un-pushed progress apart.
            # Base advances by the RAW delta even under quantization:
            # the quantization error lives in the EF residuals (not the
            # mirror), so the fold algebra below stays unchanged and
            # the error re-ships with the next sync that touches the
            # row.
            for k, d in deltas.items():
                idx = groups[self.client.full_rows[k]]
                if idx.size:
                    self._base[k][idx] += d
            # quantize on the TRAIN thread (EF state is single-writer
            # here; the comms thread only serializes the result)
            deltas = self._quantize_deltas(groups, deltas)
        self._ensure_comm_thread()
        job = {"groups": groups, "deltas": deltas,
               "done": threading.Event(), "enq_sync": self.num_syncs}
        self._inflight = job
        _SYNC_INFLIGHT.set(1)
        self._comm_q.put(job)
        _SYNCS.inc()
        self._steps = 0
        self.num_syncs += 1

    def _sync_now(self) -> None:
        """The original synchronous round-trip (also the async mode's
        barrier step): push deltas, then pull+apply the merged rows."""
        t0 = time.perf_counter()
        with _trace.bind(_trace.start_request()), \
                _trace.request_span("ps.sync.round", cat="ps"):
            with _trace.span("ps.sync.push", cat="ps"):
                got = self._touched_groups()
                if got is None:
                    got = self._scan_groups()
                groups, deltas = got
                self.client.push_sparse(groups,
                                        self._quantize_deltas(groups,
                                                              deltas),
                                        fixed_bytes=self._wire_fb(),
                                        compress=self.compress)
            t1 = time.perf_counter()
            with _trace.span("ps.sync.pull", cat="ps"):
                self._apply_pull()
        t2 = time.perf_counter()
        _SYNC_PUSH_S.observe(t1 - t0)
        _SYNC_PULL_S.observe(t2 - t1)
        _ST_SYNC.observe(t2 - t0)
        _SYNCS.inc()
        self._push_s += t1 - t0
        self._pull_s += t2 - t1
        if self.perf is not None:
            self.perf.add("ps_push", t1 - t0)
            self.perf.add("ps_pull", t2 - t1)
        self._steps = 0
        self.num_syncs += 1

    def flush(self) -> None:
        """Barrier for part ends, eval, and checkpoints: drain the
        in-flight round-trip, then run one synchronous sync — afterwards
        every local delta is merged on the servers and the local store
        holds the freshest merged state (with async off this IS
        sync()). When no minibatch ran since the last sync there is
        nothing to push (an adopted in-flight pull already refreshed the
        mirror), so back-to-back barriers — part end, then pass
        boundary, then checkpoint — cost one round-trip, not three."""
        if self.async_sync:
            self._fold_pending(wait=True)
        if self._steps == 0 and self.num_syncs > 0:
            return
        self._sync_now()

    def absorb_membership(self, mepoch: int) -> bool:
        """A membership-epoch bump (worker join/leave/evict) reached
        this worker. Barrier-flush so every local delta is durably
        merged under the OLD membership, then re-handshake the server
        group (PSClient.rehello) so a stale connection from a healed
        partition can't carry pre-bump frames. The servers themselves
        are membership-stable — only the WORKER set changed — so this
        is a fence + freshness barrier, not a reshard. Returns True
        when a bump was actually absorbed; already-seen epochs are a
        no-op, so callers can invoke this every round unconditionally.
        Composes with async sync (flush drains the in-flight
        round-trip first) and with journal replay (rehello replays
        unacked pushes through the seq fence)."""
        mepoch = int(mepoch)
        if mepoch <= self._mepoch_seen:
            return False
        self.flush()
        self.client.rehello(mepoch)
        self._mepoch_seen = mepoch
        return True

    def close(self) -> None:
        """Stop the comms thread (tests and orderly teardown; it is a
        daemon thread otherwise). Pending work is folded first."""
        if self._comm_thread is not None:
            self._fold_pending(wait=True)
            self._comm_q.put(None)
            self._comm_thread.join(timeout=10)
            self._comm_thread = None

    def maybe_sync(self) -> bool:
        self._steps += 1
        if self._steps >= self.max_delay:
            self.sync()
            return True
        return False

    def wire_stats(self) -> dict:
        """Measured wire traffic (both directions) plus the async/key-
        cache operating point, for the distributed bench's [ps-wire]
        line."""
        n = max(self.num_syncs, 1)
        c = self.client
        kc_total = c.kc_hits + c.kc_misses
        overlap = (max(0.0, 1.0 - self._wait_wall / self._rt_wall)
                   if self._rt_wall > 0 else 0.0)
        resid = (sum(e.resid_norm() ** 2 for e in self._efq.values())
                 ** 0.5 if self._efq else 0.0)
        return {"plane": "tcp",
                "num_syncs": self.num_syncs,
                "bytes_push": c.bytes_push,
                "bytes_pull": c.bytes_pull,
                "bytes_per_sync": (c.bytes_push + c.bytes_pull) / n,
                "wire_codec": self.wire_enc,
                "wire_ef": int(self.wire_ef and self.wire_enc != "raw"),
                "wire_comp": c.wire_comp,
                "wire_bytes_raw": self._wire_raw,
                "wire_bytes_wire": self._wire_bytes,
                "wire_ef_resid_norm": round(resid, 6),
                "async_sync": int(self.async_sync),
                "sync_overlap_frac": round(overlap, 4),
                "push_ms_per_sync": round(1e3 * self._push_s / n, 3),
                "pull_ms_per_sync": round(1e3 * self._pull_s / n, 3),
                "keycache": int(c.keycache),
                "keycache_hits": c.kc_hits,
                "keycache_misses": c.kc_misses,
                "keycache_hit_rate": (round(c.kc_hits / kc_total, 4)
                                      if kc_total else 0.0)}
