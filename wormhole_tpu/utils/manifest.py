"""Snapshot-set manifest: atomic discovery of a consistent shard set.

`ps_server.start_snapshots` writes each shard's `<base>_part-<rank>.npz`
independently, on its own cadence. Before this module, any reader that
wanted the full set (serving watcher, `restore_snapshot` on a rescaled
world) had to glob — and a glob can pair a rank's half-replaced file
with another rank's older one (the torn-read window). The fix is a
single `<base>_MANIFEST.json` next to the parts: every snapshot cycle a
shard updates its own entry (file name, blake2b digest, clock, epoch)
under an flock'd read-modify-write and bumps a monotone `version`
counter, writing the result with the usual temp+rename. Readers take
the manifest as ground truth: load exactly the files it names, verify
each against its digest, and retry from a fresh manifest on mismatch
(`TornSnapshot`) — a part replaced mid-read can only ever be detected,
never silently mixed in.

`version` doubles as the serving tier's model epoch: it bumps on every
manifest commit (per part for ps_server's independent shard cadences;
once per FULL set for `write_snapshot_set`, whose
`commit_manifest_set` publishes all parts in one cycle so no
intermediate manifest can pair a new part with a stale one), so "the
manifest version grew" is exactly "newer model state is on disk"
(wormhole_tpu/serving/server.py polls it).

The digest is blake2b-12 like `net.key_digest` and the pack cache's
fingerprints — fast, and collision-safe at these set sizes.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Optional

import numpy as np

from wormhole_tpu.utils.checkpoint import atomic_savez, part_name

_DIGEST_SIZE = 12


class TornSnapshot(Exception):
    """A part file did not match its manifest digest: the set was
    updated between the manifest read and the part read. Re-read the
    manifest and retry — the new one names the replacement file."""


def manifest_path(base: str) -> str:
    return base + "_MANIFEST.json"


def blob_digest(blob: bytes) -> str:
    return hashlib.blake2b(blob, digest_size=_DIGEST_SIZE).hexdigest()


def file_digest(path: str) -> str:
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    with open(path, "rb") as f:
        while chunk := f.read(1 << 20):
            h.update(chunk)
    return h.hexdigest()


def read_manifest(base: str) -> Optional[dict]:
    """Parse the manifest, or None when absent/corrupt (a crash between
    the lockfile and the rename can't corrupt it — the write is atomic —
    but a reader must survive a hand-edited or truncated file)."""
    try:
        with open(manifest_path(base), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def complete(man: Optional[dict]) -> bool:
    """True when every rank of the writing world has an entry."""
    return bool(man) and len(man.get("parts", {})) >= int(man.get("world", 0))


def _locked_commit(base: str, world: int, fold) -> int:
    """One flock'd read-modify-write manifest cycle: `fold(parts)`
    mutates the part map in place, then the whole manifest is replaced
    atomically with `version` bumped ONCE. A world change resets the
    part set — mixed-world entries must never coexist, or a reader
    would concatenate incompatible shards."""
    import fcntl

    mpath = manifest_path(base)
    with open(mpath + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        man = read_manifest(base) or {}
        if int(man.get("world", world)) != world:
            man = {}
        version = int(man.get("version", 0)) + 1
        parts = man.get("parts", {})
        full_rows = fold(parts)
        man = {"version": version, "world": int(world), "parts": parts,
               "full_rows": {k: int(v) for k, v in (full_rows or {}).items()}}
        tmp = f"{mpath}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(man, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, mpath)
    return version


def update_manifest(base: str, rank: int, world: int, path: str,
                    clock: int, epoch: int, full_rows: dict,
                    digest: Optional[str] = None) -> int:
    """Fold one shard's freshly written part into the manifest and bump
    `version`; returns the new version. Concurrent shard processes
    serialize on an flock'd sidecar (the manifest itself is replaced
    atomically, so the lock only orders read-modify-write cycles)."""
    if digest is None:
        digest = file_digest(path)

    def fold(parts: dict) -> dict:
        parts[str(rank)] = {
            "file": os.path.basename(path),
            "digest": digest,
            "clock": int(clock),
            "epoch": int(epoch),
        }
        return full_rows

    return _locked_commit(base, world, fold)


def commit_manifest_set(base: str, world: int, entries: dict,
                        full_rows: dict) -> int:
    """Publish a FULL part set in ONE manifest cycle — `entries` maps
    every rank to its part entry dict (file/digest/clock/epoch). Unlike
    world per-part `update_manifest` calls, no intermediate manifest
    ever pairs a new part with a stale one, so a watcher can never
    adopt (and stamp a version on) a cross-part-torn set. This is the
    commit `write_snapshot_set` uses; ps_server keeps per-part updates
    because its shards genuinely snapshot on independent cadences."""
    if sorted(entries) != list(range(world)):
        raise ValueError(f"entries must cover ranks 0..{world - 1}, "
                         f"got {sorted(entries)}")

    def fold(parts: dict) -> dict:
        parts.clear()
        for r, e in entries.items():
            parts[str(r)] = dict(e)
        return full_rows

    return _locked_commit(base, world, fold)


def read_part(base: str, man: dict, rank: int) -> dict[str, np.ndarray]:
    """One part's arrays, digest-verified against the manifest. The file
    is slurped once and both hashed and parsed from that buffer, so the
    verified bytes ARE the loaded bytes even if the file is replaced
    between the two."""
    entry = man["parts"].get(str(rank))
    if entry is None:
        raise TornSnapshot(f"manifest names no part for rank {rank}")
    path = os.path.join(os.path.dirname(base) or ".", entry["file"])
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise TornSnapshot(f"part {path} unreadable: {e}") from e
    if blob_digest(blob) != entry["digest"]:
        raise TornSnapshot(f"part {path} does not match its manifest "
                           "digest (replaced mid-read?)")
    return dict(np.load(io.BytesIO(blob)))


def shard_range(n: int, rank: int, world: int) -> tuple[int, int]:
    """The same even split ps_server/checkpoint use (duplicated here so
    utils never imports the runtime package)."""
    return n * rank // world, n * (rank + 1) // world


def load_slices(base: str, want: dict[str, tuple[int, int]],
                manifest: Optional[dict] = None) -> tuple[dict, dict]:
    """Load row ranges `{table: (lo, hi)}` of the full (concatenated)
    tables from a manifest-described snapshot set, reading only the
    parts that overlap each range. Returns `(tables, meta)` where meta
    carries the manifest version and the max part clock/epoch. Raises
    `TornSnapshot` when a part fails digest verification and
    FileNotFoundError when no complete manifest exists."""
    man = manifest if manifest is not None else read_manifest(base)
    if not complete(man):
        raise FileNotFoundError(f"no complete snapshot manifest at "
                                f"{manifest_path(base)}")
    world = int(man["world"])
    full_rows = {k: int(v) for k, v in man.get("full_rows", {}).items()}
    loaded: dict[int, dict] = {}

    def part(rank: int) -> dict:
        if rank not in loaded:
            loaded[rank] = read_part(base, man, rank)
        return loaded[rank]

    out: dict[str, np.ndarray] = {}
    for t, (lo, hi) in want.items():
        rows = full_rows.get(t)
        if rows is None:
            raise KeyError(f"table {t!r} not in snapshot manifest "
                           f"(has {sorted(full_rows)})")
        pieces = []
        for r in range(world):
            plo, phi = shard_range(rows, r, world)
            if phi <= lo or plo >= hi:
                continue
            a = part(r)[t]
            pieces.append(a[max(lo, plo) - plo:min(hi, phi) - plo])
        out[t] = pieces[0] if len(pieces) == 1 else np.concatenate(pieces)
    meta = {
        "version": int(man["version"]),
        "world": world,
        "full_rows": full_rows,
        "clock": max(int(p["clock"]) for p in man["parts"].values()),
        "epoch": max(int(p["epoch"]) for p in man["parts"].values()),
    }
    return out, meta


def write_snapshot_set(base: str, tables: dict[str, np.ndarray],
                       world: int = 1, clock: int = 0, epoch: int = 0,
                       compressed: bool = True) -> int:
    """Write a full snapshot set (parts + manifest) from in-memory full
    tables — the producer side of the ps_server snapshot format, for
    tools/serve_lab, benches, and tests that need a model on disk
    without running a training job. All parts land on disk first, then
    ONE manifest commit publishes the whole set (+1 version bump) — a
    reader mid-window either sees the old manifest (whose digests flag
    the replaced files as TornSnapshot, so it retries) or the new set,
    never a mix. Returns the committed version."""
    os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
    full_rows = {k: int(v.shape[0]) for k, v in tables.items()}
    entries = {}
    for r in range(world):
        arrays = {}
        for k, v in tables.items():
            lo, hi = shard_range(full_rows[k], r, world)
            arrays[k] = np.ascontiguousarray(v[lo:hi], np.float32)
        meta = {"clock": int(clock), "epoch": int(epoch), "world": world,
                "full_rows": full_rows, "derived": {}, "last_seq": {},
                "full_shapes": {k: list(v.shape) for k, v in tables.items()},
                "zero_flags": None}
        arrays["__snap__"] = np.frombuffer(
            json.dumps(meta).encode(), np.uint8).copy()
        path = part_name(base, None, r) + ".npz"
        atomic_savez(path, compressed=compressed, **arrays)
        entries[r] = {"file": os.path.basename(path),
                      "digest": file_digest(path),
                      "clock": int(clock), "epoch": int(epoch)}
    return commit_manifest_set(base, world, entries, full_rows)
