"""Model checkpoint save/load with the reference's part-file naming.

Parity with reference iter_solver.h:99-119: each server writes its model
shard to `<base>[_iter-K]_part-<rank>`; load concatenates all parts. Here
"rank" is the model-axis shard index of each KVStore table, so a
checkpoint written on an N-shard mesh can be read back on any mesh (parts
are concatenated on the bucket axis). Arrays are stored as one .npz per
part. Solver-level resume (load_iter / save_iter, minibatch_solver.h:
97-133) builds on these names.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Optional

import numpy as np


def atomic_savez(path: str, compressed: bool = False, **arrays) -> None:
    """np.savez via temp file + os.replace so a crash mid-write can never
    leave a truncated checkpoint that bricks resume. Non-local URIs
    (gs:// etc) upload a complete buffer on close — object stores make
    whole-object writes atomic by nature (iter_solver.h writes model
    shards to HDFS/S3 the same way)."""
    from wormhole_tpu.data import filesys as fsys

    scheme, spath = fsys.split_scheme(path)
    if scheme == "file":
        path, scheme = spath, ""  # local branch with the scheme stripped
    if scheme:
        if not path.endswith(".npz"):
            path += ".npz"
        import io

        buf = io.BytesIO()
        (np.savez_compressed if compressed else np.savez)(buf, **arrays)
        with fsys.open_stream(path, "wb") as f:
            f.write(buf.getvalue())
        return
    tmp = path + ".tmp"
    (np.savez_compressed if compressed else np.savez)(tmp, **arrays)
    # savez appends .npz to paths without the suffix
    if not tmp.endswith(".npz"):
        tmp += ".npz"
    os.replace(tmp, path if path.endswith(".npz") else path + ".npz")


def part_name(base: str, it: Optional[int], rank: int) -> str:
    s = base
    if it is not None and it >= 0:
        s += f"_iter-{it}"
    return s + f"_part-{rank}"


def save_prefix(base: str, it: Optional[int]) -> str:
    """The `<base>[_iter-K]` prefix all part files of one save share —
    the single source of the naming contract (reference iter_solver.h:
    115-119 `_iter-K_part-R`)."""
    return part_name(base, it, 0)[: -len("_part-0")]


def save_model(store, base: str, it: Optional[int] = None) -> list[str]:
    """Write one npz per model shard (reference SaveModel task fan-out).
    A single-shard model is written as plain `<base>[_iter-K].npz` (the
    demo-conf contract); multi-shard saves use the `_part-R` fan-out.
    Stale files from a previous save with a different shard count are
    removed so a later load never concatenates mixed-generation parts."""
    os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
    prefix = save_prefix(base, it)
    for old in glob.glob(prefix + "_part-*.npz") + glob.glob(prefix + ".npz"):
        os.remove(old)
    arrays = store.to_numpy()
    nshards = store.mesh.shape.get("model", 1)
    if nshards <= 1:
        atomic_savez(prefix + ".npz", compressed=True, **arrays)
        return [prefix + ".npz"]
    out = []
    for r in range(nshards):
        shard = {}
        for k, v in arrays.items():
            n = v.shape[0]
            lo, hi = n * r // nshards, n * (r + 1) // nshards
            shard[k] = v[lo:hi]
        path = part_name(base, it, r)
        atomic_savez(path + ".npz", compressed=True, **shard)
        out.append(path + ".npz")
    return out


def load_parts(base: str, it: Optional[int] = None) -> dict[str, np.ndarray]:
    """Read a checkpoint written with any shard count — either the plain
    `<base>.npz` single file or `_part-R` files concatenated on the bucket
    axis — into full-model numpy arrays."""
    from wormhole_tpu.data import filesys as fsys

    scheme, sbase = fsys.split_scheme(base)
    if scheme == "file":
        base, scheme = sbase, ""
    prefix = save_prefix(base, it)
    if scheme:
        import io

        def load_uri(u):
            with fsys.open_stream(u, "rb") as f:
                return dict(np.load(io.BytesIO(f.read())))

        if fsys.isfile(prefix + ".npz"):
            return load_uri(prefix + ".npz")
        d, b = fsys.dirname(prefix), fsys.basename(prefix)
        paths = sorted(
            (fsys.join(d, n) for n in fsys.list_dir(d)
             if re.fullmatch(re.escape(b) + r"_part-\d+\.npz", n)),
            key=lambda p: int(re.search(r"_part-(\d+)\.npz$", p).group(1)),
        )
        if not paths:
            raise FileNotFoundError(
                f"no checkpoint matches {prefix}.npz or {prefix}_part-*")
        parts = [load_uri(p) for p in paths]
        return {k: np.concatenate([p[k] for p in parts], axis=0)
                for k in parts[0] if not k.startswith("__")}
    if os.path.exists(prefix + ".npz"):
        return {k: v for k, v in np.load(prefix + ".npz").items()
                if not k.startswith("__")}
    paths = sorted(
        glob.glob(prefix + "_part-*.npz"),
        key=lambda p: int(re.search(r"_part-(\d+)\.npz$", p).group(1)),
    )
    if not paths:
        raise FileNotFoundError(
            f"no checkpoint matches {prefix}.npz or {prefix}_part-*")
    parts = [dict(np.load(p)) for p in paths]
    # "__"-prefixed keys are per-part metadata (e.g. the server's
    # __full_rows__ tag), not model tables
    return {
        k: np.concatenate([p[k] for p in parts], axis=0)
        for k in parts[0] if not k.startswith("__")
    }


def load_model(store, base: str, it: Optional[int] = None) -> None:
    """Read a checkpoint (single file or parts) into the store."""
    store.from_numpy(load_parts(base, it))
