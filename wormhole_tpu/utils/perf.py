"""Lightweight performance instrumentation.

Parity targets (SURVEY §5 tracing/profiling):
- the reference worker accumulates per-minibatch compute time and logs
  the average plus the share of time spent outside compute ("comm
  overhead") when a workload finishes (minibatch_solver.h:246-275);
- difacto's server classifies ops (push-count / push-grad / pull) and
  logs mean latencies every N ops (difacto async_sgd.h:108-127);
- beyond parity: `maybe_trace` hooks the JAX profiler so a run can emit
  an XProf trace by setting WORMHOLE_PROFILE_DIR.

Every Perf.add is mirrored into the process-wide metrics registry
(wormhole_tpu/obs) as histogram `perf.<op>_s`, so Perf timings ride the
heartbeat-piggybacked snapshots and land in run_report.json without
callers changing anything. The local sums/counts (and their API:
snapshot/mean_ms/total/count/row) stay as the cheap in-object view the
solver and tests already use.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Optional

from wormhole_tpu.obs import metrics as _obs


class Perf:
    """Per-op-class wall-time accounting (ISGDHandle::Perf parity).

    add(op, sec) accumulates; every `log_every` recorded ops the mean
    latency per class is logged, mirroring the reference's periodic
    perf rows. Thread-safe (loader threads record alongside the main
    thread)."""

    def __init__(self, log: Optional[Callable[[str], None]] = None,
                 log_every: int = 0):
        self._sum: dict[str, float] = {}
        self._cnt: dict[str, int] = {}
        self._hists: dict[str, _obs.Histogram] = {}  # registry mirrors
        self._lock = threading.Lock()
        self._log = log
        self._log_every = log_every
        self._since_log = 0

    def add(self, op: str, sec: float) -> None:  # wormlint: thread-entry
        h = self._hists.get(op)
        if h is None:
            # double-checked: the unlocked miss re-checks under the lock
            # so two threads racing a new op share one mirror handle
            with self._lock:
                h = self._hists.get(op)
                if h is None:
                    h = self._hists[op] = _obs.REGISTRY.histogram(
                        f"perf.{op}_s")
        h.observe(sec)
        with self._lock:
            self._sum[op] = self._sum.get(op, 0.0) + sec
            self._cnt[op] = self._cnt.get(op, 0) + 1
            self._since_log += 1
            due = self._log_every and self._since_log >= self._log_every
            if due:
                self._since_log = 0
                line = self._row_locked()
        if self._log and self._log_every and due:
            self._log(line)

    @contextlib.contextmanager
    def timer(self, op: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(op, time.perf_counter() - t0)

    def snapshot(self) -> tuple[dict, dict]:
        """Consistent (sums, counts) copies taken under the lock."""
        with self._lock:
            return dict(self._sum), dict(self._cnt)

    def mean_ms(self, op: str) -> float:
        with self._lock:
            c = self._cnt.get(op, 0)
            return 1e3 * self._sum.get(op, 0.0) / c if c else 0.0

    def total(self, op: str) -> float:
        with self._lock:
            return self._sum.get(op, 0.0)

    def count(self, op: str) -> int:
        with self._lock:
            return self._cnt.get(op, 0)

    def _row_locked(self) -> str:
        parts = [f"{op} {1e3 * self._sum[op] / self._cnt[op]:.2f}ms"
                 f"x{self._cnt[op]}"
                 for op in sorted(self._sum)]
        return "perf: " + "  ".join(parts)

    def row(self) -> str:
        with self._lock:
            return self._row_locked()


@contextlib.contextmanager
def maybe_trace(label: str = "run"):
    """Wrap a region in a JAX profiler trace when WORMHOLE_PROFILE_DIR is
    set; no-op (and no jax import) otherwise."""
    out = os.environ.get("WORMHOLE_PROFILE_DIR")
    if not out:
        yield
        return
    import jax

    os.makedirs(out, exist_ok=True)
    with jax.profiler.trace(out):
        yield
