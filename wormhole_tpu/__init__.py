"""wormhole-tpu: a TPU-native distributed machine-learning framework.

Capabilities mirror DMLC Wormhole (reference: mstebelev/wormhole): sparse
linear models (SGD/AdaGrad/FTRL), the DiFacto factorization machine, k-means,
distributed L-BFGS/OWL-QN, and histogram GBDT — redesigned for TPU:

- model/optimizer state lives as named-sharded jax Arrays in HBM (the
  "parameter server" of ps-lite becomes a hashed, mesh-sharded table);
- gradient aggregation and parameter exchange are XLA collectives (psum /
  all-gather / reduce-scatter) over ICI/DCN under jit/shard_map, replacing
  rabit allreduce and zmq push/pull;
- sparse feature-matrix x weight products compile to XLA segment ops and
  Pallas kernels;
- the host side (data parsing, workload scheduling, minibatch streaming)
  keeps Wormhole's architecture: parsers, MinibatchIter, WorkloadPool,
  scheduler/worker harness — with the hot parsing path in native C++.

See SURVEY.md for the reference structural analysis this build follows.
"""

__version__ = "0.1.0"

import os as _os


def _honor_jax_platforms_env() -> None:
    """Make the JAX_PLATFORMS env var effective even on images whose
    sitecustomize registers a TPU plugin AND calls
    jax.config.update("jax_platforms", ...) at interpreter startup — the
    explicit config value silently outranks the env var, so a launcher
    subprocess spawned with JAX_PLATFORMS=cpu would still initialize the
    TPU backend (and on this image funnel every device transfer through
    the one-chip relay). Apps import wormhole_tpu before touching any
    backend, so re-aligning the config here is safe and cheap."""
    want = _os.environ.get("JAX_PLATFORMS")
    if not want or "axon" in want:
        return  # default TPU path: leave the plugin's selection alone
    try:
        import jax

        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass  # no jax / backends already initialized: nothing to fix


_honor_jax_platforms_env()


def _install_stackdump() -> None:
    """WORMHOLE_STACKDUMP=1: dump all-thread Python stacks to stderr on
    SIGUSR1 — the only way to see where a launcher-spawned role process
    is stuck on boxes without gdb/py-spy (used to diagnose the r3 PS
    bench stall)."""
    if _os.environ.get("WORMHOLE_STACKDUMP") != "1":
        return
    try:
        import faulthandler
        import signal

        faulthandler.register(signal.SIGUSR1, all_threads=True)
    except (ImportError, AttributeError, ValueError):
        pass  # non-main thread / platform without SIGUSR1


_install_stackdump()


def _arm_wormsan() -> bool:
    """WH_SAN=1: install the runtime concurrency sanitizer
    (tools/wormsan) before any submodule import creates a lock, so every
    ``threading.Lock``/``RLock`` in the process is wrapped.  Class
    instrumentation (the lockset race detector over wormlint's
    shared-state model) is deferred to after this package finishes
    importing — instrumenting imports the model's modules, which would
    re-enter a half-initialized wormhole_tpu."""
    if _os.environ.get("WH_SAN") != "1":
        return False
    try:
        from tools import wormsan
    except ImportError:
        import sys as _sys

        _sys.stderr.write("[wormsan] WH_SAN=1 but tools.wormsan is not "
                          "importable (run from the repo root)\n")
        return False
    wormsan.install(instrument=False)
    return True


_WORMSAN_ARMED = _arm_wormsan()

from wormhole_tpu.data.rowblock import RowBlock, DeviceBatch  # noqa: F401

if _WORMSAN_ARMED:
    from tools import wormsan as _wormsan

    _wormsan.instrument_classes()
