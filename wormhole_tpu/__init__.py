"""wormhole-tpu: a TPU-native distributed machine-learning framework.

Capabilities mirror DMLC Wormhole (reference: mstebelev/wormhole): sparse
linear models (SGD/AdaGrad/FTRL), the DiFacto factorization machine, k-means,
distributed L-BFGS/OWL-QN, and histogram GBDT — redesigned for TPU:

- model/optimizer state lives as named-sharded jax Arrays in HBM (the
  "parameter server" of ps-lite becomes a hashed, mesh-sharded table);
- gradient aggregation and parameter exchange are XLA collectives (psum /
  all-gather / reduce-scatter) over ICI/DCN under jit/shard_map, replacing
  rabit allreduce and zmq push/pull;
- sparse feature-matrix x weight products compile to XLA segment ops and
  Pallas kernels;
- the host side (data parsing, workload scheduling, minibatch streaming)
  keeps Wormhole's architecture: parsers, MinibatchIter, WorkloadPool,
  scheduler/worker harness — with the hot parsing path in native C++.

See SURVEY.md for the reference structural analysis this build follows.
"""

__version__ = "0.1.0"

from wormhole_tpu.data.rowblock import RowBlock, DeviceBatch  # noqa: F401
