"""ctypes bindings for the native parsing core.

The reference's data path is native C++ (learn/base/*_parser.h over
dmlc-core's parser machinery); this package is its equivalent: a small
C++ shared library (`src/parsers.cc`) built with plain g++ and bound via
ctypes (no pybind11 in the image). The Python parsers in
wormhole_tpu/data/parsers.py stay the reference implementation and the
fallback — `tests/test_native.py` cross-checks the two bit-for-bit.

The library is built lazily on first use (`make -C wormhole_tpu/native`);
set WORMHOLE_NO_NATIVE=1 to force the pure-Python path, or
WORMHOLE_NATIVE_LIB=/path/to/lib.so to load a specific build — that is
how the sanitizer CI job runs the suite against the asan/tsan/ubsan
targets of the Makefile (the race/memory checking the reference never
had, SURVEY §5).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libwormhole_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _stale() -> bool:
    """True when the built library predates any native source file — a
    stale .so from an older checkout lacks newer symbols and must be
    rebuilt rather than dlopened."""
    if not os.path.exists(_SO):
        return True
    so_m = os.path.getmtime(_SO)
    srcdir = os.path.join(_DIR, "src")
    for name in os.listdir(srcdir):
        if os.path.getmtime(os.path.join(srcdir, name)) > so_m:
            return True
    return False


def _build() -> bool:
    """Compile to a per-process temp name, then os.replace into place, so
    concurrent first-use builds (multi-process launches on a shared
    filesystem) can never dlopen a half-written .so."""
    tmp = f"libwormhole_native.{os.getpid()}.tmp.so"
    try:
        r = subprocess.run(
            ["make", "-C", _DIR, "-s", f"OUT={tmp}"],
            capture_output=True, timeout=120)
        if r.returncode != 0:
            return False
        os.replace(os.path.join(_DIR, tmp), _SO)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        try:
            os.remove(os.path.join(_DIR, tmp))
        except OSError:
            pass


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.wh_parse.restype = ctypes.c_void_p
    lib.wh_parse.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                             ctypes.c_int64]
    lib.wh_rb_size.restype = ctypes.c_int64
    lib.wh_rb_size.argtypes = [ctypes.c_void_p]
    lib.wh_rb_nnz.restype = ctypes.c_int64
    lib.wh_rb_nnz.argtypes = [ctypes.c_void_p]
    lib.wh_rb_has_value.restype = ctypes.c_int
    lib.wh_rb_has_value.argtypes = [ctypes.c_void_p]
    lib.wh_rb_error.restype = ctypes.c_int64
    lib.wh_rb_error.argtypes = [ctypes.c_void_p]
    lib.wh_rb_copy.restype = None
    lib.wh_rb_copy.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 4
    lib.wh_rb_free.restype = None
    lib.wh_rb_free.argtypes = [ctypes.c_void_p]
    lib.wh_cityhash64.restype = ctypes.c_uint64
    lib.wh_cityhash64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if os.environ.get("WORMHOLE_NO_NATIVE"):
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        override = os.environ.get("WORMHOLE_NATIVE_LIB")
        if override:
            # an explicit override must fail LOUDLY: silently returning
            # None would make every native test skip and a sanitizer CI
            # job pass while testing nothing
            try:
                _lib = _bind(ctypes.CDLL(override))
            except (OSError, AttributeError) as e:
                raise RuntimeError(
                    f"WORMHOLE_NATIVE_LIB={override!r} failed to load or "
                    f"is missing symbols: {e}") from e
            return _lib
        if _stale() and not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


_FORMATS = {"libsvm", "criteo", "criteo_test", "adfea"}


def parse_text(text: str, fmt: str):
    """Native parse of a text chunk -> RowBlock; None when the native path
    can't serve this request (lib missing or unknown format)."""
    lib = get_lib()
    if lib is None or fmt not in _FORMATS:
        return None
    from wormhole_tpu.data.rowblock import RowBlock

    data = text.encode("utf-8")
    h = lib.wh_parse(fmt.encode(), data, len(data))
    if not h:
        return None
    try:
        err = lib.wh_rb_error(h)
        if err >= 0:
            raise ValueError(
                f"malformed {fmt} input at row {err} (native parser)")
        n = lib.wh_rb_size(h)
        nnz = lib.wh_rb_nnz(h)
        has_val = bool(lib.wh_rb_has_value(h))
        label = np.empty(n, np.float32)
        offset = np.empty(n + 1, np.int64)
        index = np.empty(nnz, np.uint64)
        value = np.empty(nnz, np.float32) if has_val else None
        lib.wh_rb_copy(
            h,
            label.ctypes.data_as(ctypes.c_void_p),
            offset.ctypes.data_as(ctypes.c_void_p),
            index.ctypes.data_as(ctypes.c_void_p),
            value.ctypes.data_as(ctypes.c_void_p) if has_val else None,
        )
        return RowBlock(label=label, offset=offset, index=index, value=value)
    finally:
        lib.wh_rb_free(h)


def cityhash64(data) -> int:
    """Native CityHash64; falls back to the Python implementation."""
    lib = get_lib()
    s = data.encode() if isinstance(data, str) else bytes(data)
    if lib is None:
        from wormhole_tpu.ops.hashing import cityhash64 as py

        return py(s)
    return int(lib.wh_cityhash64(s, len(s)))


def radix_argsort(keys):
    """Stable argsort of uint32/uint64 keys via the native LSD radix sort;
    returns int32 order, or None when the native path is unavailable
    (callers fall back to np.argsort)."""
    lib = get_lib()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys)
    n = keys.shape[0]
    if n >= 2 ** 31:
        return None
    out = np.empty(n, np.int32)
    if keys.dtype == np.uint32:
        fn = lib.wh_argsort_u32
    elif keys.dtype == np.uint64:
        fn = lib.wh_argsort_u64
    elif keys.dtype == np.int32 and (n == 0 or keys.min() >= 0):
        keys = keys.view(np.uint32)
        fn = lib.wh_argsort_u32
    elif keys.dtype == np.int64 and (n == 0 or keys.min() >= 0):
        keys = keys.view(np.uint64)
        fn = lib.wh_argsort_u64
    else:
        return None
    fn.restype = None
    fn(keys.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(n),
       out.ctypes.data_as(ctypes.c_void_p))
    return out


def gather(src, order):
    """out[i] = src[order[i]] via the parallel native core for 4/8-byte
    element types; None when unavailable (callers use numpy indexing)."""
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(src)
    if src.ndim != 1 or len(order) >= 2 ** 31 or src.shape[0] >= 2 ** 31:
        return None  # int32 index domain only; callers fall back to numpy
    order = np.ascontiguousarray(order, dtype=np.int32)
    n = order.shape[0]
    if src.dtype.itemsize == 4:
        fn = lib.wh_gather_32
    elif src.dtype.itemsize == 8:
        fn = lib.wh_gather_64
    else:
        return None
    out = np.empty(n, src.dtype)
    fn.restype = None
    fn(src.ctypes.data_as(ctypes.c_void_p),
       order.ctypes.data_as(ctypes.c_void_p), ctypes.c_int64(n),
       out.ctypes.data_as(ctypes.c_void_p))
    return out
