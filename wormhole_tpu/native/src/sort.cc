// Radix sorts for the hot host-side packing path.
//
// The Pallas COO pack (ops/coo_kernels.pack_sorted_coo) and the
// Localizer (ops/localizer.py) argsort each minibatch's bucket ids —
// ~640k keys at Criteo shape. numpy's comparison argsort costs ~45 ms
// there; an LSD radix pass over 32-bit keys is ~5-8x faster, keeping
// the loader pipeline ahead of a ~2.5M-examples/sec device. This plays
// the role of the reference's parallel_sort.h (learn/base/
// parallel_sort.h) in its Localizer hot path.

#include <cstdint>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

// Parallel LSD radix argsort, 8 bits per pass (stable): each thread
// histograms its contiguous chunk, a (bucket-major, thread-minor)
// prefix assigns disjoint output ranges, then each thread places its
// chunk — the classic parallel counting sort, the analog of the
// reference's thread-recursive parallel_sort.h.
template <typename K>
void radix_argsort(const K* keys, int64_t n, int32_t* out) {
  constexpr int kBits = 8;
  constexpr int kBuckets = 1 << kBits;
  constexpr int kPasses = static_cast<int>(sizeof(K));
#ifdef _OPENMP
  const int nt = n > (1 << 16) ? omp_get_max_threads() : 1;
#else
  const int nt = 1;
#endif
  std::vector<int32_t> tmp(n);
  std::vector<K> kcur(keys, keys + n);
  std::vector<K> ktmp(n);
  for (int64_t i = 0; i < n; ++i) out[i] = static_cast<int32_t>(i);
  int32_t* src = out;
  int32_t* dst = tmp.data();
  K* ksrc = kcur.data();
  K* kdst = ktmp.data();
  std::vector<int64_t> counts(static_cast<size_t>(nt) * kBuckets);
  for (int pass = 0; pass < kPasses; ++pass) {
    const int shift = pass * kBits;
    std::memset(counts.data(), 0, counts.size() * sizeof(int64_t));
#pragma omp parallel for num_threads(nt) schedule(static)
    for (int t = 0; t < nt; ++t) {
      const int64_t lo = n * t / nt, hi = n * (t + 1) / nt;
      int64_t* c = counts.data() + static_cast<size_t>(t) * kBuckets;
      for (int64_t i = lo; i < hi; ++i)
        ++c[(ksrc[i] >> shift) & (kBuckets - 1)];
    }
    // skip passes whose byte is constant (common for bucket ids well
    // below 2^32)
    int nonzero = 0;
    for (int b = 0; b < kBuckets && nonzero <= 1; ++b) {
      int64_t tot = 0;
      for (int t = 0; t < nt; ++t)
        tot += counts[static_cast<size_t>(t) * kBuckets + b];
      nonzero += tot != 0;
    }
    if (nonzero <= 1) continue;
    // bucket-major, thread-minor exclusive prefix: thread t's share of
    // bucket b starts after all threads' smaller buckets and earlier
    // threads' bucket b — this preserves stability
    int64_t pos = 0;
    for (int b = 0; b < kBuckets; ++b) {
      for (int t = 0; t < nt; ++t) {
        int64_t& c = counts[static_cast<size_t>(t) * kBuckets + b];
        const int64_t cc = c;
        c = pos;
        pos += cc;
      }
    }
#pragma omp parallel for num_threads(nt) schedule(static)
    for (int t = 0; t < nt; ++t) {
      const int64_t lo = n * t / nt, hi = n * (t + 1) / nt;
      int64_t* c = counts.data() + static_cast<size_t>(t) * kBuckets;
      for (int64_t i = lo; i < hi; ++i) {
        const int64_t p = c[(ksrc[i] >> shift) & (kBuckets - 1)]++;
        dst[p] = src[i];
        kdst[p] = ksrc[i];
      }
    }
    std::swap(src, dst);
    std::swap(ksrc, kdst);
  }
  if (src != out) std::memcpy(out, src, n * sizeof(int32_t));
}

}  // namespace

namespace {

template <typename T>
void gather(const T* src, const int32_t* order, int64_t n, T* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) out[i] = src[order[i]];
}

}  // namespace

extern "C" {

void wh_argsort_u32(const uint32_t* keys, int64_t n, int32_t* out) {
  radix_argsort<uint32_t>(keys, n, out);
}

void wh_argsort_u64(const uint64_t* keys, int64_t n, int32_t* out) {
  radix_argsort<uint64_t>(keys, n, out);
}

void wh_gather_32(const uint32_t* src, const int32_t* order, int64_t n,
                  uint32_t* out) {
  gather<uint32_t>(src, order, n, out);
}

void wh_gather_64(const uint64_t* src, const int32_t* order, int64_t n,
                  uint64_t* out) {
  gather<uint64_t>(src, order, n, out);
}

}  // extern "C"
