// Native parsing core: libsvm / criteo / adfea text -> CSR row blocks.
//
// The TPU-native equivalent of the reference's C++ parsers
// (reference learn/base/criteo_parser.h, adfea_parser.h and dmlc-core's
// LibSVMParser): hand-rolled scanners over a byte buffer, ~100x the
// Python path's throughput, feeding the same RowBlock layout. Exposed as
// a C ABI for ctypes (no pybind11 in this image); semantics are kept
// bit-identical to wormhole_tpu/data/parsers.py, which remains the
// reference implementation and the fallback.

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "cityhash64.h"

namespace {

struct RowBlockBuf {
  std::vector<float> label;
  std::vector<int64_t> offset{0};
  std::vector<uint64_t> index;
  std::vector<float> value;
  bool has_val = false;
  // row index of the first malformed line, -1 if clean. The Python
  // reference parsers raise on malformed input; the ctypes wrapper turns
  // this into the same ValueError instead of silently diverging.
  int64_t error_row = -1;
};

inline bool is_space(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v' ||
         c == '\f';
}

// ---- libsvm: "label idx:val idx:val ..." --------------------------------

void parse_libsvm(const char* buf, size_t len, RowBlockBuf* out) {
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* eol = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!eol) eol = end;
    const char* q = p;
    while (q < eol && is_space(*q)) ++q;
    if (q == eol || *q == '#') {  // blank / comment line
      p = eol + 1;
      continue;
    }
    char* next = nullptr;
    float lab = strtof(q, &next);
    if (next == q) {  // non-numeric label (python float() would raise)
      out->error_row = static_cast<int64_t>(out->label.size());
      return;
    }
    q = next;
    out->label.push_back(lab);
    while (q < eol) {
      while (q < eol && is_space(*q)) ++q;
      if (q >= eol) break;
      uint64_t idx = strtoull(q, &next, 10);
      if (next == q) {  // non-numeric token (python int() would raise)
        out->error_row = static_cast<int64_t>(out->label.size()) - 1;
        return;
      }
      q = next;
      float v = 1.0f;
      if (q < eol && *q == ':') {
        // the value must start right after ':' — strtof skips leading
        // whitespace, which would silently consume the NEXT token where
        // python float('') raises on the empty value
        if (q + 1 >= eol || is_space(q[1])) {
          out->error_row = static_cast<int64_t>(out->label.size()) - 1;
          return;
        }
        v = strtof(q + 1, &next);
        // empty/garbage value, or strtof skipped past the newline into
        // the next line (python float('') would raise)
        if (next == q + 1 || next > eol) {
          out->error_row = static_cast<int64_t>(out->label.size()) - 1;
          return;
        }
        q = next;
        if (v != 1.0f) out->has_val = true;
      }
      out->index.push_back(idx);
      out->value.push_back(v);
    }
    out->offset.push_back(static_cast<int64_t>(out->index.size()));
    p = eol + 1;
  }
}

// ---- criteo: label \t I1..I13 \t C1..C26, CityHash64 field-packed -------
// key = (CityHash64(token) >> 10) | (field << 54)
// (reference learn/base/criteo_parser.h:69-82)

void parse_criteo(const char* buf, size_t len, bool has_label,
                  RowBlockBuf* out) {
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* eol = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!eol) eol = end;
    const char* line_end = eol;
    while (line_end > p && (line_end[-1] == '\r')) --line_end;
    // skip whitespace-only lines (python: `if not line.strip(): continue`)
    {
      const char* q = p;
      while (q < line_end && is_space(*q)) ++q;
      if (q == line_end) {
        p = eol + 1;
        continue;
      }
    }
    const char* q = p;
    int field = 0;
    if (has_label) {
      const char* tab =
          static_cast<const char*>(memchr(q, '\t', line_end - q));
      const char* tok_end = tab ? tab : line_end;
      // the label token must contain a non-space char inside [q, tok_end):
      // strtof skips whitespace (including the '\t' separator), so an
      // empty label field would silently read the first feature as the
      // label where python float('') raises
      const char* s = q;
      while (s < tok_end && is_space(*s)) ++s;
      char* next = nullptr;
      float lab = (s < tok_end) ? strtof(s, &next) : 0.0f;
      if (s >= tok_end || next == s) {  // python float() would raise
        out->error_row = static_cast<int64_t>(out->label.size());
        return;
      }
      // full-token consumption (python float('1abc') raises); trailing
      // whitespace is fine — python float() strips it
      while (next < tok_end && is_space(*next)) ++next;
      if (next != tok_end) {
        out->error_row = static_cast<int64_t>(out->label.size());
        return;
      }
      out->label.push_back(lab);
      q = tab ? tab + 1 : line_end;
    } else {
      out->label.push_back(0.0f);
    }
    while (q <= line_end && field < 39) {
      const char* tab =
          static_cast<const char*>(memchr(q, '\t', line_end - q));
      const char* tok_end = tab ? tab : line_end;
      if (tok_end > q) {
        uint64_t h = wormhole::CityHash64(q, tok_end - q);
        out->index.push_back((h >> 10) |
                             (static_cast<uint64_t>(field & 0x3FF) << 54));
      }
      ++field;
      if (!tab) break;
      q = tab + 1;
    }
    out->offset.push_back(static_cast<int64_t>(out->index.size()));
    p = eol + 1;
  }
}

// ---- adfea: "lineid num_features label fid:gid ..." ---------------------
// key = (fid >> 10) | ((gid & 0x3FF) << 54)
// (reference learn/base/adfea_parser.h:56-64)

void parse_adfea(const char* buf, size_t len, RowBlockBuf* out) {
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* eol = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!eol) eol = end;
    // tokenize on whitespace
    const char* q = p;
    int tok_i = 0;
    float label = 0.0f;
    bool have_label = false;
    size_t nnz_before = out->index.size();
    while (q < eol) {
      while (q < eol && is_space(*q)) ++q;
      if (q >= eol) break;
      const char* tok = q;
      while (q < eol && !is_space(*q)) ++q;
      if (tok_i == 2) {
        char* next = nullptr;
        std::string ls(tok, q - tok);
        label = strtof(ls.c_str(), &next);
        // full consumption: python float('1x') raises
        if (next == ls.c_str() || *next != '\0') {
          out->error_row = static_cast<int64_t>(out->label.size());
          return;
        }
        have_label = true;
      } else if (tok_i >= 3) {
        const char* colon =
            static_cast<const char*>(memchr(tok, ':', q - tok));
        char* next = nullptr;
        // tokens are whitespace-split, so python int() accepts exactly a
        // full run of digits — require strtoull to consume to the
        // delimiter (int('12x') raises)
        if (colon) {
          uint64_t fid = strtoull(tok, &next, 10);
          bool bad = (next != colon);
          uint64_t gid = strtoull(colon + 1, &next, 10);
          bad |= (next != q);
          if (bad) {  // python int() would raise
            out->error_row = static_cast<int64_t>(out->label.size());
            return;
          }
          out->index.push_back((fid >> 10) | ((gid & 0x3FF) << 54));
        } else {
          uint64_t fid = strtoull(tok, &next, 10);
          if (next != q) {
            out->error_row = static_cast<int64_t>(out->label.size());
            return;
          }
          out->index.push_back(fid);
        }
      }
      ++tok_i;
    }
    if (tok_i >= 3 && have_label) {
      out->label.push_back(label > 0 ? 1.0f : 0.0f);
      out->offset.push_back(static_cast<int64_t>(out->index.size()));
    } else {
      out->index.resize(nnz_before);  // drop short line (python parity)
    }
    p = eol + 1;
  }
}

}  // namespace

// ---- C ABI ---------------------------------------------------------------

extern "C" {

void* wh_parse(const char* fmt, const char* buf, int64_t len) {
  auto* out = new RowBlockBuf();
  if (strcmp(fmt, "libsvm") == 0) {
    parse_libsvm(buf, static_cast<size_t>(len), out);
  } else if (strcmp(fmt, "criteo") == 0) {
    parse_criteo(buf, static_cast<size_t>(len), true, out);
  } else if (strcmp(fmt, "criteo_test") == 0) {
    parse_criteo(buf, static_cast<size_t>(len), false, out);
  } else if (strcmp(fmt, "adfea") == 0) {
    parse_adfea(buf, static_cast<size_t>(len), out);
  } else {
    delete out;
    return nullptr;
  }
  return out;
}

int64_t wh_rb_size(void* h) {
  return static_cast<int64_t>(static_cast<RowBlockBuf*>(h)->label.size());
}

int64_t wh_rb_nnz(void* h) {
  return static_cast<int64_t>(static_cast<RowBlockBuf*>(h)->index.size());
}

int wh_rb_has_value(void* h) {
  return static_cast<RowBlockBuf*>(h)->has_val ? 1 : 0;
}

int64_t wh_rb_error(void* h) {
  return static_cast<RowBlockBuf*>(h)->error_row;
}

void wh_rb_copy(void* h, float* label, int64_t* offset, uint64_t* index,
                float* value) {
  auto* rb = static_cast<RowBlockBuf*>(h);
  memcpy(label, rb->label.data(), rb->label.size() * sizeof(float));
  memcpy(offset, rb->offset.data(), rb->offset.size() * sizeof(int64_t));
  memcpy(index, rb->index.data(), rb->index.size() * sizeof(uint64_t));
  if (value && !rb->value.empty())
    memcpy(value, rb->value.data(), rb->value.size() * sizeof(float));
}

void wh_rb_free(void* h) { delete static_cast<RowBlockBuf*>(h); }

uint64_t wh_cityhash64(const char* buf, int64_t len) {
  return wormhole::CityHash64(buf, static_cast<size_t>(len));
}

}  // extern "C"
