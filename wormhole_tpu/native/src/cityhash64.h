// CityHash64 (v1.1) — the hash the reference uses for criteo feature keys
// (reference learn/base/criteo_parser.h:69-82, built from the cityhash dep,
// reference make/deps.mk:73-83). Implemented from the public algorithm;
// cross-checked bit-for-bit against the pure-Python implementation in
// wormhole_tpu/ops/hashing.py by tests/test_native.py.
#pragma once

#include <cstdint>
#include <cstring>

namespace wormhole {

namespace detail {

inline uint64_t Fetch64(const char* p) {
  uint64_t r;
  std::memcpy(&r, p, 8);
  return r;  // little-endian hosts only (x86-64 / aarch64)
}

inline uint32_t Fetch32(const char* p) {
  uint32_t r;
  std::memcpy(&r, p, 4);
  return r;
}

inline uint64_t Rotate(uint64_t v, int s) {
  return s == 0 ? v : (v >> s) | (v << (64 - s));
}

inline uint64_t ShiftMix(uint64_t v) { return v ^ (v >> 47); }

inline uint64_t Bswap64(uint64_t v) { return __builtin_bswap64(v); }

constexpr uint64_t k0 = 0xc3a5c85c97cb3127ULL;
constexpr uint64_t k1 = 0xb492b66fbe98f273ULL;
constexpr uint64_t k2 = 0x9ae16a3b2f90404fULL;
constexpr uint64_t kMul = 0x9ddfea08eb382d69ULL;

inline uint64_t HashLen16(uint64_t u, uint64_t v, uint64_t mul) {
  uint64_t a = (u ^ v) * mul;
  a ^= a >> 47;
  uint64_t b = (v ^ a) * mul;
  b ^= b >> 47;
  return b * mul;
}

inline uint64_t Hash128to64(uint64_t u, uint64_t v) {
  return HashLen16(u, v, kMul);
}

inline uint64_t HashLen0to16(const char* s, size_t n) {
  if (n >= 8) {
    uint64_t mul = k2 + n * 2;
    uint64_t a = Fetch64(s) + k2;
    uint64_t b = Fetch64(s + n - 8);
    uint64_t c = Rotate(b, 37) * mul + a;
    uint64_t d = (Rotate(a, 25) + b) * mul;
    return HashLen16(c, d, mul);
  }
  if (n >= 4) {
    uint64_t mul = k2 + n * 2;
    uint64_t a = Fetch32(s);
    return HashLen16(n + (a << 3), Fetch32(s + n - 4), mul);
  }
  if (n > 0) {
    uint8_t a = s[0], b = s[n >> 1], c = s[n - 1];
    uint32_t y = static_cast<uint32_t>(a) + (static_cast<uint32_t>(b) << 8);
    uint32_t z = static_cast<uint32_t>(n) + (static_cast<uint32_t>(c) << 2);
    return ShiftMix(y * k2 ^ z * k0) * k2;
  }
  return k2;
}

inline uint64_t HashLen17to32(const char* s, size_t n) {
  uint64_t mul = k2 + n * 2;
  uint64_t a = Fetch64(s) * k1;
  uint64_t b = Fetch64(s + 8);
  uint64_t c = Fetch64(s + n - 8) * mul;
  uint64_t d = Fetch64(s + n - 16) * k2;
  return HashLen16(Rotate(a + b, 43) + Rotate(c, 30) + d,
                   a + Rotate(b + k2, 18) + c, mul);
}

inline uint64_t HashLen33to64(const char* s, size_t n) {
  uint64_t mul = k2 + n * 2;
  uint64_t a = Fetch64(s) * k2;
  uint64_t b = Fetch64(s + 8);
  uint64_t c = Fetch64(s + n - 24);
  uint64_t d = Fetch64(s + n - 32);
  uint64_t e = Fetch64(s + 16) * k2;
  uint64_t f = Fetch64(s + 24) * 9;
  uint64_t g = Fetch64(s + n - 8);
  uint64_t h = Fetch64(s + n - 16) * mul;
  uint64_t u = Rotate(a + g, 43) + (Rotate(b, 30) + c) * 9;
  uint64_t v = ((a + g) ^ d) + f + 1;
  uint64_t w = Bswap64((u + v) * mul) + h;
  uint64_t x = Rotate(e + f, 42) + c;
  uint64_t y = (Bswap64((v + w) * mul) + g) * mul;
  uint64_t z = e + f + c;
  a = Bswap64((x + z) * mul + y) + b;
  b = ShiftMix((z + a) * mul + d + h) * mul;
  return b + x;
}

struct U64Pair {
  uint64_t first, second;
};

inline U64Pair WeakHashLen32WithSeeds(uint64_t w, uint64_t x, uint64_t y,
                                      uint64_t z, uint64_t a, uint64_t b) {
  a += w;
  b = Rotate(b + a + z, 21);
  uint64_t c = a;
  a += x;
  a += y;
  b += Rotate(a, 44);
  return {a + z, b + c};
}

inline U64Pair WeakHashLen32WithSeeds(const char* s, uint64_t a, uint64_t b) {
  return WeakHashLen32WithSeeds(Fetch64(s), Fetch64(s + 8), Fetch64(s + 16),
                                Fetch64(s + 24), a, b);
}

}  // namespace detail

inline uint64_t CityHash64(const char* s, size_t n) {
  using namespace detail;
  if (n <= 16) return HashLen0to16(s, n);
  if (n <= 32) return HashLen17to32(s, n);
  if (n <= 64) return HashLen33to64(s, n);
  uint64_t x = Fetch64(s + n - 40);
  uint64_t y = Fetch64(s + n - 16) + Fetch64(s + n - 56);
  uint64_t z = Hash128to64(Fetch64(s + n - 48) + n, Fetch64(s + n - 24));
  U64Pair v = WeakHashLen32WithSeeds(s + n - 64, n, z);
  U64Pair w = WeakHashLen32WithSeeds(s + n - 32, y + k1, x);
  x = x * k1 + Fetch64(s);
  size_t pos = 0;
  size_t rem = (n - 1) & ~static_cast<size_t>(63);
  do {
    x = Rotate(x + y + v.first + Fetch64(s + pos + 8), 37) * k1;
    y = Rotate(y + v.second + Fetch64(s + pos + 48), 42) * k1;
    x ^= w.second;
    y += v.first + Fetch64(s + pos + 40);
    z = Rotate(z + w.first, 33) * k1;
    v = WeakHashLen32WithSeeds(s + pos, v.second * k1, x + w.first);
    w = WeakHashLen32WithSeeds(s + pos + 32, z + w.second,
                               y + Fetch64(s + pos + 16));
    uint64_t t = z;
    z = x;
    x = t;
    pos += 64;
    rem -= 64;
  } while (rem != 0);
  return Hash128to64(Hash128to64(v.first, w.first) + ShiftMix(y) * k1 + z,
                     Hash128to64(v.second, w.second) + x);
}

}  // namespace wormhole
