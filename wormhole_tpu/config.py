"""Config system: `key = value` text files with CLI override merge.

Parity with the reference's config path (learn/base/arg_parser.h:36-60):
a conf file of `key = value` lines (the reference rewrites `=` to `:` and
parses as protobuf text format) merged with later `key=value` CLI args,
args winning. Values are typed by the dataclass-style schema each learner
declares (the reference's per-app config.proto). Repeated keys accumulate
into lists (protobuf repeated-field semantics, used for e.g. multiple
`val_data` entries).
"""

from __future__ import annotations

import dataclasses
import shlex
from typing import Any, Optional, get_args, get_origin


def parse_conf_text(text: str) -> dict[str, list[str]]:
    """Parse `key = value` lines; '#' comments; repeated keys accumulate."""
    out: dict[str, list[str]] = {}
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if "=" in line:
            k, v = line.split("=", 1)
        elif ":" in line:
            k, v = line.split(":", 1)
        else:
            raise ValueError(f"bad config line: {raw!r}")
        v = v.strip()
        if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
            v = v[1:-1]
        out.setdefault(k.strip(), []).append(v)
    return out


def parse_argv(argv: list[str]) -> dict[str, list[str]]:
    """Parse `key=value` CLI tokens (reference rabit-style SetParam args and
    the PS apps' trailing-arg merge, arg_parser.h:41-44)."""
    out: dict[str, list[str]] = {}
    for tok in argv:
        if "=" not in tok:
            raise ValueError(f"expected key=value, got {tok!r}")
        k, v = tok.split("=", 1)
        out.setdefault(k.strip().lstrip("-"), []).append(v.strip())
    return out


def _convert(val: str, typ) -> Any:
    if typ is bool:
        return val.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(val)
    if typ is float:
        return float(val)
    return val


def load_config(cls, conf_file: Optional[str] = None, argv: Optional[list[str]] = None):
    """Build a dataclass config: defaults <- conf file <- CLI args."""
    merged: dict[str, list[str]] = {}
    if conf_file:
        with open(conf_file) as f:
            for k, vs in parse_conf_text(f.read()).items():
                merged[k] = vs
    if argv:
        for k, vs in parse_argv(argv).items():
            merged.setdefault(k, [])
            merged[k] = merged[k] + vs if _is_repeated(cls, k) else vs
    return apply_config(cls, merged)


def _resolve_type(typ):
    if isinstance(typ, str):  # from __future__ annotations
        typ = eval(typ, {"Optional": Optional, "list": list, "str": str,
                         "int": int, "float": float, "bool": bool})
    return typ


def _is_repeated(cls, key: str) -> bool:
    for f in dataclasses.fields(cls):
        if f.name == key:
            return get_origin(_resolve_type(f.type)) is list
    return False


def apply_config(cls, kv: dict[str, list[str]]):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    unknown = []
    for k, vs in kv.items():
        f = fields.get(k)
        if f is None:
            unknown.append(k)
            continue
        typ = _resolve_type(f.type)
        origin = get_origin(typ)
        if origin is list:
            (elem,) = get_args(typ)
            kwargs[k] = [_convert(v, elem) for v in vs]
        elif origin is not None and type(None) in get_args(typ):  # Optional[T]
            elem = [a for a in get_args(typ) if a is not type(None)][0]
            kwargs[k] = _convert(vs[-1], elem)
        else:
            kwargs[k] = _convert(vs[-1], typ)
    if unknown:
        raise ValueError(f"unknown config keys: {unknown} for {cls.__name__}")
    return cls(**kwargs)


def config_to_text(cfg) -> str:
    lines = []
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if v is None:
            continue
        if isinstance(v, list):
            lines += [f"{f.name} = {x}" for x in v]
        else:
            lines.append(f"{f.name} = {v}")
    return "\n".join(lines) + "\n"
