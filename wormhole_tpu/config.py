"""Config system: `key = value` text files with CLI override merge.

Parity with the reference's config path (learn/base/arg_parser.h:36-60):
a conf file of `key = value` lines (the reference rewrites `=` to `:` and
parses as protobuf text format) merged with later `key=value` CLI args,
args winning. Values are typed by the dataclass-style schema each learner
declares (the reference's per-app config.proto). Repeated keys accumulate
into lists (protobuf repeated-field semantics, used for e.g. multiple
`val_data` entries).
"""

from __future__ import annotations

import dataclasses
import os
import shlex
from typing import Any, Optional, get_args, get_origin


def parse_conf_text(text: str) -> dict[str, list[str]]:
    """Parse `key = value` lines; '#' comments; repeated keys accumulate."""
    out: dict[str, list[str]] = {}
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if "=" in line:
            k, v = line.split("=", 1)
        elif ":" in line:
            k, v = line.split(":", 1)
        else:
            raise ValueError(f"bad config line: {raw!r}")
        v = v.strip()
        if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
            v = v[1:-1]
        out.setdefault(k.strip(), []).append(v)
    return out


def parse_argv(argv: list[str]) -> dict[str, list[str]]:
    """Parse `key=value` CLI tokens (reference rabit-style SetParam args and
    the PS apps' trailing-arg merge, arg_parser.h:41-44)."""
    out: dict[str, list[str]] = {}
    for tok in argv:
        if "=" not in tok:
            raise ValueError(f"expected key=value, got {tok!r}")
        k, v = tok.split("=", 1)
        out.setdefault(k.strip().lstrip("-"), []).append(v.strip())
    return out


def _convert(val: str, typ) -> Any:
    if typ is bool:
        return val.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(val)
    if typ is float:
        return float(val)
    return val


def load_config(cls, conf_file: Optional[str] = None, argv: Optional[list[str]] = None):
    """Build a dataclass config: defaults <- conf file <- CLI args."""
    merged: dict[str, list[str]] = {}
    if conf_file:
        with open(conf_file) as f:
            for k, vs in parse_conf_text(f.read()).items():
                merged[k] = vs
    if argv:
        for k, vs in parse_argv(argv).items():
            merged.setdefault(k, [])
            merged[k] = merged[k] + vs if _is_repeated(cls, k) else vs
    return apply_config(cls, merged)


def _resolve_type(typ):
    if isinstance(typ, str):  # from __future__ annotations
        typ = eval(typ, {"Optional": Optional, "list": list, "str": str,
                         "int": int, "float": float, "bool": bool})
    return typ


def _is_repeated(cls, key: str) -> bool:
    for f in dataclasses.fields(cls):
        if f.name == key:
            return get_origin(_resolve_type(f.type)) is list
    return False


def apply_config(cls, kv: dict[str, list[str]]):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    unknown = []
    for k, vs in kv.items():
        f = fields.get(k)
        if f is None:
            unknown.append(k)
            continue
        typ = _resolve_type(f.type)
        origin = get_origin(typ)
        if origin is list:
            (elem,) = get_args(typ)
            kwargs[k] = [_convert(v, elem) for v in vs]
        elif origin is not None and type(None) in get_args(typ):  # Optional[T]
            elem = [a for a in get_args(typ) if a is not type(None)][0]
            kwargs[k] = _convert(vs[-1], elem)
        else:
            kwargs[k] = _convert(vs[-1], typ)
    if unknown:
        raise ValueError(f"unknown config keys: {unknown} for {cls.__name__}")
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# Environment-knob registry
#
# Every `WH_*` / `WORMHOLE_*` environment variable the codebase reads must be
# declared here (or, for tool-local knobs, in the tool that owns it) via
# declare_knob().  The registry is the single source of truth for name, type,
# default, and doc line: `tools/wormlint` statically cross-checks declarations
# against read sites, and the docs tables in docs/distributed.md /
# docs/data_pipeline.md are generated from it (knob_table_markdown, or
# `python -m tools.wormlint --knob-docs <group>`).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EnvKnob:
    """One declared environment knob."""

    name: str
    type: type
    default: Any
    doc: str
    group: str = "runtime"


KNOBS: dict[str, EnvKnob] = {}


def declare_knob(name: str, type: type, default: Any, doc: str,
                 group: str = "runtime") -> EnvKnob:
    """Register an env knob. Idempotent for identical re-declarations;
    conflicting re-declaration is a bug and raises."""
    knob = EnvKnob(name, type, default, doc, group)
    prev = KNOBS.get(name)
    if prev is not None and prev != knob:
        raise ValueError(f"env knob {name} re-declared with a different spec: "
                         f"{prev} vs {knob}")
    KNOBS[name] = knob
    return knob


def env_flag(name: str, default: bool = False) -> bool:
    """Truthy-string env read shared by all boolean knobs (the historical
    `_env_flag` helpers in ps_server/minibatch_solver now alias this)."""
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() not in ("", "0", "false", "off")


def knob_value(name: str) -> Any:
    """Typed read of a declared knob: env value converted to the declared
    type, or the declared default when unset/empty."""
    knob = KNOBS[name]
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return knob.default
    if knob.type is bool:
        return raw.lower() not in ("", "0", "false", "off")
    return knob.type(raw)


def _fmt_default(knob: EnvKnob) -> str:
    if knob.default is None:
        return "(unset)"
    if knob.type is str and knob.default == "":
        return '`""`'
    return f"`{knob.default}`"


def knob_table_markdown(group: Optional[str] = None) -> str:
    """Render the declared knobs (optionally one group) as a Markdown table."""
    rows = sorted((k for k in KNOBS.values()
                   if group is None or k.group == group),
                  key=lambda k: k.name)
    lines = ["| Knob | Type | Default | Description |",
             "| --- | --- | --- | --- |"]
    for k in rows:
        lines.append(f"| `{k.name}` | {k.type.__name__} | {_fmt_default(k)} "
                     f"| {k.doc} |")
    return "\n".join(lines)


# --- core knob declarations (grouped; tools declare their own locally) -----

# runtime topology — set by launcher/dmlc_tpu.py contract(), read at node start
declare_knob("WH_ROLE", str, None,
             "Node role (`scheduler`/`server`/`worker`); set by the launcher.",
             group="runtime")
declare_knob("WH_RANK", int, 0,
             "Rank of this node within its role group.", group="runtime")
declare_knob("WH_NUM_WORKERS", int, 1,
             "Worker count the scheduler waits for.", group="runtime")
declare_knob("WH_NUM_SERVERS", int, 1,
             "Server count the scheduler waits for.", group="runtime")
declare_knob("WH_SCHEDULER_URI", str, "",
             "host:port of the scheduler RPC endpoint.", group="runtime")
declare_knob("WH_SCHED_PORT", int, 0,
             "Pin the launcher's scheduler RPC port so outside tooling "
             "(chaos_lab serve driver, obs_top) can dial the job; 0 = "
             "ephemeral.", group="runtime")
declare_knob("WH_COORD_URI", str, "",
             "host:port of the coordination endpoint handed to nodes.",
             group="runtime")
declare_knob("WH_NODE_TIMEOUT", float, 30.0,
             "Seconds without a heartbeat before the scheduler evicts a node.",
             group="runtime")

# fault tolerance / recovery
declare_knob("WH_FAULT_SPEC", str, "",
             "Fault-injection spec (`kind:role:rank:when`, see "
             "runtime/faults.py); empty disables injection.", group="faults")
declare_knob("WH_RESTORE_EPOCH", int, 0,
             "Epoch to restore server shards from after a respawn.",
             group="faults")
declare_knob("WH_SNAPSHOT_DIR", str, "",
             "Directory for epoch-stamped PS shard snapshots; empty disables.",
             group="faults")
declare_knob("WH_PS_RETRY_SEC", float, 0.0,
             "Client-side PS reconnect window in seconds (0 = fail fast).",
             group="faults")
declare_knob("WH_RETRY_BASE_SEC", float, 0.05,
             "Initial backoff step of the unified retry policy "
             "(runtime/retry.py); each retry doubles it up to "
             "WH_RETRY_CAP_SEC, with full jitter.", group="faults")
declare_knob("WH_RETRY_CAP_SEC", float, 1.0,
             "Backoff ceiling of the unified retry policy; sleeps never "
             "exceed this (or the budget's remaining deadline).",
             group="faults")
declare_knob("WH_SCHED_RETRY_SEC", float, 0.0,
             "Client-side scheduler RPC retry window in seconds (0 = fail "
             "fast). Retried mutating ops carry a per-sender seq the "
             "scheduler's journaled reply cache deduplicates, so retries "
             "stay exactly-once across a scheduler restart. Exported "
             "automatically by the launcher when --max-scheduler-restarts "
             "is set.", group="faults")
declare_knob("WH_SCHED_JOURNAL", bool, True,
             "Write-ahead journal for the scheduler control plane under "
             "WH_SNAPSHOT_DIR (sched.journal + sched.snapshot): every "
             "state-mutating op is fsync'd before the reply is sent, and "
             "a respawned scheduler replays it to resume the job. Only "
             "active when WH_SNAPSHOT_DIR is set.", group="faults")
declare_knob("WH_SCHED_JOURNAL_COMPACT", int, 512,
             "Compact the scheduler journal into an atomic snapshot once "
             "this many records accumulated (checked at round starts, the "
             "quiescent point). 0 disables compaction.", group="faults")

# observability
declare_knob("WH_OBS_DIR", str, "",
             "Directory for trace-span JSONL and run_report.json; empty "
             "disables file output.", group="obs")
declare_knob("WH_RUN_ID", str, None,
             "Run identifier stamped into traces/reports; generated by the "
             "launcher when unset.", group="obs")
declare_knob("WH_TRACE_SAMPLE", int, 0,
             "Cross-node request-trace sampling: every Nth request / PS sync "
             "round / BSP round carries a trace context over the wire "
             "(1 = every request, 0 = off). Needs WH_OBS_DIR.", group="obs")
declare_knob("WH_OBS_SCRAPE_SEC", float, 0.0,
             "Scheduler telemetry sampler period in seconds: each tick "
             "appends the aggregated cluster snapshot to an in-memory ring "
             "(the `metrics` verb's history=1 view). 0 = off.", group="obs")
declare_knob("WH_OBS_RING", int, 120,
             "Capacity of the scheduler's metrics-snapshot ring buffer.",
             group="obs")
declare_knob("WH_OBS_SCRAPE_PORT", int, 0,
             "Prometheus text-exposition HTTP port on the scheduler "
             "(GET /metrics). 0 = off.", group="obs")
declare_knob("WH_SLO_SERVE_P99_MS", float, 500.0,
             "Serving latency SLO: p99 of serve.latency_s must stay under "
             "this many milliseconds.", group="obs")
declare_knob("WH_SLO_SERVE_ERR_BUDGET", float, 0.001,
             "Serving error SLO: failed fraction of router requests allowed "
             "before the error budget is burned.", group="obs")
declare_knob("WH_SLO_PS_RPC_P99_MS", float, 250.0,
             "PS RPC latency SLO: p99 of ps.client.rpc_s must stay under "
             "this many milliseconds.", group="obs")
declare_knob("WH_PROF", bool, False,
             "Continuous sampling profiler (obs/pyprof.py): a daemon "
             "thread samples every thread's stack at WH_PROF_HZ into "
             "folded-stack tallies. Off = no sampler thread exists.",
             group="obs")
declare_knob("WH_PROF_HZ", float, 29.0,
             "Profiler sampling rate in Hz. A prime-ish default avoids "
             "lockstep with periodic loops.", group="obs")
declare_knob("WH_PROF_BUDGET_PCT", float, 2.0,
             "Profiler overhead budget as a percent of wall time; the "
             "sampler throttles itself (skips samples) above it.",
             group="obs")
declare_knob("WH_FLIGHT", bool, False,
             "Per-node flight recorder (obs/flight.py): fixed-size rings "
             "of recent spans, overload decisions, metric snapshots, and "
             "sampled stacks, dumped to JSONL on anomaly triggers. Off = "
             "every hook is one None check.", group="obs")
declare_knob("WH_FLIGHT_RING", int, 512,
             "Flight-recorder span/hop ring capacity (records kept).",
             group="obs")
declare_knob("WH_FLIGHT_DECISIONS", int, 256,
             "Flight-recorder overload-decision ring capacity.",
             group="obs")
declare_knob("WH_FLIGHT_SNAPS", int, 16,
             "Flight-recorder metric-snapshot ring capacity (snapshots "
             "sampled at most every ~5s while records flow).", group="obs")
declare_knob("WH_FLIGHT_DIR", str, "",
             "Directory for flight-*.jsonl dumps; empty falls back to "
             "WH_OBS_DIR.", group="obs")
declare_knob("WH_FLIGHT_MIN_SEC", float, 10.0,
             "Minimum seconds between unforced flight dumps on one node "
             "(dump storms from repeated triggers are suppressed).",
             group="obs")
declare_knob("WH_SAN", bool, False,
             "Runtime concurrency sanitizer (tools/wormsan): wraps every "
             "Lock/RLock to detect lock-order cycles, blocking calls "
             "under registry-known locks, and sampled lockset races over "
             "wormlint's shared-state model. Off = nothing is patched.",
             group="obs")
declare_knob("WH_SAN_SAMPLE", int, 1,
             "Sanitizer race-detector sampling: check 1-in-N instrumented "
             "attribute writes (1 = every write; raise to cut overhead "
             "under load).", group="obs")
declare_knob("WH_SAN_DUMP_DIR", str, "",
             "Directory for san-<pid>.jsonl finding dumps; replay with "
             "`python -m tools.wormsan <dir>`. Empty = in-process and "
             "stderr reporting only.", group="obs")

# data pipeline
declare_knob("WH_PACK_CACHE", bool, False,
             "Enable the packed-batch epoch cache.", group="data")
declare_knob("WH_PACK_CACHE_DIR", str, None,
             "Disk tier directory for the pack cache; unset = memory only.",
             group="data")
declare_knob("WH_PACK_CACHE_MB", int, 512,
             "Memory-tier byte budget for the pack cache, in MiB.",
             group="data")
declare_knob("WH_NUM_LOADERS", int, None,
             "Pin the loader thread-pool size (disables adaptive sizing "
             "unless WH_ADAPTIVE_LOADERS overrides).", group="data")
declare_knob("WH_ADAPTIVE_LOADERS", bool, True,
             "Stall-driven loader pool resizing between passes (defaults on "
             "unless WH_NUM_LOADERS pins the size).", group="data")
declare_knob("WH_DEVICE_FEED", bool, True,
             "Loader-side device staging (double-buffered feed).",
             group="data")

# PS sync plane
declare_knob("WH_ASYNC_SYNC", bool, False,
             "Overlap PS push/pull with compute on a background comms thread.",
             group="ps")
declare_knob("WH_KEYCACHE", bool, False,
             "Key-list digest caching on the PS wire (resend on miss).",
             group="ps")
declare_knob("WH_PS_PLANE", str, "auto",
             "Parameter plane: 'tcp' = SyncedStore push/pull RPCs every "
             "max_delay steps, 'hot' = device-resident sharded tables with "
             "in-jit collective aggregation and the TCP servers demoted to "
             "a cold tier synced at flush barriers, 'auto' = hot when the "
             "job's workers share one process with >=2 devices.",
             group="ps")
declare_knob("WH_NET_COMPRESS", bool, False,
             "zlib-compress every PS wire frame (negotiated in hello; both "
             "ends must enable it). Meant for the hot plane's cold-tier/"
             "snapshot path and cross-pod sync, where flush frames are "
             "large and rare.", group="ps")
declare_knob("WH_WIRE", str, "raw",
             "Value encoding on the parameter wire: 'raw' f32, 'bf16' "
             "truncation, 'int8' / 'int4' absmax quantization (per-row "
             "scales for 2-D tables, per-64-element group scales for "
             "1-D). Applies to SyncedStore pushes (accumulator tables "
             "with TableSpec.wire_cap floor at bf16), PS pull replies "
             "(capped at bf16 — absolute-state refreshes need "
             "per-element relative precision — and derived tables skip "
             "the wire: the client recomputes w from the pulled z/n), "
             "and BSP allreduce chunks; negotiated in hello with "
             "legacy-bf16 fallback for old peers.", group="ps")
declare_knob("WH_WIRE_EF", bool, True,
             "Error feedback for quantized wire values: re-inject each "
             "row's quantization error the next time it ships, making "
             "int8/int4 streams unbiased over time. PS pushes get it via "
             "the SyncedStore base algebra, pulls via server-side "
             "per-sender residuals; the BSP plane quantizes statelessly "
             "regardless (cross-round residuals would break replay "
             "bit-identity). No effect under WH_WIRE=raw.", group="ps")
declare_knob("WH_WIRE_COMP", str, "",
             "Frame compression mode: '' off, 'zlib' (the WH_NET_COMPRESS "
             "codec), 'bshuf' = byte-plane shuffle + zlib-6 (groups "
             "same-significance bytes; wins on ratio and speed for float "
             "tables, and sorted index vectors additionally ship "
             "delta-encoded). Hello-negotiated: an old peer that only "
             "acks zlib gets zlib, one that acks nothing gets raw "
             "frames.",
             group="ps")

declare_knob("WH_WIRE_DEBUG", str, "",
             "Wire-codec diagnostics to stderr: '1' prints each EFQuant "
             "residual-store merge, '2' additionally prints a per-array "
             "accounting line per sent frame (name, encoding, framing, "
             "post-compression bytes) — the breakdown that attributes "
             "bytes_per_sync to individual tables.", group="ps")

declare_knob("WH_NET_MAX_INFLIGHT", int, 0,
             "Max requests a frame server (PS shard / serving shard) admits "
             "concurrently; overflow gets a structured `busy` reply the "
             "client backs off on and retries (0 = unlimited).",
             group="ps")
declare_knob("WH_DEADLINE_SHED", bool, True,
             "Shed frames whose propagated deadline expired before dispatch "
             "(the `dl` header field); off = deadlines still ride the wire "
             "but every frame is dispatched.", group="ps")
declare_knob("WH_ADMIT_AIMD", bool, False,
             "Adaptive (AIMD) admission control on frame servers: the "
             "in-flight limit walks between WH_ADMIT_MIN and WH_ADMIT_MAX "
             "driven by measured handler latency and SLO burn, instead of "
             "the fixed WH_NET_MAX_INFLIGHT bound.", group="ps")
declare_knob("WH_ADMIT_MIN", int, 4,
             "Floor of the AIMD admission limit.", group="ps")
declare_knob("WH_ADMIT_MAX", int, 256,
             "Ceiling of the AIMD admission limit (also the adaptive "
             "starting limit when WH_NET_MAX_INFLIGHT is 0).", group="ps")
declare_knob("WH_ADMIT_LATENCY_MS", float, 50.0,
             "Service-latency target of the AIMD controller: a completion "
             "window whose EWMA handler latency exceeds this multiplies "
             "the limit by WH_ADMIT_BACKOFF.", group="ps")
declare_knob("WH_ADMIT_BACKOFF", float, 0.7,
             "Multiplicative-decrease factor of the AIMD admission "
             "controller.", group="ps")

# online serving tier (wormhole_tpu/serving/)
declare_knob("WH_NUM_SERVE", int, 0,
             "Serving-shard count the launcher's --serve role group exports.",
             group="serve")
declare_knob("WH_SERVE_SNAPSHOT", str, "",
             "Snapshot base path the serving shards load and watch "
             "(default: <WH_SNAPSHOT_DIR>/srv — the trainer's PS shard "
             "snapshots).", group="serve")
declare_knob("WH_SERVE_POLL_SEC", float, 1.0,
             "Hot-swap watcher poll interval: how often a serving shard "
             "checks the snapshot manifest for a newer model version.",
             group="serve")
declare_knob("WH_SERVE_RETRY_SEC", float, 30.0,
             "Router-side retry window for a dead serving shard: how long "
             "predict fan-outs re-resolve and redial before a batch fails.",
             group="serve")
declare_knob("WH_SERVE_WIRE", str, "raw",
             "Serving reply encoding: 'raw' keeps the bit-identity "
             "contract vs the trainer's predict_batch; 'bf16' truncates "
             "fetch/score reply values (round-to-nearest-even) for half "
             "the reply bytes, relaxing scores to a documented ulp "
             "contract. Request-stamped, so retried frames replay "
             "byte-identically either way.", group="serve")
declare_knob("WH_SERVE_MODE", str, "auto",
             "Serving dataflow: 'fetch' ships weight rows to the router, "
             "'score' runs the shard-local fast path (partial margins "
             "summed router-side), 'auto' picks score whenever the "
             "scorer supports it.", group="serve")
declare_knob("WH_SERVE_BATCH_MAX", int, 64,
             "Micro-batcher round size cap: at most this many concurrent "
             "predict requests coalesce into one score fan-out.",
             group="serve")
declare_knob("WH_SERVE_BATCH_WAIT_MS", float, 0.0,
             "Micro-batcher linger: how long a round holds for more "
             "arrivals before flushing (0 = flush immediately; batching "
             "still emerges from arrivals during an executing round). "
             "Ignored while degraded mode is active.", group="serve")
declare_knob("WH_DEADLINE_MS", float, 0.0,
             "Per-request deadline the router binds around each predict "
             "batch, propagated to shards in frame headers; expired work "
             "is shed instead of computed (0 = no implicit deadline).",
             group="serve")
declare_knob("WH_HEDGE", bool, False,
             "Hedged fan-out: a shard RPC still unanswered after the "
             "rolling WH_HEDGE_QUANTILE latency gets ONE backup request "
             "on a fresh connection; the shard reply cache keeps the "
             "duplicate exactly-once.", group="serve")
declare_knob("WH_HEDGE_QUANTILE", float, 0.95,
             "Latency quantile of recent primary RPCs after which a hedge "
             "fires.", group="serve")
declare_knob("WH_HEDGE_BUDGET_PCT", float, 5.0,
             "Hedge budget: backups may add at most this percent to the "
             "primary RPC count.", group="serve")
declare_knob("WH_HEDGE_MIN_MS", float, 5.0,
             "Floor of the hedge delay, so a fast window cannot hedge "
             "aggressively enough to double load.", group="serve")
declare_knob("WH_DEGRADE", bool, True,
             "Degraded-mode serving: under sustained SLO burn the router "
             "stops the mixed-version fan-out replay and serves bounded-"
             "staleness replies stamped degraded=1, recovering when burn "
             "clears.", group="serve")
declare_knob("WH_DEGRADE_BURN", float, 5.0,
             "Burn-rate threshold (violating fraction over the SLO "
             "allowance) that arms degraded mode.", group="serve")
declare_knob("WH_DEGRADE_AFTER_SEC", float, 2.0,
             "Seconds the burn must stay above WH_DEGRADE_BURN before "
             "degraded mode activates.", group="serve")
declare_knob("WH_DEGRADE_CLEAR_SEC", float, 5.0,
             "Seconds the burn must stay clear before degraded mode "
             "deactivates.", group="serve")

# BSP allreduce plane (runtime/allreduce.py)
declare_knob("WH_BSP_STEP_TIMEOUT", float, 2.0,
             "Seconds a BSP worker blocks on one ring step before "
             "re-polling the tracker for a membership change.",
             group="bsp")
declare_knob("WH_BSP_RETRY_SEC", float, 120.0,
             "Total seconds a blocked BSP collective waits for a dead "
             "peer's respawn before failing the job.",
             group="bsp")

# elastic worker membership (tracker join/leave + launcher supervisor)
declare_knob("WH_ELASTIC", bool, False,
             "Elastic worker membership: the launcher supervises the worker "
             "set and spawns/retires workers on scheduler decisions "
             "(MembershipController or WH_ELASTIC_PLAN).", group="elastic")
declare_knob("WH_ELASTIC_SEC", float, 5.0,
             "Cadence of the scheduler's membership-controller loop (and "
             "the launcher's elastic-decision poll).", group="elastic")
declare_knob("WH_ELASTIC_MIN", int, 1,
             "Floor of the elastic worker count; the controller never "
             "shrinks below it.", group="elastic")
declare_knob("WH_ELASTIC_MAX", int, 0,
             "Ceiling of the elastic worker count (0 = twice the launch "
             "size).", group="elastic")
declare_knob("WH_ELASTIC_JOIN", bool, False,
             "Set by the launcher's elastic supervisor on workers it spawns "
             "mid-job: announce a `join` to the scheduler before taking "
             "work (internal handshake, not user-facing).", group="elastic")
declare_knob("WH_ELASTIC_PLAN", str, "",
             "Scripted membership plan `join@<sec>,leave@<sec>,...` "
             "(seconds from job start): deterministic churn for drills; "
             "empty = gauge-driven controller decisions.", group="elastic")

# kernel tuning (WORMHOLE_* block-size overrides for Pallas kernels)
declare_knob("WORMHOLE_TILE_HI", int, 512,
             "Sublanes per tile in the COO kernels.", group="kernel")
declare_knob("WORMHOLE_BLK", int, 4096,
             "Nonzeros per grid block in the COO kernels.", group="kernel")
declare_knob("WORMHOLE_FM_BLK", int, 1024,
             "FM kernel block size.", group="kernel")
declare_knob("WORMHOLE_FM_VMEM", int, 64 * 2**20,
             "FM kernel VMEM budget in bytes.", group="kernel")
declare_knob("WORMHOLE_VMEM", int, 96 * 2**20,
             "COO kernel VMEM budget in bytes.", group="kernel")
declare_knob("WORMHOLE_BLK_U", int, 1024,
             "Update-kernel block size.", group="kernel")
declare_knob("WORMHOLE_HIST_FGROUP", int, 7,
             "Features per group in the GBDT histogram kernel.",
             group="kernel")

# debug / native escape hatches
declare_knob("WORMHOLE_STACKDUMP", bool, False,
             "Install a SIGUSR1 stack-dump handler at import.", group="debug")
declare_knob("WORMHOLE_DEBUG", bool, False,
             "Verbose debug printing in the GBDT trainer.", group="debug")
declare_knob("WORMHOLE_NO_NATIVE", bool, False,
             "Skip loading the native acceleration library.", group="debug")
declare_knob("WORMHOLE_NATIVE_LIB", str, None,
             "Explicit path to the native library (overrides discovery).",
             group="debug")
declare_knob("WORMHOLE_PROFILE_DIR", str, None,
             "Directory for utils/perf.py profile dumps.", group="debug")

# tools (cross-tool knobs owned by the core registry)
declare_knob("WH_CRITEO_DIR", str, "data",
             "Criteo dataset directory for tools/criteo_kaggle_parity.py.",
             group="tools")


def config_to_text(cfg) -> str:
    lines = []
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if v is None:
            continue
        if isinstance(v, list):
            lines += [f"{f.name} = {x}" for x in v]
        else:
            lines.append(f"{f.name} = {v}")
    return "\n".join(lines) + "\n"
