"""CRB: compressed row block binary format (reader + writer).

Structural parity with reference learn/base/compressed_row_block.h +
crb_parser.h: each record is one RowBlock with every field (label / offset /
index / value / weight) compressed independently, framed by a magic number
and an index-type tag, stored in a recordio-style stream that can be split
by record for sharded reading. Codec is zlib (in the Python stdlib) rather
than LZ4 — the on-disk format is ours, only the design is parity.

Record layout (little-endian):
  u32 magic (0x57524254 'WRBT') | u32 flags | u32 num_rows |
  5 x { u64 compressed_len | bytes }   fields in order:
      label f32[n], offset i64[n+1], index u64[nnz], value f32[nnz] (may be
      empty -> binary), weight f32[n] (may be empty)
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, Optional

import numpy as np

from wormhole_tpu.data.rowblock import RowBlock

MAGIC = 0x57524254


def _pack_field(arr: Optional[np.ndarray]) -> bytes:
    raw = b"" if arr is None else np.ascontiguousarray(arr).tobytes()
    comp = zlib.compress(raw, 1)
    return struct.pack("<Q", len(comp)) + comp


def write_crb(path: str, blocks, append: bool = False) -> int:
    """Write RowBlocks as CRB records; returns #records written."""
    n = 0
    from wormhole_tpu.data import filesys as fsys

    with fsys.open_stream(path, "ab" if append else "wb") as f:
        for blk in blocks:
            rec = [struct.pack("<III", MAGIC, 0, blk.size)]
            rec.append(_pack_field(np.asarray(blk.label, np.float32)))
            rec.append(_pack_field(np.asarray(blk.offset, np.int64)))
            rec.append(_pack_field(np.asarray(blk.index, np.uint64)))
            rec.append(_pack_field(blk.value))
            rec.append(_pack_field(blk.weight))
            f.write(b"".join(rec))
            n += 1
    return n


def _read_field(f, dtype) -> Optional[np.ndarray]:
    (clen,) = struct.unpack("<Q", f.read(8))
    raw = zlib.decompress(f.read(clen))
    if not raw:
        return None
    return np.frombuffer(raw, dtype=dtype).copy()


def _read_record(f) -> Optional[RowBlock]:
    hdr = f.read(12)
    if len(hdr) < 12:
        return None
    magic, _flags, _n = struct.unpack("<III", hdr)
    if magic != MAGIC:
        raise ValueError(f"bad CRB magic {magic:#x}")
    label = _read_field(f, np.float32)
    offset = _read_field(f, np.int64)
    index = _read_field(f, np.uint64)
    value = _read_field(f, np.float32)
    weight = _read_field(f, np.float32)
    if index is None:
        index = np.zeros(0, dtype=np.uint64)
    return RowBlock(label=label, offset=offset, index=index, value=value,
                    weight=weight)


def _skip_record(f) -> bool:
    """Seek past one record without decompressing; False at EOF."""
    hdr = f.read(12)
    if len(hdr) < 12:
        return False
    magic, _flags, _n = struct.unpack("<III", hdr)
    if magic != MAGIC:
        raise ValueError(f"bad CRB magic {magic:#x}")
    for _ in range(5):
        (clen,) = struct.unpack("<Q", f.read(8))
        f.seek(clen, 1)
    return True


def read_crb(path: str, part: int = 0, num_parts: int = 1) -> Iterator[RowBlock]:
    """Stream records of (part k of n): records are dealt round-robin to
    parts (disjoint-cover contract of InputSplit); other parts' records are
    seeked over via the length prefixes, not decompressed."""
    from wormhole_tpu.data import filesys as fsys

    with fsys.open_stream(path, "rb") as f:
        i = 0
        while True:
            if i % num_parts == part:
                blk = _read_record(f)
                if blk is None:
                    return
                yield blk
            elif not _skip_record(f):
                return
            i += 1
