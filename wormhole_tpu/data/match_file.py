"""Regex file matching over a directory, URI-style.

Parity with reference learn/base/match_file.h:12-45: the pattern's
directory part is listed and the basename is applied as a POSIX regex
against entries. Works uniformly over URI schemes through data/filesys
(local fully; gs:// when the client library is present; hdfs/s3 via
register_filesystem) — the reference routes through dmlc-core
FileSystem::ListDirectory the same way.
"""

from __future__ import annotations

import re

from wormhole_tpu.data import filesys as fsys


def match_file(pattern: str) -> list[str]:
    """Return sorted files whose basename matches the regex ``pattern``'s
    basename, within its directory. A plain existing file matches itself."""
    if fsys.isfile(pattern):
        return [pattern]
    dirname = fsys.dirname(pattern) or "."
    base = fsys.basename(pattern)
    try:
        rx = re.compile(base)
    except re.error as e:
        raise ValueError(f"bad file regex {base!r}: {e}") from None
    if not fsys.isdir(dirname):
        return []
    out = [
        fsys.join(dirname, name)
        for name in fsys.list_dir(dirname)
        if rx.search(name) and fsys.isfile(fsys.join(dirname, name))
    ]
    return sorted(out)
