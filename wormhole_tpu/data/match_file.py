"""Regex file matching over a directory, URI-style.

Parity with reference learn/base/match_file.h:12-45: the pattern's directory
part is listed and the basename is applied as a POSIX regex against entries.
Works for local paths; GCS-style URIs would plug in here (the reference
supports hdfs://, s3:// through dmlc-core filesystems).
"""

from __future__ import annotations

import os
import re


def match_file(pattern: str) -> list[str]:
    """Return sorted files whose basename matches the regex ``pattern``'s
    basename, within its directory. A plain existing file matches itself."""
    if os.path.isfile(pattern):
        return [pattern]
    dirname = os.path.dirname(pattern) or "."
    base = os.path.basename(pattern)
    try:
        rx = re.compile(base)
    except re.error as e:
        raise ValueError(f"bad file regex {base!r}: {e}") from None
    if not os.path.isdir(dirname):
        return []
    out = [
        os.path.join(dirname, name)
        for name in os.listdir(dirname)
        if rx.search(name) and os.path.isfile(os.path.join(dirname, name))
    ]
    return sorted(out)
