"""Text parsers: libsvm, Criteo CTR, adfea -> RowBlock.

Python reference implementations. A native C++ parsing fast path (planned
under wormhole_tpu/native) will be cross-checked against these; until it
lands, these are the production parsers.

Format parity with the reference:
- libsvm "label idx:val ..."                 (dmlc-core LibSVMParser)
- criteo tab-separated, 13 int + 26 categorical, features hashed with
  CityHash64 and field-packed (reference learn/base/criteo_parser.h:38-88)
- adfea "lineid #feat label fid:gid ..."     (learn/base/adfea_parser.h:35-90)
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from wormhole_tpu.data.rowblock import RowBlock
from wormhole_tpu.ops.hashing import cityhash64

_M = (1 << 64) - 1


def parse_libsvm(text: str) -> RowBlock:
    labels: list[float] = []
    offsets: list[int] = [0]
    idx: list[int] = []
    val: list[float] = []
    has_val = False
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        for tok in parts[1:]:
            if ":" in tok:
                k, v = tok.split(":", 1)
                idx.append(int(k))
                v = float(v)
                val.append(v)
                if v != 1.0:
                    has_val = True
            else:
                idx.append(int(tok))
                val.append(1.0)
        offsets.append(len(idx))
    return RowBlock(
        label=np.asarray(labels, dtype=np.float32),
        offset=np.asarray(offsets, dtype=np.int64),
        index=np.asarray(idx, dtype=np.uint64),
        # binary compaction: drop the all-ones value array
        # (reference minibatch_iter.h:114-116)
        value=np.asarray(val, dtype=np.float32) if has_val else None,
    )


def _criteo_key(token: str, field: int) -> int:
    return ((cityhash64(token) >> 10) | ((field & 0x3FF) << 54)) & _M


def parse_criteo(text: str, has_label: bool = True) -> RowBlock:
    """Criteo CTR lines: label \\t I1..I13 \\t C1..C26 (train) or no label
    (test). Integer features are hashed as "<field>/<value>" is NOT the
    reference scheme — the reference hashes the raw token text and packs the
    field id into the top 10 bits (criteo_parser.h:69-82); we do the same.
    Missing fields are skipped. All features are binary (value 1)."""
    labels: list[float] = []
    offsets: list[int] = [0]
    idx: list[int] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        toks = line.rstrip("\n").split("\t")
        pos = 0
        if has_label:
            labels.append(float(toks[0]))
            pos = 1
        else:
            labels.append(0.0)
        for field, tok in enumerate(toks[pos:]):
            if field >= 39:
                break
            if tok == "":
                continue
            idx.append(_criteo_key(tok, field))
        offsets.append(len(idx))
    return RowBlock(
        label=np.asarray(labels, dtype=np.float32),
        offset=np.asarray(offsets, dtype=np.int64),
        index=np.asarray(idx, dtype=np.uint64),
        value=None,
    )


def parse_adfea(text: str) -> RowBlock:
    """adfea: "lineid num_features label fid:gid fid:gid ...". The group id
    is packed into the top 10 bits like criteo (adfea_parser.h:56-64);
    labels are 0/1 like the other parsers (adfea_parser.h emits 0/1)."""
    labels: list[float] = []
    offsets: list[int] = [0]
    idx: list[int] = []
    for line in text.splitlines():
        parts = line.split()
        if len(parts) < 3:
            continue
        labels.append(1.0 if float(parts[2]) > 0 else 0.0)
        for tok in parts[3:]:
            if ":" in tok:
                fid, gid = tok.split(":", 1)
                key = ((int(fid) >> 10) | ((int(gid) & 0x3FF) << 54)) & _M
            else:
                key = int(tok)
            idx.append(key)
        offsets.append(len(idx))
    return RowBlock(
        label=np.asarray(labels, dtype=np.float32),
        offset=np.asarray(offsets, dtype=np.int64),
        index=np.asarray(idx, dtype=np.uint64),
        value=None,
    )


_PARSERS = {
    "libsvm": lambda t: parse_libsvm(t),
    "criteo": lambda t: parse_criteo(t, has_label=True),
    "criteo_test": lambda t: parse_criteo(t, has_label=False),
    "adfea": lambda t: parse_adfea(t),
}


def parse_text(text: str, fmt: str) -> RowBlock:
    """Parse a chunk of text in the given format (dispatch parity with
    reference minibatch_iter.h:42-59). Uses the native C++ core when its
    shared library is available (wormhole_tpu/native), with these Python
    parsers as the reference implementation and fallback."""
    if fmt not in _PARSERS:
        raise ValueError(f"unknown data format: {fmt!r}")
    from wormhole_tpu import native

    blk = native.parse_text(text, fmt)
    if blk is not None:
        return blk
    return _PARSERS[fmt](text)


def iter_file_chunks(
    path: str,
    part: int = 0,
    num_parts: int = 1,
    chunk_bytes: int = 1 << 24,
) -> Iterator[str]:
    """Yield text chunks of (part k of n) of a file, split on line
    boundaries — the InputSplit contract (dmlc-core InputSplit::Create):
    a part starts at the first line beginning at-or-after its byte range
    start and ends at the first line boundary at-or-after its range end.
    `path` may be any URI data/filesys.py supports (Stream::Create
    parity)."""
    from wormhole_tpu.data import filesys as fsys

    size = fsys.getsize(path)
    begin = size * part // num_parts
    end = size * (part + 1) // num_parts
    with fsys.open_stream(path, "rb") as f:
        if begin > 0:
            f.seek(begin - 1)
            # consume the partial line belonging to the previous part
            f.readline()
        pos = f.tell()
        buf: list[bytes] = []
        buffered = 0
        while pos < end:
            line = f.readline()
            if not line:
                break
            pos = f.tell()
            buf.append(line)
            buffered += len(line)
            if buffered >= chunk_bytes:
                yield b"".join(buf).decode("utf-8", errors="replace")
                buf, buffered = [], 0
        if buf:
            yield b"".join(buf).decode("utf-8", errors="replace")
