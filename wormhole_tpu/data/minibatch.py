"""MinibatchIter: stream fixed-size RowBlock minibatches from file parts.

Parity with reference learn/base/minibatch_iter.h:
- wraps the parser in a background prefetch thread (ThreadedParser, :60)
- fixed minibatch size with carry-over across parsed chunks (:75-131)
- shuffle buffer: accumulate `shuf_buf` rows, random-permute, emit (:83-91)
- negative downsampling with label-dependent keep probability (:103-107)
- format dispatch libsvm/criteo/criteo_test/adfea/crb (:42-59)
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from wormhole_tpu.data.rowblock import RowBlock
from wormhole_tpu.data import parsers


def _iter_rowblocks(
    filename: str, part: int, num_parts: int, fmt: str
) -> Iterator[RowBlock]:
    if fmt == "crb":
        from wormhole_tpu.data import crb

        yield from crb.read_crb(filename, part, num_parts)
        return
    for chunk in parsers.iter_file_chunks(filename, part, num_parts):
        blk = parsers.parse_text(chunk, fmt)
        if blk.size:
            yield blk


#: end-of-stream marker on the ThreadedParser queue
_END = object()


class _ParserError:
    """Queue sentinel carrying a producer-thread exception to the
    consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class ThreadedParser:
    """Background prefetch over a RowBlock source (the reference's
    ThreadedParser, minibatch_iter.h:60).

    The producer thread's terminal state — end-of-stream OR an
    exception — always travels on the queue itself (`_END` /
    `_ParserError` sentinels), so a consumer blocked in `get()` is
    guaranteed a next item even when the parser dies mid-stream; the
    exception re-raises at the consumer's iteration point instead of
    the thread dying silently with the iterator parked forever."""

    def __init__(self, src, maxsize: int = 4):
        self._src = src
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that gives up if the consumer went away, so
        abandoning the iterator mid-stream can't park the producer (and
        its open file) forever."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for blk in self._src:
                if not self._put(blk):
                    return
            self._put(_END)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            self._put(_ParserError(e))

    def close(self) -> None:
        self._stop.set()

    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if item is _END:
                    return
                if isinstance(item, _ParserError):
                    raise item.exc
                yield item
        finally:
            self.close()


class MinibatchIter:
    """Iterate fixed-size minibatches over (part k of n) of one file.

    Args mirror the reference's knobs (minibatch_iter.h:20-41 +
    config surface config.proto:88-133): minibatch_size, shuf_buf rows of
    shuffling, neg_sampling keep-probability for negative examples.
    """

    def __init__(
        self,
        filename: str,
        part: int = 0,
        num_parts: int = 1,
        fmt: str = "libsvm",
        minibatch_size: int = 1024,
        shuf_buf: int = 0,
        neg_sampling: float = 1.0,
        prefetch: bool = True,
        seed: int = 0,
    ):
        self.filename = filename
        self.part = part
        self.num_parts = num_parts
        self.fmt = fmt
        self.minibatch_size = int(minibatch_size)
        self.shuf_buf = int(shuf_buf)
        self.neg_sampling = float(neg_sampling)
        self.prefetch = prefetch
        self.rng = np.random.default_rng(seed)

    # -- internal stream of raw parsed blocks, optionally prefetched --------
    def _raw_blocks(self) -> Iterator[RowBlock]:
        src = _iter_rowblocks(self.filename, self.part, self.num_parts, self.fmt)
        if not self.prefetch:
            yield from src
            return
        yield from ThreadedParser(src)

    def _transformed(self) -> Iterator[RowBlock]:
        for blk in self._raw_blocks():
            if self.neg_sampling < 1.0:
                blk = self._neg_sample(blk)
                if blk.size == 0:
                    continue
            yield blk

    def _neg_sample(self, blk: RowBlock) -> RowBlock:
        keep = (blk.label > 0) | (
            self.rng.random(blk.size) < self.neg_sampling
        )
        if keep.all():
            return blk
        rows = np.nonzero(keep)[0]
        return _take_rows(blk, rows)

    def __iter__(self) -> Iterator[RowBlock]:
        mb = self.minibatch_size
        if self.shuf_buf > 0:
            buf: list[RowBlock] = []
            buffered = 0
            for blk in self._transformed():
                buf.append(blk)
                buffered += blk.size
                if buffered >= max(self.shuf_buf, mb):
                    yield from self._drain(buf, flush=False)
                    buffered = sum(b.size for b in buf)
            if buf:
                yield from self._drain(buf, flush=True)
        else:
            # emit cursor-advanced slices of each parsed chunk; only the
            # sub-minibatch tail is carried (and concat'd) into the next
            # chunk, keeping batching O(rows) overall
            tail: Optional[RowBlock] = None
            for blk in self._transformed():
                if tail is not None and tail.size:
                    blk = RowBlock.concat([tail, blk])
                    tail = None
                pos = 0
                while blk.size - pos >= mb:
                    yield blk.slice(pos, pos + mb)
                    pos += mb
                tail = blk.slice(pos, blk.size) if pos < blk.size else None
            if tail is not None and tail.size:
                yield tail

    def _drain(self, buf: list[RowBlock], flush: bool) -> Iterator[RowBlock]:
        big = RowBlock.concat(buf)
        perm = self.rng.permutation(big.size)
        big = _take_rows(big, perm)
        mb = self.minibatch_size
        n_emit = big.size if flush else (big.size // mb) * mb
        for b in range(0, n_emit, mb):
            yield big.slice(b, min(b + mb, n_emit))
        buf.clear()
        if n_emit < big.size:
            buf.append(big.slice(n_emit, big.size))


def _take_rows(blk: RowBlock, rows: np.ndarray) -> RowBlock:
    """Gather a subset/permutation of rows into a new RowBlock."""
    lens = np.diff(blk.offset)[rows]
    offset = np.zeros(len(rows) + 1, dtype=np.int64)
    np.cumsum(lens, out=offset[1:])
    # per-row source ranges -> flat nonzero gather indices
    starts = blk.offset[rows]
    gather = np.concatenate(
        [np.arange(s, s + l, dtype=np.int64) for s, l in zip(starts, lens)]
    ) if len(rows) else np.zeros(0, dtype=np.int64)
    return RowBlock(
        label=blk.label[rows],
        offset=offset,
        index=blk.index[gather],
        value=None if blk.value is None else blk.value[gather],
        weight=None if blk.weight is None else blk.weight[rows],
    )
