from wormhole_tpu.data.rowblock import RowBlock, DeviceBatch  # noqa: F401
from wormhole_tpu.data.minibatch import MinibatchIter  # noqa: F401
from wormhole_tpu.data.match_file import match_file  # noqa: F401
