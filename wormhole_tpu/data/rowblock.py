"""RowBlock: the CSR minibatch container, and its fixed-shape device form.

Host side, a RowBlock is numpy CSR — the same batch abstraction as the
reference's ``dmlc::RowBlock<I>`` (consumed all over, e.g. reference
learn/base/spmv.h:49, learn/base/localizer.h:42). Feature ids are uint64
(hashed keys may use all 64 bits, reference learn/base/criteo_parser.h:69-82).

Device side, XLA needs static shapes, so a RowBlock is flattened into a
``DeviceBatch``: padded COO arrays of a fixed capacity (``num_rows`` rows x
``capacity`` nonzeros) with zero-valued padding. Padding entries carry
``val == 0`` and point at row ``num_rows-1`` / key 0, so they contribute
nothing to SpMV / segment-sum gradients and need no masks in the compute
path (only ``row_mask`` for per-example metrics).

This replaces the reference's dynamic-size minibatches (minibatch_iter.h)
with the fixed-capacity buffer strategy SURVEY.md §7 "hard parts" calls for.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class RowBlock:
    """CSR batch of `size` examples.

    label:  float32[size]          (0/1 or -1/+1; may be all-zero for predict)
    offset: int64[size+1]          row pointer
    index:  uint64[nnz]            feature ids (possibly hashed 64-bit keys)
    value:  float32[nnz] or None   None means binary features (all ones),
                                   matching the reference's binary compaction
                                   (minibatch_iter.h:114-116)
    weight: float32[size] or None  per-example weights
    """

    label: np.ndarray
    offset: np.ndarray
    index: np.ndarray
    value: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None

    @property
    def size(self) -> int:
        return len(self.offset) - 1

    @property
    def nnz(self) -> int:
        return int(self.offset[-1])

    def __len__(self) -> int:
        return self.size

    @property
    def nbytes(self) -> int:
        """Host footprint of the batch's arrays (cache byte budgeting)."""
        return sum(a.nbytes for a in (self.label, self.offset, self.index,
                                      self.value, self.weight)
                   if a is not None)

    def slice(self, begin: int, end: int) -> "RowBlock":
        """Zero-copy row range view (offsets are rebased)."""
        end = min(end, self.size)
        o = self.offset[begin : end + 1]
        lo, hi = int(o[0]), int(o[-1])
        return RowBlock(
            label=self.label[begin:end],
            offset=o - lo,
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
            weight=None if self.weight is None else self.weight[begin:end],
        )

    def values_or_ones(self) -> np.ndarray:
        if self.value is not None:
            return self.value
        return np.ones(self.nnz, dtype=np.float32)

    @staticmethod
    def concat(blocks: "list[RowBlock]") -> "RowBlock":
        assert blocks
        sizes = [b.size for b in blocks]
        offs = [np.asarray(b.offset, dtype=np.int64) for b in blocks]
        out_off = np.zeros(sum(sizes) + 1, dtype=np.int64)
        pos, base = 1, 0
        for o in offs:
            out_off[pos : pos + len(o) - 1] = o[1:] + base
            base += int(o[-1])
            pos += len(o) - 1
        any_val = any(b.value is not None for b in blocks)
        return RowBlock(
            label=np.concatenate([b.label for b in blocks]),
            offset=out_off,
            index=np.concatenate([b.index for b in blocks]),
            value=(
                np.concatenate([b.values_or_ones() for b in blocks])
                if any_val
                else None
            ),
            weight=(
                np.concatenate(
                    [
                        (
                            b.weight
                            if b.weight is not None
                            else np.ones(b.size, dtype=np.float32)
                        )
                        for b in blocks
                    ]
                )
                if any(b.weight is not None for b in blocks)
                else None
            ),
        )


@dataclasses.dataclass
class DeviceBatch:
    """Fixed-shape COO batch ready for the device.

    All arrays have static shapes so consecutive minibatches hit the same
    XLA executable. Built by :func:`to_device_batch`.

    seg:      int32[capacity]  row id of each nonzero (padding -> num_rows-1)
    idx:      int32[capacity]  bucket id in [0, num_buckets) (padding -> 0)
    val:      float32[capacity] feature value (padding -> 0)
    label:    float32[num_rows] (padding rows -> 0)
    row_mask: float32[num_rows] 1 for real rows, 0 for padding
    dropped_rows: examples excluded because the batch overflowed capacity
    """

    seg: np.ndarray
    idx: np.ndarray
    val: np.ndarray
    label: np.ndarray
    row_mask: np.ndarray
    dropped_rows: int = 0

    @property
    def num_rows(self) -> int:
        return len(self.label)

    @property
    def capacity(self) -> int:
        return len(self.seg)

    @property
    def nbytes(self) -> int:
        """Host footprint of the padded arrays (cache byte budgeting)."""
        return (self.seg.nbytes + self.idx.nbytes + self.val.nbytes
                + self.label.nbytes + self.row_mask.nbytes)


def bucketize(index: np.ndarray, num_buckets: int) -> np.ndarray:
    """Map raw uint64 keys to [0, num_buckets) bucket ids.

    The mod-by-capacity "hash kernel" is the reference's own escape hatch for
    bounding the key space (localizer.h:107-115 under ps::FLAGS_max_key);
    upstream hashing (criteo/adfea parsers) has already spread the keys.
    """
    return (index % np.uint64(num_buckets)).astype(np.int32)


def to_device_batch(
    blk: RowBlock,
    num_rows: int,
    capacity: int,
    num_buckets: int,
    index_map: Optional[np.ndarray] = None,
) -> DeviceBatch:
    """Pad/truncate a RowBlock into a fixed-shape DeviceBatch.

    If ``index_map`` is given it is used as the per-nonzero bucket ids
    (already localized); otherwise raw ids are bucketized mod num_buckets.
    Rows beyond ``num_rows`` are dropped. If the nonzeros overflow
    ``capacity``, the partially-represented row and everything after it are
    dropped whole (masked out) rather than trained on truncated features;
    the count is reported in ``dropped_rows`` so callers can warn.
    """
    dropped = max(blk.size - num_rows, 0)
    n = min(blk.size, num_rows)
    if blk.size > num_rows:
        blk = blk.slice(0, num_rows)
    nnz = int(blk.nnz)
    if nnz > capacity:
        # keep only rows fully contained in the first `capacity` nonzeros
        cut = int(np.searchsorted(blk.offset, capacity, side="right")) - 1
        dropped += n - cut
        n = cut
        blk = blk.slice(0, cut)
        nnz = int(blk.nnz)

    seg = np.full(capacity, max(num_rows - 1, 0), dtype=np.int32)
    idx = np.zeros(capacity, dtype=np.int32)
    val = np.zeros(capacity, dtype=np.float32)
    label = np.zeros(num_rows, dtype=np.float32)
    row_mask = np.zeros(num_rows, dtype=np.float32)

    # expand row pointers to per-nonzero segment ids
    seg_src = np.repeat(
        np.arange(n, dtype=np.int32), np.diff(blk.offset[: n + 1]).astype(np.int64)
    )
    seg[:nnz] = seg_src
    if index_map is not None:
        idx[:nnz] = index_map[:nnz]
    else:
        idx[:nnz] = bucketize(blk.index, num_buckets)
    val[:nnz] = blk.values_or_ones()
    if blk.weight is not None:
        # example weights fold into the values for linear models
        val[:nnz] *= blk.weight[seg_src]
    label[:n] = blk.label[:n]
    row_mask[:n] = 1.0
    return DeviceBatch(seg=seg, idx=idx, val=val, label=label,
                       row_mask=row_mask, dropped_rows=dropped)
