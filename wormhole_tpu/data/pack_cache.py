"""Packed-batch epoch cache: skip the host pack from epoch 2 onward.

PERF.md's round-5 verdict: at the headline linear shape the device step
is ~17 ms but each batch costs the loader ~100 ms of sort + localize
pack — and that host work is bit-identical every epoch (the pack is a
pure function of the batch bytes and the pack parameters). This module
is the tf.data-style cache (Murray et al., VLDB 2021 §3.2 "cache") for
that work: prepared batches are stored under a content/config
fingerprint and replayed on later epochs, so the loader threads feed
the device from memory (or mmap'd disk) instead of re-sorting 2.5M keys
per batch.

Two tiers:

- an in-memory tier holding the prepared objects themselves, LRU-evicted
  against a byte budget (``WH_PACK_CACHE_MB``, default 512). Consumers
  treat prepared batches as read-only (they only ``jnp.asarray`` /
  ``device_put`` them), so handing back the same object is safe and
  bit-identical by construction;
- an optional disk tier (``WH_PACK_CACHE_DIR``): each entry is one file
  written atomically (temp + ``os.replace``) and loaded mmap-backed, so
  a cache shared across runs never serves a half-written entry and a
  100-GB cache costs no RSS until batches are actually consumed.

Keying: callers build keys with :func:`fingerprint` from (file part
identity + mtime/size, batch index within the part, pack parameters,
learner pack version). A learner that cannot replay a pack bit-
identically (e.g. difacto's train pack, whose admission depends on the
evolving count mirror) declines by returning ``None`` from its
``pack_cache_token`` — the loader then simply packs as before.

Everything is default-off: no env knob set means no cache object exists
and the loader path is byte-for-byte the pre-cache code.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import logging
import os
import pickle
import tempfile
import threading
from collections import OrderedDict
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from wormhole_tpu.obs.metrics import REGISTRY

log = logging.getLogger(__name__)

#: bump when the on-disk entry format or the flatten skeleton changes
FORMAT_VERSION = 1

_MAGIC = b"WHPK%d\n" % FORMAT_VERSION

_HITS = REGISTRY.counter("pack_cache.hits")
_MISSES = REGISTRY.counter("pack_cache.misses")
_DISK_HITS = REGISTRY.counter("pack_cache.disk_hits")
_EVICTS = REGISTRY.counter("pack_cache.evictions")
_CORRUPT = REGISTRY.counter("pack_cache.corrupt")
_BYTES = REGISTRY.gauge("pack_cache.bytes")


def fingerprint(*parts) -> str:
    """Stable hex digest of a tuple of primitives / nested tuples.

    Cheap and collision-safe for cache keying; callers include every
    input that changes the pack output (file identity + mtime + size,
    batch index, pack geometry, learner pack version)."""
    h = hashlib.blake2b(repr(parts).encode(), digest_size=16)
    return h.hexdigest()


def file_stamp(path: str) -> tuple:
    """(size, mtime_ns) content stamp so an overwritten input file can
    never serve stale packs. Missing files stamp as None (remote URIs:
    the caller should fold its own version into the key instead)."""
    try:
        st = os.stat(path)
        return (st.st_size, st.st_mtime_ns)
    except OSError:
        return (None, None)


# ------------------------------------------------------- pytree plumbing
# Prepared batches are nested tuples/dataclasses of numpy arrays plus
# static metadata (SortedCOO, TileCOO, DeviceBatch, plain tuples...).
# _flatten pulls the array leaves out and leaves a picklable skeleton;
# _unflatten rebuilds the object around a fresh (possibly mmap-backed)
# leaf list. Device (jax) arrays are snapshotted to host numpy — the
# consumer re-stages them anyway.

_ARR = "__whpk_arr__"


def _flatten(obj, leaves: list) -> Any:
    if isinstance(obj, np.ndarray):
        leaves.append(obj)
        return (_ARR, len(leaves) - 1)
    if obj is None or isinstance(obj, (bool, int, float, str, bytes,
                                       np.integer, np.floating)):
        return obj
    if isinstance(obj, tuple):
        return ("__tuple__", [_flatten(x, leaves) for x in obj])
    if isinstance(obj, list):
        return ("__list__", [_flatten(x, leaves) for x in obj])
    if isinstance(obj, dict):
        return ("__dict__", [(k, _flatten(v, leaves))
                             for k, v in obj.items()])
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return ("__dc__", type(obj),
                [(f.name, _flatten(getattr(obj, f.name), leaves))
                 for f in dataclasses.fields(obj)])
    if hasattr(obj, "__array__"):  # jax.Array and friends -> host snapshot
        leaves.append(np.asarray(obj))
        return (_ARR, len(leaves) - 1)
    raise TypeError(f"pack_cache cannot serialize {type(obj)!r}")


def _unflatten(skel, leaves: list) -> Any:
    if isinstance(skel, tuple) and skel and skel[0] == _ARR:
        return leaves[skel[1]]
    if isinstance(skel, tuple) and skel and skel[0] == "__tuple__":
        return tuple(_unflatten(x, leaves) for x in skel[1])
    if isinstance(skel, tuple) and skel and skel[0] == "__list__":
        return [_unflatten(x, leaves) for x in skel[1]]
    if isinstance(skel, tuple) and skel and skel[0] == "__dict__":
        return {k: _unflatten(v, leaves) for k, v in skel[1]}
    if isinstance(skel, tuple) and skel and skel[0] == "__dc__":
        _, cls, fields = skel
        return cls(**{k: _unflatten(v, leaves) for k, v in fields})
    return skel


def nbytes_of(obj) -> int:
    """Approximate footprint of a prepared batch: the array leaves plus
    a small per-entry constant for the skeleton."""
    leaves: list = []
    _flatten(obj, leaves)
    return sum(a.nbytes for a in leaves) + 512


# ------------------------------------------------------------- disk tier
def _encode(obj) -> bytes:
    leaves: list = []
    skel = _flatten(obj, leaves)
    manifest = []
    off = 0
    for a in leaves:
        a = np.ascontiguousarray(a)
        manifest.append((str(a.dtype), a.shape, off, a.nbytes))
        off += a.nbytes
    head = pickle.dumps({"skel": skel, "manifest": manifest,
                         "data_bytes": off})
    buf = io.BytesIO()
    buf.write(_MAGIC)
    buf.write(len(head).to_bytes(8, "little"))
    buf.write(head)
    for a in leaves:
        buf.write(np.ascontiguousarray(a).tobytes())
    return buf.getvalue()


def _decode_file(path: str, mmap: bool = True):
    """Load one entry; raises on any structural damage (magic, header
    pickle, or file-size mismatch) — the caller treats that as a miss
    and deletes the file so the batch is simply repacked."""
    with open(path, "rb") as fh:
        magic = fh.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"bad pack-cache magic in {path}")
        head_len = int.from_bytes(fh.read(8), "little")
        if head_len <= 0 or head_len > 1 << 30:
            raise ValueError("implausible pack-cache header length")
        head = pickle.loads(fh.read(head_len))
        data_start = len(_MAGIC) + 8 + head_len
    expect = data_start + head["data_bytes"]
    if os.path.getsize(path) != expect:
        raise ValueError(f"truncated pack-cache entry {path}")
    leaves = []
    for dtype, shape, off, nb in head["manifest"]:
        if mmap and nb:
            a = np.memmap(path, dtype=np.dtype(dtype), mode="r",
                          offset=data_start + off, shape=tuple(shape))
        else:
            with open(path, "rb") as fh:
                fh.seek(data_start + off)
                a = np.frombuffer(fh.read(nb), dtype=np.dtype(dtype)
                                  ).reshape(tuple(shape))
        leaves.append(a)
    return _unflatten(head["skel"], leaves)


class PackCache:
    """Two-tier packed-batch cache. Thread-safe: loader threads get/put
    concurrently; the lock covers only the in-memory index, disk I/O
    runs outside it (atomic temp+rename makes concurrent same-key
    writers harmless — last rename wins with identical bytes)."""

    def __init__(self, mem_bytes: int = 512 << 20,
                 disk_dir: Optional[str] = None, mmap: bool = True):
        self.mem_bytes = int(mem_bytes)
        self.disk_dir = disk_dir
        self.mmap = mmap
        self._lock = threading.Lock()
        self._mem: OrderedDict[str, tuple[Any, int]] = OrderedDict()
        self._mem_used = 0
        self.hits = 0
        self.misses = 0
        self.disk_hits = 0
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)

    # ---------------------------------------------------------------- get
    def get(self, key: str):
        """The cached object or None. Memory first, then disk (a disk
        hit is promoted into the memory tier)."""
        with self._lock:
            got = self._mem.get(key)
            if got is not None:
                self._mem.move_to_end(key)
                self.hits += 1
                _HITS.inc()
                return got[0]
        if self.disk_dir:
            path = self._path(key)
            try:
                if os.path.exists(path):
                    obj = _decode_file(path, mmap=self.mmap)
                    with self._lock:
                        self.hits += 1
                        self.disk_hits += 1
                    _HITS.inc()
                    _DISK_HITS.inc()
                    self._mem_insert(key, obj, nbytes_of(obj))
                    return obj
            except Exception as e:
                _CORRUPT.inc()
                log.warning("pack cache: dropping corrupt entry %s (%s); "
                            "the batch will be repacked", path, e)
                try:
                    os.remove(path)
                except OSError:
                    pass
        with self._lock:
            self.misses += 1
        _MISSES.inc()
        return None

    # ---------------------------------------------------------------- put
    def put(self, key: str, obj) -> bool:
        """Insert into both tiers. Returns False (and caches nothing) if
        the object holds leaves the flattener does not understand —
        callers then just skip caching that batch."""
        try:
            nb = nbytes_of(obj)
        except TypeError as e:
            log.warning("pack cache: uncacheable batch (%s)", e)
            return False
        self._mem_insert(key, obj, nb)
        if self.disk_dir:
            path = self._path(key)
            if not os.path.exists(path):
                try:
                    blob = _encode(obj)
                    fd, tmp = tempfile.mkstemp(dir=self.disk_dir,
                                               prefix=".whpk_tmp_")
                    try:
                        with os.fdopen(fd, "wb") as fh:
                            fh.write(blob)
                        os.replace(tmp, path)  # atomic publish
                    except BaseException:
                        try:
                            os.remove(tmp)
                        except OSError:
                            pass
                        raise
                except Exception as e:
                    log.warning("pack cache: disk spill failed for %s "
                                "(%s)", key, e)
        return True

    def _mem_insert(self, key: str, obj, nb: int) -> None:
        if nb > self.mem_bytes:
            return  # larger than the whole budget: disk-tier only
        with self._lock:
            old = self._mem.pop(key, None)
            if old is not None:
                self._mem_used -= old[1]
            self._mem[key] = (obj, nb)
            self._mem_used += nb
            while self._mem_used > self.mem_bytes and self._mem:
                _, (_, enb) = self._mem.popitem(last=False)
                self._mem_used -= enb
                _EVICTS.inc()
            _BYTES.set(self._mem_used)

    def _path(self, key: str) -> str:
        return os.path.join(self.disk_dir, f"{key}.whpack")

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "disk_hits": self.disk_hits,
                "hit_rate": self.hits / total if total else 0.0,
                "mem_bytes": self._mem_used,
                "mem_entries": len(self._mem),
            }

    def clear_memory(self) -> None:
        with self._lock:
            self._mem.clear()
            self._mem_used = 0
            _BYTES.set(0)


def from_env() -> Optional[PackCache]:
    """The run's cache per env knobs, or None (the default-off path:
    no object, no code-path change). WH_PACK_CACHE=1 enables the
    in-memory tier; WH_PACK_CACHE_DIR enables (and implies) the disk
    tier; WH_PACK_CACHE_MB sizes the memory tier (default 512)."""
    disk = os.environ.get("WH_PACK_CACHE_DIR") or None
    on = os.environ.get("WH_PACK_CACHE", "").lower() not in (
        "", "0", "false", "off")
    if not on and not disk:
        return None
    mem_mb = int(os.environ.get("WH_PACK_CACHE_MB", "512"))
    return PackCache(mem_bytes=mem_mb << 20, disk_dir=disk)


# ---------------------------------------------------- whole-part replay
def iter_part_cached(cache: Optional[PackCache], part_key,
                     raw_iter_fn: Callable[[], Iterable],
                     prepare_fn: Callable[[Any], Any]) -> Iterator:
    """Iterate one file part's prepared batches through the cache.

    ``part_key`` identifies the part AND the full pack configuration
    (fingerprint input tuple); batch ``i`` lives under
    fingerprint(part_key, i) and a terminal count entry under
    fingerprint(part_key, "n") records how many batches the part
    yields. On a warm epoch the part is replayed entirely from the
    cache — the source file is never opened, no parse and no pack run.

    Degradation is per-batch: if an entry was evicted (or a disk entry
    corrupted) mid-replay, the source iterator is reopened and fast-
    forwarded — already-served batches are re-parsed but NOT re-packed
    or re-yielded — and filling resumes from the gap.

    With ``cache`` or ``part_key`` None this is exactly the uncached
    loop (the default-off path)."""
    if cache is None or part_key is None:
        for blk in raw_iter_fn():
            yield prepare_fn(blk)
        return
    start = 0
    n = cache.get(fingerprint(part_key, "n"))
    if n is not None:
        for i in range(int(n)):
            b = cache.get(fingerprint(part_key, i))
            if b is None:
                break
            yield b
            start = i + 1
        else:
            return
    count = start
    for i, blk in enumerate(raw_iter_fn()):
        if i < start:
            continue  # already served from cache before the gap
        b = prepare_fn(blk)
        cache.put(fingerprint(part_key, i), b)
        count = i + 1
        yield b
    cache.put(fingerprint(part_key, "n"), count)
