"""Uniform filesystem access over URIs (dmlc-core parity).

The reference reads data and writes models through dmlc-core's
`Stream::Create(uri)` / `FileSystem::ListDirectory(uri)`, which treat
local paths, hdfs://, and s3:// uniformly (reference
learn/base/match_file.h:12-45, solver/iter_solver.h:104-110,
doc/common/input.rst:53-115). This module is the TPU build's analog:

- local paths (and file://) are fully implemented;
- remote schemes resolve through a registry. gs:// (the TPU-native
  cloud filesystem) auto-binds when `google-cloud-storage` is
  importable, s3:// when `boto3` is; hdfs:// raises a clear error
  pointing at `register_filesystem`, matching the reference's
  compile-time USE_HDFS/USE_S3 gating (make/config.mk:24-27) — there
  the missing backend is a build flag, here it is a runtime plug-in.

Every consumer (file matching, parsers, CRB reader/writer) goes through
`open_stream` / `list_dir` / `isfile` / `getsize`, so adding a scheme in
one place makes data, model, and predict paths remote-capable at once.
"""

from __future__ import annotations

import io
import os
import re
from typing import IO, Optional, Protocol

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")


def split_scheme(uri: str) -> tuple[str, str]:
    """('gs', 'bucket/path') for gs://bucket/path; ('', path) for local."""
    m = _SCHEME_RE.match(uri)
    if not m:
        return "", uri
    return m.group(1).lower(), uri[m.end():]


class FileSystem(Protocol):
    """The dmlc FileSystem surface the framework consumes."""

    def open(self, path: str, mode: str = "rb") -> IO: ...
    def list_dir(self, path: str) -> list[str]: ...
    def isfile(self, path: str) -> bool: ...
    def isdir(self, path: str) -> bool: ...
    def getsize(self, path: str) -> int: ...


class LocalFS:
    def open(self, path: str, mode: str = "rb") -> IO:
        if "w" in mode or "a" in mode:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return open(path, mode)

    def list_dir(self, path: str) -> list[str]:
        return sorted(os.listdir(path))

    def isfile(self, path: str) -> bool:
        return os.path.isfile(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)


def _check_write_mode(scheme: str, mode: str) -> None:
    """Object stores only support whole-object replacement: an 'a'/'+'
    open silently truncate-writing (e.g. `write_crb(append=True)` onto
    an existing object) would REPLACE data the caller meant to extend —
    refuse loudly instead of corrupting (ADVICE #2)."""
    if "a" in mode or "+" in mode:
        raise NotImplementedError(
            f"{scheme}:// objects cannot be opened in {mode!r}: object "
            "stores only support whole-object writes (no append/update-"
            "in-place). Download, modify, and re-upload — or write to a "
            "new object.")


class GcsFS:
    """gs:// over google-cloud-storage (present on most TPU VMs).
    Reads download whole blobs into memory buffers (data files are
    already sharded into parts well below RAM); writes upload on close."""

    def __init__(self):
        try:
            from google.cloud import storage  # type: ignore
        except ImportError as e:
            raise ImportError(
                "gs:// paths need the google-cloud-storage package "
                "(preinstalled on Cloud TPU VMs). Install it or "
                "register_filesystem('gs', <your fs>) with a custom "
                "implementation."
            ) from e
        self._client = storage.Client()

    def _blob(self, path: str):
        bucket, _, name = path.partition("/")
        return self._client.bucket(bucket).blob(name)

    def open(self, path: str, mode: str = "rb") -> IO:
        if "r" in mode:
            data = self._blob(path).download_as_bytes()
            return io.BytesIO(data) if "b" in mode else io.StringIO(
                data.decode("utf-8", errors="replace"))
        _check_write_mode("gs", mode)
        blob = self._blob(path)

        class _Upload(io.BytesIO):
            def close(self_inner):  # noqa: N805
                blob.upload_from_string(self_inner.getvalue())
                super().close()

        buf = _Upload()
        if "b" not in mode:
            return io.TextIOWrapper(buf, encoding="utf-8")
        return buf

    def list_dir(self, path: str) -> list[str]:
        bucket, _, prefix = path.partition("/")
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        names = set()
        for b in self._client.list_blobs(bucket, prefix=prefix):
            rest = b.name[len(prefix):]
            if rest:
                names.add(rest.split("/", 1)[0])
        return sorted(names)

    def isfile(self, path: str) -> bool:
        return self._blob(path).exists()

    def isdir(self, path: str) -> bool:
        return bool(self.list_dir(path))

    def getsize(self, path: str) -> int:
        blob = self._blob(path)
        blob.reload()
        return int(blob.size)


class S3FS:
    """s3:// over boto3 (optional-import, mirroring GcsFS; reference
    reads S3 natively via dmlc-core, doc/common/input.rst:53-115).
    Reads download whole objects into memory buffers; writes upload on
    close. Credentials resolve through boto3's normal chain (env vars,
    ~/.aws, instance metadata)."""

    def __init__(self, client=None):
        if client is None:
            try:
                import boto3  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "s3:// paths need the boto3 package. Install it or "
                    "register_filesystem('s3', <your fs>) with a custom "
                    "implementation."
                ) from e
            client = boto3.client("s3")
        self._client = client

    @staticmethod
    def _split(path: str) -> tuple[str, str]:
        bucket, _, key = path.partition("/")
        return bucket, key

    def open(self, path: str, mode: str = "rb") -> IO:
        bucket, key = self._split(path)
        if "r" in mode:
            data = self._client.get_object(
                Bucket=bucket, Key=key)["Body"].read()
            return io.BytesIO(data) if "b" in mode else io.StringIO(
                data.decode("utf-8", errors="replace"))
        _check_write_mode("s3", mode)
        client = self._client

        class _Upload(io.BytesIO):
            def close(self_inner):  # noqa: N805
                client.put_object(Bucket=bucket, Key=key,
                                  Body=self_inner.getvalue())
                super().close()

        buf = _Upload()
        if "b" not in mode:
            return io.TextIOWrapper(buf, encoding="utf-8")
        return buf

    def _iter_keys(self, bucket: str, prefix: str):
        token = None
        while True:
            kw = {"Bucket": bucket, "Prefix": prefix}
            if token:
                kw["ContinuationToken"] = token
            resp = self._client.list_objects_v2(**kw)
            for obj in resp.get("Contents", []):
                yield obj
            token = resp.get("NextContinuationToken")
            if not token:
                return

    def list_dir(self, path: str) -> list[str]:
        bucket, prefix = self._split(path)
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        names = set()
        for obj in self._iter_keys(bucket, prefix):
            rest = obj["Key"][len(prefix):]
            if rest:
                names.add(rest.split("/", 1)[0])
        return sorted(names)

    def isfile(self, path: str) -> bool:
        bucket, key = self._split(path)
        try:
            self._client.head_object(Bucket=bucket, Key=key)
            return True
        except Exception as e:
            # only a definite not-found is False; credential/endpoint/
            # network failures must surface, not read as "no such file"
            code = str(getattr(e, "response", {}).get(
                "Error", {}).get("Code", ""))
            if code in ("404", "NoSuchKey", "NotFound"):
                return False
            raise

    def isdir(self, path: str) -> bool:
        return bool(self.list_dir(path))

    def getsize(self, path: str) -> int:
        bucket, key = self._split(path)
        return int(self._client.head_object(
            Bucket=bucket, Key=key)["ContentLength"])


class _UnavailableFS:
    def __init__(self, scheme: str, hint: str):
        self.scheme = scheme
        self.hint = hint

    def _raise(self, *_a, **_k):
        raise NotImplementedError(
            f"{self.scheme}:// filesystem is not bound in this build. "
            f"{self.hint} Use register_filesystem({self.scheme!r}, fs) "
            "to plug one in (the reference gates these behind "
            "USE_HDFS/USE_S3 build flags, make/config.mk:24-27).")

    open = list_dir = isfile = isdir = getsize = _raise


_REGISTRY: dict[str, object] = {}


def register_filesystem(scheme: str, fs) -> None:
    _REGISTRY[scheme.lower()] = fs


def get_filesystem(uri: str) -> tuple[object, str]:
    """Resolve a URI to (filesystem, scheme-local path)."""
    scheme, path = split_scheme(uri)
    fs = _REGISTRY.get(scheme)
    if fs is None:
        if scheme in ("", "file"):
            fs = LocalFS()
        elif scheme == "gs":
            fs = GcsFS()  # raises with guidance if the client is absent
        elif scheme == "s3":
            fs = S3FS()  # raises with guidance if boto3 is absent
        elif scheme in ("hdfs", "azure"):
            fs = _UnavailableFS(
                scheme, "On TPU, stage data to gs:// or local SSD.")
        else:
            raise ValueError(f"unknown filesystem scheme {scheme!r} "
                             f"in {uri!r}")
        _REGISTRY[scheme] = fs
    return fs, path


def open_stream(uri: str, mode: str = "rb") -> IO:
    """dmlc Stream::Create parity: open any URI for reading/writing."""
    fs, path = get_filesystem(uri)
    return fs.open(path, mode)


def list_dir(uri: str) -> list[str]:
    fs, path = get_filesystem(uri)
    return fs.list_dir(path)


def isfile(uri: str) -> bool:
    fs, path = get_filesystem(uri)
    return fs.isfile(path)


def isdir(uri: str) -> bool:
    fs, path = get_filesystem(uri)
    return fs.isdir(path)


def getsize(uri: str) -> int:
    fs, path = get_filesystem(uri)
    return fs.getsize(path)


def join(uri_dir: str, name: str) -> str:
    scheme, _ = split_scheme(uri_dir)
    if scheme:
        return uri_dir.rstrip("/") + "/" + name
    return os.path.join(uri_dir, name)


def dirname(uri: str) -> str:
    scheme, path = split_scheme(uri)
    d = os.path.dirname(path)
    return f"{scheme}://{d}" if scheme else d


def basename(uri: str) -> str:
    _, path = split_scheme(uri)
    return os.path.basename(path)
