"""Histogram gradient-boosted decision trees, TPU-native.

Parity target: the reference's distributed xgboost build — `bin/xgboost.dmlc`
run over rabit with row-split data (reference Makefile:63-72,
learn/xgboost/mushroom.hadoop.conf). The conf surface kept is exactly the
mushroom conf's: booster=gbtree, objective=binary:logistic, eta, gamma,
min_child_weight, max_depth, num_round, save_period, eval_train, dsplit=row,
plus lambda (leaf L2) and max_bin.

TPU design (vs the reference's CPU allreduce xgboost):
- features are quantile-binned once on the host into a dense uint8 matrix
  [rows, features]; rows are sharded over the mesh data axis (dsplit=row);
- tree growth is depth-wise: one jitted step per level builds the
  (node, feature, bin) gradient/hessian histograms with a flat
  segment-sum, `psum`s them over the data axis — the literal TPU analog
  of distributed xgboost's rabit::Allreduce of histograms — then scans
  cumulative G/H over bins to score every candidate split at once
  (gain = 1/2[GL^2/(HL+l) + GR^2/(HR+l) - G^2/(H+l)] - gamma) and routes
  rows to children, all with static shapes;
- trees are heap-indexed arrays (split_feat/split_bin/is_split/leaf_value)
  replicated over the mesh; prediction is a `fori_loop` of gathers scanned
  over rounds.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from wormhole_tpu.data.rowblock import RowBlock
from wormhole_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    make_mesh,
    replicated,
    shard_map,
)
from wormhole_tpu.solver.workload import iter_rowblocks


@dataclasses.dataclass
class GbdtConfig:
    """mushroom.hadoop.conf surface (names kept; `lambda` -> reg_lambda)."""

    train_data: str = ""
    eval_data: Optional[str] = None   # conf key eval[<name>] = path
    eval_name: str = "test"
    data_format: str = "libsvm"
    model_out: Optional[str] = None
    model_in: Optional[str] = None
    # xgboost CLI task surface: task=pred + test:data + name_pred
    task: str = "train"
    test_data: Optional[str] = None
    pred_out: str = "pred.txt"

    booster: str = "gbtree"
    objective: str = "binary:logistic"   # or reg:squarederror
    eta: float = 0.3
    gamma: float = 0.0
    min_child_weight: float = 1.0
    max_depth: int = 6
    reg_lambda: float = 1.0              # xgboost `lambda`
    num_round: int = 10
    save_period: int = 0
    eval_train: int = 0
    dsplit: str = "row"                  # only row split is supported
    base_score: float = 0.5

    # multi-process SPMD over one jax.distributed mesh (apps/gbdt.py
    # _global_worker_body; the reference's rabit world)
    global_mesh: bool = False
    # multi-process BSP over the native allreduce ring (apps/gbdt.py
    # _bsp_worker_body over runtime/allreduce.py): each rank keeps its
    # own local mesh and row shard; per-level histograms reduce over
    # the worker ring — the literal rabit::Allreduce of histograms,
    # fault-tolerant via version checkpoints
    bsp: bool = False
    # TPU-native knobs
    max_bin: int = 256
    dim: int = 0        # feature count; 0 = discover from data
    minibatch: int = 65536  # streaming-load chunk size
    num_parts_per_file: int = 1
    seed: int = 0
    # histogram backend: mxu (Pallas one-hot-matmul kernel,
    # ops/hist.py — ~40x faster than the scatter on TPU) | xla
    # (segment-sum scatter) | auto (mxu on TPU, xla elsewhere — the
    # interpreted kernel is too slow for CPU test loops)
    hist_kernel: str = "auto"


# ---------------------------------------------------------------------------
# host-side dataset loading + quantile binning
# ---------------------------------------------------------------------------

_SKETCH_ROWS = 1 << 17  # quantile-sketch sample cap (approx sketch parity)


class Reservoir:
    """Uniform reservoir of sparse rows over any RowBlock stream (rows
    kept as (index, value) triples so no dense matrix exists before the
    feature count is known); tracks the running max feature id."""

    def __init__(self, cap: int, seed: int):
        self.cap = max(int(cap), 1)
        self.rng = np.random.default_rng(seed)
        self.sample: list = []
        self.n_seen = 0
        self.max_feat = -1

    def add_block(self, blk: RowBlock) -> None:
        if blk.nnz:
            self.max_feat = max(self.max_feat, int(blk.index.max()))
        vals = blk.values_or_ones()
        for r in range(blk.size):
            lo, hi = blk.offset[r], blk.offset[r + 1]
            row = (blk.index[lo:hi].copy(), vals[lo:hi].copy())
            if len(self.sample) < self.cap:
                self.sample.append(row)
            else:
                # classic reservoir: keep each new row with prob cap/n
                j = self.rng.integers(0, self.n_seen + 1)
                if j < self.cap:
                    self.sample[j] = row
            self.n_seen += 1


def _reservoir_sample(pattern: str, fmt: str, num_parts_per_file: int,
                      minibatch: int, seed: int,
                      cap: int = _SKETCH_ROWS):
    """One streaming pass: reservoir-sample up to `cap` rows and
    discover the feature dimension — the global approx sketch +
    Allreduce<Max> dim discovery of xgboost without materializing the
    dataset."""
    res = Reservoir(cap, seed)
    for blk in iter_rowblocks(pattern, num_parts_per_file, fmt,
                              minibatch, node="gbdt-sketch", seed=seed):
        res.add_block(blk)
    if res.n_seen == 0:
        raise ValueError(f"no rows in {pattern}")
    return res.sample, res.n_seen, res.max_feat


def _densify_sample(sample, dim: int) -> np.ndarray:
    X = np.zeros((len(sample), dim), np.float32)
    for r, (idx, val) in enumerate(sample):
        keep = idx < dim
        X[r, idx[keep].astype(np.int64)] = val[keep]
    return X


def _densify(blk: RowBlock, dim: int) -> np.ndarray:
    """Sparse CSR rows -> dense [n, dim] float32 (absent feature = 0,
    matching xgboost's default missing=0 treatment for libsvm data)."""
    n = blk.size
    X = np.zeros((n, dim), np.float32)
    rows = np.repeat(np.arange(n), np.diff(blk.offset).astype(np.int64))
    cols = blk.index.astype(np.int64)
    keep = cols < dim
    X[rows[keep], cols[keep]] = blk.values_or_ones()[keep]
    return X


def quantile_edges(X: np.ndarray, max_bin: int) -> np.ndarray:
    """Per-feature cut points, [dim, max_bin-1], padded with +inf.

    bin(x) = searchsorted(edges, x, 'right'); few distinct values get
    midpoint cuts, many get quantile cuts — the histogram/approx sketch
    of xgboost, computed on a host sample."""
    dim = X.shape[1]
    edges = np.full((dim, max_bin - 1), np.inf, np.float32)
    for f in range(dim):
        col = X[:, f]
        uniq = np.unique(col)
        if len(uniq) <= 1:
            continue
        if len(uniq) <= max_bin:
            cuts = (uniq[:-1] + uniq[1:]) / 2.0
        else:
            qs = np.quantile(col, np.linspace(0, 1, max_bin + 1)[1:-1])
            cuts = np.unique(qs.astype(np.float32))
        edges[f, : len(cuts)] = cuts
    return edges


def bin_matrix(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Apply cut points -> uint8 bins [n, dim]."""
    n, dim = X.shape
    out = np.empty((n, dim), np.uint8)
    for f in range(dim):
        e = edges[f]
        e = e[np.isfinite(e)]
        out[:, f] = np.searchsorted(e, X[:, f], side="right").astype(np.uint8)
    return out


@dataclasses.dataclass
class BinnedDataset:
    """Device-resident binned dataset, rows sharded over the data axis."""

    binned: jax.Array   # uint8 [N, dim]  (N padded to mesh data size)
    label: jax.Array    # float32 [N]
    mask: jax.Array     # float32 [N]  (0 for padding rows)
    num_real: int


# ---------------------------------------------------------------------------
# learner
# ---------------------------------------------------------------------------


class GbdtLearner:
    """Depth-wise histogram GBDT over a (data,) sharded row matrix."""

    def __init__(self, cfg: GbdtConfig, mesh=None):
        if cfg.booster != "gbtree":
            raise NotImplementedError(
                f"booster={cfg.booster!r}: only gbtree; for gblinear use "
                "wormhole_tpu.models.linear (the reference's gblinear is a "
                "distributed linear model)")
        if cfg.dsplit != "row":
            raise NotImplementedError("only dsplit=row (the reference "
                                      "mushroom.hadoop.conf:36 setting)")
        assert cfg.max_bin <= 256, "bins are uint8"
        self.cfg = cfg
        # the user-requested boosting rounds; cfg.num_round later becomes
        # the running total when continuing from model_in, so repeated
        # fit() calls must not compound it
        self._requested_rounds = cfg.num_round
        self.mesh = mesh if mesh is not None else make_mesh(num_model=1)
        self._n_data = self.mesh.shape[DATA_AXIS]
        self.edges: Optional[np.ndarray] = None   # [dim, max_bin-1]
        # stacked per-round trees, each [T] where T = 2^(max_depth+1)-1
        self.trees: dict[str, np.ndarray] = _empty_trees(cfg)
        self._level_fns: dict = {}
        self._jit_cache: dict = {}
        # optional host allreduce over the worker ring (BSP mode): a
        # callable f(np.ndarray) -> np.ndarray summing over all ranks.
        # When set, fit_prepared reduces every level's histogram block
        # and the eval metric sums through it instead of assuming the
        # local mesh holds all the data.
        self.reducer = None

    # -- data ---------------------------------------------------------------
    def load_dataset(self, pattern: str, fit_bins: bool = False) -> BinnedDataset:
        """Stream the dataset into device-resident uint8 bins in bounded
        host memory: a sketch pass (reservoir sample -> quantile edges,
        discovering dim by running max — the Allreduce<Max> parity,
        lbfgs.cc:107-113) followed by a binning pass that densifies one
        chunk at a time. The full dataset never exists on the host as
        either CSR or float — only as the uint8 bin matrix it ships to
        the device as."""
        cfg = self.cfg
        if fit_bins or self.edges is None:
            sample, _, max_feat = _reservoir_sample(
                pattern, cfg.data_format, cfg.num_parts_per_file,
                cfg.minibatch, cfg.seed)
            if cfg.dim == 0:
                cfg.dim = max(max_feat + 1, 1)
            self.edges = quantile_edges(_densify_sample(sample, cfg.dim),
                                        cfg.max_bin)
            del sample
        # binning pass: one float chunk at a time
        chunks, labels = [], []
        for blk in iter_rowblocks(pattern, cfg.num_parts_per_file,
                                  cfg.data_format, cfg.minibatch,
                                  node="gbdt-load"):
            chunks.append(bin_matrix(_densify(blk, cfg.dim), self.edges))
            labels.append(blk.label.astype(np.float32))
        if not chunks:
            raise ValueError(f"no rows in {pattern}")
        n = sum(c.shape[0] for c in chunks)
        # pad rows to a multiple of the data axis
        pad = (-n) % self._n_data
        if pad:
            chunks.append(np.zeros((pad, cfg.dim), np.uint8))
        binned = np.concatenate(chunks)
        del chunks
        label = np.zeros(n + pad, np.float32)
        label[:n] = np.concatenate(labels)
        mask = np.zeros(n + pad, np.float32)
        mask[:n] = 1.0
        b1 = batch_sharding(self.mesh, 1)
        b2 = batch_sharding(self.mesh, 2)
        return BinnedDataset(
            binned=jax.device_put(binned, b2),
            label=jax.device_put(label, b1),
            mask=jax.device_put(mask, b1),
            num_real=n,
        )

    # -- objective ----------------------------------------------------------
    def _grad_hess(self, margin, label, mask):
        obj = self.cfg.objective
        if obj == "binary:logistic":
            p = jax.nn.sigmoid(margin)
            return (p - label) * mask, jnp.maximum(p * (1 - p), 1e-16) * mask
        if obj in ("reg:squarederror", "reg:linear"):
            return (margin - label) * mask, mask
        raise NotImplementedError(f"objective={obj!r}")

    def _base_margin(self):
        if self.cfg.objective == "binary:logistic":
            s = min(max(self.cfg.base_score, 1e-6), 1 - 1e-6)
            return float(np.log(s / (1 - s)))
        return float(self.cfg.base_score)

    # -- per-level jitted step ---------------------------------------------
    def _hyper_key(self):
        """Cache key component for every cfg field a compiled fn closes
        over, so mutating cfg (e.g. via load()) can never reuse stale
        compilations."""
        c = self.cfg
        return (c.dim, c.max_bin, c.max_depth, c.reg_lambda, c.gamma,
                c.min_child_weight, c.eta, c.objective, c.hist_kernel)

    def _level_parts(self, num_nodes: int, offset: int, last: bool):
        """Two traceable halves of one tree level.

        `hist_part` produces the level's stacked [G, H] statistics block
        (already psum'd over the LOCAL data axis) and `apply_part`
        consumes such a block to subtract siblings, score splits, and
        route rows. The single-process/global-mesh path composes them
        inside one jit (`_level_fn`), where the local psum already spans
        all the data; the BSP path jits them separately
        (`_bsp_level_fns`) and host-allreduces the block over the worker
        ring in between — the literal rabit::Allreduce of gradient
        histograms."""
        cfg = self.cfg
        F, B = cfg.dim, cfg.max_bin
        lam, gam, mcw, eta = (cfg.reg_lambda, cfg.gamma,
                              cfg.min_child_weight, cfg.eta)
        mesh = self.mesh
        # sibling subtraction (xgboost's classic halving): levels past
        # the root accumulate only the LEFT child of every split pair —
        # half the one-hot-matmul M axis — and derive the right child as
        # parent − left. Rows of a NON-splitting parent are active in
        # neither child, so its "right child" slot derives to the
        # parent's own histogram — garbage, but unreachable: routing
        # only ever descends into children of split nodes.
        sibling = num_nodes > 1
        hist_nodes = num_nodes // 2 if sibling else num_nodes

        use_mxu_hist = cfg.hist_kernel == "mxu" or (
            cfg.hist_kernel == "auto" and jax.default_backend() == "tpu")

        def local_hist(binned, g, h, rel):
            """Per-shard (node, feature, bin) histograms + psum — the
            rabit::Allreduce of gradient histograms."""
            if use_mxu_hist:
                # MXU one-hot-matmul histogram (ops/hist.py): the XLA
                # scatter costs ~10ns per rows x F element on TPU
                from wormhole_tpu.ops.hist import level_hist

                G, H = level_hist(binned, g, h, rel, hist_nodes, B)
            else:
                n = g.shape[0]
                base = (rel[:, None] * (F * B)
                        + jnp.arange(F, dtype=jnp.int32)[None, :] * B)
                idx = base + binned.astype(jnp.int32)      # [n, F]
                # inactive rows got rel == hist_nodes -> index >=
                # num_segments, dropped by the scatter
                gb = jnp.broadcast_to(g[:, None], (n, F)).ravel()
                hb = jnp.broadcast_to(h[:, None], (n, F)).ravel()
                flat = idx.ravel()
                G = jax.ops.segment_sum(
                    gb, flat, num_segments=hist_nodes * F * B)
                H = jax.ops.segment_sum(
                    hb, flat, num_segments=hist_nodes * F * B)
                G = G.reshape(hist_nodes, F, B)
                H = H.reshape(hist_nodes, F, B)
            G = jax.lax.psum(G, DATA_AXIS)
            H = jax.lax.psum(H, DATA_AXIS)
            return G, H

        hist = shard_map(
            local_hist, mesh=mesh,
            in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,  # pallas_call out_shape carries no vma
        )

        def local_totals(g, h, relh):
            """Per-pair (Σg, Σh) via a fused masked reduce + psum — the
            LAST level needs only node totals for leaf values, so the
            full (F, B) histogram pass (the round's single most
            expensive level) is skipped entirely."""
            sel = (jax.lax.broadcasted_iota(jnp.int32,
                                            (hist_nodes, g.shape[0]), 0)
                   == relh[None, :])
            Gt = jnp.sum(jnp.where(sel, g[None, :], 0.0), axis=-1)
            Ht = jnp.sum(jnp.where(sel, h[None, :], 0.0), axis=-1)
            return (jax.lax.psum(Gt, DATA_AXIS),
                    jax.lax.psum(Ht, DATA_AXIS))

        totals = shard_map(
            local_totals, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        )

        def hist_part(binned, g, h, node, active):
            """Local [2, ...] stacked G/H statistics for this level —
            the unit the BSP ring sums. Shape depends only on
            (num_nodes, F, B), never on the local row count, so every
            rank's block lines up regardless of data skew."""
            rel = jnp.where(active, node - offset, num_nodes).astype(jnp.int32)
            if sibling:
                # accumulate left children only (even rel -> pair id)
                relh = jnp.where(active & (rel % 2 == 0), rel // 2,
                                 hist_nodes).astype(jnp.int32)
                if last:
                    # leaf-only level: totals suffice (see local_totals)
                    Gt_l, Ht_l = totals(g, h, relh)
                    return jnp.stack([Gt_l, Ht_l])     # [2, hist_nodes]
                Gl, Hl = hist(binned, g, h, relh)
                return jnp.stack([Gl, Hl])     # [2, hist_nodes, F, B]
            G, H = hist(binned, g, h, rel)
            return jnp.stack([G, H])           # [2, num_nodes, F, B]

        def apply_part(stat, binned, node, active, trees, Gp, Hp):
            """Consume the (globally summed) statistics block: sibling
            subtraction, split scoring, row routing."""
            if sibling and last:
                Gt_l, Ht_l = stat[0], stat[1]
                Gt_p = Gp[:, 0, :].sum(-1)
                Ht_p = Hp[:, 0, :].sum(-1)
                Gt = jnp.stack([Gt_l, Gt_p - Gt_l], 1).reshape(
                    num_nodes)
                Ht = jnp.stack([Ht_l, Ht_p - Ht_l], 1).reshape(
                    num_nodes)
                leaf = -Gt / (Ht + lam) * eta
                sl = slice(offset, offset + num_nodes)
                trees = dict(trees)
                trees["leaf_value"] = trees["leaf_value"].at[sl].set(
                    leaf)
                return node, jnp.zeros_like(active), trees, Gp, Hp
            if sibling:
                Gl, Hl = stat[0], stat[1]
                G = jnp.stack([Gl, Gp - Gl], axis=1).reshape(
                    num_nodes, F, B)
                H = jnp.stack([Hl, Hp - Hl], axis=1).reshape(
                    num_nodes, F, B)
            else:
                G, H = stat[0], stat[1]
            Gt, Ht = G[:, 0, :].sum(-1), H[:, 0, :].sum(-1)   # node totals
            leaf = -Gt / (Ht + lam) * eta
            sl = slice(offset, offset + num_nodes)
            if last:
                trees = dict(trees)
                trees["leaf_value"] = trees["leaf_value"].at[sl].set(leaf)
                return node, jnp.zeros_like(active), trees, G, H
            # candidate splits: left = bins <= b (cumulative), right = rest
            GL = jnp.cumsum(G, axis=2)
            HL = jnp.cumsum(H, axis=2)
            GR, HR = Gt[:, None, None] - GL, Ht[:, None, None] - HL
            gain = 0.5 * (GL * GL / (HL + lam) + GR * GR / (HR + lam)
                          - (Gt * Gt / (Ht + lam))[:, None, None]) - gam
            ok = (HL >= mcw) & (HR >= mcw)
            ok = ok & (jnp.arange(B) < B - 1)[None, None, :]
            gain = jnp.where(ok, gain, -jnp.inf)
            flat_gain = gain.reshape(num_nodes, F * B)
            best = jnp.argmax(flat_gain, axis=1)
            best_gain = jnp.take_along_axis(flat_gain, best[:, None], 1)[:, 0]
            do_split = best_gain > 0.0
            bf = (best // B).astype(jnp.int32)
            bb = (best % B).astype(jnp.int32)
            trees = dict(trees)
            trees["split_feat"] = trees["split_feat"].at[sl].set(bf)
            trees["split_bin"] = trees["split_bin"].at[sl].set(bb)
            trees["is_split"] = trees["is_split"].at[sl].set(do_split)
            trees["leaf_value"] = trees["leaf_value"].at[sl].set(
                jnp.where(do_split, 0.0, leaf))
            # route rows into children (one-hot lookups: XLA per-row
            # gathers cost ~7ns/row even from a 127-entry table)
            T_all = trees["split_feat"].shape[0]
            nf, thr, isp, _ = _tree_lookup(node, trees, T_all)
            bv = _binned_at(binned, nf, F)
            splitting = isp & active
            node = jnp.where(splitting,
                             2 * node + 1 + (bv > thr).astype(jnp.int32),
                             node)
            return node, splitting, trees, G, H

        return hist_part, apply_part

    def _level_fn(self, num_nodes: int, offset: int, last: bool):
        key = (num_nodes, offset, last, self._hyper_key())
        fn = self._level_fns.get(key)
        if fn is not None:
            return fn
        hp, ap = self._level_parts(num_nodes, offset, last)

        @jax.jit
        def level_step(binned, g, h, node, active, trees, Gp, Hp):
            return ap(hp(binned, g, h, node, active), binned, node,
                      active, trees, Gp, Hp)

        self._level_fns[key] = level_step
        return level_step

    def _bsp_level_fns(self, num_nodes: int, offset: int, last: bool):
        """The level's halves jitted SEPARATELY, so the histogram block
        can hop to the host for the ring allreduce between them (the
        fused per-round program cannot host-call mid-trace)."""
        key = ("bsp", num_nodes, offset, last, self._hyper_key())
        fns = self._level_fns.get(key)
        if fns is None:
            hp, ap = self._level_parts(num_nodes, offset, last)
            fns = self._level_fns[key] = (jax.jit(hp), jax.jit(ap))
        return fns

    # -- boosting -----------------------------------------------------------
    def _fused_round_fn(self):
        """One jitted call per boosting round: grad/hess, every tree
        level, and the margin update in a single dispatch. The per-level
        steps are all static-shape, so the whole depth unrolls into one
        XLA program — one dispatch round-trip per boosting round instead
        of ~9 (a ~5x round-time cut at the HIGGS bench shape before the
        histogram/routing kernels; PERF.md has the corrected table)."""
        key = ("fused_round", self._hyper_key())
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        T = 2 ** (cfg.max_depth + 1) - 1

        @jax.jit
        def round_fn(binned, label, mask, margin):
            g, h = self._grad_hess(margin, label, mask)
            trees = {
                "split_feat": jnp.zeros(T, jnp.int32),
                "split_bin": jnp.zeros(T, jnp.int32),
                "is_split": jnp.zeros(T, jnp.bool_),
                "leaf_value": jnp.zeros(T, jnp.float32),
            }
            node = jnp.zeros(label.shape, jnp.int32)
            active = mask > 0
            # parent histograms thread level-to-level for the sibling
            # subtraction (level 0 ignores the zero placeholder)
            F, B = cfg.dim, cfg.max_bin
            Gp = jnp.zeros((1, F, B), jnp.float32)
            Hp = jnp.zeros((1, F, B), jnp.float32)
            for d in range(cfg.max_depth + 1):
                num_nodes, offset = 2 ** d, 2 ** d - 1
                fn_l = self._level_fn(num_nodes, offset,
                                      last=(d == cfg.max_depth))
                node, active, trees, Gp, Hp = fn_l(binned, g, h, node,
                                                   active, trees, Gp, Hp)
            _, _, _, leaf = _tree_lookup(node, trees, T)
            margin2 = margin + leaf
            return trees, node, margin2

        self._jit_cache[key] = round_fn
        return round_fn

    def _round_fns(self):
        key = ("round", self._hyper_key())
        fns = self._jit_cache.get(key)
        if fns is None:
            gh = jax.jit(lambda m, y, msk: self._grad_hess(m, y, msk))
            upd = jax.jit(lambda m, lv, node: m + lv[node])
            fns = self._jit_cache[key] = (gh, upd)
        return fns

    def _bsp_round(self, train: BinnedDataset, margin):
        """One boosting round with the histogram allreduce over the
        worker ring: grad/hess and each level's halves are jitted device
        steps; between a level's halves the stacked [G, H] block hops to
        the host and sums over all ranks through `self.reducer`. The
        ring fixes its accumulation order, so every rank consumes
        bit-identical reduced blocks — and therefore grows bit-identical
        trees, which is what lets a respawned worker's replay converge
        exactly (tests assert recovered == fault-free model)."""
        cfg = self.cfg
        T = 2 ** (cfg.max_depth + 1) - 1
        gh, upd = self._round_fns()
        g, h = gh(margin, train.label, train.mask)
        trees = {
            "split_feat": jnp.zeros(T, jnp.int32),
            "split_bin": jnp.zeros(T, jnp.int32),
            "is_split": jnp.zeros(T, jnp.bool_),
            "leaf_value": jnp.zeros(T, jnp.float32),
        }
        node = jnp.zeros(train.label.shape, jnp.int32)
        active = train.mask > 0
        F, B = cfg.dim, cfg.max_bin
        Gp = jnp.zeros((1, F, B), jnp.float32)
        Hp = jnp.zeros((1, F, B), jnp.float32)
        for d in range(cfg.max_depth + 1):
            num_nodes, offset = 2 ** d, 2 ** d - 1
            hp, ap = self._bsp_level_fns(num_nodes, offset,
                                         last=(d == cfg.max_depth))
            stat = hp(train.binned, g, h, node, active)
            stat = jnp.asarray(self.reducer(np.asarray(stat)))
            node, active, trees, Gp, Hp = ap(stat, train.binned, node,
                                             active, trees, Gp, Hp)
        margin2 = upd(margin, trees["leaf_value"], node)
        return trees, node, margin2

    def _metric_sums(self):
        """Jitted per-shard metric SUM vector — the sum-decomposable
        form that can ride the same allreduce as the histograms."""
        key = ("metric_sums", self._hyper_key())
        fn = self._jit_cache.get(key)
        if fn is None:
            if self.cfg.objective == "binary:logistic":

                @jax.jit
                def sums(margin, label, mask):
                    pred = (margin > 0).astype(jnp.float32)
                    err = jnp.sum(mask * jnp.abs(pred - label))
                    ll = jnp.sum(mask * (label * jax.nn.softplus(-margin)
                                         + (1.0 - label)
                                         * jax.nn.softplus(margin)))
                    return jnp.stack([err, ll, jnp.sum(mask)])
            else:

                @jax.jit
                def sums(margin, label, mask):
                    sq = jnp.sum(mask * (margin - label) ** 2)
                    return jnp.stack([sq, jnp.sum(mask)])

            fn = self._jit_cache[key] = sums
        return fn

    def _metrics_reduced(self, margin, ds: BinnedDataset) -> dict:
        """Distributed eval metrics: reduce per-rank sum vectors over
        the ring, finish the division on the host. AUC is skipped in
        BSP mode — it needs a global rank ordering of predictions and
        is not sum-decomposable over row shards."""
        s = self.reducer(
            np.asarray(self._metric_sums()(margin, ds.label, ds.mask)))
        if self.cfg.objective == "binary:logistic":
            n = max(float(s[2]), 1.0)
            return {"error": float(s[0]) / n, "logloss": float(s[1]) / n}
        n = max(float(s[1]), 1.0)
        return {"rmse": float(np.sqrt(float(s[0]) / n))}

    def _base_margins(self, ds: BinnedDataset):
        m = jnp.full(ds.label.shape, self._base_margin(), jnp.float32)
        return jax.device_put(m, batch_sharding(self.mesh, 1))

    def fit(self, verbose: bool = True) -> dict:
        """The boosting loop; prints `[round] name-metric:value` rows like
        the reference xgboost CLI. With model_in, continues boosting on
        top of the loaded trees (cfg.num_round more rounds), replaying
        the prior trees into the margins first."""
        cfg = self.cfg
        extra = self._requested_rounds
        r0 = 0
        if cfg.model_in:
            self.load(cfg.model_in)  # sets edges/dim/max_depth/objective
            r0 = cfg.num_round
            cfg.num_round = r0 + extra
        train = self.load_dataset(cfg.train_data, fit_bins=(r0 == 0))
        evals = []
        if cfg.eval_data:
            evals.append((cfg.eval_name, self.load_dataset(cfg.eval_data)))
        if cfg.eval_train:
            evals.append(("train", train))
        return self.fit_prepared(train, evals, r0=r0, verbose=verbose)

    def fit_prepared(self, train: BinnedDataset, evals, r0: int = 0,
                     verbose: bool = True, on_round=None) -> dict:
        """The boosting loop over already-loaded datasets — the entry the
        multi-process global-mesh app uses after assembling globally
        sharded datasets (every process must call this in lockstep: each
        round's histogram/split/metric steps are collectives). With
        `self.reducer` set (BSP mode) the per-level blocks and metric
        sums instead reduce over the worker ring; `on_round(r)` fires
        after round r's trees and metrics land — the BSP app's
        checkpoint hook (its placement matters: every collective of
        round r must complete BEFORE the checkpoint bumps the version,
        so a resumed worker's counter sequence lines up with the
        survivors')."""
        cfg = self.cfg
        prior = self.trees
        self.trees = _empty_trees(cfg)
        for k in self.trees:
            self.trees[k][:r0] = prior[k][:r0]
        _, upd = self._round_fns()
        margin = self._base_margins(train)
        margins = {name: self._base_margins(ds)
                   for name, ds in evals if ds is not train}
        for r in range(r0):  # replay loaded trees (warm start)
            tree = {k: jnp.asarray(v[r]) for k, v in self.trees.items()}
            margin = upd(margin, tree["leaf_value"], self._route(train, tree))
            for name, ds in evals:
                if ds is not train:
                    margins[name] = upd(margins[name], tree["leaf_value"],
                                        self._route(ds, tree))
        last = {}
        round_fn = self._fused_round_fn() if self.reducer is None else None
        for r in range(r0, cfg.num_round):
            if self.reducer is not None:
                tree, node, margin = self._bsp_round(train, margin)
            else:
                tree, node, margin = round_fn(train.binned, train.label,
                                              train.mask, margin)
            if os.environ.get("WORMHOLE_DEBUG", "") not in ("", "0"):
                validate_routing(tree, node)
            for k in self.trees:
                self.trees[k][r] = np.asarray(tree[k])
            msgs = []
            for name, ds in evals:
                if ds is train:
                    em = margin
                else:
                    em = margins[name] = upd(
                        margins[name], tree["leaf_value"],
                        self._route(ds, tree))
                last[name] = m = (self._metrics_reduced(em, ds)
                                  if self.reducer is not None
                                  else self._metrics(em, ds))
                msgs += [f"{name}-{k}:{v:.6f}" for k, v in m.items()]
            if verbose:
                print(f"[{r}]\t" + "\t".join(msgs), flush=True)
            if on_round is not None:
                on_round(r)
            if cfg.save_period and cfg.model_out and (r + 1) % cfg.save_period == 0:
                self.save(f"{cfg.model_out}.{r + 1:04d}", rounds=r + 1)
        if cfg.model_out:
            self.save(cfg.model_out)
        return last

    # -- eval / predict -----------------------------------------------------
    def _route(self, ds: BinnedDataset, tree):
        key = ("route", ds.binned.shape, self.cfg.max_depth)
        fn = self._jit_cache.get(key)
        if fn is None:
            depth = self.cfg.max_depth

            @jax.jit
            def route(binned, sf, sb, isp):
                node = jnp.zeros(binned.shape[0], jnp.int32)
                F = binned.shape[1]
                trees_v = {"split_feat": sf, "split_bin": sb,
                           "is_split": isp,
                           "leaf_value": jnp.zeros_like(sf, jnp.float32)}

                def body(_, node):
                    f, sb_n, isp_n, _ = _tree_lookup(node, trees_v,
                                                     sf.shape[0])
                    bv = _binned_at(binned, f, F)
                    child = 2 * node + 1 + (bv > sb_n).astype(jnp.int32)
                    return jnp.where(isp_n, child, node)

                return jax.lax.fori_loop(0, depth + 1, body, node)

            fn = self._jit_cache[key] = route
        return fn(ds.binned, tree["split_feat"], tree["split_bin"],
                  tree["is_split"])

    def _metrics(self, margin, ds: BinnedDataset) -> dict:
        from wormhole_tpu.ops import metrics as M

        key = ("metrics", margin.shape, self._hyper_key())
        fn = self._jit_cache.get(key)
        if fn is None:
            if self.cfg.objective == "binary:logistic":

                @jax.jit
                def mfn(margin, label, mask):
                    return {
                        "error": 1.0 - M.accuracy(label, margin, mask),
                        "logloss": M.logloss(label, margin, mask),
                        "auc": M.auc(label, margin, mask),
                    }
            else:

                @jax.jit
                def mfn(margin, label, mask):
                    n = jnp.maximum(jnp.sum(mask), 1.0)
                    return {"rmse": jnp.sqrt(
                        jnp.sum(mask * (margin - label) ** 2) / n)}

            fn = self._jit_cache[key] = mfn
        return {k: float(v) for k, v in
                fn(margin, ds.label, ds.mask).items()}

    def predict_margin(self, ds: BinnedDataset, num_round: Optional[int] = None
                       ) -> np.ndarray:
        R = num_round if num_round is not None else self.cfg.num_round
        m = jnp.full(ds.label.shape, self._base_margin(), jnp.float32)
        for r in range(R):
            tree = {k: jnp.asarray(v[r]) for k, v in self.trees.items()}
            m = m + tree["leaf_value"][self._route(ds, tree)]
        return np.asarray(m)[: ds.num_real]

    def predict_blk(self, blk: RowBlock) -> np.ndarray:
        """Predict probabilities (binary:logistic) / values on raw rows."""
        assert self.edges is not None, "model not fit/loaded"
        X = _densify(blk, self.cfg.dim)
        binned = bin_matrix(X, self.edges)
        pad = (-blk.size) % self._n_data
        if pad:
            binned = np.concatenate(
                [binned, np.zeros((pad, self.cfg.dim), np.uint8)])
        ds = BinnedDataset(
            binned=jax.device_put(binned, batch_sharding(self.mesh, 2)),
            label=jnp.zeros(blk.size + pad, jnp.float32),
            mask=jnp.concatenate([jnp.ones(blk.size), jnp.zeros(pad)]),
            num_real=blk.size,
        )
        m = self.predict_margin(ds)
        if self.cfg.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-m))
        return m

    # -- persistence --------------------------------------------------------
    def save(self, path: str, rounds: Optional[int] = None) -> None:
        from wormhole_tpu.utils.checkpoint import atomic_savez

        R = rounds if rounds is not None else self.cfg.num_round
        R = min(R, len(self.trees["leaf_value"]))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        atomic_savez(
            path,
            edges=self.edges,
            num_round=R,
            dim=self.cfg.dim,
            max_depth=self.cfg.max_depth,
            objective=np.bytes_(self.cfg.objective.encode()),
            base_score=self.cfg.base_score,
            **{k: v[:R] for k, v in self.trees.items()},
        )

    def load(self, path: str) -> None:
        if not os.path.exists(path) and not path.endswith(".npz"):
            path += ".npz"  # atomic_savez appends the suffix
        st = np.load(path)
        self.edges = st["edges"]
        self.cfg.dim = int(st["dim"])
        self.cfg.max_depth = int(st["max_depth"])
        self.cfg.num_round = int(st["num_round"])
        self.cfg.objective = bytes(st["objective"]).decode()
        self.cfg.base_score = float(st["base_score"])
        self.trees = {k: np.array(st[k]) for k in
                      ("split_feat", "split_bin", "is_split", "leaf_value")}


def _tree_lookup(node, trees, T: int):
    """Per-row lookups into the (T,)-sized tree arrays as one one-hot
    matmul — XLA's per-row gather from even a tiny table costs ~7ns/row
    on TPU (~14ms at the 2M-row HIGGS shape), the dominant cost of
    routing. Every channel must survive the bf16 encoding exactly:
    split_feat can exceed 256 (bf16's exact-integer limit), so it rides
    as hi/lo bytes (exact for dim < 65536); split_bin is < 256 (uint8
    bins); leaf values go through a bf16 hi/lo split (~f32 precision).
    Returns (split_feat, split_bin, is_split, leaf_value) per row."""
    oh = (node[:, None]
          == jnp.arange(T, dtype=jnp.int32)[None, :]).astype(jnp.bfloat16)
    lv = trees["leaf_value"]
    lv_hi = lv.astype(jnp.bfloat16)
    lv_lo = (lv - lv_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    sf = trees["split_feat"]
    tab = jnp.stack([
        (sf >> 8).astype(jnp.bfloat16),
        (sf & 255).astype(jnp.bfloat16),
        trees["split_bin"].astype(jnp.bfloat16),
        trees["is_split"].astype(jnp.bfloat16),
        lv_hi, lv_lo,
    ], axis=1)                                      # (T, 6)
    got = jax.lax.dot_general(
        oh, tab, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # (n, 6)
    nf = (got[:, 0].astype(jnp.int32) << 8) | got[:, 1].astype(jnp.int32)
    thr = got[:, 2].astype(jnp.int32)
    isp = got[:, 3] > 0.5
    leaf = got[:, 4] + got[:, 5]
    return nf, thr, isp, leaf


def _binned_at(binned, nf, F: int):
    """binned[i, nf[i]] as a one-hot masked sum (take_along_axis's
    per-row gather costs ~30ms at the HIGGS shape)."""
    oh = nf[:, None] == jnp.arange(F, dtype=jnp.int32)[None, :]
    return jnp.sum(jnp.where(oh, binned.astype(jnp.int32), 0), axis=1)


def validate_routing(tree, node) -> None:
    """Machine check for the sibling-subtraction invariant (the prose at
    `_level_fn`): the derived right-child histogram of a NON-splitting
    parent is garbage, which is safe only because routing never descends
    past a non-split node. This verifies exactly that — every node a row
    actually landed in must have an all-split ancestor chain — so a
    future routing edit that lets rows leak into a non-splitting
    parent's children trips here instead of silently training on garbage
    histograms. Enabled per round via WORMHOLE_DEBUG=1 (host-side walk
    over the unique landing nodes: O(T log T), negligible vs a round)."""
    isp = np.asarray(tree["is_split"])
    for t in np.unique(np.asarray(node)):
        path = []
        while t > 0:
            t = (t - 1) // 2
            path.append(t)
        bad = [p for p in path if not isp[p]]
        if bad:
            raise AssertionError(
                f"sibling-subtraction invariant violated: a row landed "
                f"in a descendant of non-split node(s) {bad} — routing "
                f"descended past a non-splitting parent, so derived "
                f"right-child histograms were trained on garbage")


def _empty_trees(cfg: GbdtConfig) -> dict[str, np.ndarray]:
    T = 2 ** (cfg.max_depth + 1) - 1
    R = cfg.num_round
    return {
        "split_feat": np.zeros((R, T), np.int32),
        "split_bin": np.zeros((R, T), np.int32),
        "is_split": np.zeros((R, T), np.bool_),
        "leaf_value": np.zeros((R, T), np.float32),
    }
