"""Spherical k-means, TPU-native.

Parity target: reference learn/kmeans/kmeans.cc — BSP Lloyd iterations
with cosine distance: rows are unit-normalized, each rank sums its
assigned points into a (k x d+1) matrix (count in the last column), the
matrix is allreduced, and centroids are recomputed by dividing by counts
(kmeans.cc:169-208); init picks k random rows broadcast from random ranks
(:89-106); per-iteration checkpoints bound lost work on failure (:204).

TPU design: the assignment pass is two matmuls on the MXU — similarities
X_hat @ C_hat^T and the accumulation onehot(assign)^T @ [X | 1] — with the
minibatch sharded over the data axis and the (k x d+1) partial sums
psum-reduced by XLA (the rabit::Allreduce of kmeans.cc:190). The host
drives Lloyd iterations and writes a checkpoint per iteration.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.data import pack_cache as _pc
from wormhole_tpu.data.rowblock import RowBlock, to_device_batch
from wormhole_tpu.parallel.mesh import batch_sharding, make_mesh, replicated
from wormhole_tpu.solver.workload import iter_parts, iter_rowblocks


@dataclasses.dataclass
class KmeansConfig:
    train_data: str = ""
    data_format: str = "libsvm"
    num_clusters: int = 10
    dim: int = 0               # feature-space dim; 0 = discover from data
    max_iter: int = 10
    minibatch: int = 4096
    nnz_per_row: int = 64
    num_parts_per_file: int = 1
    model_out: Optional[str] = None
    checkpoint_dir: Optional[str] = None  # per-iter state for resume
    seed: int = 0
    # multi-process SPMD over one jax.distributed mesh (apps/kmeans.py
    # _global_worker; the reference's rabit world)
    global_mesh: bool = False
    # assignment kernel: dense ([B, d] densify + two MXU matmuls — best
    # for small/moderate d like MNIST-784) | sparse (per-nonzero gathers
    # and scatter-adds, never materializing [B, d] — required for huge
    # hashed feature spaces, the reference's streaming sparse rows,
    # kmeans.cc:119-130) | auto (sparse when d > 16384)
    assign_kernel: str = "auto"
    # densify-kernel dtype for the packed fast path: f32 = exact
    # (matches the XLA scatter bit-for-bit); bf16 = documented
    # throughput opt-in (input values round to bfloat16; sums still
    # accumulate in f32) — ~40% faster on v5e
    kernel_dtype: str = "f32"


def discover_dim(pattern: str, fmt: str = "libsvm",
                 num_parts_per_file: int = 1) -> int:
    """Max feature id + 1 over all files — the Allreduce<Max> dimension
    discovery of the reference BSP apps (kmeans.cc:160, lbfgs.cc:107-113)."""
    max_id = -1
    for blk in iter_rowblocks(pattern, num_parts_per_file, fmt,
                              node="dim-scan"):
        if blk.nnz:
            max_id = max(max_id, int(blk.index.max()))
    return max_id + 1


class KmeansLearner:
    def __init__(self, cfg: KmeansConfig, mesh=None):
        if cfg.dim == 0:
            cfg.dim = discover_dim(cfg.train_data, cfg.data_format,
                                   cfg.num_parts_per_file)
        assert cfg.dim > 0, "empty data: could not discover dim"
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(num_model=1)
        self._bsh = batch_sharding(self.mesh, 1)
        self.centroids: Optional[jax.Array] = None  # [k, d], row-normalized
        self.start_iter = 0
        # epoch pack cache (data/pack_cache.py): None unless enabled by
        # env — the Lloyd loop replays identical batches every iteration
        self.pack_cache = _pc.from_env()

        k, d, B = cfg.num_clusters, cfg.dim, cfg.minibatch
        self._use_sparse = cfg.assign_kernel == "sparse" or (
            cfg.assign_kernel == "auto" and d > 16384)

        @jax.jit
        def densify(seg, idx, val, mask):
            """Sparse COO batch -> row-normalized dense [B, d]."""
            X = jnp.zeros((B, d), jnp.float32).at[seg, idx].add(val)
            X = X * mask[:, None]
            norm = jnp.linalg.norm(X, axis=1, keepdims=True)
            return X / jnp.maximum(norm, 1e-12)

        def _assign_from_dense(C, X, mask):
            """Assignment + accumulation given row-normalized dense X:
            returns ([k, d] sums, [k] counts, batch cost). Cosine
            distance = 1 - X_hat.C_hat."""
            Cn = C / jnp.maximum(
                jnp.linalg.norm(C, axis=1, keepdims=True), 1e-12)
            sim = X @ Cn.T                                   # MXU [B, k]
            assign = jnp.argmax(sim, axis=1)
            best = jnp.max(sim, axis=1)
            onehot = jax.nn.one_hot(assign, k, dtype=jnp.float32)
            onehot = onehot * mask[:, None]
            sums = onehot.T @ X                              # MXU [k, d]
            counts = jnp.sum(onehot, axis=0)
            cost = jnp.sum((1.0 - best) * mask)
            return sums, counts, cost

        @jax.jit
        def assign_accumulate(C, seg, idx, val, mask):
            """One assignment pass over a raw COO batch."""
            return _assign_from_dense(C, densify(seg, idx, val, mask),
                                      mask)

        @jax.jit
        def assign_accumulate_sparse(C, seg, idx, val, mask):
            """Same contract without ever building [B, d]: similarities
            by gathering centroid columns per nonzero and segment-summing
            per row; accumulation by scatter-adding normalized values
            into the assigned centroid's row. Work is O(nnz * k), HBM is
            O(k * d) — the sparse streaming of the reference
            (kmeans.cc:119-130) for hashed feature spaces where B x d
            cannot exist."""
            Cn = C / jnp.maximum(
                jnp.linalg.norm(C, axis=1, keepdims=True), 1e-12)
            # row norms from the nonzeros alone
            sq = jax.ops.segment_sum(val * val, seg, num_segments=B)
            inv_norm = 1.0 / jnp.maximum(jnp.sqrt(sq), 1e-12)
            # sim[i, c] = sum_nz val * Cn[c, idx] / ||x_i||
            contrib = val[:, None] * jnp.take(Cn.T, idx, axis=0)  # [nnz, k]
            sim = jax.ops.segment_sum(contrib, seg, num_segments=B)
            sim = sim * inv_norm[:, None]
            # padding rows (mask 0) must not attract real similarity
            sim = sim * mask[:, None]
            assign = jnp.argmax(sim, axis=1)
            best = jnp.max(sim, axis=1)
            xhat_nz = val * jnp.take(inv_norm * mask, seg)
            sums = jnp.zeros((k, d), jnp.float32).at[
                jnp.take(assign, seg), idx].add(xhat_nz)
            counts = jax.ops.segment_sum(mask, assign, num_segments=k)
            cost = jnp.sum((1.0 - best) * mask)
            return sums, counts, cost

        self._assign_accumulate = (
            assign_accumulate_sparse if self._use_sparse
            else assign_accumulate)
        self._assign_dense = assign_accumulate
        self._assign_sparse = assign_accumulate_sparse
        self._densify = densify

        # packed fast path: the XLA densify scatter (2.6M random writes
        # at the MNIST bench shape, ~26 ms — the step's wall, PERF.md)
        # becomes the tile-scatter kernel over a flattened
        # (row * stride + col) bucket space — the same coo_spmv_t that
        # plays the linear gradient scatter. f32 (HIGHEST) so densify
        # is exact; the host pack rides the loader threads like every
        # other learner's.
        from wormhole_tpu.ops import coo_kernels as ck

        self._flat_stride = -(-d // 128) * 128
        self._num_flat = -(-(B * self._flat_stride) // ck.TILE) * ck.TILE
        # the kernel's dual vector wants lane-aligned rows (odd batch
        # sizes keep the scatter densify), and the raw pallas call has
        # no mesh variant — a data-sharded in-process mesh keeps the
        # GSPMD-partitioned scatter path
        self._use_packed = (not self._use_sparse and B % 128 == 0
                            and self.mesh.shape.get("data", 1) == 1)
        assert cfg.kernel_dtype in ("f32", "bf16"), (
            f"kernel_dtype must be 'f32' or 'bf16', got "
            f"{cfg.kernel_dtype!r}")

        _kdt = (jnp.bfloat16 if cfg.kernel_dtype == "bf16"
                else jnp.float32)

        @jax.jit
        def assign_accumulate_packed(C, sidx, sseg, sval, tmap, first,
                                     mask):
            ones = jnp.ones((B,), jnp.float32)
            Xf = ck.coo_spmv_t(ones, sidx, sseg, sval, tmap, first,
                               self._num_flat, dtype=_kdt)
            X = Xf[: B * self._flat_stride].reshape(
                B, self._flat_stride)[:, :d]
            X = X * mask[:, None]
            norm = jnp.linalg.norm(X, axis=1, keepdims=True)
            X = X / jnp.maximum(norm, 1e-12)
            return _assign_from_dense(C, X, mask)

        self._assign_packed = assign_accumulate_packed

    def pack_batch(self, seg, idx, val):
        """Host-side pack for the flat-bucket densify kernel (numpy, on
        the loader threads; device transfer happens at consumption so
        the pack output stays cacheable)."""
        from wormhole_tpu.ops import coo_kernels as ck

        flat = (np.asarray(seg, np.int64) * self._flat_stride
                + np.asarray(idx, np.int64))
        cap = self.cfg.minibatch * self.cfg.nnz_per_row
        p = ck.pack_sorted_coo(flat, seg, val, self._num_flat,
                               capacity=cap)
        return (p.idx, p.seg, p.val, p.tmap, p.first)

    # -- data plumbing ------------------------------------------------------
    # The Lloyd loop re-reads the SAME batches every iteration (the seed
    # only matters to shuffle/negative sampling, both off here), which
    # makes k-means the ideal epoch-cache client: iteration 2+ replays
    # prepared batches from the cache instead of re-parsing and
    # re-packing. The loop runs per part so the cache keys whole parts.

    #: bump when _prep_db / pack_batch output layout changes
    _PACK_VERSION = 1

    def _part_key(self, f, mode: str):
        from wormhole_tpu.ops import coo_kernels as ck

        cfg = self.cfg
        return ("kmeans", self._PACK_VERSION, mode, cfg.dim,
                cfg.minibatch, cfg.nnz_per_row, self._flat_stride,
                self._num_flat, ck.TILE, ck.BLK, ck.LANES,
                f.filename, f.part, f.num_parts, cfg.data_format,
                _pc.file_stamp(f.filename))

    def _prep_db(self, blk: RowBlock):
        cfg = self.cfg
        if blk.nnz and int(blk.index.max()) >= cfg.dim:
            raise ValueError(
                f"feature id {int(blk.index.max())} >= dim "
                f"{cfg.dim}; set dim=0 to auto-discover")
        return to_device_batch(blk, cfg.minibatch,
                               cfg.minibatch * cfg.nnz_per_row, cfg.dim)

    def _host_dbs(self, mode: str, prep):
        """Per-part cached DeviceBatch/packed stream; with no cache
        configured this is exactly the old flat loop."""
        from wormhole_tpu.data.minibatch import MinibatchIter

        cfg = self.cfg
        for f in iter_parts(cfg.train_data, cfg.num_parts_per_file,
                            cfg.data_format, node="kmeans"):
            def raw(f=f):
                return MinibatchIter(f.filename, f.part, f.num_parts,
                                     f.format,
                                     minibatch_size=cfg.minibatch)
            key = (self._part_key(f, mode)
                   if self.pack_cache is not None else None)
            yield from _pc.iter_part_cached(self.pack_cache, key,
                                            raw, prep)

    def _host_batches(self, seed=0):
        yield from self._host_dbs("raw", self._prep_db)

    def _batches(self, seed=0):
        for db in self._host_batches(seed):
            put = lambda x: jax.device_put(x, self._bsh)
            yield (put(db.seg), put(db.idx), put(db.val),
                   put(db.row_mask))

    def _batches_packed(self, seed=0):
        """(packed flat-bucket COO, mask) pairs for the fast dense
        path."""
        def prep(blk):
            db = self._prep_db(blk)
            return (self.pack_batch(db.seg, db.idx, db.val), db.row_mask)

        for pk, mask in self._host_dbs("packed", prep):
            yield (tuple(jnp.asarray(a) for a in pk),
                   jax.device_put(mask, self._bsh))

    # -- init: random rows (kmeans.cc:89-106) -------------------------------
    def init_centroids(self) -> None:
        cfg = self.cfg
        rng = np.random.default_rng(cfg.seed)
        rows = []
        for b in self._batches():
            if self._use_sparse:
                # huge d: densify ONLY the sampled candidate rows on the
                # host instead of the whole [B, d] batch
                seg, idx, val, mask = (np.asarray(x) for x in b)
                n_real = int(mask.sum())
                take = min(cfg.num_clusters * 4, n_real)
                pick = rng.choice(n_real, size=take, replace=False)
                slot = np.full(len(mask), -1, np.int64)
                slot[pick] = np.arange(take)
                keep = (slot[seg] >= 0) & (val != 0)
                X = np.zeros((take, cfg.dim), np.float32)
                X[slot[seg[keep]], idx[keep].astype(np.int64)] = val[keep]
                norm = np.maximum(
                    np.linalg.norm(X, axis=1, keepdims=True), 1e-12)
                rows.append(X / norm)
            else:
                seg, idx, val, mask = b
                X = np.asarray(self._densify(seg, idx, val, mask))
                n_real = int(np.asarray(mask).sum())
                take = min(cfg.num_clusters * 4, n_real)
                rows.append(X[rng.choice(n_real, size=take, replace=False)])
            if sum(len(r) for r in rows) >= cfg.num_clusters * 8:
                break
        cand = np.concatenate(rows)
        if len(cand) < cfg.num_clusters:
            # fewer rows than clusters: reuse rows with jitter so every
            # centroid is initialized (empty clusters resolve in-loop)
            extra = cand[rng.integers(0, len(cand),
                                      cfg.num_clusters - len(cand))]
            extra = extra + 0.01 * rng.standard_normal(extra.shape)
            cand = np.concatenate([cand, extra.astype(cand.dtype)])
        # k distinct-ish rows among candidates
        pick = rng.choice(len(cand), size=cfg.num_clusters, replace=False)
        self.centroids = jax.device_put(
            jnp.asarray(cand[pick]), replicated(self.mesh))

    # -- Lloyd loop (kmeans.cc:169-208) -------------------------------------
    def run(self, verbose: bool = True) -> float:
        cfg = self.cfg
        if self.centroids is None and not self._try_resume():
            self.init_centroids()
        cost = float("nan")
        for it in range(self.start_iter, cfg.max_iter):
            k, d = cfg.num_clusters, cfg.dim
            sums = jnp.zeros((k, d), jnp.float32)
            counts = jnp.zeros((k,), jnp.float32)
            cost_acc = jnp.zeros((), jnp.float32)
            n = 0
            if self._use_packed:
                batches = (
                    (self._assign_packed, (*pk, mask))
                    for pk, mask in self._batches_packed(seed=it))
            else:
                batches = ((self._assign_accumulate, b)
                           for b in self._batches(seed=it))
            for fn, b in batches:
                s, c, co = fn(self.centroids, *b)
                sums, counts = sums + s, counts + c
                cost_acc = cost_acc + co
                n += 1
            # empty clusters keep their previous centroid (divide-by-count
            # only where count > 0)
            new_C = jnp.where(
                counts[:, None] > 0,
                sums / jnp.maximum(counts[:, None], 1.0),
                self.centroids,
            )
            self.centroids = jax.device_put(new_C, replicated(self.mesh))
            cost = float(cost_acc) / max(float(jnp.sum(counts)), 1.0)
            if verbose:
                print(f"kmeans iter {it}: mean cosine distance {cost:.6f}",
                      flush=True)
            if cfg.checkpoint_dir:
                self._checkpoint(it)
        if cfg.model_out:
            self.save(cfg.model_out)
        return cost

    # -- persistence --------------------------------------------------------
    def save(self, path: str) -> None:
        """Text centroids, rank-0-writes-model parity (kmeans.cc:212-217)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        C = np.asarray(self.centroids)
        with open(path, "w") as f:
            for row in C:
                f.write(" ".join(f"{v:.6g}" for v in row) + "\n")

    def _checkpoint(self, it: int) -> None:
        from wormhole_tpu.utils.checkpoint import atomic_savez

        os.makedirs(self.cfg.checkpoint_dir, exist_ok=True)
        atomic_savez(os.path.join(self.cfg.checkpoint_dir, "state.npz"),
                     centroids=np.asarray(self.centroids), next_iter=it + 1)

    def _try_resume(self) -> bool:
        """LoadCheckPoint parity (kmeans.cc:157-164): resume mid-run."""
        cdir = self.cfg.checkpoint_dir
        if not cdir or not os.path.exists(os.path.join(cdir, "state.npz")):
            return False
        st = np.load(os.path.join(cdir, "state.npz"))
        self.centroids = jax.device_put(jnp.asarray(st["centroids"]),
                                        replicated(self.mesh))
        self.start_iter = int(st["next_iter"])
        return True
