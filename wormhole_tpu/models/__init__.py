from wormhole_tpu.models.linear import LinearConfig, LinearLearner  # noqa: F401
