"""DiFacto: asynchronous factorization machine, TPU-native.

Parity target: reference learn/difacto (async_sgd.h, loss.h, config.proto;
doc/learn/difacto.rst): the FM model

    f(x) = <w, x> + 1/2 sum_k [ (Xv)_k^2 - (X^2)(V^2)_k ]

with adaptive embedding memory — the reference allocates a key's V slice
only once its occurrence count reaches `threshold` (and optionally only
while w != 0, the `l1_shrk` trick, difacto.rst:24-32); w trains with FTRL,
V with AdaGrad (async_sgd.h:262-296).

TPU design (SURVEY §7.5 two-table plan):
- `w` (+ FTRL z, n) tables over `num_buckets`, exactly as the linear
  learner;
- a separate dense `V` table [v_buckets, dim] (+ AdaGrad nV) with its own
  (smaller) hashed bucket space — the fixed-capacity stand-in for the
  reference's variable-length server entries;
- a `cnt` table accumulates per-bucket occurrence counts in-step (the
  pass-0 kPushFeaCnt push, async_sgd.h:374-381, becomes a fused
  segment-sum: the count push and the admission test live in the same
  jitted step, so no separate count pass is needed);
- admission = (cnt >= threshold) [* (w != 0) if l1_shrk]; the quadratic
  term and the V update both see V through the admission mask, so a
  never-admitted bucket behaves exactly like an unallocated entry.
- grad dropout / clipping / normalization knobs (loss.h:145-155).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.data.rowblock import DeviceBatch, RowBlock, to_device_batch
from wormhole_tpu.models import linear as linmod
from wormhole_tpu.ops import metrics as M
from wormhole_tpu.ops.penalty import l1l2_solve
from wormhole_tpu.ops.spmv import row_squares, spmm, spmv, spmv_t
from wormhole_tpu.parallel.kvstore import KVStore, TableSpec, quantize_push
from wormhole_tpu.parallel.mesh import batch_sharding, make_mesh


@dataclasses.dataclass
class DifactoConfig(linmod.LinearConfig):
    """Extends the linear config surface with the embedding block of
    reference difacto config.proto (dim/threshold/lambda/init_scale/
    dropout/grad_clipping/grad_normalization)."""

    dim: int = 8                 # embedding dimension V_k
    threshold: int = 2           # occurrence count to admit an embedding
    l1_shrk: bool = False        # require w != 0 for admission
    lambda_V: float = 0.01       # l2 on V (AdaGrad update)
    V_init_scale: float = 0.01   # N(0, scale) init
    V_lr_eta: float = 0.01
    V_lr_beta: float = 1.0
    grad_clipping: float = 0.0   # clip each V grad entry to [-c, c]; 0=off
    grad_normalization: bool = False  # scale V grad by 1/|batch|
    dropout: float = 0.0         # zero a fraction of V grads
    v_buckets: int = 0           # embedding table size; 0 = num_buckets
    # early stop when val objv improves less than this (async_sgd.h:31-49)
    early_stop_epsilon: float = 0.0

    @property
    def vb(self) -> int:
        return self.v_buckets or self.num_buckets


def _fm_forward(cfg: DifactoConfig, w, V, cnt, seg, idx, vidx, val,
                num_rows: int):
    """Admission mask + FM margin, shared by the train and eval steps so
    the two can never desync. Returns (margin, xw, xv, vval)."""
    admit = cnt >= cfg.threshold
    if cfg.l1_shrk:
        admit = admit & (w != 0)
    admit_nz = jnp.take(admit.astype(jnp.float32), idx)
    xw = spmv(seg, idx, val, w, num_rows)
    vval = val * admit_nz  # un-admitted keys contribute no V terms
    xv = spmm(seg, vidx, vval, V, num_rows)          # [B, k]
    x2v2 = row_squares(seg, vidx, vval, V, num_rows)  # [B, k]
    margin = xw + 0.5 * jnp.sum(xv * xv - x2v2, axis=-1)
    return margin, xw, xv, vval


def _tables_for(cfg: DifactoConfig) -> dict[str, TableSpec]:
    def v_init(key, shape, dtype):
        return cfg.V_init_scale * jax.random.normal(key, shape, dtype)

    return {
        "w": TableSpec(),
        "z": TableSpec(),
        "n": TableSpec(),
        "cnt": TableSpec(dtype=jnp.float32),
        "V": TableSpec(tail=(cfg.dim,), init=v_init),
        "nV": TableSpec(tail=(cfg.dim,)),
    }


class _CombinedStore:
    """Checkpoint adapter presenting the w-tables and V-tables as one
    store (utils/checkpoint.py only needs to_numpy/from_numpy/mesh)."""

    def __init__(self, *stores):
        self.stores = stores
        self.mesh = stores[0].mesh

    def to_numpy(self):
        out = {}
        for s in self.stores:
            out.update(s.to_numpy())
        return out

    def from_numpy(self, arrays):
        known = set().union(*(s.state for s in self.stores))
        unknown = set(arrays) - known
        assert not unknown, f"unknown tables {sorted(unknown)}"
        for s in self.stores:
            own = {k: v for k, v in arrays.items() if k in s.state}
            s.from_numpy(own)

    def nnz(self, name="w"):
        for s in self.stores:
            if name in s.state:
                return s.nnz(name)
        raise KeyError(name)


class DifactoLearner:
    """Jitted FM train/eval/predict over sharded w and V tables."""

    def __init__(self, cfg: DifactoConfig, mesh=None, seed: int = 0):
        assert 0 < cfg.vb <= cfg.num_buckets, (
            f"v_buckets must be in (0, num_buckets]; got {cfg.vb}")
        assert cfg.algo == "ftrl", (
            "difacto trains w with FTRL (reference async_sgd.h:262-286); "
            f"algo={cfg.algo!r} is not supported here")
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(num_model=1)
        self.store = KVStore(self.mesh, cfg.num_buckets,
                             {k: v for k, v in _tables_for(cfg).items()
                              if v.tail == ()}, seed=seed)
        # V tables may use a smaller bucket space; keep them in a second
        # KVStore so each table's bucket axis shards over the model axis
        self.vstore = KVStore(self.mesh, cfg.vb,
                              {k: v for k, v in _tables_for(cfg).items()
                               if v.tail != ()}, seed=seed + 1)
        self._bsh1 = batch_sharding(self.mesh, 1)
        self._dropped_rows = 0
        self._step_count = 0
        self.ckpt_store = _CombinedStore(self.store, self.vstore)

        @partial(jax.jit, donate_argnums=(0, 1))
        def train_step(state, vstate, seg, idx, vidx, val, label, mask, rngkey):
            new_state = dict(state)
            new_vstate = dict(vstate)
            nb, vb, dim = cfg.num_buckets, cfg.vb, cfg.dim

            # ---- count push + admission (kPushFeaCnt parity) -------------
            push_cnt = self.store.constrain(
                "cnt",
                jax.ops.segment_sum((val != 0).astype(jnp.float32), idx,
                                    num_segments=nb))
            cnt = state["cnt"] + push_cnt
            new_state["cnt"] = cnt

            # ---- forward -------------------------------------------------
            w = state["w"]
            V = vstate["V"]
            margin, xw, xv, vval = _fm_forward(
                cfg, w, V, cnt, seg, idx, vidx, val, label.shape[0])
            obj, d = linmod._loss_dual(cfg.loss, label, margin)
            d = d * mask

            # ---- gradients ----------------------------------------------
            gw = spmv_t(seg, idx, val, d, nb)
            gw = quantize_push(gw, cfg.fixed_bytes)
            gw = self.store.constrain("w", gw)
            touched_w = (push_cnt > 0).astype(jnp.float32)

            # dV_j = sum_i d_i x_ij (Xv_i - x_ij V_j)   (loss.h:183-279)
            d_nz = jnp.take(d, seg) * vval                      # [nnz]
            xv_nz = jnp.take(xv, seg, axis=0)                   # [nnz, k]
            v_nz = jnp.take(V, vidx, axis=0)                    # [nnz, k]
            contrib = d_nz[:, None] * (xv_nz - vval[:, None] * v_nz)
            gV = jax.ops.segment_sum(contrib, vidx, num_segments=vb)
            if cfg.grad_normalization:
                gV = gV / jnp.maximum(jnp.sum(mask), 1.0)
            if cfg.grad_clipping > 0:
                gV = jnp.clip(gV, -cfg.grad_clipping, cfg.grad_clipping)
            if cfg.dropout > 0:
                keep = jax.random.bernoulli(rngkey, 1.0 - cfg.dropout,
                                            gV.shape)
                gV = gV * keep
            gV = quantize_push(gV, cfg.fixed_bytes)
            gV = self.vstore.constrain("V", gV)
            touched_v = self.vstore.constrain(
                "nV",
                jax.ops.segment_sum(
                    (vval != 0).astype(jnp.float32), vidx, num_segments=vb
                )[:, None] * jnp.ones((1, dim)),
            )
            touched_v = (touched_v > 0).astype(jnp.float32)

            # ---- updates: w by FTRL, V by AdaGrad ------------------------
            lin_state = {"w": state["w"], "z": state["z"], "n": state["n"]}
            lin_new = linmod._update("ftrl", lin_state, gw, touched_w, cfg)
            new_state.update(lin_new)

            nV = vstate["nV"] + touched_v * gV * gV
            eta = (cfg.V_lr_beta + jnp.sqrt(nV)) / cfg.V_lr_eta
            V_new = V - touched_v * (gV + cfg.lambda_V * V) / eta
            new_vstate["V"] = jnp.where(touched_v > 0, V_new, V)
            new_vstate["nV"] = nV

            new_w = (jnp.sum(new_state["w"] != 0)
                     - jnp.sum(w != 0)).astype(jnp.float32)
            prog = linmod._progress(obj, margin, label, mask, new_w)
            obj_w, _ = linmod._loss_dual(cfg.loss, label, xw)
            prog["objv_w"] = jnp.sum(obj_w * mask)
            return new_state, new_vstate, prog

        @jax.jit
        def fwd(state, vstate, seg, idx, vidx, val, label, mask):
            margin, _, _, _ = _fm_forward(
                cfg, state["w"], vstate["V"], state["cnt"],
                seg, idx, vidx, val, label.shape[0])
            obj, _ = linmod._loss_dual(cfg.loss, label, margin)
            return margin, linmod._progress(obj, margin, label, mask)

        self._train_step = train_step
        self._fwd = fwd
        self._rng = jax.random.PRNGKey(seed + 17)

    def derived_tables(self) -> dict:
        """w trains by FTRL (async_sgd.h:262-286): non-additive prox of
        the additive (z, n), recomputed server-side (see
        LinearLearner.derived_tables)."""
        cfg = self.cfg
        return {"w": {"kind": "ftrl_prox", "lr_eta": cfg.lr_eta,
                      "lr_beta": cfg.lr_beta, "lambda_l1": cfg.lambda_l1,
                      "lambda_l2": cfg.lambda_l2}}

    # -- plumbing ----------------------------------------------------------
    def _batch(self, blk: RowBlock):
        cfg = self.cfg
        db = to_device_batch(blk, cfg.minibatch, cfg.row_capacity,
                             cfg.num_buckets)
        if db.dropped_rows:
            self._dropped_rows += db.dropped_rows
        vidx = (db.idx % np.int32(cfg.vb)).astype(np.int32)
        put = lambda x: jax.device_put(x, self._bsh1)
        return (put(db.seg), put(db.idx), put(vidx), put(db.val),
                put(db.label), put(db.row_mask))

    def train_batch(self, blk: RowBlock) -> dict:
        self._rng, sub = jax.random.split(self._rng)
        self.store.state, self.vstore.state, prog = self._train_step(
            self.store.state, self.vstore.state, *self._batch(blk), sub)
        self._step_count += 1
        return jax.tree_util.tree_map(float, prog)

    def eval_batch(self, blk: RowBlock) -> dict:
        _, prog = self._fwd(self.store.state, self.vstore.state,
                            *self._batch(blk))
        return jax.tree_util.tree_map(float, prog)

    def predict_batch(self, blk: RowBlock) -> np.ndarray:
        margin, _ = self._fwd(self.store.state, self.vstore.state,
                              *self._batch(blk))
        out = np.asarray(margin)[: blk.size]
        if self.cfg.prob_predict:
            out = 1.0 / (1.0 + np.exp(-out))
        return out

    def nnz(self) -> int:
        return self.store.nnz("w")

    def num_admitted(self) -> int:
        cnt = np.asarray(self.store.state["cnt"])
        admit = cnt >= self.cfg.threshold
        if self.cfg.l1_shrk:
            admit &= np.asarray(self.store.state["w"]) != 0
        return int(admit.sum())


def make_early_stop_hook(cfg: DifactoConfig):
    """Early stop when validation objective stops improving by epsilon
    (reference AsyncScheduler::Stop, difacto async_sgd.h:31-49)."""
    best = {"objv": None}

    def hook(prog, dp, key) -> bool:
        if cfg.early_stop_epsilon <= 0 or key != "val":
            return False
        objv = prog.mean("objv")  # the trained objective, loss-agnostic
        if best["objv"] is not None and (
            best["objv"] - objv < cfg.early_stop_epsilon
        ):
            return True
        if best["objv"] is None or objv < best["objv"]:
            best["objv"] = objv
        return False

    return hook
