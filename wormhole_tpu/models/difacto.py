"""DiFacto: asynchronous factorization machine, TPU-native.

Parity target: reference learn/difacto (async_sgd.h, loss.h, config.proto;
doc/learn/difacto.rst): the FM model

    f(x) = <w, x> + 1/2 sum_k [ (Xv)_k^2 - (X^2)(V^2)_k ]

with adaptive embedding memory — the reference allocates a key's V slice
only once its occurrence count reaches `threshold` (and optionally only
while w != 0, the `l1_shrk` trick, difacto.rst:24-32); w trains with FTRL,
V with AdaGrad (async_sgd.h:262-296).

TPU design (SURVEY §7.5 two-table plan):
- `w` (+ FTRL z, n) tables over `num_buckets`, exactly as the linear
  learner;
- a separate dense `V` table [v_buckets, dim] (+ AdaGrad nV) with its own
  (smaller) hashed bucket space — the fixed-capacity stand-in for the
  reference's variable-length server entries;
- a `cnt` table accumulates per-bucket occurrence counts in-step (the
  pass-0 kPushFeaCnt push, async_sgd.h:374-381, becomes a fused
  segment-sum: the count push and the admission test live in the same
  jitted step, so no separate count pass is needed);
- admission = (cnt >= threshold) [* (w != 0) if l1_shrk]; the quadratic
  term and the V update both see V through the admission mask, so a
  never-admitted bucket behaves exactly like an unallocated entry.
- grad dropout / clipping / normalization knobs (loss.h:145-155).
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.data.rowblock import DeviceBatch, RowBlock, to_device_batch
from wormhole_tpu.models import linear as linmod
from wormhole_tpu.ops import coo_kernels as ck
from wormhole_tpu.ops import metrics as M
from wormhole_tpu.ops.localizer import localize
from wormhole_tpu.ops.penalty import l1l2_solve
from wormhole_tpu.ops.spmv import row_squares, spmm, spmv, spmv_t
from wormhole_tpu.parallel.kvstore import KVStore, TableSpec, quantize_push
from wormhole_tpu.parallel.mesh import batch_sharding, make_mesh


@dataclasses.dataclass
class DifactoConfig(linmod.LinearConfig):
    """Extends the linear config surface with the embedding block of
    reference difacto config.proto (dim/threshold/lambda/init_scale/
    dropout/grad_clipping/grad_normalization)."""

    dim: int = 8                 # embedding dimension V_k
    threshold: int = 2           # occurrence count to admit an embedding
    l1_shrk: bool = False        # require w != 0 for admission
    lambda_V: float = 0.01       # l2 on V (AdaGrad update)
    V_init_scale: float = 0.01   # N(0, scale) init
    V_lr_eta: float = 0.01
    V_lr_beta: float = 1.0
    grad_clipping: float = 0.0   # clip each V grad entry to [-c, c]; 0=off
    grad_normalization: bool = False  # scale V grad by 1/|batch|
    dropout: float = 0.0         # zero a fraction of V grads
    v_buckets: int = 0           # embedding table size; 0 = num_buckets
    # early stop when val objv improves less than this (async_sgd.h:31-49)
    early_stop_epsilon: float = 0.0

    @property
    def vb(self) -> int:
        return self.v_buckets or self.num_buckets


def _fm_forward(cfg: DifactoConfig, w, V, cnt, seg, idx, vidx, val,
                num_rows: int):
    """Admission mask + FM margin, shared by the train and eval steps so
    the two can never desync. Returns (margin, xw, xv, vval)."""
    admit = cnt >= cfg.threshold
    if cfg.l1_shrk:
        admit = admit & (w != 0)
    admit_nz = jnp.take(admit.astype(jnp.float32), idx)
    xw = spmv(seg, idx, val, w, num_rows)
    vval = val * admit_nz  # un-admitted keys contribute no V terms
    xv = spmm(seg, vidx, vval, V, num_rows)          # [B, k]
    x2v2 = row_squares(seg, vidx, vval, V, num_rows)  # [B, k]
    margin = xw + 0.5 * jnp.sum(xv * xv - x2v2, axis=-1)
    return margin, xw, xv, vval


def _tables_for(cfg: DifactoConfig) -> dict[str, TableSpec]:
    def v_init(key, shape, dtype):
        return cfg.V_init_scale * jax.random.normal(key, shape, dtype)

    return {
        "w": TableSpec(),
        "z": TableSpec(),
        # second-moment / count accumulators floor at bf16 on the push
        # wire (huge-dynamic-range nonnegative deltas: see TableSpec)
        "n": TableSpec(wire_cap="bf16"),
        "cnt": TableSpec(dtype=jnp.float32, wire_cap="bf16"),
        "V": TableSpec(tail=(cfg.dim,), init=v_init),
        "nV": TableSpec(tail=(cfg.dim,), wire_cap="bf16"),
    }


class _CombinedStore:
    """Checkpoint adapter presenting the w-tables and V-tables as one
    store (utils/checkpoint.py only needs to_numpy/from_numpy/mesh)."""

    def __init__(self, *stores):
        self.stores = stores
        self.mesh = stores[0].mesh

    on_load = None  # callback fired after from_numpy (count-mirror sync)
    on_sparse_pull = None  # callback fired with {table: (idx, rows)}

    def to_numpy(self):
        out = {}
        for s in self.stores:
            out.update(s.to_numpy())
        return out

    def from_numpy(self, arrays):
        known = set().union(*(s.state for s in self.stores))
        unknown = set(arrays) - known
        assert not unknown, f"unknown tables {sorted(unknown)}"
        for s in self.stores:
            own = {k: v for k, v in arrays.items() if k in s.state}
            s.from_numpy(own)
        if self.on_load is not None:
            self.on_load()

    def _sub(self, name):
        for s in self.stores:
            if name in s.state:
                return s
        raise KeyError(name)

    def gather_rows(self, name, idx):
        return self._sub(name).gather_rows(name, idx)

    def gather_rows_multi(self, names, idx):
        by_store = {}
        for k in names:
            by_store.setdefault(id(self._sub(k)), (self._sub(k), []))[1] \
                .append(k)
        out = {}
        for s, ks in by_store.values():
            out.update(s.gather_rows_multi(ks, idx))
        return out

    def scatter_rows(self, name, idx, vals):
        self._sub(name).scatter_rows(name, idx, vals)

    def zero_init_names(self):
        out = set()
        for s in self.stores:
            out |= s.zero_init_names()
        return out

    def wire_cap_names(self):
        out = set()
        for s in self.stores:
            out |= s.wire_cap_names()
        return out

    @property
    def state(self):
        """Merged read view over both table groups (do not assign into
        it; use the sub-stores)."""
        out = {}
        for s in self.stores:
            out.update(s.state)
        return out

    def nnz(self, name="w"):
        for s in self.stores:
            if name in s.state:
                return s.nnz(name)
        raise KeyError(name)


class DifactoLearner:
    """Jitted FM train/eval/predict over sharded w and V tables."""

    def __init__(self, cfg: DifactoConfig, mesh=None, seed: int = 0):
        assert 0 < cfg.vb <= cfg.num_buckets, (
            f"v_buckets must be in (0, num_buckets]; got {cfg.vb}")
        assert cfg.algo == "ftrl", (
            "difacto trains w with FTRL (reference async_sgd.h:262-286); "
            f"algo={cfg.algo!r} is not supported here")
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(num_model=1)
        self.store = KVStore(self.mesh, cfg.num_buckets,
                             {k: v for k, v in _tables_for(cfg).items()
                              if v.tail == ()}, seed=seed)
        # V tables may use a smaller bucket space; keep them in a second
        # KVStore so each table's bucket axis shards over the model axis
        self.vstore = KVStore(self.mesh, cfg.vb,
                              {k: v for k, v in _tables_for(cfg).items()
                               if v.tail != ()}, seed=seed + 1)
        self._bsh1 = batch_sharding(self.mesh, 1)
        self._dropped_rows = 0
        self._step_count = 0
        self.ckpt_store = _CombinedStore(self.store, self.vstore)
        # compact Pallas FM path (see the block comment above _pack_fm);
        # l1_shrk needs device-resident w, sharded meshes use the XLA
        # collectives path
        D = self.mesh.shape.get("data", 1)
        M_ = self.mesh.shape.get("model", 1)
        self._use_fm_pallas = (
            cfg.kernel == "pallas"
            or (cfg.kernel == "auto" and jax.default_backend() == "tpu")
        ) and (not cfg.l1_shrk and D == 1 and M_ == 1
               and cfg.minibatch % 128 == 0
               # the fused in-place V update needs rows that tile cleanly:
               # dim a power of two dividing 128, V table a whole number
               # of (TILE_HI, 128) flat tiles
               and cfg.dim & (cfg.dim - 1) == 0 and 128 % cfg.dim == 0
               and (cfg.vb * cfg.dim) % ck.TILE == 0
               # the fused w update streams whole (TILE_HI, 128) tiles
               and cfg.num_buckets % ck.TILE == 0
               # the row-gather kernels compute flat int32 offsets
               # uniq * dim, so the flat V table must fit int32
               # (ADVICE r2; pack_tile_coo asserts the same for w)
               and cfg.vb * cfg.dim < 2**31)
        self._fm_caps = None
        self._fm_steps = None
        self._fm_lock = threading.Lock()
        self._cnt_host = np.zeros(cfg.num_buckets, np.float32)
        # pack-version counter for the epoch cache: bumped whenever the
        # count mirror resyncs, since admission (hence the packed vval)
        # is a function of the mirror's contents
        self._pack_epoch = 0
        self.ckpt_store.on_load = self.refresh_count_mirror
        self.ckpt_store.on_sparse_pull = self._on_sparse_pull
        # sparse PS wire hints: unique w-space / V-space rows touched by
        # trained batches since the last collect_touched() drain
        self.track_touched = False
        self._touched_lock = threading.Lock()
        self._touched_w: list[np.ndarray] = []
        self._touched_v: list[np.ndarray] = []

        @partial(jax.jit, donate_argnums=(0, 1))
        def train_step(state, vstate, seg, idx, vidx, val, label, mask, rngkey):
            new_state = dict(state)
            new_vstate = dict(vstate)
            nb, vb, dim = cfg.num_buckets, cfg.vb, cfg.dim

            # ---- count push + admission (kPushFeaCnt parity) -------------
            push_cnt = self.store.constrain(
                "cnt",
                jax.ops.segment_sum((val != 0).astype(jnp.float32), idx,
                                    num_segments=nb))
            cnt = state["cnt"] + push_cnt
            new_state["cnt"] = cnt

            # ---- forward -------------------------------------------------
            w = state["w"]
            V = vstate["V"]
            margin, xw, xv, vval = _fm_forward(
                cfg, w, V, cnt, seg, idx, vidx, val, label.shape[0])
            obj, d = linmod._loss_dual(cfg.loss, label, margin)
            d = d * mask

            # ---- gradients ----------------------------------------------
            gw = spmv_t(seg, idx, val, d, nb)
            gw = quantize_push(gw, cfg.fixed_bytes)
            gw = self.store.constrain("w", gw)
            touched_w = (push_cnt > 0).astype(jnp.float32)

            # dV_j = sum_i d_i x_ij (Xv_i - x_ij V_j)   (loss.h:183-279)
            d_nz = jnp.take(d, seg) * vval                      # [nnz]
            xv_nz = jnp.take(xv, seg, axis=0)                   # [nnz, k]
            v_nz = jnp.take(V, vidx, axis=0)                    # [nnz, k]
            contrib = d_nz[:, None] * (xv_nz - vval[:, None] * v_nz)
            gV = jax.ops.segment_sum(contrib, vidx, num_segments=vb)
            if cfg.grad_normalization:
                gV = gV / jnp.maximum(jnp.sum(mask), 1.0)
            if cfg.grad_clipping > 0:
                gV = jnp.clip(gV, -cfg.grad_clipping, cfg.grad_clipping)
            if cfg.dropout > 0:
                keep = jax.random.bernoulli(rngkey, 1.0 - cfg.dropout,
                                            gV.shape)
                gV = gV * keep
            gV = quantize_push(gV, cfg.fixed_bytes)
            gV = self.vstore.constrain("V", gV)
            touched_v = self.vstore.constrain(
                "nV",
                jax.ops.segment_sum(
                    (vval != 0).astype(jnp.float32), vidx, num_segments=vb
                )[:, None] * jnp.ones((1, dim)),
            )
            touched_v = (touched_v > 0).astype(jnp.float32)

            # ---- updates: w by FTRL, V by AdaGrad ------------------------
            lin_state = {"w": state["w"], "z": state["z"], "n": state["n"]}
            lin_new = linmod._update("ftrl", lin_state, gw, touched_w, cfg)
            new_state.update(lin_new)

            nV = vstate["nV"] + touched_v * gV * gV
            eta = (cfg.V_lr_beta + jnp.sqrt(nV)) / cfg.V_lr_eta
            V_new = V - touched_v * (gV + cfg.lambda_V * V) / eta
            new_vstate["V"] = jnp.where(touched_v > 0, V_new, V)
            new_vstate["nV"] = nV

            new_w = (jnp.sum(new_state["w"] != 0)
                     - jnp.sum(w != 0)).astype(jnp.float32)
            prog = linmod._progress(obj, margin, label, mask, new_w)
            obj_w, _ = linmod._loss_dual(cfg.loss, label, xw)
            prog["objv_w"] = jnp.sum(obj_w * mask)
            return new_state, new_vstate, prog

        @jax.jit
        def fwd(state, vstate, seg, idx, vidx, val, label, mask):
            margin, _, _, _ = _fm_forward(
                cfg, state["w"], vstate["V"], state["cnt"],
                seg, idx, vidx, val, label.shape[0])
            obj, _ = linmod._loss_dual(cfg.loss, label, margin)
            return margin, linmod._progress(obj, margin, label, mask)

        self._train_step = train_step
        self._fwd = fwd
        self._rng = jax.random.PRNGKey(seed + 17)

    def derived_tables(self) -> dict:
        """w trains by FTRL (async_sgd.h:262-286): non-additive prox of
        the additive (z, n), recomputed server-side (see
        LinearLearner.derived_tables)."""
        cfg = self.cfg
        return {"w": {"kind": "ftrl_prox", "lr_eta": cfg.lr_eta,
                      "lr_beta": cfg.lr_beta, "lambda_l1": cfg.lambda_l1,
                      "lambda_l2": cfg.lambda_l2}}

    # -- compact Pallas FM path ---------------------------------------------
    # The XLA segment-op step spends ~85ms/step at Criteo shape: per-nnz
    # [nnz, dim] gathers + two segment-sums for the V terms, a 4M-wide
    # count scatter, and dense table updates. The compact path localizes
    # both key spaces on the host (the Localizer role), runs the scalar
    # COO kernels on the compact w domain and the FM/SpMM kernels
    # (fm_pull/fm_push) on the compact V domain, and updates/scatters
    # only touched entries. Admission (cnt >= threshold) is computed on a
    # HOST count mirror during packing — counts are pure data statistics
    # the host can track exactly, and the mirror resyncs from the store
    # after loads and PS pulls. l1_shrk needs device-resident w, so it
    # stays on the XLA path.

    def refresh_count_mirror(self) -> None:
        self._cnt_host = np.asarray(self.store.state["cnt"]).copy()
        self._pack_epoch += 1

    def on_pass_start(self) -> None:
        """Solver hook: resync the count mirror from the device table so
        any drift (e.g. batches packed but never consumed after an
        aborted pass) is bounded to one pass."""
        with self._fm_lock:
            self.refresh_count_mirror()

    def _fm_dtype_of(self):
        cfg = self.cfg
        if cfg.kernel_dtype == "f32":
            return jnp.float32
        if cfg.kernel_dtype == "auto" and cfg.fixed_bytes == 0:
            return jnp.float32
        return None  # kernel default (bf16 on TPU, f32 in interpret)

    @property
    def _v_rows_per_tile(self) -> int:
        return ck.TILE // self.cfg.dim

    def _pack_fm(self, db: DeviceBatch, train: bool):
        """Host pack (loader threads, serialized by _fm_lock so the count
        mirror sees batches in order): localize w keys and V row ids into
        tile-run-aligned compact slots (coo_kernels.assign_tile_slots),
        apply admission to the V values, and lay both out for the
        kernels. The tile alignment is what lets the training step update
        both tables in place (ops/fused_update.py) with no XLA element
        gathers or scatters."""
        cfg = self.cfg
        idx64 = db.idx.astype(np.int64)
        live = db.val != 0
        loc = localize(idx64.astype(np.uint64))
        uniq = loc.uniq_keys.astype(np.int64)
        inv = loc.local_index
        live_counts = np.bincount(
            inv[live], minlength=len(uniq)).astype(np.float32)
        with self._fm_lock:
            if self._fm_caps is None:
                # the first batch to pack may be a short tail part: scale
                # its unique counts up to a full minibatch's worth (capped
                # at 4x) so the permanent capacities are not sized from a
                # fragment
                fill = cfg.row_capacity / max(int(live.sum()), 1)
                scale = 1.5 * min(max(fill, 1.0), 4.0)
                blocks_w = ck.tile_blocks_needed(uniq, ck.TILE)
                uw = (-(-int(scale * blocks_w) * ck.BLK_U // ck.TILE)
                      * ck.TILE)
                vuniq0 = (np.unique(idx64[live] % cfg.vb)
                          if live.any() else np.zeros(1, np.int64))
                blocks_v = ck.tile_blocks_needed(vuniq0,
                                                 self._v_rows_per_tile)
                uv = int(scale * blocks_v + 1) * ck.BLK_U
                self._fm_caps = (uw, uv)
                self._build_fm(uw, uv)
        uw_cap, uv_cap = self._fm_caps

        ts_w = ck.assign_tile_slots(uniq, ck.TILE, uw_cap, cfg.num_buckets)
        slot_nz = ts_w.slot_of_uniq[inv]
        keep = slot_nz < uw_cap
        dropped = int(np.count_nonzero(~keep & live))
        idx64, seg, val, slot_nz = (idx64[keep], db.seg[keep],
                                    db.val[keep], slot_nz[keep])
        live = val != 0
        kept_r = ts_w.slot_of_uniq < uw_cap
        wcnts = np.zeros(uw_cap, np.float32)
        wcnts[ts_w.slot_of_uniq[kept_r]] = live_counts[kept_r]

        # admission per key from the mirror; training includes this
        # batch's own counts (the reference makes the weight pull depend
        # on the count push of the same minibatch, async_sgd.h:374-381).
        # Only this mirror read-modify-write needs the lock — packing
        # itself runs concurrently across loader threads.
        with self._fm_lock:
            cnt_key = self._cnt_host[uniq]
            if train:
                cnt_key = cnt_key + live_counts
                self._cnt_host[uniq[kept_r]] += live_counts[kept_r]
        adm_nz = (cnt_key >= cfg.threshold)[inv][keep] & live

        # V domain: localize (bucket % vb) row ids of the kept nonzeros
        vidx = (idx64 % cfg.vb).astype(np.uint64)
        loc_v = localize(vidx)
        ts_v = ck.assign_tile_slots(loc_v.uniq_keys, self._v_rows_per_tile,
                                    uv_cap, cfg.vb)
        vslot_nz = ts_v.slot_of_uniq[loc_v.local_index]
        vval = np.where(adm_nz, val, 0.0).astype(np.float32)
        keepv = vslot_nz < uv_cap
        dropped += int(np.count_nonzero(~keepv & (vval != 0)))
        segv, vvalv, vslotv = seg[keepv], vval[keepv], vslot_nz[keepv]
        # row-major padded view (minibatch x nnz_per_row) of the live
        # nonzeros, laid out over the W-SLOT domain (ck.build_rm): the
        # forward's xw AND xv/x2 sums become ONE XLA row gather from the
        # unified compact table U = [V-row | w] (indexed by w slot; see
        # _build_fm) + a dense reshape-reduce — no radix-image kernel on
        # the whole forward path. Slot `uw_cap` is the appended zero
        # row. Three channels ride the layout: the w slot, the w value
        # (all live nonzeros), and the ADMITTED value (V side — zero
        # where the count threshold or uv_cap overflow masks the
        # embedding, matching the reference's unallocated entries).
        W = cfg.nnz_per_row
        mb = cfg.minibatch
        rm_slot, (rm_wval, rm_vval), over = ck.build_rm(
            seg, slot_nz, val, mb, W, uw_cap,
            extra=(np.where(keepv, vval, 0.0),))
        rm_dropped = 0
        if len(over):
            # overflow beyond nnz_per_row: since the forward's xw rides
            # the SAME row-major layout, a row's nonzeros past
            # nnz_per_row are dropped from EVERY layout (rm forward —
            # including the linear xw term — wcoo backward, vcoo
            # backward) so pull and push agree about which nonzeros
            # exist
            rm_dropped = int(np.count_nonzero(val[over]))
            val = val.copy()
            val[over] = 0.0
            mask_src = np.ones(len(seg), bool)
            mask_src[over] = False
            vvalv[~mask_src[keepv]] = 0.0
        # per-w-slot V row for the unified table: slot's key -> its V
        # bucket's compact slot (uv_cap sentinel -> zero V row, covering
        # alignment holes AND uv_cap-overflowed keys)
        vslot_w = np.full(uw_cap, uv_cap, np.int32)
        w_slots_valid = np.flatnonzero(ts_w.uniq < cfg.num_buckets)
        vkeys = (ts_w.uniq[w_slots_valid].astype(np.int64)
                 % cfg.vb).astype(np.uint64)
        li = np.searchsorted(loc_v.uniq_keys, vkeys)
        li = np.clip(li, 0, max(len(loc_v.uniq_keys) - 1, 0))
        ok = loc_v.uniq_keys[li] == vkeys
        vs = np.minimum(ts_v.slot_of_uniq[li], uv_cap).astype(np.int32)
        vslot_w[w_slots_valid] = np.where(ok, vs, uv_cap)
        if dropped or rm_dropped:
            # two distinct causes with distinct remedies, counted
            # separately so an undersized nnz_per_row is diagnosable
            # (ADVICE #4): slot-cap overflow (the compact W/V tables
            # sized off the first batch ran out of slots — raise
            # compact caps / first-batch key diversity) vs row-cap
            # overflow (a row carried more than nnz_per_row nonzeros —
            # raise nnz_per_row; note the rm layout caps the xw forward
            # too, not just the V embeddings)
            import logging

            logging.getLogger(__name__).warning(
                "fm compaction overflow: dropped %d nonzeros to the "
                "slot caps (caps %s — raise key diversity of the first "
                "batch) and %d to the nnz_per_row row cap (%d — raise "
                "nnz_per_row; the row-major forward caps xw too)",
                dropped, self._fm_caps, rm_dropped, W)
        if not train:
            # eval/predict never scatter: the sorted COO streams (and
            # their radix sorts) are a train-only cost
            return (ts_w, wcnts, None, ts_v, None, None,
                    rm_slot, rm_wval, rm_vval, vslot_w)
        wcoo = ck.pack_sorted_coo(slot_nz, seg, val, uw_cap,
                                  capacity=cfg.row_capacity)
        vtouched = np.zeros(uv_cap, np.float32)
        vtouched[np.unique(vslotv[vvalv != 0])] = 1.0
        vcoo = ck.pack_sorted_coo(vslotv, segv, vvalv, uv_cap,
                                  capacity=cfg.row_capacity,
                                  tile=ck.TILE_HI, blk=ck.FM_BLK)
        return (ts_w, wcnts, wcoo, ts_v, vtouched, vcoo,
                rm_slot, rm_wval, rm_vval, vslot_w)

    def _build_fm(self, uw_cap: int, uv_cap: int) -> None:
        cfg = self.cfg
        dt = self._fm_dtype_of()
        # wire dtype for the XLA gather operands (U, xvd): dt resolves
        # to None in bf16 mode (the kernels pick bf16 internally), but
        # astype(None) is a float32 no-op — so name the gather dtype
        # explicitly. Half-width rows halve the forward/backward gather
        # bytes; sums still accumulate in f32 (bf16 mode is the
        # documented throughput opt-in; f32 mode stays exact).
        wire = dt if dt is not None else (
            jnp.float32 if ck._use_interpret() else jnp.bfloat16)
        from wormhole_tpu.ops.fused_update import (row_tile_gather,
                                                   scatter_update,
                                                   v_scatter_update)

        def gather_compact(state, vstate, uniq_w, wtm, uniq_v, vtm):
            wc = ck.tile_gather(state["w"].reshape(-1, ck.LANES),
                                uniq_w, wtm, dtype=dt)
            Vc = row_tile_gather(vstate["V"].reshape(-1, ck.LANES),
                                 uniq_v, vtm, cfg.dim, dtype=dt)
            return wc, Vc

        def forward_rm(wc, Vc, rm_slot, rm_wval, rm_vval, vslot_w):
            # row-major forward over the UNIFIED compact table
            # U[s] = [V-row of slot s's key | w[s]]: ONE XLA row gather
            # + a dense reshape-reduce yields xw AND xv/x2 together —
            # no radix-image kernel anywhere on the forward path (the
            # former coo_spmv xw was ~7.5 ms of the step, r4 PERF.md).
            # U's V side is a u_cap-sized row gather (cheap: compact
            # rows, not nnz), its w side is the tile-gathered compact
            # w. Rows move at the kernel dtype (half the bytes in bf16
            # mode); products and sums accumulate in f32.
            Vcz = jnp.concatenate(
                [Vc.astype(wire), jnp.zeros((1, cfg.dim), wire)], axis=0)
            U = jnp.concatenate(
                [jnp.take(Vcz, vslot_w, axis=0),
                 wc.astype(wire)[:, None]], axis=1)   # [uw_cap, dim+1]
            Uz = jnp.concatenate(
                [U, jnp.zeros((1, cfg.dim + 1), wire)], axis=0)
            U_nnz = jnp.take(Uz, rm_slot, axis=0)     # [mb*W, dim+1]
            xw = (rm_wval * U_nnz[:, cfg.dim].astype(jnp.float32)
                  ).reshape(cfg.minibatch, -1).sum(1)
            p = rm_vval[:, None] * U_nnz[:, :cfg.dim].astype(jnp.float32)
            xv = p.reshape(cfg.minibatch, -1, cfg.dim).sum(1)
            x2 = (p * p).reshape(cfg.minibatch, -1, cfg.dim).sum(1)
            margin = xw + 0.5 * jnp.sum(xv * xv - x2, axis=-1)
            return xw, xv, margin

        @partial(jax.jit, donate_argnums=(0, 1))
        def train_fm(state, vstate, uniq_w, wtm, wfi, wla, wcnts,
                     widx, wseg, wval, wtmap, wfirst,
                     uniq_v, vtm, vfi, vla, vtouched,
                     vidx, vseg, vval, vtmap, vfirst,
                     rm_slot, rm_wval, rm_vval, vslot_w,
                     label, mask, rngkey):
            wc, Vc = gather_compact(state, vstate, uniq_w, wtm,
                                    uniq_v, vtm)
            xw, xv, margin = forward_rm(wc, Vc, rm_slot, rm_wval,
                                        rm_vval, vslot_w)
            obj, d = linmod._loss_dual(cfg.loss, label, margin)
            d = d * mask

            # w: FTRL at the key's storage — scatter + handle update run
            # inside the fused kernel over touched tiles, in place
            gw = ck.coo_spmv_t(d, widx, wseg, wval, wtmap, wfirst,
                               uw_cap, dtype=dt)
            # cnt rides the fused update's touched-tile walk as an
            # additive table (an XLA element scatter into the 4M-bucket
            # table costs ~4 ms at the Criteo shape; sentinel slots
            # carry all-zero one-hot rows and scatter nothing)
            new_state, new_w = scatter_update(
                "ftrl", state, gw, uniq_w, wtm, wfi, wla,
                lr_eta=cfg.lr_eta, lr_beta=cfg.lr_beta,
                lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
                fixed_bytes=cfg.fixed_bytes, dtype=dt,
                add_table="cnt", add_values=wcnts)

            # V: AdaGrad at the row's storage, same treatment; the grad
            # filters apply on the compact gradient beforehand.
            # dV_j += sum_i c*(xv_i - val*V_j), c = d_i*val: the xv and
            # d factors ride ONE row gather from the [mb, dim+1] row
            # layout (padding entries carry val = 0 and vanish); the
            # kernel only re-derives tile V rows and scatters.
            xvd = jnp.concatenate([xv, d[:, None]], axis=1).astype(wire)
            G = jnp.take(xvd, vseg, axis=0)
            c = G[:, cfg.dim].astype(jnp.float32) * vval
            # kernel operands at the wire dtype: the contrib matmul
            # runs at the kernel dtype anyway, so f32 a/b would only
            # double the HBM traffic into the scatter kernel
            a = (c[:, None] * G[:, :cfg.dim].astype(jnp.float32)
                 ).astype(wire)
            b = (c * vval).astype(wire)
            gV = ck.fm_push_contrib(Vc, a, b, vidx, vtmap, vfirst,
                                    dtype=dt)
            if cfg.grad_normalization:
                gV = gV / jnp.maximum(jnp.sum(mask), 1.0)
            if cfg.grad_clipping > 0:
                gV = jnp.clip(gV, -cfg.grad_clipping, cfg.grad_clipping)
            if cfg.dropout > 0:
                keep = jax.random.bernoulli(rngkey, 1.0 - cfg.dropout,
                                            gV.shape)
                gV = gV * keep
            gV = quantize_push(gV, cfg.fixed_bytes)
            Vn, nVn = v_scatter_update(
                vstate["V"], vstate["nV"], gV, vtouched, uniq_v,
                vtm, vfi, vla, dim=cfg.dim, V_lr_eta=cfg.V_lr_eta,
                V_lr_beta=cfg.V_lr_beta, lambda_V=cfg.lambda_V, dtype=dt)
            new_vstate = dict(vstate)
            new_vstate["V"] = Vn
            new_vstate["nV"] = nVn

            prog = linmod._progress(obj, margin, label, mask, new_w)
            obj_w, _ = linmod._loss_dual(cfg.loss, label, xw)
            prog["objv_w"] = jnp.sum(obj_w * mask)
            return new_state, new_vstate, prog

        @jax.jit
        def fwd_fm(state, vstate, uniq_w, wtm, uniq_v, vtm,
                   rm_slot, rm_wval, rm_vval, vslot_w, label, mask):
            # eval/predict never scatter: only the compact gathers and
            # the rm channels ride along (the COO streams are a train-
            # only cost — _pack_fm skips packing them when train=False)
            wc, Vc = gather_compact(state, vstate, uniq_w, wtm,
                                    uniq_v, vtm)
            margin = forward_rm(wc, Vc, rm_slot, rm_wval, rm_vval,
                                vslot_w)[2]
            obj, _ = linmod._loss_dual(cfg.loss, label, margin)
            return margin, linmod._progress(obj, margin, label, mask)

        self._fm_steps = (train_fm, fwd_fm)

    def prepare_batch(self, blk: RowBlock, train: bool = True):
        """Host-side batch prep for the solver's loader threads."""
        cfg = self.cfg
        db = to_device_batch(blk, cfg.minibatch, cfg.row_capacity,
                             cfg.num_buckets)
        if db.dropped_rows:
            self._dropped_rows += db.dropped_rows
        if not self._use_fm_pallas:
            return ("xla", db, blk.size)
        pk = self._pack_fm(db, train)
        args = tuple(jax.device_put(a) for a in
                     self._fm_args(pk, db.label, db.row_mask, train))
        ids = None
        if train and self.track_touched:
            # host-side touched rows for the sparse PS wire, extracted
            # before the pack moves to device (sentinel slots filtered)
            ts_w, ts_v = pk[0], pk[3]
            ids = (ts_w.uniq[ts_w.uniq < cfg.num_buckets].astype(np.int64),
                   ts_v.uniq[ts_v.uniq < cfg.vb].astype(np.int64))
        return ("fm", args, blk.size, train, ids)

    def _fm_args(self, pk, label, mask, train: bool):
        (ts_w, wcnts, wcoo, ts_v, vtouched, vcoo,
         rm_slot, rm_wval, rm_vval, vslot_w) = pk
        j = jnp.asarray
        rm_parts = [j(rm_slot), j(rm_wval), j(rm_vval), j(vslot_w)]
        if train:
            wparts = [j(wcoo.idx), j(wcoo.seg), j(wcoo.val),
                      j(wcoo.tmap), j(wcoo.first)]
            vparts = [j(vcoo.idx), j(vcoo.seg), j(vcoo.val),
                      j(vcoo.tmap), j(vcoo.first)] + rm_parts
            return ([j(ts_w.uniq), j(ts_w.tmap_u), j(ts_w.first_u),
                     j(ts_w.last_u), j(wcnts)] + wparts
                    + [j(ts_v.uniq), j(ts_v.tmap_u), j(ts_v.first_u),
                       j(ts_v.last_u), j(vtouched)] + vparts
                    + [j(label), j(mask)])
        return ([j(ts_w.uniq), j(ts_w.tmap_u), j(ts_v.uniq),
                 j(ts_v.tmap_u)] + rm_parts + [j(label), j(mask)])

    # -- global-mesh SPMD protocol (apps/_runner._global_train) ------------
    def global_step_protocol(self):
        """(train_fn, eval_fn) over (seg, idx, val, label, mask) GLOBAL
        arrays; vidx derives on device. Both mutate learner state and
        return a progress dict of device scalars."""
        vb = self.cfg.vb

        def train_fn(args, rng):
            seg, idx, val, label, mask = args
            vidx = idx % np.int32(vb)
            self.store.state, self.vstore.state, prog = self._train_step(
                self.store.state, self.vstore.state, seg, idx, vidx, val,
                label, mask, rng)
            return prog

        def eval_fn(args):
            seg, idx, val, label, mask = args
            vidx = idx % np.int32(vb)
            _, prog = self._fwd(self.store.state, self.vstore.state,
                                seg, idx, vidx, val, label, mask)
            return prog

        return train_fn, eval_fn

    def global_predict_protocol(self):
        """pred_fn over (seg, idx, val, mask) GLOBAL arrays — see
        LinearLearner.global_predict_protocol."""
        import jax.numpy as jnp

        from wormhole_tpu.parallel.mesh import batch_sharding

        vb = self.cfg.vb
        bsh = batch_sharding(self.mesh, 1)

        @jax.jit
        def pred(state, vstate, seg, idx, val, mask):
            vidx = idx % np.int32(vb)
            margin, _ = self._fwd(state, vstate, seg, idx, vidx, val,
                                  jnp.zeros_like(mask), mask)
            return (jax.lax.with_sharding_constraint(margin, bsh),
                    jnp.sum(mask))

        def pred_fn(args):
            seg, idx, val, mask = args
            return pred(self.store.state, self.vstore.state,
                        seg, idx, val, mask)

        return pred_fn

    # -- epoch pack cache ----------------------------------------------------
    #: bump when prepare_batch's output layout changes for identical input
    _PACK_VERSION = 1

    def pack_cache_token(self, train: bool = True):
        """See LinearLearner.pack_cache_token. The compact FM train pack
        is NOT bit-identically replayable: admission depends on the
        evolving count mirror AND packing mutates it (_pack_fm), so a
        replayed pack would both be stale and skip the count push —
        decline with None. Eval packs are pure given a mirror snapshot,
        keyed by the pack-epoch counter that advances on every mirror
        resync. The XLA fallback path packs with no host state at all
        and caches for both."""
        cfg = self.cfg
        base = ("difacto", self._PACK_VERSION, self._use_fm_pallas,
                cfg.minibatch, cfg.nnz_per_row, cfg.num_buckets, cfg.vb,
                cfg.dim, cfg.threshold, cfg.l1_shrk)
        if not self._use_fm_pallas:
            return base
        if train:
            return None
        if self._fm_caps is None:
            return None  # slot caps not yet sized from a first batch
        return base + (self._fm_caps, self._pack_epoch,
                       ck.TILE, ck.BLK_U, ck.TILE_HI, ck.FM_BLK,
                       ck.LANES)

    # -- double-buffered device feed -----------------------------------------
    def stage_batch(self, b, train: bool = True):
        """Loader-side device placement. The compact FM pack already
        device_puts its args in prepare_batch; only the XLA fallback
        still carries host arrays, so stage those here."""
        b = self._prepared(b, train)
        if b[0] != "xla":
            return b
        db, size = b[1], b[2]
        ids = None
        if train and self.track_touched:
            ids_w = np.unique(db.idx[db.val != 0]).astype(np.int64)
            ids = (ids_w, ids_w % self.cfg.vb)
        return ("xla_staged", self._xla_args(db), size, train, ids)

    def _prepared(self, blk, train: bool):
        if isinstance(blk, RowBlock):
            return self.prepare_batch(blk, train=train)
        return blk

    def _xla_args(self, db):
        vidx = (db.idx % np.int32(self.cfg.vb)).astype(np.int32)
        put = lambda x: jax.device_put(x, self._bsh1)
        return (put(db.seg), put(db.idx), put(vidx), put(db.val),
                put(db.label), put(db.row_mask))

    def train_batch(self, blk) -> dict:
        b = self._prepared(blk, train=True)
        self._rng, sub = jax.random.split(self._rng)
        if b[0] == "fm":
            args = b[1]
            self.store.state, self.vstore.state, prog = self._fm_steps[0](
                self.store.state, self.vstore.state, *args, sub)
            if self.track_touched:
                self._note_touched(b[4])
        elif b[0] == "xla_staged":
            self.store.state, self.vstore.state, prog = self._train_step(
                self.store.state, self.vstore.state, *b[1], sub)
            if self.track_touched:
                self._note_touched(b[4])
        else:
            db = b[1]
            self.store.state, self.vstore.state, prog = self._train_step(
                self.store.state, self.vstore.state,
                *self._xla_args(db), sub)
            if self.track_touched:
                ids_w = np.unique(db.idx[db.val != 0]).astype(np.int64)
                self._note_touched((ids_w, ids_w % self.cfg.vb))
        self._step_count += 1
        return jax.tree_util.tree_map(float, prog)

    # -- sparse PS wire hints ------------------------------------------------
    def _note_touched(self, ids) -> None:
        if ids is None:
            ids = (None, None)
        with self._touched_lock:
            self._touched_w.append(ids[0])
            self._touched_v.append(ids[1])

    def collect_touched(self):
        """Sorted-unique global rows touched since the last call, per
        table (the sparse PS push set; reference ZPush of the
        minibatch's keys, async_sgd.h:270-287). Returns None if any
        trained batch lacked a hint (SyncedStore then falls back to a
        full delta scan for this sync)."""
        with self._touched_lock:
            tw, tv = self._touched_w, self._touched_v
            self._touched_w, self._touched_v = [], []
        if any(a is None for a in tw):
            return None
        uw = (np.unique(np.concatenate(tw)) if tw
              else np.empty(0, np.int64))
        uv = (np.unique(np.concatenate(tv)) if tv
              else np.empty(0, np.int64))
        out = {k: uw for k in self.store.state}
        out.update({k: uv for k in self.vstore.state})
        return out

    def _on_sparse_pull(self, updates) -> None:
        """Keep the host count mirror coherent with sparse PS pulls (the
        dense path refreshes it via on_load/from_numpy)."""
        got = updates.get("cnt")
        if got is None:
            return
        idx, rows = got
        with self._fm_lock:
            self._cnt_host[idx] = rows

    def _fwd_any(self, blk):
        b = self._prepared(blk, train=False)
        if b[0] == "fm":
            args, size = b[1], b[2]
            margin, prog = self._fm_steps[1](
                self.store.state, self.vstore.state, *args)
        elif b[0] == "xla_staged":
            size = b[2]
            margin, prog = self._fwd(self.store.state, self.vstore.state,
                                     *b[1])
        else:
            size = b[2]
            margin, prog = self._fwd(self.store.state, self.vstore.state,
                                     *self._xla_args(b[1]))
        return margin, prog, size

    def eval_batch(self, blk) -> dict:
        _, prog, _ = self._fwd_any(blk)
        return jax.tree_util.tree_map(float, prog)

    def predict_batch(self, blk) -> np.ndarray:
        margin, _, size = self._fwd_any(blk)
        out = np.asarray(margin)[:size]
        if self.cfg.prob_predict:
            out = 1.0 / (1.0 + np.exp(-out))
        return out

    def nnz(self) -> int:
        return self.store.nnz("w")

    def num_admitted(self) -> int:
        cnt = np.asarray(self.store.state["cnt"])
        admit = cnt >= self.cfg.threshold
        if self.cfg.l1_shrk:
            admit &= np.asarray(self.store.state["w"]) != 0
        return int(admit.sum())

    def v_collision_rate(self) -> float:
        """Fraction of ADMITTED keys whose V bucket (key % v_buckets) is
        shared with another admitted key. The reference stores exact
        per-key embeddings (async_sgd.h:135-209); the fixed-capacity V
        table is a hash kernel, and this is the metric that bounds the
        aliasing it introduces — size v_buckets so this stays small
        (rate ~ n_admitted / v_buckets for a uniform hash; see
        docs/difacto.md)."""
        cnt = np.asarray(self.store.state["cnt"])
        admit = cnt >= self.cfg.threshold
        if self.cfg.l1_shrk:
            admit &= np.asarray(self.store.state["w"]) != 0
        keys = np.flatnonzero(admit)
        if len(keys) == 0:
            return 0.0
        vb_of = keys % self.cfg.vb
        _, counts = np.unique(vb_of, return_counts=True)
        collided = int(np.sum(counts[counts > 1]))
        return collided / len(keys)


def make_early_stop_hook(cfg: DifactoConfig):
    """Early stop when validation objective stops improving by epsilon
    (reference AsyncScheduler::Stop, difacto async_sgd.h:31-49)."""
    best = {"objv": None}

    def hook(prog, dp, key) -> bool:
        if cfg.early_stop_epsilon <= 0 or key != "val":
            return False
        objv = prog.mean("objv")  # the trained objective, loss-agnostic
        if best["objv"] is not None and (
            best["objv"] - objv < cfg.early_stop_epsilon
        ):
            return True
        if best["objv"] is None or objv < best["objv"]:
            best["objv"] = objv
        return False

    return hook
