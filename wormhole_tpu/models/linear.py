"""Sparse linear learner: async-SGD logistic regression, TPU-native.

Parity target: the reference's flagship `linear.dmlc` app
(learn/linear/async_sgd.h, loss.h, penalty.h, config.proto) — logistic /
squared-hinge loss over hashed sparse features, with per-key SGD / AdaGrad /
FTRL update rules and elastic-net regularization.

TPU design (vs the reference's worker/server processes):
- the weight/optimizer tables are a KVStore: hashed buckets sharded over
  the mesh model axis (the servers);
- a training step jits pull -> SpMV -> loss grad -> SpMV^T -> handle update
  end-to-end; the minibatch is sharded over the data axis (the workers) and
  XLA inserts the gather / reduce-scatter collectives that play
  ZPull/ZPush;
- the per-key Handle branches (async_sgd.h:71-180) become masked dense
  vector updates: untouched buckets carry zero gradient and a zero
  touched-mask, making the update a no-op exactly where the reference
  would not receive a push.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.data.rowblock import DeviceBatch, RowBlock, to_device_batch
from wormhole_tpu.ops import coo_kernels as ck
from wormhole_tpu.ops import metrics as M
from wormhole_tpu.ops.penalty import l1l2_solve
from wormhole_tpu.ops.spmv import spmv, spmv_t
from wormhole_tpu.parallel.kvstore import KVStore, TableSpec, quantize_push
from wormhole_tpu.parallel.mesh import batch_sharding, make_mesh


@dataclasses.dataclass
class LinearConfig:
    """Config surface of reference learn/linear/config.proto (subset that
    is meaningful on TPU; names kept)."""

    train_data: str = ""
    val_data: Optional[str] = None
    model_out: Optional[str] = None
    model_in: Optional[str] = None
    predict_out: Optional[str] = None
    data_format: str = "libsvm"
    max_data_pass: int = 1

    # loss/penalty (config.proto:24-43)
    loss: str = "logit"  # logit | square_hinge
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0

    # learning rate / algorithm (config.proto:45-77)
    algo: str = "ftrl"  # ftrl | adagrad | sgd
    lr_eta: float = 0.1
    lr_beta: float = 1.0

    # data / system knobs (config.proto:88-133)
    minibatch: int = 1000
    num_parts_per_file: int = 2
    rand_shuffle: int = 0  # shuffle buffer in minibatches (0 = off)
    neg_sampling: float = 1.0
    fixed_bytes: int = 0  # gradient-push quantization filter
    print_sec: int = 1
    save_iter: int = -1
    load_iter: int = -1

    # TPU-native capacity knobs (replace dynamic shapes; SURVEY §7 hard
    # parts): table size = hash-kernel bucket count (ps FLAGS_max_key
    # analog), row_capacity = max nnz per minibatch
    num_buckets: int = 1 << 20
    nnz_per_row: int = 64

    # kernel = pallas (tiled MXU COO kernels, ops/coo_kernels.py) | xla
    # (segment ops) | auto (pallas on an unsharded-table TPU run, else xla)
    kernel: str = "auto"

    @property
    def row_capacity(self) -> int:
        return self.minibatch * self.nnz_per_row


def _loss_dual(loss: str, y01, xw):
    """Per-example objective and gradient dual d = dObj/dXw.

    logit (reference linear/loss.h:93-130): obj = softplus(xw) - y*xw,
    d = sigmoid(xw) - y    (y in {0,1})
    square_hinge (loss.h:132-157): obj = max(0, 1 - ys*xw)^2,
    d = -2 ys max(0, 1 - ys*xw)   (ys in {-1,+1})
    """
    if loss == "logit":
        obj = jax.nn.softplus(xw) - y01 * xw
        d = jax.nn.sigmoid(xw) - y01
    elif loss == "square_hinge":
        ys = 2.0 * y01 - 1.0
        m = jnp.maximum(0.0, 1.0 - ys * xw)
        obj = m * m
        d = -2.0 * ys * m
    else:
        raise ValueError(f"unknown loss {loss!r}")
    return obj, d


def _update(algo: str, state, g, touched, cfg: LinearConfig):
    """Per-bucket update rules (reference async_sgd.h:71-180 handles).

    touched masks buckets that received a push this step, so regularizer
    shrinkage applies exactly when the reference's per-key Push would run.
    """
    out = dict(state)
    if algo == "ftrl":
        w, z, n = state["w"], state["z"], state["n"]
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / cfg.lr_eta
        z = z + touched * (g - sigma * w)
        n = n + touched * g * g
        eta = (cfg.lr_beta + jnp.sqrt(n)) / cfg.lr_eta
        w_new = l1l2_solve(-z, eta, cfg.lambda_l1, cfg.lambda_l2)
        out["w"] = jnp.where(touched > 0, w_new, w)
        out["z"], out["n"] = z, n
    elif algo == "adagrad":
        w, n = state["w"], state["n"]
        n = n + touched * g * g
        eta = (cfg.lr_beta + jnp.sqrt(n)) / cfg.lr_eta
        w_new = l1l2_solve(eta * w - g, eta, cfg.lambda_l1, cfg.lambda_l2)
        out["w"] = jnp.where(touched > 0, w_new, w)
        out["n"] = n
    elif algo == "sgd":
        w = state["w"]
        eta = 1.0 / cfg.lr_eta  # constant step size lr_eta
        w_new = l1l2_solve(eta * w - g, eta, cfg.lambda_l1, cfg.lambda_l2)
        out["w"] = jnp.where(touched > 0, w_new, w)
    else:
        raise ValueError(f"unknown algo {algo!r}")
    return out


def _tables_for(algo: str) -> dict[str, TableSpec]:
    t = {"w": TableSpec()}
    if algo == "ftrl":
        t["z"] = TableSpec()
        t["n"] = TableSpec()
    elif algo == "adagrad":
        t["n"] = TableSpec()
    return t


class LinearLearner:
    """Jitted train/eval/predict steps over a sharded weight table."""

    def __init__(self, cfg: LinearConfig, mesh=None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(num_model=1)
        self.store = KVStore(self.mesh, cfg.num_buckets, _tables_for(cfg.algo))
        self._bsh1 = batch_sharding(self.mesh, 1)
        self._dropped_rows = 0
        self.use_pallas = cfg.kernel == "pallas" or (
            cfg.kernel == "auto"
            and jax.default_backend() == "tpu"
            and self.mesh.shape.get("model", 1) == 1
            and self.mesh.shape.get("data", 1) == 1
            and cfg.num_buckets % ck.TILE == 0
            and cfg.minibatch % ck.LANES == 0
        )
        if self.use_pallas:
            assert cfg.num_buckets % ck.TILE == 0, (
                f"pallas kernel needs num_buckets % {ck.TILE} == 0")
            assert cfg.minibatch % ck.LANES == 0, (
                f"pallas kernel needs minibatch % {ck.LANES} == 0")

        @partial(jax.jit, donate_argnums=0)
        def train_step(state, seg, idx, val, label, mask):
            w = state["w"]
            xw = spmv(seg, idx, val, w, label.shape[0])
            obj, d = _loss_dual(cfg.loss, label, xw)
            d = d * mask
            g = spmv_t(seg, idx, val, d, cfg.num_buckets)
            # touched is derived from the unquantized gradient so that
            # values the transfer filter rounds to zero still count as
            # pushed (the reference server receives and shrinks them too)
            raw_g = g
            g = quantize_push(g, cfg.fixed_bytes)
            g = self.store.constrain("w", g)
            # The touched mask marks buckets that received a push this step.
            # For FTRL it is unnecessary: g == 0 leaves z and n unchanged and
            # w is a pure function of (z, n), so untouched buckets are exact
            # no-ops without masking — this saves a second full scatter
            # (~25% of step time on TPU). adagrad/sgd apply repeated L1
            # shrinkage through l1l2_solve, so they still need the mask;
            # g != 0 reproduces the reference's per-key Push granularity
            # (async_sgd.h:160-175) except for exact zero-cancellation
            # gradients, which the reference would push and shrink on.
            if cfg.algo == "ftrl":
                touched = 1.0
            else:
                touched = (raw_g != 0).astype(jnp.float32)
            new_state = _update(cfg.algo, state, g, touched, cfg)
            prog = _progress(obj, xw, label, mask)
            return new_state, prog

        @jax.jit
        def eval_step(state, seg, idx, val, label, mask):
            xw = spmv(seg, idx, val, state["w"], label.shape[0])
            obj, _ = _loss_dual(cfg.loss, label, xw)
            return _progress(obj, xw, label, mask)

        @jax.jit
        def predict_step(state, seg, idx, val):
            return spmv(seg, idx, val, state["w"], cfg.minibatch)

        self._train_step = train_step
        self._eval_step = eval_step
        self._predict_step = predict_step

        @partial(jax.jit, donate_argnums=0)
        def train_step_coo(state, sidx, sseg, sval, tmap, first, label, mask):
            xw = ck.coo_spmv(state["w"], sidx, sseg, sval, tmap, first,
                             cfg.minibatch)
            obj, d = _loss_dual(cfg.loss, label, xw)
            d = d * mask
            g = ck.coo_spmv_t(d, sidx, sseg, sval, tmap, first,
                              cfg.num_buckets)
            raw_g = g
            g = quantize_push(g, cfg.fixed_bytes)
            if cfg.algo == "ftrl":
                touched = 1.0
            else:
                touched = (raw_g != 0).astype(jnp.float32)
            new_state = _update(cfg.algo, state, g, touched, cfg)
            return new_state, _progress(obj, xw, label, mask)

        @jax.jit
        def eval_step_coo(state, sidx, sseg, sval, tmap, first, label, mask):
            xw = ck.coo_spmv(state["w"], sidx, sseg, sval, tmap, first,
                             cfg.minibatch)
            obj, _ = _loss_dual(cfg.loss, label, xw)
            return _progress(obj, xw, label, mask)

        @jax.jit
        def predict_step_coo(state, sidx, sseg, sval, tmap, first):
            return ck.coo_spmv(state["w"], sidx, sseg, sval, tmap, first,
                               cfg.minibatch)

        self._train_step_coo = train_step_coo
        self._eval_step_coo = eval_step_coo
        self._predict_step_coo = predict_step_coo

    # -- device batch plumbing ---------------------------------------------
    def _shard(self, *arrays):
        return tuple(jax.device_put(x, self._bsh1) for x in arrays)

    def make_device_batch(self, blk: RowBlock) -> DeviceBatch:
        db = to_device_batch(
            blk, self.cfg.minibatch, self.cfg.row_capacity, self.cfg.num_buckets
        )
        if db.dropped_rows:
            self._dropped_rows += db.dropped_rows
            import logging

            logging.getLogger(__name__).warning(
                "minibatch overflow: dropped %d rows (total %d) — raise "
                "nnz_per_row or minibatch capacity",
                db.dropped_rows, self._dropped_rows,
            )
        return db

    def prepare_batch(self, blk: RowBlock):
        """Host-side batch prep (runs in loader threads): pad to the fixed
        device shape, and for the pallas path additionally tile-sort the
        COO triples (the Localizer role). Returns an opaque prepared batch
        accepted by train/eval/predict_batch."""
        db = self.make_device_batch(blk)
        if not self.use_pallas:
            return ("xla", db, blk.size)
        p = ck.pack_sorted_coo(db.idx, db.seg, db.val, self.cfg.num_buckets,
                               capacity=self.cfg.row_capacity)
        return ("coo", p, db.label, db.row_mask, blk.size)

    def _prepared(self, x):
        if isinstance(x, RowBlock):
            x = self.prepare_batch(x)
        return x

    def train_batch(self, blk) -> dict:
        b = self._prepared(blk)
        if b[0] == "coo":
            _, p, label, mask, _ = b
            self.store.state, prog = self._train_step_coo(
                self.store.state, *self._coo_args(p, label, mask))
        else:
            db = b[1]
            self.store.state, prog = self._train_step(
                self.store.state,
                *self._shard(db.seg, db.idx, db.val, db.label, db.row_mask))
        return jax.tree_util.tree_map(float, prog)

    def eval_batch(self, blk) -> dict:
        b = self._prepared(blk)
        if b[0] == "coo":
            _, p, label, mask, _ = b
            prog = self._eval_step_coo(
                self.store.state, *self._coo_args(p, label, mask))
        else:
            db = b[1]
            prog = self._eval_step(
                self.store.state,
                *self._shard(db.seg, db.idx, db.val, db.label, db.row_mask))
        return jax.tree_util.tree_map(float, prog)

    def predict_batch(self, blk) -> np.ndarray:
        b = self._prepared(blk)
        if b[0] == "coo":
            _, p, _, _, size = b
            xw = self._predict_step_coo(
                self.store.state, *self._coo_args(p))
        else:
            db, size = b[1], b[2]
            xw = self._predict_step(
                self.store.state, *self._shard(db.seg, db.idx, db.val))
        return np.asarray(xw)[:size]

    def _coo_args(self, p, label=None, mask=None):
        args = [jnp.asarray(p.idx), jnp.asarray(p.seg), jnp.asarray(p.val),
                jnp.asarray(p.tmap), jnp.asarray(p.first)]
        if label is not None:
            args += [jnp.asarray(label), jnp.asarray(mask)]
        return args

    def nnz(self) -> int:
        return self.store.nnz("w")


def _progress(obj, xw, label, mask):
    """Per-batch mergeable progress vector (reference linear/progress.h:
    objv, auc, acc, #ex; scheduler-side weighted averaging)."""
    n = jnp.sum(mask)
    return {
        "objv": jnp.sum(obj * mask),
        "auc": M.auc(label, xw, mask) * n,
        "acc": M.accuracy(label, xw, mask) * n,
        "logloss": M.logloss(label, xw, mask) * n,
        "nex": n,
    }
