"""Sparse linear learner: async-SGD logistic regression, TPU-native.

Parity target: the reference's flagship `linear.dmlc` app
(learn/linear/async_sgd.h, loss.h, penalty.h, config.proto) — logistic /
squared-hinge loss over hashed sparse features, with per-key SGD / AdaGrad /
FTRL update rules and elastic-net regularization.

TPU design (vs the reference's worker/server processes):
- the weight/optimizer tables are a KVStore: hashed buckets sharded over
  the mesh model axis (the servers);
- a training step jits pull -> SpMV -> loss grad -> SpMV^T -> handle update
  end-to-end; the minibatch is sharded over the data axis (the workers) and
  XLA inserts the gather / reduce-scatter collectives that play
  ZPull/ZPush;
- the per-key Handle branches (async_sgd.h:71-180) become masked dense
  vector updates: untouched buckets carry zero gradient and a zero
  touched-mask, making the update a no-op exactly where the reference
  would not receive a push.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from wormhole_tpu.data.rowblock import DeviceBatch, RowBlock, to_device_batch
from wormhole_tpu.ops import coo_kernels as ck
from wormhole_tpu.ops import metrics as M
from wormhole_tpu.ops.penalty import l1l2_solve
from wormhole_tpu.ops.spmv import spmv, spmv_t
from wormhole_tpu.parallel.kvstore import KVStore, TableSpec, quantize_push
from wormhole_tpu.parallel.mesh import batch_sharding, make_mesh


@dataclasses.dataclass
class LinearConfig:
    """Config surface of reference learn/linear/config.proto (subset that
    is meaningful on TPU; names kept)."""

    train_data: str = ""
    val_data: Optional[str] = None
    model_out: Optional[str] = None
    model_in: Optional[str] = None
    predict_out: Optional[str] = None
    data_format: str = "libsvm"
    max_data_pass: int = 1

    # loss/penalty (config.proto:24-43)
    loss: str = "logit"  # logit | square_hinge
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    # predict output: raw margins (default) or probabilities
    # (reference linear/loss.h:55-63 prob_prediction)
    prob_predict: bool = False

    # learning rate / algorithm (config.proto:45-77)
    algo: str = "ftrl"  # ftrl | adagrad | sgd
    lr_eta: float = 0.1
    lr_beta: float = 1.0

    # data / system knobs (config.proto:88-133)
    minibatch: int = 1000
    num_parts_per_file: int = 2
    rand_shuffle: int = 0  # shuffle buffer in minibatches (0 = off)
    neg_sampling: float = 1.0
    fixed_bytes: int = 0  # gradient-push quantization filter
    # zlib-compress the PS delta stream (the reference's msg_compression
    # filter, config.proto:123-133; COMPRESSING in async_sgd.h:290-301)
    msg_compression: int = 0
    # bounded staleness (reference config.proto:122 max_delay,
    # criteo.conf:21): in the multi-process launch, the max number of
    # minibatches a worker trains between syncs against the server group
    max_delay: int = 16
    # concurrent in-flight minibatches per worker (reference
    # minibatch_solver.h:215-242 max_concurrency): here the number of
    # loader threads preparing batches (parse + pack) while the device
    # steps — the synchronous-XLA analog of overlapping pull/compute/push
    # of successive minibatches. 4 keeps a ~17 ms device step fed when a
    # 64k-row pack costs ~100 ms of host work.
    max_concurrency: int = 4
    # multi-process dispatch: online (greedy, straggler-reassigning) or
    # batch (stable n/num_workers assignment per pass); local_data asks
    # each worker to match train_data against ITS filesystem and report,
    # giving its parts node affinity (reference data_parallel.h:54-100,
    # config.proto local_data)
    dispatch: str = "online"
    local_data: bool = False
    # fault tolerance (docs/distributed.md "Fault tolerance"): cadence of
    # the ps servers' async shard snapshots (effective only when the
    # launcher provides a snapshot dir), and the worker-side PS retry
    # budget in seconds — 0 keeps the default fail-fast-on-server-death
    # behavior; the launcher's --max-server-restarts exports a matching
    # budget via WH_PS_RETRY_SEC, which a nonzero value here overrides
    server_snapshot_sec: float = 5.0
    ps_retry_sec: float = 0.0
    # global-mesh mode: the -n worker processes jax.distributed-initialize
    # into ONE SPMD mesh; gradients aggregate over ICI/DCN collectives
    # instead of the TCP parameter server (parallel/multihost.py)
    global_mesh: bool = False
    print_sec: int = 1
    save_iter: int = -1
    load_iter: int = -1

    # TPU-native capacity knobs (replace dynamic shapes; SURVEY §7 hard
    # parts): table size = hash-kernel bucket count (ps FLAGS_max_key
    # analog), row_capacity = max nnz per minibatch
    num_buckets: int = 1 << 20
    nnz_per_row: int = 64

    # in-process model-axis sharding: split the state tables over this
    # many mesh "model" shards (HBM residency for the hot parameter
    # plane; 1 = tables replicated, all devices on the data axis).
    # num_buckets must divide evenly over the shards.
    model_shards: int = 1

    # kernel = pallas (tiled MXU COO kernels, ops/coo_kernels.py) | xla
    # (segment ops) | auto (pallas on an unsharded-table TPU run, else xla)
    kernel: str = "auto"
    # Tile-aligned unique-key compaction (the Localizer path,
    # ops/localizer.py + coo_kernels.pack_tile_coo): the minibatch's
    # unique buckets get compact slots grouped by their home table tile;
    # a Pallas kernel streams only the TOUCHED tiles to gather w
    # (tile_gather), the COO kernels run over the compact domain, and the
    # optimizer update happens inside a Pallas kernel that rewrites each
    # touched tile in place (ops/fused_update.py) — no XLA element
    # gathers or scatters of state at all. Step cost O(touched tiles +
    # unique keys) instead of O(num_buckets): the TPU analog of the
    # reference server updating only pushed keys at their storage
    # (async_sgd.h:160-180). -1 = auto (sized from the first batch,
    # engaged when the compact domain is well under the table size),
    # 0 = off, >0 = explicit slot capacity (rounded up to a whole tile).
    compact_cap: int = -1
    # MXU compute dtype for the pallas kernels: bf16 (half the MXU cost;
    # table values and per-nnz gradients round to bfloat16) | f32 (exact,
    # matches kernel=xla numerics) | auto (f32 when fixed_bytes == 0 —
    # i.e. when gradient quantization is nominally off the kernel does not
    # silently re-introduce rounding — else bf16). Default "auto": default
    # numerics match the XLA path; bf16 is the documented opt-in for the
    # extra throughput (VERDICT r2 #8; both measured in PERF.md).
    kernel_dtype: str = "auto"

    @property
    def row_capacity(self) -> int:
        return self.minibatch * self.nnz_per_row


def _loss_dual(loss: str, y01, xw):
    """Per-example objective and gradient dual d = dObj/dXw.

    logit (reference linear/loss.h:93-130): obj = softplus(xw) - y*xw,
    d = sigmoid(xw) - y    (y in {0,1})
    square_hinge (loss.h:132-157): obj = max(0, 1 - ys*xw)^2,
    d = -2 ys max(0, 1 - ys*xw)   (ys in {-1,+1})
    """
    if loss == "logit":
        obj = jax.nn.softplus(xw) - y01 * xw
        d = jax.nn.sigmoid(xw) - y01
    elif loss == "square_hinge":
        ys = 2.0 * y01 - 1.0
        m = jnp.maximum(0.0, 1.0 - ys * xw)
        obj = m * m
        d = -2.0 * ys * m
    else:
        raise ValueError(f"unknown loss {loss!r}")
    return obj, d


def _update(algo: str, state, g, touched, cfg: LinearConfig):
    """Per-bucket update rules (reference async_sgd.h:71-180 handles).

    touched masks buckets that received a push this step, so regularizer
    shrinkage applies exactly when the reference's per-key Push would run.
    """
    out = dict(state)
    if algo == "ftrl":
        w, z, n = state["w"], state["z"], state["n"]
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / cfg.lr_eta
        z = z + touched * (g - sigma * w)
        n = n + touched * g * g
        eta = (cfg.lr_beta + jnp.sqrt(n)) / cfg.lr_eta
        w_new = l1l2_solve(-z, eta, cfg.lambda_l1, cfg.lambda_l2)
        out["w"] = jnp.where(touched > 0, w_new, w)
        out["z"], out["n"] = z, n
    elif algo == "adagrad":
        w, n = state["w"], state["n"]
        n = n + touched * g * g
        eta = (cfg.lr_beta + jnp.sqrt(n)) / cfg.lr_eta
        w_new = l1l2_solve(eta * w - g, eta, cfg.lambda_l1, cfg.lambda_l2)
        out["w"] = jnp.where(touched > 0, w_new, w)
        out["n"] = n
    elif algo == "sgd":
        w = state["w"]
        eta = 1.0 / cfg.lr_eta  # constant step size lr_eta
        w_new = l1l2_solve(eta * w - g, eta, cfg.lambda_l1, cfg.lambda_l2)
        out["w"] = jnp.where(touched > 0, w_new, w)
    else:
        raise ValueError(f"unknown algo {algo!r}")
    return out


def _tables_for(algo: str) -> dict[str, TableSpec]:
    t = {"w": TableSpec()}
    if algo == "ftrl":
        t["z"] = TableSpec()
        t["n"] = TableSpec(wire_cap="bf16")  # second moment: see TableSpec
    elif algo == "adagrad":
        t["n"] = TableSpec(wire_cap="bf16")
    return t


class LinearLearner:
    """Jitted train/eval/predict steps over a sharded weight table."""

    def __init__(self, cfg: LinearConfig, mesh=None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(num_model=1)
        self.store = KVStore(self.mesh, cfg.num_buckets, _tables_for(cfg.algo))
        self._bsh1 = batch_sharding(self.mesh, 1)
        self._dropped_rows = 0
        D = self.mesh.shape.get("data", 1)
        M = self.mesh.shape.get("model", 1)
        # per-shard kernel constraints: each model shard owns whole tiles,
        # each data shard owns whole lane groups (mesh_coo_* wrappers)
        shapes_ok = (cfg.num_buckets % (M * ck.TILE) == 0
                     and cfg.minibatch % (D * ck.LANES) == 0)
        self.use_pallas = cfg.kernel == "pallas" or (
            cfg.kernel == "auto"
            and jax.default_backend() == "tpu"
            and shapes_ok
        )
        # mesh layout (shard_map + psum collectives) whenever any axis > 1
        self._mesh_coo = self.use_pallas and (D > 1 or M > 1)
        self._shard_cap = ck.mesh_capacity(cfg.row_capacity, D, M)
        if self.use_pallas:
            assert cfg.num_buckets % (M * ck.TILE) == 0, (
                f"pallas kernel needs num_buckets % {M * ck.TILE} == 0")
            assert cfg.minibatch % (D * ck.LANES) == 0, (
                f"pallas kernel needs minibatch % {D * ck.LANES} == 0")
        # MXU compute dtype for the COO kernels. None defers to the kernel
        # default (bf16 on TPU, f32 in interpret mode); "auto" keeps f32
        # whenever fixed_bytes == 0 so disabling gradient quantization also
        # disables the kernels' bf16 rounding (ADVICE r1).
        if cfg.kernel_dtype == "f32":
            self._coo_dtype = jnp.float32
        elif cfg.kernel_dtype == "auto" and cfg.fixed_bytes == 0:
            self._coo_dtype = jnp.float32
        else:
            self._coo_dtype = None

        @partial(jax.jit, donate_argnums=0)
        def train_step(state, seg, idx, val, label, mask):
            w = state["w"]
            xw = spmv(seg, idx, val, w, label.shape[0])
            obj, d = _loss_dual(cfg.loss, label, xw)
            d = d * mask
            g = spmv_t(seg, idx, val, d, cfg.num_buckets)
            # touched is derived from the unquantized gradient so that
            # values the transfer filter rounds to zero still count as
            # pushed (the reference server receives and shrinks them too)
            raw_g = g
            g = quantize_push(g, cfg.fixed_bytes)
            g = self.store.constrain("w", g)
            # The touched mask marks buckets that received a push this step.
            # For FTRL it is unnecessary: g == 0 leaves z and n unchanged and
            # w is a pure function of (z, n), so untouched buckets are exact
            # no-ops without masking — this saves a second full scatter
            # (~25% of step time on TPU). adagrad/sgd apply repeated L1
            # shrinkage through l1l2_solve, so they still need the mask;
            # g != 0 reproduces the reference's per-key Push granularity
            # (async_sgd.h:160-175) except for exact zero-cancellation
            # gradients, which the reference would push and shrink on.
            if cfg.algo == "ftrl":
                touched = 1.0
            else:
                touched = (raw_g != 0).astype(jnp.float32)
            new_state = _update(cfg.algo, state, g, touched, cfg)
            new_w = (jnp.sum(new_state["w"] != 0)
                     - jnp.sum(w != 0)).astype(jnp.float32)
            prog = _progress(obj, xw, label, mask, new_w)
            return new_state, prog

        @jax.jit
        def eval_step(state, seg, idx, val, label, mask):
            xw = spmv(seg, idx, val, state["w"], label.shape[0])
            obj, _ = _loss_dual(cfg.loss, label, xw)
            return _progress(obj, xw, label, mask)

        @jax.jit
        def predict_step(state, seg, idx, val):
            return spmv(seg, idx, val, state["w"], cfg.minibatch)

        self._train_step = train_step
        self._eval_step = eval_step
        self._predict_step = predict_step

        @partial(jax.jit, donate_argnums=0)
        def train_step_coo(state, sidx, sseg, sval, tmap, first, label, mask):
            # NOTE r5: a row-major xw (XLA row gather from a widened w
            # table) was tried here and measured ~50 ns/row — the dense
            # table (num_buckets x 8 B, 32 MB at the headline shape) is
            # too large for the fast-gather regime, unlike the compact
            # paths (PERF.md "Row-gather regimes"). The radix-image
            # kernel stays.
            xw = ck.coo_spmv(state["w"], sidx, sseg, sval, tmap, first,
                             cfg.minibatch, dtype=self._coo_dtype)
            obj, d = _loss_dual(cfg.loss, label, xw)
            d = d * mask
            g = ck.coo_spmv_t(d, sidx, sseg, sval, tmap, first,
                              cfg.num_buckets, dtype=self._coo_dtype)
            raw_g = g
            g = quantize_push(g, cfg.fixed_bytes)
            if cfg.algo == "ftrl":
                touched = 1.0
            else:
                touched = (raw_g != 0).astype(jnp.float32)
            new_w = -jnp.sum(state["w"] != 0).astype(jnp.float32)
            new_state = _update(cfg.algo, state, g, touched, cfg)
            new_w = new_w + jnp.sum(new_state["w"] != 0)
            return new_state, _progress(obj, xw, label, mask, new_w)

        @jax.jit
        def eval_step_coo(state, sidx, sseg, sval, tmap, first, label, mask):
            xw = ck.coo_spmv(state["w"], sidx, sseg, sval, tmap, first,
                             cfg.minibatch, dtype=self._coo_dtype)
            obj, _ = _loss_dual(cfg.loss, label, xw)
            return _progress(obj, xw, label, mask)

        @jax.jit
        def predict_step_coo(state, sidx, sseg, sval, tmap, first):
            return ck.coo_spmv(state["w"], sidx, sseg, sval, tmap, first,
                               cfg.minibatch, dtype=self._coo_dtype)

        self._train_step_coo = train_step_coo
        self._eval_step_coo = eval_step_coo
        self._predict_step_coo = predict_step_coo

        # mesh variants: tiles shard_map'ed over the model axis, rows over
        # the data axis; psum plays ZPull/ZPush (async_sgd.h:277-287)
        mesh = self.mesh

        @partial(jax.jit, donate_argnums=0)
        def train_step_mcoo(state, sidx, sseg, sval, tmap, first,
                            label, mask):
            w = state["w"]
            xw = ck.mesh_coo_spmv(mesh, w, sidx, sseg, sval, tmap, first,
                                  cfg.minibatch, dtype=self._coo_dtype)
            obj, d = _loss_dual(cfg.loss, label, xw)
            d = d * mask
            g = ck.mesh_coo_spmv_t(mesh, d, sidx, sseg, sval, tmap, first,
                                   cfg.num_buckets, dtype=self._coo_dtype)
            raw_g = g
            g = quantize_push(g, cfg.fixed_bytes)
            if cfg.algo == "ftrl":
                touched = 1.0
            else:
                touched = (raw_g != 0).astype(jnp.float32)
            new_state = _update(cfg.algo, state, g, touched, cfg)
            new_w = (jnp.sum(new_state["w"] != 0)
                     - jnp.sum(w != 0)).astype(jnp.float32)
            return new_state, _progress(obj, xw, label, mask, new_w)

        @jax.jit
        def eval_step_mcoo(state, sidx, sseg, sval, tmap, first,
                           label, mask):
            xw = ck.mesh_coo_spmv(mesh, state["w"], sidx, sseg, sval,
                                  tmap, first, cfg.minibatch,
                                  dtype=self._coo_dtype)
            obj, _ = _loss_dual(cfg.loss, label, xw)
            return _progress(obj, xw, label, mask)

        @jax.jit
        def predict_step_mcoo(state, sidx, sseg, sval, tmap, first):
            return ck.mesh_coo_spmv(mesh, state["w"], sidx, sseg, sval,
                                    tmap, first, cfg.minibatch,
                                    dtype=self._coo_dtype)

        self._train_step_mcoo = train_step_mcoo
        self._eval_step_mcoo = eval_step_mcoo
        self._predict_step_mcoo = predict_step_mcoo

        # compacted steps are built lazily once the unique-key capacity
        # is known (auto mode sizes it from the first batch); the lock
        # serializes the decide+build against concurrent loader threads
        self._compact_cap: Optional[int] = None
        self._tcoo_steps = None
        self._compact_lock = threading.Lock()
        if self._mesh_coo or not self.use_pallas or cfg.compact_cap == 0:
            self._compact_cap = 0
        # sparse PS wire hints: unique buckets touched by trained batches
        # since the last collect_touched() drain (runtime/ps_server)
        self.track_touched = False
        self._touched_lock = threading.Lock()
        self._touched: list[Optional[np.ndarray]] = []

    # -- global-mesh SPMD protocol (apps/_runner._global_train) ------------
    def global_step_protocol(self):
        def train_fn(args, rng):
            self.store.state, prog = self._train_step(
                self.store.state, *args)
            return prog

        def eval_fn(args):
            return self._eval_step(self.store.state, *args)

        return train_fn, eval_fn

    def global_predict_protocol(self):
        """pred_fn over (seg, idx, val, mask) GLOBAL arrays returning
        (margins pinned to the batch sharding — so each rank reads back
        exactly its contributed rows — and the GLOBAL live-row count
        that drives the lockstep drain decision)."""
        from wormhole_tpu.parallel.mesh import batch_sharding

        bsh = batch_sharding(self.mesh, 1)

        @jax.jit
        def pred(state, seg, idx, val, mask):
            xw = self._predict_step(state, seg, idx, val)
            return jax.lax.with_sharding_constraint(xw, bsh), jnp.sum(mask)

        def pred_fn(args):
            seg, idx, val, mask = args
            return pred(self.store.state, seg, idx, val, mask)

        return pred_fn

    def derived_tables(self) -> dict:
        """Tables that are non-additive pure functions of additive ones,
        for server-side recomputation in the multi-process PS data plane
        (runtime/ps_server.ServerNode._recompute_derived)."""
        cfg = self.cfg
        if cfg.algo != "ftrl":
            return {}
        return {"w": {"kind": "ftrl_prox", "lr_eta": cfg.lr_eta,
                      "lr_beta": cfg.lr_beta, "lambda_l1": cfg.lambda_l1,
                      "lambda_l2": cfg.lambda_l2}}

    # -- unique-key compaction ---------------------------------------------
    def ensure_compact(self, idx) -> int:
        """Decide (once, from the first batch) whether the unique-key
        compacted path engages and build its jitted steps. Returns the
        compact capacity (0 = dense path)."""
        with self._compact_lock:
            if self._compact_cap is None:
                cap = self._decide_compact_cap(idx)
                if cap:
                    self._build_tcoo(cap)
                # publish the cap only after the steps exist, so a racing
                # reader can never see cap set but steps still None
                self._compact_cap = cap
        return self._compact_cap

    def _decide_compact_cap(self, idx) -> int:
        """Pick the compact slot capacity from the first batch: 1.5x
        headroom in update blocks over what the batch needs (batches draw
        from the same key distribution; overflow falls back to
        drop-and-warn), rounded to whole tiles. Engaged only when the
        compact domain is well under the table size — otherwise the dense
        path's per-tile padding is already cheaper than the extra
        tile_gather / scatter_update streaming (constant measured on
        v5e)."""
        cfg = self.cfg
        if cfg.compact_cap > 0:
            return -(-cfg.compact_cap // ck.TILE) * ck.TILE
        ids = np.unique(np.asarray(idx, np.int64))
        blocks = ck.tile_blocks_needed(ids, ck.TILE)
        cand = -(-int(1.5 * blocks) * ck.BLK_U // ck.TILE) * ck.TILE
        if cfg.num_buckets >= 32 * cand:
            return cand
        return 0

    def _build_tcoo(self, U: int):
        cfg = self.cfg
        from wormhole_tpu.ops.fused_update import scatter_update

        def rm_xw_c(wc, rm_slot, rm_val):
            # same row-major pull as the dense path, over the compact wc
            wz = jnp.concatenate([wc, jnp.zeros((1,), wc.dtype)])
            w2c = jnp.stack([wz, wz], axis=1)
            got = jnp.take(w2c, rm_slot, axis=0)[:, 0]
            return (rm_val * got).reshape(cfg.minibatch, -1).sum(1)

        @partial(jax.jit, donate_argnums=0)
        def train_step_tcoo(state, uniq, tmap_u, first_u, last_u,
                            sidx, sseg, sval, tmap, first,
                            rm_slot, rm_val, label, mask):
            w2 = state["w"].reshape(-1, ck.LANES)
            wc = ck.tile_gather(w2, uniq, tmap_u, dtype=self._coo_dtype)
            xw = rm_xw_c(wc, rm_slot, rm_val)
            obj, d = _loss_dual(cfg.loss, label, xw)
            d = d * mask
            g = ck.coo_spmv_t(d, sidx, sseg, sval, tmap, first, U,
                              dtype=self._coo_dtype)
            # the scatter, quantization filter, touched masking, and the
            # per-key handle update all happen inside the fused kernel,
            # in place on the touched tiles
            new_state, new_w = scatter_update(
                cfg.algo, state, g, uniq, tmap_u, first_u, last_u,
                lr_eta=cfg.lr_eta, lr_beta=cfg.lr_beta,
                lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
                fixed_bytes=cfg.fixed_bytes, dtype=self._coo_dtype)
            return new_state, _progress(obj, xw, label, mask, new_w)

        # eval/predict read only the gathered compact w and the row-major
        # (rm_slot, rm_val) pull — the COO stream and update-block bounds
        # are train-only, so shipping them host→device every eval batch
        # was pure waste (ADVICE #3)
        @jax.jit
        def eval_step_tcoo(state, uniq, tmap_u, rm_slot, rm_val,
                           label, mask):
            w2 = state["w"].reshape(-1, ck.LANES)
            wc = ck.tile_gather(w2, uniq, tmap_u, dtype=self._coo_dtype)
            xw = rm_xw_c(wc, rm_slot, rm_val)
            obj, _ = _loss_dual(cfg.loss, label, xw)
            return _progress(obj, xw, label, mask)

        @jax.jit
        def predict_step_tcoo(state, uniq, tmap_u, rm_slot, rm_val):
            w2 = state["w"].reshape(-1, ck.LANES)
            wc = ck.tile_gather(w2, uniq, tmap_u, dtype=self._coo_dtype)
            return rm_xw_c(wc, rm_slot, rm_val)

        self._tcoo_steps = (train_step_tcoo, eval_step_tcoo,
                            predict_step_tcoo)

    # -- device batch plumbing ---------------------------------------------
    def _shard(self, *arrays):
        return tuple(jax.device_put(x, self._bsh1) for x in arrays)

    def make_device_batch(self, blk: RowBlock) -> DeviceBatch:
        db = to_device_batch(
            blk, self.cfg.minibatch, self.cfg.row_capacity, self.cfg.num_buckets
        )
        if db.dropped_rows:
            self._dropped_rows += db.dropped_rows
            import logging

            logging.getLogger(__name__).warning(
                "minibatch overflow: dropped %d rows (total %d) — raise "
                "nnz_per_row or minibatch capacity",
                db.dropped_rows, self._dropped_rows,
            )
        return db

    def prepare_batch(self, blk: RowBlock, train: bool = True):
        """Host-side batch prep (runs in loader threads): pad to the fixed
        device shape, and for the pallas path additionally tile-sort the
        COO triples (the Localizer role). Returns an opaque prepared batch
        accepted by train/eval/predict_batch."""
        db = self.make_device_batch(blk)
        if not self.use_pallas:
            return ("xla", db, blk.size)
        if self._mesh_coo:
            D = self.mesh.shape.get("data", 1)
            M = self.mesh.shape.get("model", 1)
            mc = ck.pack_mesh_coo(db.idx, db.seg, db.val,
                                  self.cfg.num_buckets, self.cfg.minibatch,
                                  D, M, self._shard_cap)
            if mc.dropped_nnz:
                import logging

                logging.getLogger(__name__).warning(
                    "mesh shard overflow: dropped %d nonzeros — raise "
                    "nnz_per_row or mesh_capacity slack", mc.dropped_nnz)
            return ("mcoo", mc, db.label, db.row_mask, blk.size)
        if self.ensure_compact(db.idx):
            tc = ck.pack_tile_coo(db.idx, db.seg, db.val,
                                  self.cfg.num_buckets, self._compact_cap,
                                  capacity=self.cfg.row_capacity,
                                  rm_rows=self.cfg.minibatch,
                                  rm_width=self.cfg.nnz_per_row)
            if tc.dropped_nnz:
                import logging

                logging.getLogger(__name__).warning(
                    "compaction overflow: dropped %d unique keys "
                    "(%d nonzeros) — raise compact_cap (currently %d)",
                    tc.dropped_uniq, tc.dropped_nnz, self._compact_cap)
            return ("tcoo", tc, db.label, db.row_mask, blk.size)
        p = ck.pack_sorted_coo(db.idx, db.seg, db.val, self.cfg.num_buckets,
                               capacity=self.cfg.row_capacity)
        return ("coo", p, db.label, db.row_mask, blk.size)

    def _prepared(self, x):
        if isinstance(x, RowBlock):
            x = self.prepare_batch(x)
        return x

    # -- epoch pack cache ----------------------------------------------------
    #: bump when prepare_batch's output layout changes for identical input
    _PACK_VERSION = 1

    def pack_cache_token(self, train: bool = True):
        """Everything (beyond the raw batch bytes) that decides what
        prepare_batch emits, or None while that is still undecided. The
        compact-path decision is made lazily from the first batch
        (ensure_compact), so until `_compact_cap` resolves the pack
        output is not yet a pure function of the key — the first cold
        part simply goes uncached and caching engages from the next
        part on."""
        if self._compact_cap is None:
            return None
        cfg = self.cfg
        return ("linear", self._PACK_VERSION, self.use_pallas,
                self._mesh_coo, self._compact_cap, self._shard_cap,
                cfg.minibatch, cfg.nnz_per_row, cfg.num_buckets,
                self.mesh.shape.get("data", 1),
                self.mesh.shape.get("model", 1),
                ck.TILE, ck.BLK, ck.BLK_U, ck.LANES)

    # -- double-buffered device feed -----------------------------------------
    def stage_batch(self, b, train: bool = True):
        """Move a prepared batch's arrays to the device from the loader
        thread, so the host->device transfer of batch N+1 overlaps the
        main thread's step on batch N. Returns a staged tuple that
        train_batch/eval_batch consume without further transfers. The
        `train` flag must match the consuming step (tcoo ships the COO
        stream + update-block bounds only for training)."""
        b = self._prepared(b)
        if b[0] == "staged":
            return b
        kind, size = b[0], b[-1]
        # touched-id extraction needs the host arrays; grab it now
        # because after staging only device arrays remain
        ids = self._touched_ids(b) if (train and self.track_touched) \
            else None
        if kind == "mcoo":
            _, mc, label, mask, _ = b
            args = tuple(self._mcoo_args(mc, label, mask))
        elif kind == "tcoo":
            _, tc, label, mask, _ = b
            args = tuple(self._tcoo_args(tc, label, mask, train=train))
        elif kind == "coo":
            _, p, label, mask, _ = b
            args = tuple(self._coo_args(p, label, mask))
        else:
            db = b[1]
            args = self._shard(db.seg, db.idx, db.val, db.label,
                               db.row_mask)
        return ("staged", kind, args, size, ids, train)

    # -- sparse PS wire hints ------------------------------------------------
    def _touched_ids(self, b) -> Optional[np.ndarray]:
        """Unique buckets a prepared batch touches, from its host arrays
        (the sparse PS push set; reference ZPush of the minibatch's keys,
        async_sgd.h:270-287). None = unknown (forces a full delta scan)."""
        kind = b[0]
        if kind == "staged":
            return b[4]
        if kind == "xla":
            db = b[1]
            ids = np.unique(db.idx[db.val != 0])
        elif kind == "coo":
            p = b[1]
            ids = np.unique(p.idx[p.val != 0])
        elif kind == "tcoo":
            u = b[1].uniq
            ids = u[u < self.cfg.num_buckets]
        else:  # mcoo holds shard-local layouts; fall back to the scan
            return None
        return ids.astype(np.int64)

    def _note_touched(self, b) -> None:
        with self._touched_lock:
            self._touched.append(self._touched_ids(b))

    def collect_touched(self):
        """Sorted-unique global rows touched since the last call, per
        table, or None if any batch lacked a hint (SyncedStore then
        falls back to a full delta scan for this sync)."""
        with self._touched_lock:
            acc = self._touched
            self._touched = []
        if any(a is None for a in acc):
            return None
        u = (np.unique(np.concatenate(acc)) if acc
             else np.empty(0, np.int64))
        return {k: u for k in self.store.state}

    def train_batch(self, blk) -> dict:
        b = self._prepared(blk)
        if self.track_touched:
            self._note_touched(b)
        if b[0] == "staged":
            _, kind, args, _, _, st_train = b
            assert st_train, "batch was staged for eval, not train"
            step = {"mcoo": self._train_step_mcoo,
                    "coo": self._train_step_coo,
                    "xla": self._train_step}.get(kind)
            if step is None:  # tcoo builds lazily
                step = self._tcoo_steps[0]
            self.store.state, prog = step(self.store.state, *args)
            return jax.tree_util.tree_map(float, prog)
        if b[0] == "mcoo":
            _, mc, label, mask, _ = b
            self.store.state, prog = self._train_step_mcoo(
                self.store.state, *self._mcoo_args(mc, label, mask))
        elif b[0] == "tcoo":
            _, tc, label, mask, _ = b
            self.store.state, prog = self._tcoo_steps[0](
                self.store.state,
                *self._tcoo_args(tc, label, mask, train=True))
        elif b[0] == "coo":
            _, p, label, mask, _ = b
            self.store.state, prog = self._train_step_coo(
                self.store.state, *self._coo_args(p, label, mask))
        else:
            db = b[1]
            self.store.state, prog = self._train_step(
                self.store.state,
                *self._shard(db.seg, db.idx, db.val, db.label, db.row_mask))
        return jax.tree_util.tree_map(float, prog)

    def eval_batch(self, blk) -> dict:
        b = self._prepared(blk)
        if b[0] == "staged":
            _, kind, args, _, _, st_train = b
            assert not st_train, "batch was staged for train, not eval"
            step = {"mcoo": self._eval_step_mcoo,
                    "coo": self._eval_step_coo,
                    "xla": self._eval_step}.get(kind)
            if step is None:
                step = self._tcoo_steps[1]
            prog = step(self.store.state, *args)
            return jax.tree_util.tree_map(float, prog)
        if b[0] == "mcoo":
            _, mc, label, mask, _ = b
            prog = self._eval_step_mcoo(
                self.store.state, *self._mcoo_args(mc, label, mask))
        elif b[0] == "tcoo":
            _, tc, label, mask, _ = b
            prog = self._tcoo_steps[1](
                self.store.state, *self._tcoo_args(tc, label, mask))
        elif b[0] == "coo":
            _, p, label, mask, _ = b
            prog = self._eval_step_coo(
                self.store.state, *self._coo_args(p, label, mask))
        else:
            db = b[1]
            prog = self._eval_step(
                self.store.state,
                *self._shard(db.seg, db.idx, db.val, db.label, db.row_mask))
        return jax.tree_util.tree_map(float, prog)

    def predict_batch(self, blk) -> np.ndarray:
        b = self._prepared(blk)
        if b[0] == "mcoo":
            _, mc, _, _, size = b
            xw = self._predict_step_mcoo(
                self.store.state, *self._mcoo_args(mc))
        elif b[0] == "tcoo":
            _, tc, _, _, size = b
            xw = self._tcoo_steps[2](
                self.store.state, *self._tcoo_args(tc))
        elif b[0] == "coo":
            _, p, _, _, size = b
            xw = self._predict_step_coo(
                self.store.state, *self._coo_args(p))
        else:
            db, size = b[1], b[2]
            xw = self._predict_step(
                self.store.state, *self._shard(db.seg, db.idx, db.val))
        out = np.asarray(xw)[:size]
        if self.cfg.prob_predict:
            out = 1.0 / (1.0 + np.exp(-out))
        return out

    def _tcoo_args(self, tc, label=None, mask=None, train=False):
        # the COO stream + update-block bounds feed only the train step's
        # gradient transpose and fused scatter; eval/predict take the
        # short form (see eval_step_tcoo)
        args = [jnp.asarray(tc.uniq), jnp.asarray(tc.tmap_u)]
        if train:
            p = tc.coo
            args += [jnp.asarray(tc.first_u), jnp.asarray(tc.last_u),
                     jnp.asarray(p.idx), jnp.asarray(p.seg),
                     jnp.asarray(p.val), jnp.asarray(p.tmap),
                     jnp.asarray(p.first)]
        args += [jnp.asarray(tc.rm_slot), jnp.asarray(tc.rm_val)]
        if label is not None:
            args += [jnp.asarray(label), jnp.asarray(mask)]
        return args

    def _coo_args(self, p, label=None, mask=None):
        args = [jnp.asarray(p.idx), jnp.asarray(p.seg), jnp.asarray(p.val),
                jnp.asarray(p.tmap), jnp.asarray(p.first)]
        if label is not None:
            args += [jnp.asarray(label), jnp.asarray(mask)]
        return args

    def _mcoo_args(self, mc, label=None, mask=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P("data", "model", None))
        args = [jax.device_put(x, sh) for x in
                (mc.sidx, mc.sseg, mc.sval, mc.tmap, mc.first)]
        if label is not None:
            args += [jax.device_put(label, self._bsh1),
                     jax.device_put(mask, self._bsh1)]
        return args

    def nnz(self) -> int:
        return self.store.nnz("w")


def _progress(obj, xw, label, mask, new_w=None):
    """Per-batch mergeable progress vector (reference linear/progress.h:
    objv, auc, acc, #ex, new_w; scheduler-side weighted averaging).
    clk/pclk feed the COPC column (binary_class_evaluation.h:76-85);
    new_w is the |w|_0 delta the train step computed device-side."""
    n = jnp.sum(mask)
    p = {
        "objv": jnp.sum(obj * mask),
        "auc": M.auc(label, xw, mask) * n,
        "acc": M.accuracy(label, xw, mask) * n,
        "logloss": M.logloss(label, xw, mask) * n,
        "nex": n,
        "clk": jnp.sum(label * mask),
        "pclk": jnp.sum(jax.nn.sigmoid(xw) * mask),
    }
    if new_w is not None:
        p["new_w"] = new_w
    return p
