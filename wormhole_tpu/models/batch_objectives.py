"""Batch objectives for the L-BFGS solver: linear and FM.

Parity targets:
- learn/lbfgs-linear (lbfgs.cc, linear.h): logistic/linear regression with
  the bias stored at w[num_feature] (linear.h:91-99), feature count
  discovered as the max column id over all data shards (lbfgs.cc:107-113,
  an Allreduce<Max> in the reference — here a max over the host scan), and
  L1 via the solver's OWL-QN path.
- learn/lbfgs-fm (fm.cc, fm.h): factorization machine with the flat
  parameter layout [w(d); V(d x k); bias] (fm.cc:133-140), V initialized
  N(0, sigma) (fm.cc:141-156), FM margin math (fm.h:84-107).

TPU design: the dataset is loaded once into fixed-shape device batches
sharded over the data axis (the reference's per-rank RowBlockIter cache);
the flat parameter vector is sharded over all devices; each objective is a
pure per-batch loss and jax.grad produces the exact gradient — the
per-thread gradient buffers and hand-written backward passes of the
reference (fm.cc:209-242) are unnecessary under XLA.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from wormhole_tpu.data.rowblock import to_device_batch
from wormhole_tpu.parallel.mesh import batch_sharding
from wormhole_tpu.solver.workload import iter_rowblocks


def load_batches(pattern: str, mesh, fmt: str = "libsvm",
                 minibatch: int = 4096, nnz_per_row: int = 64,
                 num_parts_per_file: int = 1):
    """Read all data into device-resident fixed-shape batches; returns
    (batches, num_feature) with num_feature = max id + 1 over all shards
    (the Allreduce<Max> of lbfgs.cc:107-113)."""
    bsh = batch_sharding(mesh, 1)
    batches = []
    max_id = -1
    for blk in iter_rowblocks(pattern, num_parts_per_file, fmt, minibatch):
        if blk.nnz:
            max_id = max(max_id, int(blk.index.max()))
        # raw column ids, no hash kernel (batch solvers use the true
        # feature space like the reference's RowBlockIter path); ids
        # must fit the device index dtype
        assert max_id < 2 ** 31 - 1, "batch objectives need int32 ids"
        db = to_device_batch(blk, minibatch, minibatch * nnz_per_row,
                             2 ** 31 - 1)
        put = lambda x: jax.device_put(x, bsh)
        batches.append((put(db.seg), put(db.idx), put(db.val),
                        put(db.label), put(db.row_mask)))
    return batches, max_id + 1


def load_batches_global(pattern: str, mesh, env, fmt: str = "libsvm",
                        minibatch: int = 4096, nnz_per_row: int = 64,
                        num_parts_per_file: int = 1):
    """Multi-process variant of load_batches (requires an initialized
    jax.distributed cluster): each process reads its rank-slice of file
    parts (the reference RowBlockIter(rank, world) split, lbfgs.cc:
    229-234) and contributes minibatch/num_workers rows of every GLOBAL
    batch; ranks with fewer local batches pad with masked empties so all
    processes hold the same batch count — every eval/grad over a batch
    is an SPMD collective and must run in lockstep."""
    from wormhole_tpu.data.minibatch import MinibatchIter
    from wormhole_tpu.parallel import multihost as mh

    rank, nproc = env.rank, env.num_workers
    assert minibatch % nproc == 0, (minibatch, nproc)
    local_rows = minibatch // nproc
    local_cap = local_rows * nnz_per_row
    local, max_id = [], -1
    for f, k in mh.rank_parts(pattern, num_parts_per_file, env):
        for blk in MinibatchIter(f, k, num_parts_per_file, fmt,
                                 minibatch_size=local_rows):
            if blk.nnz:
                max_id = max(max_id, int(blk.index.max()))
            local.append(blk)
    n_batches = mh.global_scalar_max(len(local))
    num_feature = mh.global_scalar_max(max_id) + 1
    empty = mh.empty_rowblock()
    bsh = batch_sharding(mesh, 1)
    out = []
    for i in range(n_batches):
        blk = local[i] if i < len(local) else empty
        db = to_device_batch(blk, local_rows, local_cap, 2 ** 31 - 1)
        out.append(mh.global_coo_batch(bsh, db, rank, local_rows,
                                       minibatch, nnz_per_row))
    return out, num_feature


def load_batches_bsp(pattern: str, mesh, env, client, fmt: str = "libsvm",
                     minibatch: int = 4096, nnz_per_row: int = 64,
                     num_parts_per_file: int = 1, key: str = "lbfgs_dim"):
    """BSP-allreduce variant of load_batches: each rank loads ITS stable
    slice of file parts into LOCAL device batches (no jax.distributed —
    parameters are replicated per rank and the solver reduces gradients
    and losses over the worker ring instead). The global feature count
    (the Allreduce<Max> of lbfgs.cc:107-113) is agreed through the
    scheduler BLOB channel: blobs persist, so a respawned worker
    re-reads the identical value without consuming a collective counter
    — its (version, seq) sequence stays aligned with the survivors'."""
    from wormhole_tpu.data.minibatch import MinibatchIter
    from wormhole_tpu.parallel import multihost as mh

    local, max_id = [], -1
    for f, k in mh.rank_parts(pattern, num_parts_per_file, env):
        for blk in MinibatchIter(f, k, num_parts_per_file, fmt,
                                 minibatch_size=minibatch):
            if blk.nnz:
                max_id = max(max_id, int(blk.index.max()))
            local.append(blk)
    assert max_id < 2 ** 31 - 1, "batch objectives need int32 ids"
    client.blob_put(f"{key}_{env.rank}", np.int64(max_id))
    if env.rank == 0 and not client.call(op="blob_get", key=key)["ok"]:
        dims = [int(client.blob_get(f"{key}_{r}", timeout=120))
                for r in range(env.num_workers)]
        client.blob_put(key, np.int64(max(dims)))
    num_feature = int(client.blob_get(key, timeout=120)) + 1
    bsh = batch_sharding(mesh, 1)
    batches = []
    for blk in local:  # a zero-part rank simply holds no batches
        db = to_device_batch(blk, minibatch, minibatch * nnz_per_row,
                             2 ** 31 - 1)
        put = lambda x: jax.device_put(x, bsh)
        batches.append((put(db.seg), put(db.idx), put(db.val),
                        put(db.label), put(db.row_mask)))
    return batches, num_feature


class _BatchObjBase:
    """Shared accumulate-over-batches eval/grad driver.

    The flat parameter vector is sharded over ALL mesh devices — the
    reference's rank partition of the weight vector and its history basis
    (lbfgs.h:127-136, 557-645). num_dim is zero-padded up to a multiple
    of the device count (named shardings need even splits); the padding
    is provably inert: it starts 0, receives 0 gradient (no data column
    references it), has l1_mask 0, and every solver update is a linear
    combination of such vectors."""

    def __init__(self, batches, mesh):
        self.batches = batches
        self.mesh = mesh
        ndev = mesh.size
        self.num_dim_padded = -(-self.num_dim // ndev) * ndev
        self._psh = NamedSharding(mesh, P(tuple(mesh.axis_names)))

        loss = self._batch_loss

        @jax.jit
        def eval_batch(p, *b):
            return loss(p, *b)

        @jax.jit
        def grad_batch(p, *b):
            return jax.grad(loss)(p, *b)

        self._eval_batch = eval_batch
        self._grad_batch = grad_batch

    def eval(self, p) -> float:
        tot = jnp.zeros(())
        for b in self.batches:
            tot = tot + self._eval_batch(p, *b)
        return float(tot)

    def grad(self, p):
        g = jnp.zeros_like(p)
        for b in self.batches:
            g = g + self._grad_batch(p, *b)
        return g

    def place(self, p):
        pad = self.num_dim_padded - p.shape[0]
        if pad:
            p = jnp.concatenate([p, jnp.zeros(pad, p.dtype)])
        p = np.asarray(p)
        # make_array_from_callback works on multi-process meshes too
        # (device_put cannot target non-addressable devices)
        return jax.make_array_from_callback(
            p.shape, self._psh, lambda idx: p[idx])

    def pad_mask(self, m):
        """Extend a logical-length mask to the padded vector (padding 0)."""
        pad = self.num_dim_padded - m.shape[0]
        if pad:
            m = jnp.concatenate([m, jnp.zeros(pad, m.dtype)])
        return m


class LinearObjFunction(_BatchObjBase):
    """Logistic regression, layout [w(d); bias]."""

    def __init__(self, batches, num_feature: int, mesh):
        self.num_feature = num_feature
        self.num_dim = num_feature + 1
        super().__init__(batches, mesh)

    def _margin(self, p, seg, idx, val, num_rows: int):
        w, bias = p[: self.num_feature], p[self.num_feature]
        return jax.ops.segment_sum(val * jnp.take(w, idx), seg,
                                   num_segments=num_rows) + bias

    def _batch_loss(self, p, seg, idx, val, label, mask):
        xw = self._margin(p, seg, idx, val, label.shape[0])
        return jnp.sum((jax.nn.softplus(xw) - label * xw) * mask)

    def init_model(self):
        return self.place(jnp.zeros(self.num_dim, jnp.float32))

    def l1_mask(self):
        m = jnp.ones(self.num_dim, jnp.float32)
        return self.pad_mask(m.at[self.num_feature].set(0.0))  # no L1 on bias

    def predict(self, p, seg, idx, val, num_rows: int):
        return self._margin(p, seg, idx, val, num_rows)


class FmObjFunction(_BatchObjBase):
    """FM, flat layout [w(d); V(d x k); bias] (fm.cc:133-140)."""

    def __init__(self, batches, num_feature: int, dim_k: int, mesh,
                 init_scale: float = 0.01, seed: int = 0):
        self.num_feature = num_feature
        self.k = dim_k
        self.num_dim = num_feature * (1 + dim_k) + 1
        self.init_scale = init_scale
        self.seed = seed
        super().__init__(batches, mesh)

    def _split(self, p):
        d, k = self.num_feature, self.k
        # bias lives at its layout slot, not p[-1]: the vector may carry
        # sharding padding past it
        return p[:d], p[d : d + d * k].reshape(d, k), p[d + d * k]

    def _margin(self, p, seg, idx, val, num_rows: int):
        w, V, bias = self._split(p)
        xw = jax.ops.segment_sum(val * jnp.take(w, idx), seg,
                                 num_segments=num_rows)
        vrows = jnp.take(V, idx, axis=0)
        xv = jax.ops.segment_sum(val[:, None] * vrows, seg,
                                 num_segments=num_rows)
        x2v2 = jax.ops.segment_sum((val ** 2)[:, None] * vrows ** 2, seg,
                                   num_segments=num_rows)
        return xw + 0.5 * jnp.sum(xv * xv - x2v2, axis=-1) + bias

    def _batch_loss(self, p, seg, idx, val, label, mask):
        margin = self._margin(p, seg, idx, val, label.shape[0])
        return jnp.sum((jax.nn.softplus(margin) - label * margin) * mask)

    def init_model(self):
        d, k = self.num_feature, self.k
        key = jax.random.PRNGKey(self.seed)
        V = self.init_scale * jax.random.normal(key, (d * k,))
        p = jnp.concatenate(
            [jnp.zeros(d), V, jnp.zeros(1)]).astype(jnp.float32)
        return self.place(p)

    def l1_mask(self):
        # L1 only on the linear weights; V and bias are L2-only territory
        m = jnp.zeros(self.num_dim, jnp.float32)
        return self.pad_mask(m.at[: self.num_feature].set(1.0))

    def predict(self, p, seg, idx, val, num_rows: int):
        return self._margin(p, seg, idx, val, num_rows)
