"""Unified telemetry for wormhole-tpu.

Three layers, each usable alone (see docs/observability.md):

- `obs.metrics` — a process-wide registry of counters, gauges and
  bounded-reservoir histograms. Always on (an increment is a lock and
  an add); hot paths cache metric handles at module import so the
  per-event cost is constant and allocation-free.
- `obs.trace` — distributed trace spans/events as append-only JSONL,
  one file per node incarnation, opt-in via WH_OBS_DIR. Disabled it is
  a single module-level None check (the same contract as
  runtime/faults.py). `tools/trace_viewer.py` merges the per-node
  files into one Chrome-trace/Perfetto JSON.
- `obs.report` — the end-of-run report: the scheduler aggregates the
  metric snapshots nodes piggyback on their heartbeats, prints a
  summary, and `run_report.json` lands in WH_OBS_DIR (written by the
  launcher from the scheduler's `[run-report]` line, or directly by a
  single-process solver).

This package is imported by the runtime/solver modules that use it —
never by `wormhole_tpu/__init__.py` — so `import wormhole_tpu` alone
loads none of it (tests/test_obs.py pins that).
"""

from wormhole_tpu.obs import flight, metrics, pyprof, report, trace  # noqa: F401

REGISTRY = metrics.REGISTRY
