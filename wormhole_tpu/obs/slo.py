"""Declared service-level objectives and their error-budget burn.

An SLO here is a target over the metrics the registry already
collects — no new instrumentation, just judgement: a latency SLO says
"p99 of this histogram stays under X ms", an error SLO says "the bad
fraction of these counters stays under budget B". ``evaluate`` turns a
snapshot into verdicts with a *burn rate* — observed violation divided
by allowance — so 1.0 is exactly on budget, >1 is violated, and the
number stays comparable as targets are tuned via their knobs
(WH_SLO_*, group "obs").

Burn semantics per kind:

- latency: the reservoir fraction of observations above the target,
  over an implied 1% allowance (a p99 objective tolerates 1% slow
  requests by definition). observed = the p99 itself, in ms.
- errors: bad / (good + bad) over the configured budget fraction.
  observed = the error rate.

``evaluate`` also publishes each burn as a ``slo.<name>_burn`` gauge,
so burn rates ride heartbeats, the Prometheus endpoint, and the
ring-buffer history like any other metric. The run report and the
serve/chaos labs assert on these verdicts.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import metrics as _obs


@dataclasses.dataclass(frozen=True)
class SLO:
    name: str            # short id; gauge is slo.<name>_burn
    kind: str            # "latency" | "errors"
    doc: str
    hist: str = ""       # latency: histogram name
    target_knob: str = ""  # latency: knob holding the p99 target (ms)
    good: str = ""       # errors: counter of attempts that succeeded
    bad: str = ""        # errors: counter of failures
    budget_knob: str = ""  # errors: knob holding the allowed bad fraction


#: every declared objective; labs and the run report iterate this
SLOS: tuple[SLO, ...] = (
    SLO(name="serve.latency", kind="latency",
        hist="serve.latency_s", target_knob="WH_SLO_SERVE_P99_MS",
        doc="router predict p99 under WH_SLO_SERVE_P99_MS"),
    SLO(name="serve.errors", kind="errors",
        good="serve.router.requests", bad="serve.router.failures",
        budget_knob="WH_SLO_SERVE_ERR_BUDGET",
        doc="router failure fraction under WH_SLO_SERVE_ERR_BUDGET"),
    SLO(name="ps.rpc", kind="latency",
        hist="ps.client.rpc_s", target_knob="WH_SLO_PS_RPC_P99_MS",
        doc="PS client RPC p99 under WH_SLO_PS_RPC_P99_MS"),
)

_LATENCY_ALLOWANCE = 0.01  # a p99 objective tolerates 1% slow requests


def _knob_values() -> dict[str, float]:
    # literal reads so the env-knobs checker can statically tie each
    # declared WH_SLO_* knob to its read site
    return {
        "WH_SLO_SERVE_P99_MS": float(knob_value("WH_SLO_SERVE_P99_MS")),
        "WH_SLO_SERVE_ERR_BUDGET":
            float(knob_value("WH_SLO_SERVE_ERR_BUDGET")),
        "WH_SLO_PS_RPC_P99_MS": float(knob_value("WH_SLO_PS_RPC_P99_MS")),
    }


def _eval_latency(s: SLO, snap: dict) -> Optional[dict]:
    h = (snap.get("hists") or {}).get(s.hist)
    if not isinstance(h, dict) or not h.get("count"):
        return None
    target_ms = _knob_values()[s.target_knob]
    res = [float(x) for x in (h.get("res") or ())]
    if not res:
        return None
    over = sum(1 for x in res if x * 1e3 > target_ms) / len(res)
    p99 = _obs.hist_quantile(h, 0.99)
    return {
        "objective": f"p99 <= {target_ms:g} ms",
        "observed": round(float(p99) * 1e3, 3) if p99 is not None else None,
        "burn": round(over / _LATENCY_ALLOWANCE, 3),
        "count": int(h["count"]),
    }


def _eval_errors(s: SLO, snap: dict) -> Optional[dict]:
    counters = snap.get("counters") or {}
    good = int(counters.get(s.good, 0))
    bad = int(counters.get(s.bad, 0))
    total = good + bad
    if total == 0:
        return None
    budget = _knob_values()[s.budget_knob]
    rate = bad / total
    return {
        "objective": f"error rate <= {budget:g}",
        "observed": round(rate, 6),
        "burn": round(rate / budget, 3) if budget > 0 else
        (0.0 if bad == 0 else float("inf")),
        "count": total,
    }


def evaluate(snap: dict, publish: bool = True) -> list[dict]:
    """Judge every declared SLO against a snapshot. Objectives with no
    data (histogram never observed, zero attempts) are skipped — a
    training-only run doesn't fail the serving SLOs. When ``publish``,
    each burn also lands in the local registry as a slo.*_burn gauge."""
    out = []
    for s in SLOS:
        got = _eval_latency(s, snap) if s.kind == "latency" \
            else _eval_errors(s, snap)
        if got is None:
            continue
        verdict = {"name": s.name, "kind": s.kind, **got}
        verdict["ok"] = verdict["burn"] <= 1.0
        out.append(verdict)
        if publish:
            _obs.REGISTRY.gauge(f"slo.{s.name}_burn").set(verdict["burn"])
    return out


def format_lines(slos: list[dict]) -> list[str]:
    """Human lines for the run report / lab output."""
    lines = []
    for v in slos:
        mark = "ok" if v["ok"] else "VIOLATED"
        lines.append(
            f"  slo {v['name']:<14} {v['objective']:<28} "
            f"observed={v['observed']:g} burn={v['burn']:g} [{mark}]")
    return lines
