"""End-of-run telemetry report.

The scheduler (apps/_runner.py) merges its own registry with the
per-node snapshots piggybacked on heartbeats, folds in exact per-server
push/pull stats from `PSClient.stats()`, builds this report, prints a
human summary plus one machine line

    [run-report] {...json...}

and, when WH_OBS_DIR is set, writes `run_report.json` there atomically.
The launcher also watches the scheduler's stdout for the machine line
and writes the file if the scheduler's write didn't land on the
launcher's filesystem (multi-host). Single-process solver runs build
the report directly from the global registry.

Histograms are reduced to derived stats (count/sum/mean/min/max/
p50/p90/p99) so the report stays small enough for a stdout line.
"""

from __future__ import annotations

import json
import os
import time

from wormhole_tpu.obs import metrics
from wormhole_tpu.obs import slo as _slo

REPORT_PREFIX = "[run-report] "
REPORT_NAME = "run_report.json"

#: serving pipeline stages, in request order; wire/queue/partial
#: decompose fanout (they overlap it, so the explained sum doesn't
#: count them). batch_wait and partial only exist in score mode:
#: batch_wait is the coalescer queue ahead of the round's fan-out,
#: partial the slowest shard's own score-kernel time inside it.
SERVE_STAGES = ("batch_wait", "pack", "fanout", "wire", "queue",
                "partial", "score", "sum")
_PIPELINE_STAGES = ("batch_wait", "pack", "fanout", "sum", "score")


def serve_stage_table(aggregate: dict) -> dict:
    """Per-stage serving-latency attribution from the serve.stage.*
    histograms: {stages: {name: {p50_ms, p99_ms, mean_ms, count}},
    latency_p50_ms, latency_mean_ms, explained_mean_ms,
    explained_frac}. Empty when the run never served.

    ``explained_frac`` is the acceptance metric: the pipeline stages'
    MEAN sum over the end-to-end request mean. Means, not p50s —
    request latency is the sum of its stages, and the mean of a sum
    is the sum of the means regardless of how the stage durations
    correlate, while a sum of p50s understates the latency p50
    whenever a shared disturbance (a 256 MB snapshot write stealing
    the core, GC, a noisy neighbor) inflates several stages of the
    SAME request together. An attribution hole therefore shows up as
    explained_frac < 1 instead of hiding inside correlation slack."""
    hists = aggregate.get("hists") or {}
    stages = {}
    for stage in SERVE_STAGES:
        h = hists.get(f"serve.stage.{stage}_s")
        if not h or not h.get("count"):
            continue
        stages[stage] = {
            "p50_ms": _round3(_ms(metrics.hist_quantile(h, 0.50))),
            "p99_ms": _round3(_ms(metrics.hist_quantile(h, 0.99))),
            "mean_ms": _round3(_ms(h["sum"] / h["count"])),
            "count": h["count"],
        }
    if not stages:
        return {}
    out = {"stages": stages}
    lat = hists.get("serve.latency_s")
    p50 = _ms(metrics.hist_quantile(lat, 0.50))
    mean = _ms(lat["sum"] / lat["count"]) if lat and lat.get("count") \
        else 0.0
    explained = sum(stages[s]["mean_ms"] or 0.0
                    for s in _PIPELINE_STAGES if s in stages)
    out["latency_p50_ms"] = _round3(p50)
    out["latency_mean_ms"] = _round3(mean)
    out["explained_mean_ms"] = _round3(explained)
    out["explained_frac"] = (_round3(explained / mean)
                             if mean else None)
    return out


#: training-step stages, in batch order. The train thread's wall per
#: batch is load (queue wait) + step (jitted call) + metrics
#: (merge/print) — those three are the pipeline whose p50s must sum to
#: the per-batch total. pack and h2d run in loader threads overlapped
#: with compute, and sync is either inside step (synchronous mode's
#: flush) or hidden behind it (async fold wait shows up as load/step
#: stall), so they inform but don't sum.
TRAIN_STAGES = ("load", "pack", "h2d", "step", "sync", "metrics")
_TRAIN_PIPELINE = ("load", "step", "metrics")


def train_stage_table(aggregate: dict) -> dict:
    """Per-stage training-step attribution from the train.stage.*
    histograms — the serve_stage_table contract for the train plane:
    {stages: {name: {p50_ms, p99_ms, mean_ms, count}}, total_p50_ms,
    explained_p50_ms, explained_frac}. Empty when the run never
    trained. ``explained_frac`` is the acceptance metric: the train
    thread's pipeline stages' p50 sum over the per-batch total p50."""
    hists = aggregate.get("hists") or {}
    stages = {}
    for stage in TRAIN_STAGES:
        h = hists.get(f"train.stage.{stage}_s")
        if not h or not h.get("count"):
            continue
        stages[stage] = {
            "p50_ms": _round3(_ms(metrics.hist_quantile(h, 0.50))),
            "p99_ms": _round3(_ms(metrics.hist_quantile(h, 0.99))),
            "mean_ms": _round3(_ms(h["sum"] / h["count"])),
            "count": h["count"],
        }
    if not stages:
        return {}
    out = {"stages": stages}
    p50 = _ms(metrics.hist_quantile(
        hists.get("train.stage.total_s"), 0.50))
    explained = sum(stages[s]["p50_ms"] or 0.0
                    for s in _TRAIN_PIPELINE if s in stages)
    out["total_p50_ms"] = _round3(p50)
    out["explained_p50_ms"] = _round3(explained)
    out["explained_frac"] = (_round3(explained / p50)
                             if p50 else None)
    return out


def enabled() -> bool:
    return bool(os.environ.get("WH_OBS_DIR", "").strip())


def build(aggregate: dict, nodes=(), run_id=None,
          ps_stats=None, extra=None) -> dict:
    """Shape a merged metrics snapshot into the run report.

    aggregate: a snapshot dict (metrics.merge_snapshots output);
    ps_stats: {rank: stats-dict} from PSClient.stats() — its
    num_push/num_pull are authoritative (surviving-incarnation truth
    straight from the servers), counters are the fallback.
    """
    c = dict(aggregate.get("counters") or {})
    g = dict(aggregate.get("gauges") or {})
    hists = aggregate.get("hists") or {}
    num_push = num_pull = None
    if ps_stats:
        num_push = sum(int(s.get("num_push", 0)) for s in ps_stats.values())
        num_pull = sum(int(s.get("num_pull", 0)) for s in ps_stats.values())
    rpc = hists.get("ps.client.rpc_s")
    summary = {
        "num_push": num_push if num_push is not None
        else c.get("ps.server.num_push", 0),
        "num_pull": num_pull if num_pull is not None
        else c.get("ps.server.num_pull", 0),
        "bytes_pushed": c.get("ps.client.bytes_push", 0),
        "bytes_pulled": c.get("ps.client.bytes_pull", 0),
        "net_bytes_sent": c.get("net.bytes_sent", 0),
        "net_bytes_recv": c.get("net.bytes_recv", 0),
        "rpc_p50_ms": _ms(metrics.hist_quantile(rpc, 0.50)),
        "rpc_p99_ms": _ms(metrics.hist_quantile(rpc, 0.99)),
        "connect_retries": c.get("net.connect_retries", 0),
        "ps_retries": c.get("ps.client.retries", 0),
        "journal_replays": c.get("ps.client.replays", 0),
        "replay_dedup_hits": c.get("ps.client.replay_dedup", 0),
        "push_dedup_hits": c.get("ps.server.dedup_hits", 0),
        "server_recoveries": c.get("sched.server_recoveries", 0),
        "server_restores": c.get("ps.server.restores", 0),
        "liveness_evictions": c.get("sched.liveness_evictions", 0),
        "keycache_hits": c.get("ps.keycache.hits", 0),
        "keycache_misses": c.get("ps.keycache.misses", 0),
        "keycache_invalidations": c.get("ps.keycache.invalidations", 0),
        "net_compress_bytes_in": c.get("net.compress.bytes_in", 0),
        "net_compress_bytes_out": c.get("net.compress.bytes_out", 0),
        "wire_bytes_raw": c.get("wire.codec.bytes_raw", 0),
        "wire_bytes_wire": c.get("wire.codec.bytes_wire", 0),
        "wire_ef_resid_norm": g.get("wire.codec.ef_resid_norm", 0.0),
        "bshuf_bytes_in": c.get("net.bshuf.bytes_in", 0),
        "bshuf_bytes_out": c.get("net.bshuf.bytes_out", 0),
        "hot_plane_steps": c.get("ps.hot.steps", 0),
        "hot_plane_flushes": c.get("ps.hot.flushes", 0),
        "bsp_rounds": c.get("bsp.rounds", 0),
        "bsp_recoveries": c.get("bsp.recoveries", 0),
        "bsp_ring_retries": c.get("bsp.ring_retries", 0),
        "bsp_result_fetches": c.get("bsp.result_fetches", 0),
        "bsp_checkpoints": c.get("bsp.checkpoints", 0),
        "bsp_checkpoint_bytes": c.get("bsp.checkpoint_bytes", 0),
        "membership_epochs": c.get("sched.membership_epochs", 0),
        "worker_joins": c.get("sched.joins", 0),
        "worker_leaves": c.get("sched.leaves", 0),
        "ps_rehellos": c.get("ps.client.rehellos", 0),
        "retry_attempts": c.get("retry.attempts", 0),
        "retry_successes": c.get("retry.successes", 0),
        "retry_give_ups": c.get("retry.give_ups", 0),
        "sched_recoveries": c.get("sched.recoveries", 0),
        "sched_incarnation": int(g.get("sched.incarnation", 0) or 0),
        "sched_journal_appends": c.get("sched.journal.appends", 0),
        "sched_journal_replays": c.get("sched.journal.replays", 0),
        "sched_journal_compactions": c.get("sched.journal.compactions", 0),
        "sched_rpc_dedup_hits": c.get("sched.rpc.dedup_hits", 0),
        # overload-protection plane: shed/hedge/degrade tallies the
        # chaos drills pin their verdicts on
        "deadline_sheds": c.get("net.deadline.shed", 0),
        "admit_sheds": c.get("admit.sheds", 0),
        "serve_sheds_deadline": c.get("serve.shed.deadline", 0),
        "serve_sheds_busy": c.get("serve.shed.busy", 0),
        "hedges_issued": c.get("serve.hedge.issued", 0),
        "hedge_wins": c.get("serve.hedge.wins", 0),
        "hedges_suppressed": c.get("serve.hedge.suppressed", 0),
        "degraded_replies": c.get("serve.degraded.replies", 0),
        "degraded_enters": c.get("serve.degraded.enters", 0),
        "degraded_exits": c.get("serve.degraded.exits", 0),
    }
    report = {
        "run_id": run_id or os.environ.get("WH_RUN_ID"),
        "generated_unix": time.time(),
        "nodes": sorted(nodes),
        "summary": summary,
        "counters": c,
        "gauges": g,
        "hists": {k: metrics.hist_stats(h) for k, h in sorted(hists.items())
                  if h and h.get("count")},
    }
    stages = serve_stage_table(aggregate)
    if stages:
        report["serve_stages"] = stages
    tstages = train_stage_table(aggregate)
    if tstages:
        report["train_stages"] = tstages
    slos = _slo.evaluate(aggregate)
    if slos:
        report["slos"] = slos
    if ps_stats:
        report["ps_servers"] = {str(k): v for k, v in sorted(ps_stats.items())}
    if extra:
        report.update(extra)
    return report


def build_local(run_id=None, extra=None) -> dict:
    """Report for a single-process run, straight off the global
    registry (no scheduler to aggregate)."""
    from wormhole_tpu.obs import trace

    return build(metrics.REGISTRY.snapshot(), nodes=[trace.node_id()],
                 run_id=run_id, extra=extra)


def write(report: dict, out_dir=None) -> str | None:
    """Atomically write run_report.json into `out_dir` (default
    WH_OBS_DIR). Returns the path, or None when disabled."""
    out_dir = out_dir or os.environ.get("WH_OBS_DIR", "").strip()
    if not out_dir:
        return None
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, REPORT_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def machine_line(report: dict) -> str:
    """The one-line form the launcher scrapes from scheduler stdout."""
    return REPORT_PREFIX + json.dumps(report, separators=(",", ":"),
                                      sort_keys=True, default=str)


def format_lines(report: dict) -> list[str]:
    """Human summary printed at end of run."""
    s = report["summary"]
    lines = [
        "run report"
        + (f" ({report['run_id']})" if report.get("run_id") else "")
        + f": {len(report.get('nodes') or [])} nodes",
        f"  pushes={s['num_push']} pulls={s['num_pull']} "
        f"bytes_pushed={s['bytes_pushed']} bytes_pulled={s['bytes_pulled']}",
        f"  net: sent={s['net_bytes_sent']}B recv={s['net_bytes_recv']}B "
        f"connect_retries={s['connect_retries']}",
    ]
    if s["rpc_p50_ms"] is not None:
        lines.append(f"  rpc latency: p50={s['rpc_p50_ms']:.3f}ms "
                     f"p99={s['rpc_p99_ms']:.3f}ms")
    lines.append(
        f"  recovery: retries={s['ps_retries']} "
        f"replays={s['journal_replays']} "
        f"(dedup {s['replay_dedup_hits']}) "
        f"push_dedup={s['push_dedup_hits']} "
        f"server_recoveries={s['server_recoveries']} "
        f"restores={s['server_restores']} "
        f"evictions={s['liveness_evictions']}")
    if s.get("bsp_rounds") or s.get("bsp_recoveries"):
        lines.append(
            f"  bsp: rounds={s['bsp_rounds']} "
            f"checkpoints={s['bsp_checkpoints']} "
            f"({s['bsp_checkpoint_bytes']}B) "
            f"recoveries={s['bsp_recoveries']} "
            f"ring_retries={s['bsp_ring_retries']} "
            f"result_fetches={s['bsp_result_fetches']}")
    if s.get("membership_epochs"):
        lines.append(
            f"  membership: epochs={s['membership_epochs']} "
            f"joins={s['worker_joins']} leaves={s['worker_leaves']} "
            f"rehellos={s['ps_rehellos']}")
    if s.get("sched_recoveries") or s.get("sched_journal_appends"):
        lines.append(
            f"  control plane: recoveries={s['sched_recoveries']} "
            f"incarnation={s['sched_incarnation']} "
            f"journal_appends={s['sched_journal_appends']} "
            f"replays={s['sched_journal_replays']} "
            f"compactions={s['sched_journal_compactions']} "
            f"rpc_dedup={s['sched_rpc_dedup_hits']}")
    if s.get("retry_attempts") or s.get("retry_give_ups"):
        lines.append(
            f"  retry policy: attempts={s['retry_attempts']} "
            f"successes={s['retry_successes']} "
            f"give_ups={s['retry_give_ups']}")
    if s.get("keycache_hits") or s.get("keycache_misses") \
            or s.get("keycache_invalidations"):
        lines.append(
            f"  keycache: hits={s['keycache_hits']} "
            f"misses={s['keycache_misses']} "
            f"invalidations={s['keycache_invalidations']}")
    if s.get("net_compress_bytes_in") or s.get("net_compress_bytes_out"):
        lines.append(
            f"  net compress: out={s['net_compress_bytes_out']}B "
            f"in={s['net_compress_bytes_in']}B")
    if s.get("wire_bytes_raw"):
        saved = s["wire_bytes_raw"] / max(s["wire_bytes_wire"], 1)
        lines.append(
            f"  wire codec: {s['wire_bytes_wire']}B on the wire for "
            f"{s['wire_bytes_raw']}B of f32 values ({saved:.2f}x saved, "
            f"ef_resid_norm={s['wire_ef_resid_norm']:.3g})")
    if s.get("hot_plane_steps") or s.get("hot_plane_flushes"):
        lines.append(
            f"  hot plane: steps={s['hot_plane_steps']} "
            f"cold_flushes={s['hot_plane_flushes']}")
    stages = report.get("serve_stages")
    if stages:
        lines.append(
            "  serve stages (p50 ms): "
            + " ".join(f"{k}={v['p50_ms']:.2f}"
                       for k, v in stages["stages"].items()))
        if stages.get("explained_frac") is not None:
            lines.append(
                f"  serve latency mean={stages['latency_mean_ms']:.2f}ms "
                f"(p50={stages['latency_p50_ms']:.2f}ms), "
                f"{stages['explained_frac'] * 100:.0f}% explained by "
                "batch_wait+pack+fanout+sum+score")
    tstages = report.get("train_stages")
    if tstages:
        lines.append(
            "  train stages (p50 ms): "
            + " ".join(f"{k}={v['p50_ms']:.2f}"
                       for k, v in tstages["stages"].items()))
        if tstages.get("explained_frac") is not None:
            lines.append(
                f"  train step p50={tstages['total_p50_ms']:.2f}ms, "
                f"{tstages['explained_frac'] * 100:.0f}% explained by "
                "load+step+metrics")
    if report.get("slos"):
        lines.extend(_slo.format_lines(report["slos"]))
    return lines


def _ms(v):
    return None if v is None else v * 1000.0


def _round3(v):
    return None if v is None else round(v, 3)
