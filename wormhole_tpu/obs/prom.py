"""Prometheus text exposition (version 0.0.4) over metrics snapshots.

Renders the registry's JSON-able snapshot form — the same dict that
rides heartbeats and merges in the scheduler — as the plain-text
format every Prometheus-compatible scraper speaks. Pure string
assembly, no deps:

- counters become ``wh_<name>_total`` with ``# TYPE ... counter``;
- gauges become ``wh_<name>`` with ``# TYPE ... gauge``;
- histograms become summaries: ``{quantile="..."}``` sample lines
  estimated from the reservoir, plus ``_sum`` and ``_count``.

Name mangling: dotted registry names map to the Prometheus charset by
replacing every non-``[a-zA-Z0-9_]`` rune with ``_`` and prefixing
``wh_`` (``net.bytes_sent`` -> ``wh_net_bytes_sent``). Output is
sorted by metric name so consecutive scrapes diff cleanly and the
format golden test is deterministic.
"""

from __future__ import annotations

import re

from wormhole_tpu.obs.metrics import hist_quantile

_QUANTILES = (0.5, 0.9, 0.99)
_BAD_RUNE = re.compile(r"[^a-zA-Z0-9_]")


def prom_name(name: str) -> str:
    return "wh_" + _BAD_RUNE.sub("_", name)


def _num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_snapshot(snap: dict) -> str:
    """One scrape body from a snapshot dict ({"counters": ...,
    "gauges": ..., "hists": ...}); empty sections render nothing."""
    lines: list[str] = []
    for name, v in sorted((snap.get("counters") or {}).items()):
        m = prom_name(name) + "_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_num(v)}")
    for name, v in sorted((snap.get("gauges") or {}).items()):
        m = prom_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_num(v)}")
    for name, h in sorted((snap.get("hists") or {}).items()):
        if not isinstance(h, dict) or not h.get("count"):
            continue
        m = prom_name(name)
        lines.append(f"# TYPE {m} summary")
        for q in _QUANTILES:
            est = hist_quantile(h, q)
            if est is not None:
                lines.append(f'{m}{{quantile="{q}"}} {repr(float(est))}')
        lines.append(f"{m}_sum {repr(float(h.get('sum') or 0.0))}")
        lines.append(f"{m}_count {int(h['count'])}")
    return "\n".join(lines) + "\n" if lines else ""
