"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints (ISSUE 2):

- thread-safe: every instrument carries its own lock; the registry
  lock is only taken on get-or-create, so hot paths that cache their
  handles at module import never touch it again;
- near-zero cost when unused: an increment is one lock acquire and an
  integer add — no allocation, no string formatting, no I/O. Nothing
  here ever writes a file or opens a socket; export happens only when
  someone calls `snapshot()` (heartbeat piggyback, end-of-run report);
- bounded memory: histograms keep `count/sum/min/max` exactly plus a
  fixed-size reservoir (Vitter's algorithm R with a per-name seeded
  PRNG, so snapshots are deterministic under single-threaded use) from
  which quantiles are estimated. A histogram never grows past
  `reservoir` samples no matter how many observations it absorbs.

Snapshots are plain JSON-able dicts so they can ride the newline-JSON
scheduler channel unchanged:

    {"counters": {name: int}, "gauges": {name: float},
     "hists": {name: {"count": n, "sum": s, "min": lo, "max": hi,
                      "res": [float, ...]}}}

`merge_snapshots` folds any number of such dicts into one (counters
sum, gauges take the max, histograms merge moments and pool+downsample
reservoirs) — that is what the scheduler does with the per-node
snapshots nodes piggyback on their heartbeats.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
import zlib

DEFAULT_RESERVOIR = 256


class Counter:
    """Monotonic integer counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:  # wormlint: thread-entry
        with self._lock:
            self._value += n

    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-written value (restore epoch, queue depth, ...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:  # wormlint: thread-entry
        with self._lock:
            self._value = float(v)

    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Exact moments + a bounded reservoir for quantile estimates."""

    __slots__ = ("name", "reservoir", "count", "sum", "min", "max",
                 "_res", "_rng", "_lock")

    def __init__(self, name: str, reservoir: int = DEFAULT_RESERVOIR):
        self.name = name
        self.reservoir = int(reservoir)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._res: list[float] = []
        # deterministic per-name stream keeps single-threaded snapshots
        # reproducible without sharing one global PRNG (and its lock)
        self._rng = random.Random(zlib.crc32(name.encode()))
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:  # wormlint: thread-entry
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._res) < self.reservoir:
                self._res.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.reservoir:
                    self._res[j] = v

    def quantile(self, q: float) -> float | None:
        with self._lock:
            res = sorted(self._res)
        return _quantile_sorted(res, q)

    def snapshot(self) -> dict:
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max,
                    "res": list(self._res)}


def _quantile_sorted(res: list[float], q: float) -> float | None:
    if not res:
        return None
    q = min(1.0, max(0.0, float(q)))
    return res[min(len(res) - 1, int(q * len(res)))]


class Registry:
    """Get-or-create home for named instruments."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:  # wormlint: thread-entry
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:  # wormlint: thread-entry
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str,  # wormlint: thread-entry
                  reservoir: int = DEFAULT_RESERVOIR) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, reservoir)
            return h

    @contextlib.contextmanager
    def timer(self, name: str):
        """Time a block into histogram `name` (seconds)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.histogram(name).observe(time.perf_counter() - t0)

    def snapshot(self) -> dict:
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._hists.values())
        return {
            "counters": {c.name: c.value() for c in counters},
            "gauges": {g.name: g.value() for g in gauges},
            "hists": {h.name: h.snapshot() for h in hists},
        }

    def reset(self) -> None:
        """Drop every instrument (tests and respawned incarnations).

        Cached handles in already-imported modules keep working but
        stop being visible in snapshots; hot-path modules therefore
        re-fetch handles lazily or tolerate this (tests only reset
        between logical runs, never mid-run).
        """
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


#: The process-wide registry. Hot paths do
#:   _FRAMES = REGISTRY.counter("net.frames_sent")
#: once at import and call `_FRAMES.inc()` per event.
REGISTRY = Registry()


def merge_snapshots(snaps, reservoir: int = DEFAULT_RESERVOIR) -> dict:
    """Fold snapshot dicts into one: counters sum, gauges max,
    histogram moments merge and reservoirs pool then downsample."""
    counters: dict[str, int] = {}
    gauges: dict[str, float] = {}
    hists: dict[str, dict] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for k, v in (snap.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in (snap.get("gauges") or {}).items():
            gauges[k] = max(gauges.get(k, float(v)), float(v))
        for k, h in (snap.get("hists") or {}).items():
            if not isinstance(h, dict):
                continue
            m = hists.get(k)
            if m is None:
                m = hists[k] = {"count": 0, "sum": 0.0,
                                "min": None, "max": None, "res": []}
            m["count"] += int(h.get("count") or 0)
            m["sum"] += float(h.get("sum") or 0.0)
            for key, pick in (("min", min), ("max", max)):
                v = h.get(key)
                if v is not None:
                    m[key] = v if m[key] is None else pick(m[key], v)
            m["res"].extend(float(x) for x in (h.get("res") or ()))
    rng = random.Random(0)
    for m in hists.values():
        if len(m["res"]) > reservoir:
            m["res"] = rng.sample(m["res"], reservoir)
    return {"counters": counters, "gauges": gauges, "hists": hists}


class SnapshotRing:
    """Bounded ring of timestamped metrics snapshots — the scheduler's
    metrics-over-time buffer. ``add`` evicts the oldest entry past
    capacity; ``items`` hands back oldest-first copies, so a scraper
    can diff consecutive entries into rates without holding the lock."""

    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._entries: list[tuple[float, dict]] = []

    def add(self, ts: float, snap: dict) -> None:
        with self._lock:
            self._entries.append((float(ts), snap))
            if len(self._entries) > self.capacity:
                del self._entries[: len(self._entries) - self.capacity]

    def items(self) -> list[tuple[float, dict]]:
        with self._lock:
            return list(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def hist_quantile(h: dict | None, q: float) -> float | None:
    """Quantile of a snapshot-form histogram dict (or None)."""
    if not h:
        return None
    return _quantile_sorted(sorted(h.get("res") or ()), q)


def hist_stats(h: dict | None) -> dict | None:
    """Reduce a snapshot-form histogram to derived stats (drops the
    raw reservoir — this is what lands in run_report.json)."""
    if not h or not h.get("count"):
        return None
    res = sorted(h.get("res") or ())
    return {
        "count": h["count"],
        "sum": h["sum"],
        "mean": h["sum"] / h["count"],
        "min": h.get("min"),
        "max": h.get("max"),
        "p50": _quantile_sorted(res, 0.50),
        "p90": _quantile_sorted(res, 0.90),
        "p99": _quantile_sorted(res, 0.99),
    }
