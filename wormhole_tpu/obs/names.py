"""Registry of every metric, span, and event name the codebase emits.

Names follow the dotted-namespace convention ``<subsystem>.<thing>[_<unit>]``
(lowercase, segments separated by dots, underscores within a segment).
Dynamic names built with f-strings are declared with a ``*`` wildcard per
interpolated field, e.g. ``ps.server.op.*_s`` covers
``f"ps.server.op.{op}_s"``.

``tools/wormlint`` parses these dict literals statically (this module is
never imported by the checker) and cross-checks them against every
``REGISTRY.counter/gauge/histogram("...")``, ``trace.span("...")`` and
``trace.event("...")`` call site: an emit of an unregistered name, a name
violating the convention, or a registered name nothing emits are all
findings.  To add a metric, add it here first — the doc string doubles as
the metric's reference documentation.
"""

from __future__ import annotations

# fmt: off
COUNTERS: dict[str, str] = {
    "ps.server.num_push": "pushes applied by a PS shard",
    "ps.server.num_pull": "pulls served by a PS shard",
    "ps.server.dedup_hits": "replayed pushes dropped by seq dedup",
    "ps.server.snapshots": "shard snapshots written",
    "ps.server.restores": "shard restores performed",
    "ps.client.bytes_push": "payload bytes pushed to servers",
    "ps.client.bytes_pull": "payload bytes pulled from servers",
    "ps.client.retries": "client RPC retries after socket errors",
    "ps.client.replays": "journal replays sent after reconnect",
    "ps.client.replay_dedup": "replays the server acked as duplicates",
    "ps.client.rollback_repulls": "full re-pulls forced by epoch rollback",
    "ps.client.syncs": "SyncedStore sync() rounds",
    "ps.keycache.hits": "key-list digests accepted by the server",
    "ps.keycache.misses": "digest misses forcing a full key resend",
    "ps.keycache.invalidations": "key caches dropped on restore/reconnect",
    "ps.hot.steps": "train steps aggregated in-jit by the hot plane",
    "ps.hot.flushes": "hot-plane cold-tier flush round-trips",
    "sched.liveness_evictions": "nodes evicted by the liveness loop",
    "sched.server_recoveries": "server re-registrations after death",
    "sched.recoveries": "scheduler restarts resumed from the journal",
    "sched.rpc.dedup_hits": "retried scheduler RPCs answered from the reply cache",
    "sched.journal.appends": "records appended to the scheduler journal",
    "sched.journal.bytes": "bytes fsync'd into the scheduler journal",
    "sched.journal.replays": "journal records replayed at scheduler start",
    "sched.journal.compactions": "journal compactions into a state snapshot",
    "bsp.rounds": "BSP collective rounds completed (allreduce+broadcast)",
    "bsp.recoveries": "BSP worker re-registrations after death",
    "bsp.ring_retries": "ring rounds aborted and replayed on a gen bump",
    "bsp.result_fetches": "cached reduced results served to peers",
    "bsp.checkpoints": "BSP version checkpoints written",
    "bsp.checkpoint_bytes": "bytes written by BSP checkpoints",
    "serve.requests": "predict/fetch RPCs served by a serving shard",
    "serve.rows": "weight rows gathered for predict batches",
    "serve.swaps": "hot snapshot swaps performed by a serving shard",
    "serve.dedup_hits": "retried fetches answered from the reply cache",
    "serve.router.requests": "predict batches scored through the router",
    "serve.router.retries": "router shard-RPC retries after socket errors",
    "serve.router.epoch_retries": "fan-outs replayed for epoch consistency",
    "serve.router.failures": "predict batches the router gave up on",
    "sched.serve_recoveries": "serving shards that re-registered after death",
    "net.busy.rejections": "frames bounced by the max-in-flight gate",
    "net.busy.retries": "client resends after a busy reply",
    "net.deadline.shed": "frames shed because their deadline expired in transit",
    "admit.sheds": "bulk requests bounced by the admission controller",
    "serve.shed.deadline": "serving requests shed for an expired deadline",
    "serve.shed.busy": "serving requests bounced busy by the admission gate",
    "serve.hedge.issued": "backup fan-out RPCs issued to slow shards",
    "serve.hedge.wins": "fan-out legs where the hedge answered first",
    "serve.hedge.suppressed": "hedge firings denied by the hedge budget",
    "serve.degraded.replies": "predict replies served in degraded mode",
    "serve.degraded.enters": "transitions into degraded-mode serving",
    "serve.degraded.exits": "recoveries out of degraded-mode serving",
    "net.frames_sent": "frames written to sockets",
    "net.frames_recv": "frames read from sockets",
    "net.bytes_sent": "bytes written to sockets",
    "net.bytes_recv": "bytes read from sockets",
    "net.connect_retries": "connect() attempts that needed a retry",
    "net.compress.bytes_in": "compressed payload bytes received",
    "net.compress.bytes_out": "compressed payload bytes sent",
    "kv.gather_rows": "rows gathered from the local kvstore",
    "kv.scatter_rows": "rows scattered into the local kvstore",
    "kv.jit_cache_misses": "kvstore gather/scatter jit-cache compiles",
    "pack_cache.hits": "memory-tier pack cache hits",
    "pack_cache.misses": "pack cache misses (batch re-packed)",
    "pack_cache.disk_hits": "disk-tier pack cache hits",
    "pack_cache.evictions": "LRU evictions from the memory tier",
    "pack_cache.corrupt": "disk entries dropped after checksum failure",
    "obs.scrape.requests": "Prometheus /metrics scrapes served",
    "retry.attempts": "retried attempts under a deadline-budgeted policy",
    "retry.give_ups": "retry budgets exhausted (the op failed for good)",
    "retry.successes": "ops that succeeded after at least one retry",
    "sched.membership_epochs": "membership epoch bumps (join/leave/eviction)",
    "sched.joins": "workers admitted into a running job",
    "sched.leaves": "workers that left a running job cleanly",
    "elastic.spawns": "worker processes spawned by the elastic supervisor",
    "elastic.retires": "worker processes retired by the elastic supervisor",
    "ps.client.rehellos": "PSClient re-hello rounds after a membership bump",
    "flight.records": "records accepted into the flight-recorder rings",
    "flight.dumps": "flight-recorder dump files written",
    "flight.dump_errors": "flight dumps that failed to write",
    "flight.suppressed": "flight dumps suppressed by the rate limit",
    "prof.samples": "stack sweeps taken by the sampling profiler",
    "prof.throttled": "profiler sweeps skipped to stay under budget",
}

GAUGES: dict[str, str] = {
    "ps.server.restore_epoch": "epoch a shard last restored from",
    "serve.model_epoch": "active snapshot version on a serving shard",
    "ps.sync.inflight": "async sync rounds currently in flight (0/1)",
    "ps.sync.overlap_frac": "fraction of sync wall time hidden by compute",
    "queue.depth": "loader output queue depth",
    "loader.stall_s": "main-thread queue-wait total for the pass",
    "loader.pool_size": "current loader thread-pool size",
    "pack_cache.bytes": "bytes held by the pack cache memory tier",
    "obs.ring.depth": "snapshots held by the scheduler's telemetry ring",
    "sched.incarnation": "scheduler incarnation number (0 = never restarted)",
    "slo.*_burn": "error-budget burn rate per declared SLO (>1 = violated)",
    "admit.limit": "current AIMD concurrency limit of the admission gate",
    "admit.inflight": "bulk requests currently admitted into handlers",
    "serve.hedge.delay_ms": "rolling-quantile hedge delay currently in force",
    "serve.degraded.active": "1 while the router serves degraded replies",
    "prof.overhead_frac": "measured profiler overhead as a fraction of wall",
}

HISTOGRAMS: dict[str, str] = {
    "ps.server.snapshot_s": "shard snapshot write duration",
    "serve.op.*_s": "per-op serving-shard handler duration",
    "serve.latency_s": "router-side end-to-end predict batch latency",
    "serve.stage.pack_s": "router pack stage (RowBlock -> device batch + keys)",
    "serve.stage.fanout_s": "fan-out wall: RPCs issued to all replies in",
    "serve.stage.wire_s": "fan-out wall minus slowest shard's own time",
    "serve.stage.queue_s": "slowest shard's recv-to-dispatch queue wait",
    "serve.stage.score_s": "jitted margin compute over compact tables",
    "serve.stage.sum_s": "shard-piece reassembly into compact tables",
    "serve.swap_stall_s": "request-visible pause while flipping snapshots",
    "ps.server.op.*_s": "per-op PS server handler duration",
    "ps.client.rpc_s": "single client RPC round-trip",
    "ps.client.sync_push_s": "push half of a sync round",
    "ps.client.sync_pull_s": "pull half of a sync round",
    "ps.client.sync_wait_s": "train-thread wait for the async comms thread",
    "sched.barrier_wait_s": "scheduler-side barrier hold time",
    "bsp.allreduce_s": "one BSP allreduce round, wall time",
    "bsp.checkpoint_s": "one BSP checkpoint (write + cache prune)",
    "sched.op.*_s": "per-op scheduler handler duration",
    "net.encode_s": "wire message encode duration",
    "net.decode_s": "wire message decode duration",
    "kv.gather_s": "local kvstore gather duration",
    "kv.scatter_s": "local kvstore scatter duration",
    "perf.*_s": "utils.perf mirror of ad-hoc timed ops",
    "retry.backoff_s": "sleep durations taken between retry attempts",
    "train.stage.load_s": "train-thread wait for the next packed batch",
    "train.stage.pack_s": "loader-side prepare (parse + pack) per batch",
    "train.stage.h2d_s": "loader-side host-to-device staging per batch",
    "train.stage.step_s": "jitted train/eval step call per batch",
    "train.stage.sync_s": "PS sync wall attributable to the train step",
    "train.stage.metrics_s": "progress merge + printing per batch",
    "train.stage.total_s": "train-thread wall per batch (load+step+metrics)",
}

SPANS: dict[str, str] = {
    "ps.snapshot": "server-side shard snapshot",
    "ps.sync.snapshot": "client-side delta snapshot under the store lock",
    "ps.sync.push": "push half of a sync round",
    "ps.sync.pull": "pull half of a sync round",
    "rpc.*": "one client RPC, named by op",
    "barrier.*": "scheduler barrier, named by barrier",
    "solver.part": "one data part processed by a worker",
    "solver.*_pass": "one train/eval pass over the data",
    "solver.*_step": "one train/eval minibatch step",
    "serve.request": "root span of a sampled router predict request",
    "serve.rpc.fetch": "router-side shard fetch RPC within a fan-out",
    "serve.stage.pack": "pack stage of a sampled predict request",
    "serve.stage.fanout": "fan-out stage of a sampled predict request",
    "serve.stage.score": "score stage of a sampled predict request",
    "serve.stage.sum": "piece-reassembly stage of a sampled request",
    "serve.shard.*": "serving-shard handler work, named by op",
    "ps.shard.*": "PS-shard handler work under a sampled round, by op",
    "ps.sync.round": "root span of a sampled PS sync round",
    "bsp.round": "root span of a sampled BSP collective round",
    "bsp.peer.*": "BSP peer handler work under a sampled round, by op",
}

EVENTS: dict[str, str] = {
    "ps.restore": "server shard restored from snapshot",
    "serve.swap": "serving shard flipped to a newer snapshot version",
    "ps.rollback": "client detected server epoch rollback",
    "ps.reconnect": "client reconnected to a respawned server",
    "sched.server_recovered": "scheduler accepted a server re-registration",
    "sched.serve_recovered": "scheduler accepted a serving-shard re-registration",
    "sched.bsp_recovered": "scheduler accepted a BSP worker re-registration",
    "sched.liveness_evict": "scheduler evicted an unresponsive node",
    "sched.resumed": "respawned scheduler resumed state from its journal",
    "sched.member_join": "scheduler admitted a worker into a running job",
    "sched.member_leave": "scheduler processed a worker's clean leave",
}
# fmt: on

ALL_METRICS: dict[str, dict[str, str]] = {
    "counter": COUNTERS,
    "gauge": GAUGES,
    "histogram": HISTOGRAMS,
}

ALL_TRACE: dict[str, dict[str, str]] = {
    "span": SPANS,
    "event": EVENTS,
}
