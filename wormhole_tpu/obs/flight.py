"""Per-node flight recorder: bounded rings of the recent past, dumped
to JSONL when something anomalous happens.

The black-box idea: tracing everything all the time is too expensive
and sampling misses exactly the requests you care about, so instead
every node keeps fixed-size in-memory rings of

- recent span records (fed by ``obs.trace`` even when file tracing is
  off — the recorder is a second, always-cheap span sink),
- overload decisions (admission verdict + reason, hedge outcome,
  brownout transitions, deadline budget observed at each hop),
- metric snapshots (a ``SnapshotRing`` sampled every ~5s while records
  flow),
- sampled stacks (fed by ``obs.pyprof`` when the profiler runs),

and writes them all out only when a trigger fires: an SLO burn
crossing, a scheduler/server recovery, a fault-injection arm, or the
explicit ``flight`` scheduler verb. The dump is one JSONL file per
trigger,

    <dir>/flight-<node>-<pid>-<seq>.jsonl

whose first line is the same clock anchor ``obs.trace`` writes (plus
``"kind": "flight"`` and the trigger ``"reason"``), and whose records
carry monotonic ``ts`` seconds — so ``tools/trace_viewer.py`` can
align multi-node dumps on a shared wall axis and ``tools/blackbox.py``
merges them into one Perfetto-compatible timeline.

Contract (same as runtime/faults.py and obs.trace): a module-level
``ACTIVE`` handle that is None when disabled, so every hook site is a
single None check and an un-instrumented process pays nothing — no
rings are even allocated. Enabled via ``WH_FLIGHT=1`` with the dump
directory from ``WH_FLIGHT_DIR`` (falling back to ``WH_OBS_DIR``).
Unforced dumps are rate-limited to one per ``WH_FLIGHT_MIN_SEC`` so a
flapping trigger cannot storm the disk; forced dumps (the scheduler
verb, cluster-wide dump requests) always write.

This module imports only config + obs.metrics, so obs.trace and
obs.pyprof may import it without cycles.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import metrics as _metrics

_RECORDS = _metrics.REGISTRY.counter("flight.records")
_DUMPS = _metrics.REGISTRY.counter("flight.dumps")
_DUMP_ERRORS = _metrics.REGISTRY.counter("flight.dump_errors")
_SUPPRESSED = _metrics.REGISTRY.counter("flight.suppressed")

_INIT_LOCK = threading.Lock()

#: seconds between automatic metric snapshots while records flow
_SNAP_EVERY_S = 5.0


def node_id() -> str:
    role = os.environ.get("WH_ROLE")
    if role:
        return f"{role}-{os.environ.get('WH_RANK', '0')}"
    return f"local-{os.getpid()}"


class FlightRecorder:
    def __init__(self, out_dir: str, run_id: str, node: str,
                 ring: int = 512, decisions: int = 256, snaps: int = 16,
                 min_dump_sec: float = 10.0):
        self.out_dir = out_dir
        self.run_id = run_id
        self.node = node
        self.pid = os.getpid()
        self.min_dump_sec = float(min_dump_sec)
        self._lock = threading.Lock()
        self._spans: collections.deque = collections.deque(
            maxlen=max(int(ring), 1))
        self._hops: collections.deque = collections.deque(
            maxlen=max(int(ring), 1))
        self._decisions: collections.deque = collections.deque(
            maxlen=max(int(decisions), 1))
        self._stacks: collections.deque = collections.deque(maxlen=8)
        self._snaps = _metrics.SnapshotRing(max(int(snaps), 1))
        self._last_snap = 0.0
        self._last_dump: Optional[float] = None
        self._seq = 0

    # -- record sinks (each: build dict, one lock'd append) ------------

    def record_span(self, name: str, cat: str, t0: float, dur: float,
                    args: Optional[dict] = None) -> None:
        rec = {"ph": "X", "name": name, "cat": cat,
               "ts": round(t0, 6), "dur": round(dur, 6)}
        if args:
            rec["args"] = dict(args)
        with self._lock:
            self._spans.append(rec)
        _RECORDS.inc()
        self._maybe_snapshot()

    def record_event(self, name: str, cat: str, args: Optional[dict] = None,
                     ) -> None:
        rec = {"ph": "i", "name": name, "cat": cat,
               "ts": round(time.monotonic(), 6)}
        if args:
            rec["args"] = dict(args)
        with self._lock:
            self._spans.append(rec)
        _RECORDS.inc()
        self._maybe_snapshot()

    def record_decision(self, verdict: str, reason: str,
                        op: Optional[str] = None, **extra) -> None:
        """One overload-plane decision: shed / admit_shed / hedge /
        hedge_win / hedge_suppressed / brownout_enter / brownout_exit,
        with the controller's recorded reason."""
        args = {"verdict": verdict, "reason": reason}
        if op is not None:
            args["op"] = op
        for k, v in extra.items():
            if v is not None:
                args[k] = v
        rec = {"ph": "i", "name": f"overload.{verdict}", "cat": "overload",
               "ts": round(time.monotonic(), 6), "args": args}
        with self._lock:
            self._decisions.append(rec)
        _RECORDS.inc()
        self._maybe_snapshot()

    def record_hop(self, op: Optional[str], budget_s: float) -> None:
        """Deadline budget observed when a frame arrived at this hop."""
        rec = {"ph": "i", "name": "net.hop", "cat": "overload",
               "ts": round(time.monotonic(), 6),
               "args": {"op": op, "budget_ms": round(budget_s * 1e3, 3)}}
        with self._lock:
            self._hops.append(rec)
        _RECORDS.inc()

    def record_stack(self, folded: list) -> None:
        """A profiler sweep's top folded-stack lines."""
        rec = {"ph": "i", "name": "prof.stacks", "cat": "prof",
               "ts": round(time.monotonic(), 6),
               "args": {"folded": list(folded)}}
        with self._lock:
            self._stacks.append(rec)
        _RECORDS.inc()

    def _maybe_snapshot(self) -> None:
        now = time.monotonic()
        with self._lock:
            if now - self._last_snap < _SNAP_EVERY_S:
                return
            self._last_snap = now
        # snapshot() outside the ring lock: it takes the registry lock
        self._snaps.add(now, _metrics.REGISTRY.snapshot())

    # -- dump ----------------------------------------------------------

    def dump(self, reason: str, force: bool = False) -> Optional[str]:
        """Write the rings out; returns the path, or None when the
        rate limit suppressed an unforced dump (or the write failed)."""
        now = time.monotonic()
        with self._lock:
            if (not force and self._last_dump is not None
                    and now - self._last_dump < self.min_dump_sec):
                _SUPPRESSED.inc()
                return None
            self._last_dump = now
            self._seq += 1
            seq = self._seq
            records = (list(self._spans) + list(self._hops)
                       + list(self._decisions) + list(self._stacks))
        for ts, snap in self._snaps.items():
            records.append({"ph": "i", "name": "flight.snapshot",
                            "cat": "flight", "ts": round(ts, 6),
                            "args": {"snap": snap}})
        records.sort(key=lambda r: r.get("ts", 0.0))
        anchor = {"ph": "M", "kind": "flight", "run": self.run_id,
                  "node": self.node, "pid": self.pid, "reason": reason,
                  "wall": time.time(), "mono": time.monotonic()}
        path = os.path.join(
            self.out_dir, f"flight-{self.node}-{self.pid}-{seq}.jsonl")
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "w") as fh:
                fh.write(json.dumps(anchor, separators=(",", ":"),
                                    default=str) + "\n")
                for rec in records:
                    fh.write(json.dumps(rec, separators=(",", ":"),
                                        default=str) + "\n")
        except OSError:
            _DUMP_ERRORS.inc()
            return None
        _DUMPS.inc()
        return path


ACTIVE: Optional[FlightRecorder] = None


# -- module-level hooks: one None check each when disabled -------------

def record_decision(verdict: str, reason: str, op: Optional[str] = None,
                    **extra) -> None:
    r = ACTIVE
    if r is not None:
        r.record_decision(verdict, reason, op=op, **extra)


def record_hop(op: Optional[str], budget_s: float) -> None:
    r = ACTIVE
    if r is not None:
        r.record_hop(op, budget_s)


def record_stack(folded: list) -> None:
    r = ACTIVE
    if r is not None:
        r.record_stack(folded)


def dump(reason: str, force: bool = False) -> Optional[str]:
    r = ACTIVE
    if r is None:
        return None
    return r.dump(reason, force=force)


def init_from_env() -> Optional[FlightRecorder]:
    """(Re)read WH_FLIGHT*; called once at import, again by tests after
    mutating the env. Same serialization contract as obs.trace."""
    global ACTIVE
    with _INIT_LOCK:
        ACTIVE = None
        if not knob_value("WH_FLIGHT"):
            return None
        out_dir = (str(knob_value("WH_FLIGHT_DIR")).strip()
                   or os.environ.get("WH_OBS_DIR", "").strip())
        if not out_dir:
            return None
        run_id = os.environ.get("WH_RUN_ID") or f"run-{int(time.time())}"
        ACTIVE = FlightRecorder(
            out_dir, run_id, node_id(),
            ring=int(knob_value("WH_FLIGHT_RING")),
            decisions=int(knob_value("WH_FLIGHT_DECISIONS")),
            snaps=int(knob_value("WH_FLIGHT_SNAPS")),
            min_dump_sec=float(knob_value("WH_FLIGHT_MIN_SEC")))
        return ACTIVE


init_from_env()
