"""Distributed trace spans/events as per-node append-only JSONL.

Opt-in via WH_OBS_DIR (the same contract as runtime/faults.py: a
module-level handle that is None when disabled, so every hook site is
one None check and an unfaulted/untraced process pays nothing).

When enabled, each process incarnation appends to its own file

    WH_OBS_DIR/trace-<node>-<pid>.jsonl

so respawned servers never collide with their dead predecessor's file
and a crash mid-write loses at most one line (append-only,
line-buffered). The first line is a clock anchor

    {"ph": "M", "run": ..., "node": ..., "pid": ...,
     "wall": time.time(), "mono": time.monotonic()}

mapping this process's monotonic clock to wall time; every span/event
carries monotonic timestamps (immune to NTP steps) and the viewer
(tools/trace_viewer.py) uses the anchor to place nodes on a shared
wall-clock axis. Lines:

    {"ph": "X", "name": ..., "cat": ..., "ts": mono_s, "dur": s,
     "tid": small-int, "args": {...}}          # a completed span
    {"ph": "i", "name": ..., "cat": ..., "ts": mono_s, "tid": ...,
     "args": {...}}                            # an instant event

Identity: run id from WH_RUN_ID (the launcher exports one per launch),
node id "<role>-<rank>" from WH_ROLE/WH_RANK, or "local-<pid>" for
single-process runs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional


class Tracer:
    def __init__(self, out_dir: str, run_id: str, node: str):
        self.out_dir = out_dir
        self.run_id = run_id
        self.node = node
        self.pid = os.getpid()
        self.path = os.path.join(out_dir, f"trace-{node}-{self.pid}.jsonl")
        os.makedirs(out_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._tids: dict[int, int] = {}
        self._fh = open(self.path, "a", buffering=1)
        self._write({"ph": "M", "run": run_id, "node": node,
                     "pid": self.pid, "wall": time.time(),
                     "mono": time.monotonic()})

    def _write(self, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":"), default=str)
        with self._lock:
            self._fh.write(line + "\n")

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
            return tid

    def emit_span(self, name: str, cat: str, t0: float, dur: float,
                  args: Optional[dict] = None) -> None:
        rec = {"ph": "X", "name": name, "cat": cat,
               "ts": round(t0, 6), "dur": round(dur, 6),
               "tid": self._tid()}
        if args:
            rec["args"] = args
        self._write(rec)

    def event(self, name: str, cat: str = "event", **args) -> None:
        rec = {"ph": "i", "name": name, "cat": cat,
               "ts": round(time.monotonic(), 6), "tid": self._tid()}
        if args:
            rec["args"] = args
        self._write(rec)

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass


ACTIVE: Optional[Tracer] = None


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "cat", "args", "t0")

    def __init__(self, tracer: Tracer, name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, etype, exc, tb):
        dur = time.monotonic() - self.t0
        if etype is not None:
            self.args = dict(self.args or {}, error=etype.__name__)
        self.tracer.emit_span(self.name, self.cat, self.t0, dur, self.args)
        return False


def span(name: str, cat: str = "span", **args):
    """Context manager timing a block into the trace. When tracing is
    off this returns a shared no-op object — no allocation, no clock
    read — so it is safe on hot paths."""
    t = ACTIVE
    if t is None:
        return _NULL_SPAN
    return _Span(t, name, cat, args)


def event(name: str, cat: str = "event", **args) -> None:
    """Emit an instant event (recovery, restore, eviction...)."""
    t = ACTIVE
    if t is not None:
        t.event(name, cat, **args)


def node_id() -> str:
    role = os.environ.get("WH_ROLE")
    if role:
        return f"{role}-{os.environ.get('WH_RANK', '0')}"
    return f"local-{os.getpid()}"


def init_from_env() -> Optional[Tracer]:
    """(Re)read WH_OBS_DIR; called once at import. Tests call it again
    after mutating the env."""
    global ACTIVE
    if ACTIVE is not None:
        ACTIVE.close()
        ACTIVE = None
    out_dir = os.environ.get("WH_OBS_DIR", "").strip()
    if not out_dir:
        return None
    run_id = os.environ.get("WH_RUN_ID") or f"run-{int(time.time())}"
    ACTIVE = Tracer(out_dir, run_id, node_id())
    return ACTIVE


init_from_env()
