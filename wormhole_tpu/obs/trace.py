"""Distributed trace spans/events as per-node append-only JSONL.

Opt-in via WH_OBS_DIR (the same contract as runtime/faults.py: a
module-level handle that is None when disabled, so every hook site is
one None check and an unfaulted/untraced process pays nothing).

When enabled, each process incarnation appends to its own file

    WH_OBS_DIR/trace-<node>-<pid>.jsonl

so respawned servers never collide with their dead predecessor's file
and a crash mid-write loses at most one line (append-only,
line-buffered). The first line is a clock anchor

    {"ph": "M", "run": ..., "node": ..., "pid": ...,
     "wall": time.time(), "mono": time.monotonic()}

mapping this process's monotonic clock to wall time; every span/event
carries monotonic timestamps (immune to NTP steps) and the viewer
(tools/trace_viewer.py) uses the anchor to place nodes on a shared
wall-clock axis. Lines:

    {"ph": "X", "name": ..., "cat": ..., "ts": mono_s, "dur": s,
     "tid": small-int, "args": {...}}          # a completed span
    {"ph": "i", "name": ..., "cat": ..., "ts": mono_s, "tid": ...,
     "args": {...}}                            # an instant event

Identity: run id from WH_RUN_ID (the launcher exports one per launch),
node id "<role>-<rank>" from WH_ROLE/WH_RANK, or "local-<pid>" for
single-process runs.

Request tracing (cross-node causality): a sampled request carries a
*trace context* — ``(trace_id, span_id)`` — in a thread-local slot.
While bound, every span emitted on that thread gains three fields:

    "trace": trace-id    "sid": this span's id    "psid": parent span id

Span ids are ``<node>:<pid>:<n>`` strings, unique across the whole
job without coordination. The context crosses processes by riding the
``runtime/net.py`` frame header (``wire_ctx()`` on the sender,
``bind_wire()`` on the receiver — the same header-piggyback pattern as
``key_digest``), so a router request, the shard spans it fanned out
to, and the PS/BSP rounds it touched stitch into ONE flow in
``tools/trace_viewer.py``.

Sampling is deterministic and counter-based: ``start_request()`` hands
out a fresh context for every ``WH_TRACE_SAMPLE``-th call (1 = every
request, 0 = off), so a replayed run samples the same requests and the
hot path for unsampled requests is one counter bump. With tracing off
entirely, every hook is a single ``ACTIVE is None`` check.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

from wormhole_tpu.obs import flight as _flight

#: every SAMPLE_N-th start_request() gets a trace context (0 = off);
#: (re)read from WH_TRACE_SAMPLE by init_from_env
SAMPLE_N: int = 0

_INIT_LOCK = threading.Lock()
_TLS = threading.local()  # .ctx = (trace_id, span_id) while bound


class Tracer:
    def __init__(self, out_dir: str, run_id: str, node: str):
        self.out_dir = out_dir
        self.run_id = run_id
        self.node = node
        self.pid = os.getpid()
        self.path = os.path.join(out_dir, f"trace-{node}-{self.pid}.jsonl")
        os.makedirs(out_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._tids: dict[int, int] = {}
        self._sid = 0  # span-id counter (request-traced spans only)
        self._req = 0  # start_request() sampling counter
        self._closed = False
        self._fh = open(self.path, "a", buffering=1)
        self._write({"ph": "M", "run": run_id, "node": node,
                     "pid": self.pid, "wall": time.time(),
                     "mono": time.monotonic()})

    def _write(self, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":"), default=str)
        with self._lock:
            if self._closed:
                return
            self._fh.write(line + "\n")

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
            return tid

    def next_sid(self) -> str:
        """A job-unique span id (node+pid scope the counter)."""
        with self._lock:
            self._sid += 1
            n = self._sid
        return f"{self.node}:{self.pid}:{n}"

    def next_req(self) -> int:
        with self._lock:
            self._req += 1
            return self._req

    def emit_span(self, name: str, cat: str, t0: float, dur: float,
                  args: Optional[dict] = None,
                  ctx: Optional[tuple] = None) -> None:
        # ctx is (trace, sid, psid); None means "read the ambient
        # thread context", so direct emit_span call sites get request
        # attribution for free when the thread is bound
        rec = {"ph": "X", "name": name, "cat": cat,
               "ts": round(t0, 6), "dur": round(dur, 6),
               "tid": self._tid()}
        if ctx is None:
            cur = getattr(_TLS, "ctx", None)
            if cur is not None:
                # a direct emit (no _Span nesting) becomes a leaf child
                # of whatever span is ambient on this thread
                ctx = (cur[0], self.next_sid(), cur[1])
        if ctx is not None:
            rec["trace"] = ctx[0]
            rec["sid"] = ctx[1]
            if ctx[2] is not None:
                rec["psid"] = ctx[2]
        if args:
            rec["args"] = args
        self._write(rec)

    def event(self, name: str, cat: str = "event", **args) -> None:
        rec = {"ph": "i", "name": name, "cat": cat,
               "ts": round(time.monotonic(), 6), "tid": self._tid()}
        cur = getattr(_TLS, "ctx", None)
        if cur is not None:
            rec["trace"] = cur[0]
            rec["psid"] = cur[1]
        if args:
            rec["args"] = args
        self._write(rec)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._fh.close()
            except OSError:
                pass


ACTIVE: Optional[Tracer] = None


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "cat", "args", "t0", "_ctx", "_saved")

    def __init__(self, tracer: Optional[Tracer], name: str, cat: str,
                 args: dict):
        # tracer may be None: the span then only feeds the flight
        # recorder (no file, no trace context — those need a Tracer)
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        cur = getattr(_TLS, "ctx", None)
        if cur is not None and self.tracer is not None:
            sid = self.tracer.next_sid()
            self._ctx = (cur[0], sid, cur[1])
            self._saved = cur
            _TLS.ctx = (cur[0], sid)  # children parent to this span
        else:
            self._ctx = None
            self._saved = None
        self.t0 = time.monotonic()
        return self

    def __exit__(self, etype, exc, tb):
        dur = time.monotonic() - self.t0
        if etype is not None:
            self.args = dict(self.args or {}, error=etype.__name__)
        if self._ctx is not None:
            _TLS.ctx = self._saved
        if self.tracer is not None:
            self.tracer.emit_span(self.name, self.cat, self.t0, dur,
                                  self.args, ctx=self._ctx)
        fr = _flight.ACTIVE
        if fr is not None:
            fr.record_span(self.name, self.cat, self.t0, dur, self.args)
        return False


class _Bind:
    """Install a trace context on this thread for a block (None = no-op
    but still restores, so bind(start_request()) is always safe)."""

    __slots__ = ("ctx", "_saved")

    def __init__(self, ctx: Optional[tuple]):
        self.ctx = ctx

    def __enter__(self):
        self._saved = getattr(_TLS, "ctx", None)
        if self.ctx is not None:
            _TLS.ctx = self.ctx
        return self

    def __exit__(self, *exc):
        _TLS.ctx = self._saved
        return False


def span(name: str, cat: str = "span", **args):
    """Context manager timing a block into the trace. When both tracing
    and the flight recorder are off this returns a shared no-op object —
    no allocation, no clock read — so it is safe on hot paths. With only
    the flight recorder on, the span lands in its in-memory ring."""
    t = ACTIVE
    if t is None and _flight.ACTIVE is None:
        return _NULL_SPAN
    return _Span(t, name, cat, args)


def request_span(name: str, cat: str = "span", **args):
    """Like span(), but emitted ONLY when a request trace context is
    bound on this thread — the per-stage spans of a sampled request.
    Unsampled requests (and untraced processes) get the shared no-op."""
    t = ACTIVE
    if t is None or getattr(_TLS, "ctx", None) is None:
        return _NULL_SPAN
    return _Span(t, name, cat, args)


def event(name: str, cat: str = "event", **args) -> None:
    """Emit an instant event (recovery, restore, eviction...)."""
    t = ACTIVE
    if t is not None:
        t.event(name, cat, **args)
    fr = _flight.ACTIVE
    if fr is not None:
        fr.record_event(name, cat, args or None)


def start_request() -> Optional[tuple]:
    """Sampling decision at a request root (router predict, PS sync
    round, BSP collective): every WH_TRACE_SAMPLE-th call returns a
    fresh ``(trace_id, None)`` context to ``bind()``; the rest return
    None. Counter-based, so a given process samples the same request
    ordinals on every run — and an unsampled call costs one counter
    bump, nothing more."""
    t = ACTIVE
    if t is None or SAMPLE_N <= 0:
        return None
    n = t.next_req()
    if n % SAMPLE_N:
        return None
    return (f"{t.node}:{t.pid}:r{n}", None)


def bind(ctx: Optional[tuple]):
    """Install ``ctx`` (from start_request()/bind_wire parsing) on this
    thread for the block. ``bind(None)`` is a cheap no-op binding, so
    callers never branch on the sampling decision."""
    return _Bind(ctx)


def current_ctx() -> Optional[tuple]:
    """The ambient (trace_id, span_id) on this thread, for handing to a
    worker thread's bind() (thread pools don't inherit thread-locals)."""
    return getattr(_TLS, "ctx", None)


def wire_ctx() -> Optional[dict]:
    """The ambient context as a frame-header field (net.send_frame
    attaches it as ``tctx``, the key_digest piggyback pattern)."""
    if ACTIVE is None:
        return None
    cur = getattr(_TLS, "ctx", None)
    if cur is None:
        return None
    return {"t": cur[0], "s": cur[1]}


def bind_wire(header: dict):
    """Adopt the trace context a received frame carried (``tctx``):
    spans emitted inside the block parent to the sender's span, so the
    viewer stitches the two processes into one flow. No-op when the
    frame is unsampled or tracing is off."""
    if ACTIVE is None:
        return _NULL_SPAN  # nothing to adopt into; shared no-op
    tc = header.get("tctx")
    if not isinstance(tc, dict) or "t" not in tc:
        return _Bind(None)
    return _Bind((tc["t"], tc.get("s")))


def node_id() -> str:
    role = os.environ.get("WH_ROLE")
    if role:
        return f"{role}-{os.environ.get('WH_RANK', '0')}"
    return f"local-{os.getpid()}"


def _shutdown() -> None:
    """atexit hook: flush+close the active tracer so respawn-heavy runs
    (chaos labs spawning hundreds of incarnations) never leak
    descriptors when nobody called close() explicitly."""
    t = ACTIVE
    if t is not None:
        t.close()


atexit.register(_shutdown)


def init_from_env() -> Optional[Tracer]:
    """(Re)read WH_OBS_DIR / WH_TRACE_SAMPLE; called once at import.
    Tests call it again after mutating the env. Serialized by a module
    lock so concurrent re-inits (parallel test fixtures, respawn
    supervisors) can never leak a half-replaced tracer's handle."""
    global ACTIVE, SAMPLE_N
    with _INIT_LOCK:
        prev, ACTIVE = ACTIVE, None
        if prev is not None:
            prev.close()
        raw = os.environ.get("WH_TRACE_SAMPLE", "").strip()
        try:
            SAMPLE_N = int(raw) if raw else 0
        except ValueError:
            SAMPLE_N = 0
        out_dir = os.environ.get("WH_OBS_DIR", "").strip()
        if not out_dir:
            return None
        run_id = os.environ.get("WH_RUN_ID") or f"run-{int(time.time())}"
        ACTIVE = Tracer(out_dir, run_id, node_id())
        return ACTIVE


init_from_env()
