"""Continuous sampling profiler: whole-process stack sweeps at WH_PROF_HZ.

Google-Wide-Profiling style always-on capture, scaled down to one
process: a single daemon thread calls ``sys._current_frames()`` at a
modest rate (default 29 Hz — prime-ish, so it cannot phase-lock with
periodic loops), folds every thread's stack into a
``role;file:func;file:func...`` line, and tallies the lines in a dict.
The output is the standard folded-stack format (one ``line count`` per
entry) consumed by flamegraph tooling, written to

    WH_OBS_DIR/prof-<node>-<pid>.folded

at stop/atexit, and periodically fed to the flight recorder
(``obs.flight``) so anomaly dumps carry recent stacks.

Role tagging: threads self-identify via ``tag_thread("train")`` (a
single dict write, always safe to call); untagged threads fall back to
a thread-name heuristic (``ps-sync-comms`` → comms, router pool
workers → router, ...). The role prefixes the folded line, so one
glance at the profile separates the train loop from the comms thread
from the router pool.

Overhead contract: ``WH_PROF_BUDGET_PCT`` (default 2%) bounds the
measured fraction of wall time the sampler itself spends sweeping;
above budget it skips sweeps (counted in ``prof.throttled``) until the
ratio recovers. The measured ratio is exported as
``prof.overhead_frac`` so the budget claim is checkable from metrics.

Off (the default) this module starts no thread and allocates nothing:
``ACTIVE`` is None and ``tag_thread`` is one dict write.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
from typing import Optional

from wormhole_tpu.config import knob_value
from wormhole_tpu.obs import flight as _flight
from wormhole_tpu.obs import metrics as _metrics

_SAMPLES = _metrics.REGISTRY.counter("prof.samples")
_THROTTLED = _metrics.REGISTRY.counter("prof.throttled")
_OVERHEAD = _metrics.REGISTRY.gauge("prof.overhead_frac")

_INIT_LOCK = threading.Lock()

#: thread ident -> role tag, written by tag_thread()
_ROLES: dict[int, str] = {}

#: (substring of thread name, role) fallbacks for untagged threads
_NAME_ROLES = (
    ("ps-sync-comms", "comms"),
    ("router", "router"),
    ("watcher", "watcher"),
    ("loader", "loader"),
    ("MainThread", "main"),
)

_MAX_DEPTH = 64
_FLIGHT_TOP = 20  # folded lines per flight-recorder feed
_SNAP_FEED_S = 5.0  # seconds between flight-recorder stack feeds


def tag_thread(role: str) -> None:
    """Tag the calling thread's samples with a role (train loop, comms
    thread, router pool, watcher...). Idempotent and always-on cheap —
    one dict write — so hot paths may call it unconditionally."""
    _ROLES[threading.get_ident()] = role


def _role_of(ident: int, name: str) -> str:
    role = _ROLES.get(ident)
    if role:
        return role
    for sub, r in _NAME_ROLES:
        if sub in name:
            return r
    return "other"


class Profiler:
    def __init__(self, hz: float, budget_frac: float, out_dir: str,
                 node: str):
        self.hz = max(float(hz), 0.1)
        self.budget = max(float(budget_frac), 1e-4)
        self.out_dir = out_dir
        self.node = node
        self.pid = os.getpid()
        self._lock = threading.Lock()
        self._folded: dict[str, int] = {}
        self._busy_s = 0.0
        self._t_start = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="wh-pyprof", daemon=True)
        self._thread.start()

    def _loop(self) -> None:  # wormlint: thread-entry
        period = 1.0 / self.hz
        feed_every = max(int(self.hz * _SNAP_FEED_S), 1)
        n = 0
        while not self._stop.wait(period):
            wall = time.monotonic() - self._t_start
            if wall > 0 and (self._busy_s / wall) > self.budget:
                _THROTTLED.inc()
                continue
            t0 = time.monotonic()
            self._sweep()
            with self._lock:
                self._busy_s += time.monotonic() - t0
            _SAMPLES.inc()
            wall = time.monotonic() - self._t_start
            if wall > 0:
                _OVERHEAD.set(self._busy_s / wall)
            n += 1
            if n % feed_every == 0:
                _flight.record_stack(self.folded(top=_FLIGHT_TOP))

    def _sweep(self) -> None:
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        for ident, frame in frames.items():
            if ident == me:
                continue
            parts = []
            f = frame
            while f is not None and len(parts) < _MAX_DEPTH:
                code = f.f_code
                parts.append(
                    f"{os.path.basename(code.co_filename)}:{code.co_name}")
                f = f.f_back
            parts.reverse()
            key = _role_of(ident, names.get(ident, ""))
            if parts:
                key += ";" + ";".join(parts)
            with self._lock:
                self._folded[key] = self._folded.get(key, 0) + 1

    def folded(self, top: Optional[int] = None) -> list:
        """Folded-stack lines ``stack count``, heaviest first."""
        with self._lock:
            items = sorted(self._folded.items(), key=lambda kv: -kv[1])
        if top is not None:
            items = items[:top]
        return [f"{k} {v}" for k, v in items]

    def overhead_frac(self) -> float:
        wall = time.monotonic() - self._t_start
        return (self._busy_s / wall) if wall > 0 else 0.0

    def write_folded(self) -> Optional[str]:
        if not self.out_dir:
            return None
        path = os.path.join(self.out_dir,
                            f"prof-{self.node}-{self.pid}.folded")
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "w") as fh:
                for line in self.folded():
                    fh.write(line + "\n")
        except OSError:
            return None
        return path

    def stop(self) -> Optional[str]:
        self._stop.set()
        self._thread.join(timeout=2.0)
        return self.write_folded()


ACTIVE: Optional[Profiler] = None


def _shutdown() -> None:
    p = ACTIVE
    if p is not None:
        p.stop()


atexit.register(_shutdown)


def init_from_env() -> Optional[Profiler]:
    """(Re)read WH_PROF*; called once at import, again by tests after
    mutating the env. Stops any predecessor sampler first."""
    global ACTIVE
    with _INIT_LOCK:
        prev, ACTIVE = ACTIVE, None
        if prev is not None:
            prev.stop()
        if not knob_value("WH_PROF"):
            return None
        ACTIVE = Profiler(
            float(knob_value("WH_PROF_HZ")),
            float(knob_value("WH_PROF_BUDGET_PCT")) / 100.0,
            os.environ.get("WH_OBS_DIR", "").strip(),
            _flight.node_id())
        return ACTIVE


init_from_env()
